"""Block quantization formats (Q40 / Q80), TPU-native layout.

Byte-compatible with the reference `.m` tensor encoding (reference: src/quants.hpp:17-25,
src/quants.cpp:137-288, converter/writer.py:29-74) but stored on device as *planar* arrays
instead of 18/34-byte interleaved structs:

    Q40 tensor of shape (rows, n):  packed uint8 (rows, n//32, 16)  + scales f16 (rows, n//32)
    Q80 tensor of shape (rows, n):  values int8  (rows, n//32, 32)  + scales f16 (rows, n//32)

Planar layout is what TPU wants: the packed nibbles land in HBM as a dense uint8 array that
Pallas kernels / XLA can tile onto (32, 128)-shaped int8 registers, while the f16 scales form
a small separate array that broadcasts over each 32-element block. The interleaved struct
layout of the reference exists only at file I/O boundaries (`*_to_bytes` / `*_from_bytes`).

Nibble semantics match the reference exactly (src/quants.cpp:178-182): byte j of a block
holds element j in its low nibble and element j+16 in its high nibble; value = (nibble-8)*d.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

QK = 32  # block size for both Q40 and Q80 (reference: src/quants.hpp:14-15)
Q40_BLOCK_BYTES = 18  # f16 delta + 16 nibble-pair bytes
Q80_BLOCK_BYTES = 34  # f16 delta + 32 int8

_Q40_STRUCT = np.dtype([("d", "<f2"), ("qs", "u1", (QK // 2,))])
_Q80_STRUCT = np.dtype([("d", "<f2"), ("qs", "i1", (QK,))])


class FloatType(enum.IntEnum):
    """Wire/storage float types (reference: src/quants.hpp:6-12)."""

    F32 = 0
    F16 = 1
    Q40 = 2
    Q80 = 3


def batch_bytes(ftype: FloatType, n: int, d: int = 1) -> int:
    """Bytes for a (d, n) tensor in the given storage type (reference: src/quants.cpp:28-51)."""
    count = n * d
    if ftype == FloatType.F32:
        return count * 4
    if ftype == FloatType.F16:
        return count * 2
    if ftype == FloatType.Q40:
        assert n % QK == 0, (n, d)
        return (count // QK) * Q40_BLOCK_BYTES
    if ftype == FloatType.Q80:
        assert n % QK == 0, (n, d)
        return (count // QK) * Q80_BLOCK_BYTES
    raise ValueError(f"unknown float type {ftype}")


# ---------------------------------------------------------------------------
# Q40: 4-bit blocks, asymmetric-ish (min/max) scaling with +8.5 offset
# ---------------------------------------------------------------------------


def quantize_q40(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Quantize float array (..., n) to Q40 planar (packed, scales).

    Matches converter/writer.py:29-53: delta = extremum/-8 in f16, q = clip(x/delta+8.5, 0, 15).

    Returns (packed uint8 (..., n//32, 16), scales float16 (..., n//32)).
    """
    x = np.asarray(x, dtype=np.float32)
    n = x.shape[-1]
    assert n % QK == 0, n
    g = x.reshape(*x.shape[:-1], n // QK, QK)
    gmax = g.max(axis=-1)
    gmin = g.min(axis=-1)
    deltas = np.where(-gmin > gmax, gmin, gmax) / -8.0
    deltas16 = deltas.astype(np.float16)
    inv = np.divide(1.0, deltas, out=np.zeros_like(deltas), where=deltas != 0).astype(np.float32)
    q = np.clip(g * inv[..., None] + 8.5, 0, 15).astype(np.uint8)
    packed = q[..., : QK // 2] | (q[..., QK // 2 :] << 4)
    return packed, deltas16


def dequantize_q40(packed: np.ndarray, scales: np.ndarray) -> np.ndarray:
    """Planar Q40 -> float32 (..., n). Matches src/quants.cpp:170-183."""
    lo = (packed & 0x0F).astype(np.int8) - 8
    hi = (packed >> 4).astype(np.int8) - 8
    vals = np.concatenate([lo, hi], axis=-1).astype(np.float32)
    out = vals * scales[..., None].astype(np.float32)
    return out.reshape(*packed.shape[:-2], packed.shape[-2] * QK)


def q40_to_bytes(packed: np.ndarray, scales: np.ndarray) -> bytes:
    """Planar Q40 -> reference interleaved block stream (BlockQ40[])."""
    nb = int(np.prod(packed.shape[:-1]))
    out = np.empty(nb, dtype=_Q40_STRUCT)
    out["d"] = scales.reshape(nb)
    out["qs"] = packed.reshape(nb, QK // 2)
    return out.tobytes()


def q40_from_bytes(buf: bytes, shape: tuple[int, ...]) -> tuple[np.ndarray, np.ndarray]:
    """Reference BlockQ40[] stream -> planar (packed, scales) for logical shape (..., n)."""
    n = shape[-1]
    assert n % QK == 0, shape
    nb_shape = (*shape[:-1], n // QK)
    nb = int(np.prod(nb_shape))
    arr = np.frombuffer(buf, dtype=_Q40_STRUCT, count=nb)
    return arr["qs"].reshape(*nb_shape, QK // 2).copy(), arr["d"].reshape(nb_shape).copy()


# ---------------------------------------------------------------------------
# Q80: int8 blocks, symmetric absmax/127 scaling
# ---------------------------------------------------------------------------


def quantize_q80(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Quantize (..., n) to Q80 planar (values int8 (..., n//32, 32), scales f16 (..., n//32)).

    Matches converter/writer.py:55-74 / src/quants.cpp:186-268 (round-to-nearest-even).
    """
    x = np.asarray(x, dtype=np.float32)
    n = x.shape[-1]
    assert n % QK == 0, n
    g = x.reshape(*x.shape[:-1], n // QK, QK)
    absmax = np.abs(g).max(axis=-1)
    deltas = absmax / 127.0
    deltas16 = deltas.astype(np.float16)
    inv = np.divide(1.0, deltas, out=np.zeros_like(deltas), where=deltas != 0).astype(np.float32)
    q = np.round(g * inv[..., None]).astype(np.int8)
    return q, deltas16


def dequantize_q80(values: np.ndarray, scales: np.ndarray) -> np.ndarray:
    out = values.astype(np.float32) * scales[..., None].astype(np.float32)
    return out.reshape(*values.shape[:-2], values.shape[-2] * QK)


def q80_to_bytes(values: np.ndarray, scales: np.ndarray) -> bytes:
    nb = int(np.prod(values.shape[:-1]))
    out = np.empty(nb, dtype=_Q80_STRUCT)
    out["d"] = scales.reshape(nb)
    out["qs"] = values.reshape(nb, QK)
    return out.tobytes()


def q80_from_bytes(buf: bytes, shape: tuple[int, ...]) -> tuple[np.ndarray, np.ndarray]:
    n = shape[-1]
    assert n % QK == 0, shape
    nb_shape = (*shape[:-1], n // QK)
    nb = int(np.prod(nb_shape))
    arr = np.frombuffer(buf, dtype=_Q80_STRUCT, count=nb)
    return arr["qs"].reshape(*nb_shape, QK).copy(), arr["d"].reshape(nb_shape).copy()


# ---------------------------------------------------------------------------
# On-device (jnp) dequantization — the XLA-path used outside Pallas kernels
# ---------------------------------------------------------------------------


def jnp_dequantize_q40(packed: jax.Array, scales: jax.Array, dtype=jnp.bfloat16) -> jax.Array:
    """Dequantize planar Q40 on device: (..., nb, 16) u8 + (..., nb) f16 -> (..., nb*32)."""
    lo = (packed & 0x0F).astype(jnp.int8) - 8
    hi = (packed >> 4).astype(jnp.int8) - 8
    vals = jnp.concatenate([lo, hi], axis=-1).astype(dtype)
    out = vals * scales[..., None].astype(dtype)
    return out.reshape(*packed.shape[:-2], packed.shape[-2] * QK)


def jnp_dequantize_q40_tpu(packed2: jax.Array, scales: jax.Array,
                           dtype=jnp.bfloat16) -> jax.Array:
    """Dequantize the TPU-permuted layout (single segment) back to natural order."""
    nb = scales.shape[-1]
    lead = packed2.shape[:-1]
    p = packed2.reshape(*lead, 16, nb)
    lo = (p & 0x0F).astype(jnp.int8) - 8
    hi = (p >> 4).astype(jnp.int8) - 8
    w = jnp.concatenate([lo, hi], axis=-2)  # (..., 32, nb) intra-major
    w = jnp.swapaxes(w, -1, -2).astype(dtype) * scales[..., None].astype(dtype)
    return w.reshape(*lead, nb * QK)


def jnp_dequantize_q80(values: jax.Array, scales: jax.Array, dtype=jnp.bfloat16) -> jax.Array:
    out = values.astype(dtype) * scales[..., None].astype(dtype)
    return out.reshape(*values.shape[:-2], values.shape[-2] * QK)


def jnp_quantize_q80(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """On-device Q80 quantization (..., n) -> (int8 (..., nb, 32), f16 scales).

    TPU-native descendant of the reference's wire compression (src/tasks.cpp:96-135):
    used for int8-compressed collectives instead of socket payloads.
    """
    n = x.shape[-1]
    g = x.reshape(*x.shape[:-1], n // QK, QK).astype(jnp.float32)
    absmax = jnp.max(jnp.abs(g), axis=-1)
    deltas = (absmax / 127.0).astype(jnp.float16)
    inv = jnp.where(absmax > 0, 127.0 / absmax, 0.0)
    q = jnp.round(g * inv[..., None]).astype(jnp.int8)
    return q, deltas


# ---------------------------------------------------------------------------
# TPU-permuted Q40 layout for the Pallas fused dequant-matmul kernel
# ---------------------------------------------------------------------------
#
# Mosaic cannot reshape (BN, nb, 32) -> (BN, K) in registers, so the kernel needs a layout
# where scales broadcast along lanes WITHOUT a reshape. pltpu.repeat has tile semantics
# ([s0..s_nb] * 32), so we permute weight columns block-strided: element (block b,
# intra i) lives at column i*nb + b. Then lane j's scale is s[j % nb] == tile-repeat, and
# the nibble halves unpack into two contiguous lane ranges (i<16 -> low nibbles,
# i>=16 -> high). Activations get the same column permutation (cheap XLA transpose).
#
# `n_shards` makes the permutation local to each of n contiguous K-segments so a
# col-parallel (input-dim) TP shard of the packed array is itself a valid permuted layout.


def q40_repack_tpu(packed: np.ndarray, scales: np.ndarray, n_shards: int = 1) -> np.ndarray:
    """Planar Q40 packed (..., nb, 16) -> TPU-permuted packed2 (..., nb*16).

    packed2[..., j] holds (for each K-shard segment independently, nb_l = nb/n_shards):
    low nibble = element at permuted pos j = i*nb_l+b for i<16, high nibble = same j with
    i+16. scales stay (..., nb) unchanged.
    """
    nb = packed.shape[-2]
    assert nb % n_shards == 0, (nb, n_shards)
    nb_l = nb // n_shards
    lead = packed.shape[:-2]
    q = packed.reshape(*lead, n_shards, nb_l, 16)
    lo = q & 0x0F  # intra i = 0..15, element (b, i)
    hi = q >> 4  # intra i = 16..31
    # permuted: pos j = i*nb_l + b  ->  transpose (nb_l, 16) -> (16, nb_l)
    lo_p = np.swapaxes(lo, -1, -2).reshape(*lead, n_shards, nb_l * 16)
    hi_p = np.swapaxes(hi, -1, -2).reshape(*lead, n_shards, nb_l * 16)
    out = (lo_p | (hi_p << 4)).astype(np.uint8)
    return out.reshape(*lead, nb * 16)


def permute_activations_tpu(x, nb: int, n_shards: int = 1):
    """Match q40_repack_tpu's column permutation on the activation side (jnp or numpy).

    x: (..., K) with K = nb*32 -> same shape, columns permuted per K-shard segment.
    """
    xp = jnp if isinstance(x, jax.Array) else np
    k = x.shape[-1]
    assert k == nb * QK, (x.shape, nb)
    nb_l = nb // n_shards
    lead = x.shape[:-1]
    x4 = x.reshape(*lead, n_shards, nb_l, QK)
    x4 = xp.swapaxes(x4, -1, -2)  # (..., n_shards, 32, nb_l)
    return x4.reshape(*lead, k)


def dequantize_q40_tpu(packed2: np.ndarray, scales: np.ndarray,
                       n_shards: int = 1) -> np.ndarray:
    """TPU-permuted packed2 (..., nb*16) + scales (..., nb) -> natural-order floats."""
    nb = scales.shape[-1]
    nb_l = nb // n_shards
    lead = packed2.shape[:-1]
    p = packed2.reshape(*lead, n_shards, 16, nb_l)
    lo = (p & 0x0F).astype(np.int8) - 8  # i = 0..15
    hi = (p >> 4).astype(np.int8) - 8  # i = 16..31
    w = np.concatenate([lo, hi], axis=-2)  # (..., n_shards, 32, nb_l) intra-major
    w = np.swapaxes(w, -1, -2).reshape(*lead, nb, QK).astype(np.float32)
    w = w * scales[..., None].astype(np.float32)
    return w.reshape(*lead, nb * QK)


# ---------------------------------------------------------------------------
# QTensor: a quantized-or-not weight tensor as a pytree
# ---------------------------------------------------------------------------


@jax.tree_util.register_pytree_node_class
@dataclass
class QTensor:
    """A weight tensor, stored dense or block-quantized.

    For Q40/Q80 the block axis is the LAST logical axis (the contraction axis `n` of the
    reference's (d, n) row-major weights; reference blocks run along n — src/commands.cpp:22-39).
    Registered as a pytree so QTensors flow through jit/scan/shard_map and can carry per-leaf
    shardings. `shape` is derived from `data`, so it stays correct when transforms (scan
    unstacking, vmap, gathers) reshape the leaves.
    """

    ftype: FloatType
    data: jax.Array | np.ndarray  # dense values, Q40 packed u8, or Q80 int8
    scales: jax.Array | np.ndarray | None = None  # f16 per-block scales for Q40/Q80
    layout: str = "planar"  # "planar" | "tpu" (block-strided permuted, see q40_repack_tpu)

    @property
    def shape(self) -> tuple[int, ...]:
        """Logical (dequantized) shape."""
        if self.ftype in (FloatType.F32, FloatType.F16):
            return tuple(self.data.shape)
        if self.ftype == FloatType.Q40 and self.layout == "tpu":
            return (*self.data.shape[:-1], self.data.shape[-1] * 2)
        if self.ftype in (FloatType.Q40, FloatType.Q80):
            return (*self.data.shape[:-2], self.data.shape[-2] * QK)
        raise ValueError(self.ftype)

    def tree_flatten(self):
        if self.scales is None:
            return (self.data,), (self.ftype, False, self.layout)
        return (self.data, self.scales), (self.ftype, True, self.layout)

    @classmethod
    def tree_unflatten(cls, aux, children):
        ftype, has_scales, layout = aux
        if has_scales:
            data, scales = children
        else:
            (data,) = children
            scales = None
        return cls(ftype=ftype, data=data, scales=scales, layout=layout)

    def to_tpu_layout(self, n_shards: int = 1) -> "QTensor":
        """Repack planar Q40 into the Pallas kernel's block-strided layout (host-side)."""
        assert self.ftype == FloatType.Q40 and self.layout == "planar", (
            self.ftype, self.layout)
        packed2 = q40_repack_tpu(np.asarray(self.data), np.asarray(self.scales), n_shards)
        # Mosaic has no f16 support: carry scales as f32 (exact upcast, dequant unchanged)
        scales32 = np.asarray(self.scales, dtype=np.float32)
        return QTensor(self.ftype, packed2, scales32, layout="tpu")

    @classmethod
    def from_float(cls, x: np.ndarray, ftype: FloatType) -> "QTensor":
        x = np.asarray(x)
        if ftype == FloatType.F32:
            return cls(ftype, x.astype(np.float32))
        if ftype == FloatType.F16:
            return cls(ftype, x.astype(np.float16))
        if ftype == FloatType.Q40:
            packed, scales = quantize_q40(x)
            return cls(ftype, packed, scales)
        if ftype == FloatType.Q80:
            vals, scales = quantize_q80(x)
            return cls(ftype, vals, scales)
        raise ValueError(ftype)

    def dequantize(self, dtype=jnp.bfloat16) -> jax.Array:
        """Materialize logical values on device (jnp path; Pallas kernels bypass this)."""
        if self.ftype in (FloatType.F32, FloatType.F16):
            return jnp.asarray(self.data).astype(dtype)
        if self.ftype == FloatType.Q40 and self.layout == "tpu":
            return jnp_dequantize_q40_tpu(jnp.asarray(self.data), jnp.asarray(self.scales),
                                          dtype)
        if self.ftype == FloatType.Q40:
            return jnp_dequantize_q40(jnp.asarray(self.data), jnp.asarray(self.scales), dtype)
        if self.ftype == FloatType.Q80:
            return jnp_dequantize_q80(jnp.asarray(self.data), jnp.asarray(self.scales), dtype)
        raise ValueError(self.ftype)

    def to_numpy(self) -> np.ndarray:
        if self.ftype in (FloatType.F32, FloatType.F16):
            return np.asarray(self.data, dtype=np.float32)
        if self.ftype == FloatType.Q40 and self.layout == "tpu":
            return dequantize_q40_tpu(np.asarray(self.data), np.asarray(self.scales))
        if self.ftype == FloatType.Q40:
            return dequantize_q40(np.asarray(self.data), np.asarray(self.scales))
        if self.ftype == FloatType.Q80:
            return dequantize_q80(np.asarray(self.data), np.asarray(self.scales))
        raise ValueError(self.ftype)

    def nbytes(self) -> int:
        n = self.data.nbytes
        if self.scales is not None:
            n += self.scales.nbytes
        return n
