"""Hung-engine supervisor: escalate the dispatch watchdog from gauge to act.

PR 4 gave the BatchEngine a watchdog *reading* — `batch_dispatch_age_seconds`,
seconds since the scheduler last completed a device dispatch while work is in
flight — but nothing consumed it: a wedged engine (a dispatch hung in the
backend, the BENCH_r03/r04 documented outage mode where even a trivial fenced
op never completes) sat at 100% unavailability while /healthz kept answering
"ok" and every queued client waited forever.

The EngineSupervisor closes that loop (docs/ROBUSTNESS.md "Hung-engine
supervision"). A daemon thread polls `engine.dispatch_age()`; when the age
crosses `threshold` seconds it escalates:

1. flip this supervisor (and therefore the replica's /healthz, which
   api_server wires to `healthy`) UNHEALTHY — a fleet router ejects the
   replica within one membership poll and resumes its journaled in-flight
   requests on surviving replicas (docs/FLEET.md "Resume protocol");
2. call `engine.recover_wedged()`: fail every in-flight/queued request with
   the RETRIABLE EngineWedged, abandon the stuck scheduler thread (engine
   epoch bump), and re-initialize the backend (drop compiled programs,
   fresh KV caches);
3. on successful re-init, flip healthy again — the replica rejoins rotation
   on the router's next clean poll. `max_recoveries` consecutive escalations
   without an intervening healthy period marks the engine FAILED: /healthz
   stays unhealthy so the operator (or the orchestrator's restart policy)
   takes over instead of the supervisor thrashing a dead backend.

The supervisor never *prevents* a wedge — it bounds the blast to
`threshold + poll` seconds of stall followed by retriable failures, instead
of an unbounded silent outage.
"""

from __future__ import annotations

import threading
import time

from ..obs import metrics

__all__ = ["EngineSupervisor"]

_STATE = metrics.gauge(
    "engine_supervisor_state",
    "Hung-engine supervisor state: 0 ok, 1 recovering, 2 failed "
    "(docs/ROBUSTNESS.md)")

_STATES = {"ok": 0, "recovering": 1, "failed": 2}


class EngineSupervisor:
    """Watch one BatchEngine-shaped object (`dispatch_age()`,
    `recover_wedged()`, `scheduler_alive()`) and act on a hang.

    `threshold` — dispatch age (seconds) past which the engine counts as
    wedged; size it well above the slowest legitimate dispatch (a prefill
    chunk on cold compile can take tens of seconds on first use).
    `poll` — watchdog sampling period; detection latency is threshold+poll.
    `max_recoveries` — consecutive recoveries (no healthy dispatch observed
    between them) before the supervisor gives up and stays unhealthy.
    `reinit` — forward to recover_wedged (tests disable to isolate the
    abandon/fail half).
    """

    def __init__(self, engine, threshold: float = 60.0, poll: float = 1.0,
                 max_recoveries: int = 3, reinit: bool = True):
        assert threshold > 0, "use threshold>0 (0 disables the supervisor)"
        self.engine = engine
        self.threshold = float(threshold)
        self.poll = float(poll)
        self.max_recoveries = max_recoveries
        self.reinit = reinit
        self.state = "ok"  # ok | recovering | failed
        self.recoveries = 0  # lifetime escalations
        self._consecutive = 0  # escalations without dispatch progress between
        self._progress_mark = self._progress()
        self.last_recovery_t: float | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        _STATE.set(0)

    # ------------------------------------------------------------------

    @property
    def healthy(self) -> bool:
        """False while a recovery is in progress or the engine is failed —
        the reading api_server's /healthz folds in so the router ejects the
        replica for exactly the unhealthy window."""
        return self.state == "ok"

    def stats(self) -> dict:
        return {"state": self.state, "threshold_s": self.threshold,
                "recoveries": self.recoveries,
                "dispatch_age_s": round(self.engine.dispatch_age(), 3)}

    # ------------------------------------------------------------------

    def start(self) -> "EngineSupervisor":
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="engine-supervisor")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.poll + 1.0)

    def _run(self) -> None:
        while not self._stop.wait(self.poll):
            try:
                self.check_once()
            except Exception as e:  # the supervisor itself must not die
                print(f"⚠️  supervisor check failed: {e!r}")

    def _progress(self) -> tuple:
        """Dispatch-progress reading: counters that only a COMPLETED device
        dispatch advances. The consecutive-escalation guard keys on these —
        an idle age of 0 right after a recovery (slots just cleared) is NOT
        evidence the engine works, so it must not reset the counter or a
        permanently broken backend would thrash ok→wedged forever instead
        of reaching the terminal 'failed' state."""
        eng = self.engine
        return (getattr(eng, "decode_steps", 0),
                getattr(eng, "prefilled_tokens", 0))

    def check_once(self) -> None:
        """One watchdog sample + escalation decision (called from the loop;
        tests call it directly for deterministic timing)."""
        if self.state == "failed":
            return
        age = self.engine.dispatch_age()
        if age <= self.threshold:
            if self._consecutive and self._progress() != self._progress_mark:
                # real dispatches completed since the last escalation:
                # isolated wedges spread over a long uptime never
                # accumulate into a spurious "failed"
                self._consecutive = 0
            return
        self._escalate(age)

    def _escalate(self, age: float) -> None:
        self._set_state("recovering")
        self.recoveries += 1
        self._consecutive += 1
        self._progress_mark = self._progress()
        self.last_recovery_t = time.monotonic()
        print(f"🔴 supervisor: engine made no dispatch progress for "
              f"{age:.1f}s (threshold {self.threshold:.1f}s) — failing "
              f"in-flight requests (retriable) and re-initializing "
              f"(recovery {self._consecutive}/{self.max_recoveries})")
        ok = False
        try:
            ok = self.engine.recover_wedged(reinit=self.reinit)
        except Exception as e:
            print(f"🔴 supervisor: recover_wedged raised: {e!r}")
        if not ok or self._consecutive >= self.max_recoveries:
            self._set_state("failed")
            print("🔴 supervisor: engine marked FAILED "
                  f"(reinit_ok={ok}, consecutive={self._consecutive}) — "
                  "/healthz stays unhealthy; restart the replica")
        else:
            self._set_state("ok")

    def _set_state(self, state: str) -> None:
        self.state = state
        _STATE.set(_STATES[state])
