"""Typed errors for the resilience layer (docs/ROBUSTNESS.md).

The pre-PR-4 serving stack had exactly one failure shape: a bare
`RuntimeError` that meant anything from "the queue is full" to "the scheduler
thread crashed mid-dispatch". These types give every failure mode a name the
HTTP layer can map to an honest status code (and tests can assert on):

    EngineClosed / EngineDraining  -> 503 (server going away)
    EngineSaturated                -> 503 + Retry-After (load shed)
    QuotaExceeded                  -> 429 + Retry-After (tenant quota)
    DeadlineExceeded               -> 408 (queue TTL / generation deadline)
    InvalidRequest                 -> 400 (caller error, not server error)
    TransientDispatchError         -> retried by the scheduler, never surfaced
                                      unless retries are exhausted

`classify()` is the single blast-radius switch the BatchEngine scheduler
uses: every exception escaping a dispatch is sorted into `transient`
(retry in place), `request` (fail only the attributable request; the other
co-batched slots keep decoding), or `engine` (fail all in-flight, survive,
back off). Exceptions may carry an explicit `fault_scope` attribute — the
fault-injection framework (faults.py) uses it to declare the blast radius a
test intends.
"""

from __future__ import annotations

__all__ = ["EngineClosed", "EngineDraining", "EngineSaturated",
           "QuotaExceeded", "DeadlineExceeded", "InvalidRequest",
           "TransientDispatchError", "EngineWedged", "FaultInjected",
           "classify", "retriable"]


class EngineClosed(RuntimeError):
    """The engine is shut down; queued/in-flight requests were aborted."""


class EngineDraining(EngineClosed):
    """The engine is draining (SIGTERM): in-flight requests finish, new
    admissions are refused. A subclass of EngineClosed so existing
    `except EngineClosed` handlers cover both."""


class EngineSaturated(RuntimeError):
    """Admission refused: the submit queue is at --max-queue, or SLO-aware
    shedding (docs/SERVING.md "Multi-tenant serving") projected the queue
    wait past the request class's TTFT target. Carries `retry_after`
    (seconds, advisory — derived from the measured queue drain rate by the
    raiser, resilience/tenancy.py DrainRate, never a hardcoded constant)
    for the HTTP 503 Retry-After header."""

    def __init__(self, msg: str, retry_after: float = 1.0):
        super().__init__(msg)
        self.retry_after = retry_after


class QuotaExceeded(RuntimeError):
    """A tenant's token-bucket quota is exhausted (resilience/tenancy.py):
    admission refused before any queue or slot work, HTTP 429 +
    Retry-After. `retry_after` comes from the bucket's own refill
    arithmetic (seconds until the debit can succeed); `tenant` is the
    policy name for per-tenant throttle metrics. NOT retriable on another
    replica — the quota is the tenant's, not the replica's."""

    def __init__(self, msg: str, retry_after: float = 1.0, tenant: str = ""):
        super().__init__(msg)
        self.retry_after = retry_after
        self.tenant = tenant


class DeadlineExceeded(RuntimeError):
    """The request's queue TTL or wall-clock generation deadline expired
    before completion (finish reason "deadline")."""


class InvalidRequest(ValueError):
    """The request itself is malformed (prompt exceeds seq_len, bad
    max_tokens): a 400, never a 500 or a stall."""


class TransientDispatchError(RuntimeError):
    """A dispatch failure expected to succeed on retry (injected transient
    faults; preemption-shaped runtime errors registered by the caller). The
    scheduler retries these with capped exponential backoff before treating
    them as engine-scope."""

    fault_scope = "transient"


class EngineWedged(RuntimeError):
    """The dispatch watchdog escalated: the engine stopped making progress
    (a device dispatch hung past the supervisor threshold) and the
    supervisor failed this in-flight request while it attempts backend
    re-initialization (resilience/supervisor.py). RETRIABLE by contract:
    the request itself is innocent — a fleet router should resume it on
    another replica (docs/FLEET.md "Resume protocol")."""


class FaultInjected(RuntimeError):
    """Raised by the fault-injection framework at a named point. `scope`
    declares the blast radius the scheduler may assume: "request" faults are
    attributable to one request (the injection fired before any shared state
    changed), "engine" faults are not."""

    def __init__(self, msg: str, scope: str = "request"):
        super().__init__(msg)
        assert scope in ("request", "engine"), scope
        self.fault_scope = scope


def classify(exc: BaseException) -> str:
    """Blast radius of an exception: 'transient' | 'request' | 'engine'.

    Honors an explicit `fault_scope` attribute first (set by FaultInjected /
    TransientDispatchError), then falls back to 'engine' — the conservative
    default: a real, unattributed dispatch failure may have left the shared
    caches indeterminate, so it must fail every in-flight request rather
    than silently corrupt a survivor."""
    scope = getattr(exc, "fault_scope", None)
    if scope in ("transient", "request", "engine"):
        return scope
    return "engine"


def retriable(exc: BaseException) -> bool:
    """Whether a request that failed with `exc` may be re-submitted (resumed)
    on another replica without changing client-visible semantics — the
    durable router's mid-stream failover switch (fleet/router.py):

    - deterministic caller errors (InvalidRequest / any ValueError) and
      expired deadlines would fail identically anywhere: NOT retriable;
    - saturation is handled by the router's own failover/Retry-After path,
      not the resume machinery: NOT retriable here;
    - the request-innocent failures — engine wedged/closed under it,
      transient dispatch errors that exhausted retries, engine-scope faults,
      and any unclassified server error — ARE retriable: the replica died
      around the request, the request did not poison the replica.

    Request-scope injected faults are the one judgment call: the fault fired
    inside THIS request's own callbacks/prefill, so a blind resume could
    loop forever on a deterministic trigger — treat as NOT retriable."""
    if isinstance(exc, (DeadlineExceeded, ValueError, EngineSaturated,
                        QuotaExceeded)):
        return False
    if isinstance(exc, FaultInjected):
        return exc.fault_scope == "engine"
    return True
