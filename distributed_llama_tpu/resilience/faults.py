"""Deterministic fault injection for the serving runtime (docs/ROBUSTNESS.md).

Nothing in the stack *tested* the unhappy path before this module existed: a
poisoned request, a hung dispatch, or a transient runtime error could only be
reproduced by getting unlucky in production. This framework turns those into
named, seedable events:

- **Injection points** are `faults.fire("name", **ctx)` calls wired into the
  runtime hot paths (engine dispatch, BatchEngine prefill/dispatch/emit/seed,
  device-loop dispatch, paged-cache append/cold-attend, api request entry).
  The full inventory lives in docs/ROBUSTNESS.md and perf/fault_matrix.py.
- **FaultSpec** describes what happens at a point: raise an error (with a
  declared blast-radius `scope`), raise a `TransientDispatchError` (the
  scheduler retries these), or inject a latency spike. Specs select by point
  name (fnmatch glob), optional context match (e.g. `match={"slot": 1}`),
  per-fire probability, a skip-first-N `after`, and a max-fires `count`.
- **Determinism**: probability draws come from one `random.Random(seed)`
  owned by the plan, so a chaos run replays exactly under the same seed and
  schedule.
- **Activation**: `faults.active(...)` (context manager, tests),
  `faults.install(...)` (process-wide), or the `DLLAMA_FAULTS` env var parsed
  by `install_from_env()` (wired into the dllama / api_server entry points):

      DLLAMA_FAULTS="point:kind[:prob[:count[:delay_ms[:duration_s]]]][,...]"
      DLLAMA_FAULT_SEED=7

  e.g. `DLLAMA_FAULTS="batch.dispatch:transient:0.01"` injects a 1% transient
  dispatch-failure rate into a live server.

The disabled hot path is one module-global None check (`fire()` returns
immediately) — the same discipline as obs/trace.py's no-op tracer, so the
points can stay wired in production builds.
"""

from __future__ import annotations

import fnmatch
import os
import random
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field

from ..obs import flight, metrics
from .errors import FaultInjected, TransientDispatchError

__all__ = ["KINDS", "FaultSpec", "FaultPlan", "fire", "install", "uninstall",
           "active", "current", "parse_faults", "install_from_env"]

KINDS = ("error", "transient", "latency")

_INJECTED = metrics.counter(
    "faults_injected_total",
    "Faults fired by the injection framework (docs/ROBUSTNESS.md)",
    labelnames=("point", "kind"))


@dataclass
class FaultSpec:
    """One injection rule. `point` is an exact name or fnmatch glob
    ("batch.*"); `kind` is error | transient | latency; `scope` declares the
    blast radius an *error* fault promises (the injection fires before the
    guarded operation touches shared state, so "request" is sound for the
    per-request points); `match` filters on fire-site context kwargs."""

    point: str
    kind: str = "error"
    prob: float = 1.0
    count: int | None = None   # max fires (None = unlimited)
    after: int = 0             # skip the first N matching hits
    delay_ms: float = 25.0     # latency kind: injected stall
    scope: str = "request"     # error kind: request | engine
    match: dict = field(default_factory=dict)
    # sustained-degradation window (gray failures, docs/ROBUSTNESS.md
    # "Gray failures"): the spec stops firing `duration_s` seconds after
    # its FIRST fire — "this replica is 10x slow for two minutes, then
    # recovers", the shape probation entry/exit detection needs. None =
    # no window (the per-call behavior all older specs keep).
    duration_s: float | None = None
    seen: int = 0              # matching hits observed (runtime state)
    fired: int = 0             # faults actually injected (runtime state)
    first_fire_t: float = 0.0  # monotonic of the first fire (runtime state)

    def __post_init__(self):
        assert self.kind in KINDS, f"unknown fault kind {self.kind!r}"
        assert self.scope in ("request", "engine"), self.scope
        assert 0.0 <= self.prob <= 1.0, self.prob


class FaultPlan:
    """An installed set of FaultSpecs sharing one seeded RNG."""

    def __init__(self, specs, seed: int = 0):
        self.specs = list(specs)
        self.seed = seed
        self._rng = random.Random(seed)
        self._lock = threading.Lock()

    def fired(self) -> int:
        return sum(s.fired for s in self.specs)

    def fire(self, point: str, **ctx) -> None:
        for spec in self.specs:
            if not fnmatch.fnmatchcase(point, spec.point):
                continue
            if any(ctx.get(k) != v for k, v in spec.match.items()):
                continue
            with self._lock:
                spec.seen += 1
                if spec.seen <= spec.after:
                    continue
                if spec.count is not None and spec.fired >= spec.count:
                    continue
                if (spec.duration_s is not None and spec.first_fire_t
                        and time.monotonic() - spec.first_fire_t
                        > spec.duration_s):
                    continue  # sustained-degradation window expired
                if spec.prob < 1.0 and self._rng.random() >= spec.prob:
                    continue
                spec.fired += 1
                if not spec.first_fire_t:
                    spec.first_fire_t = time.monotonic()
            _INJECTED.labels(point=point, kind=spec.kind).inc()
            # flight-recorder timeline hook: when the injection point fires
            # inside a request's bound trace context (per-request points:
            # prefill-of-slot, emit, cache-seed, api entry), the injected
            # fault lands on THAT request's timeline — a chaos run's victim
            # explains itself at GET /v1/requests/<id>
            flight.note_fault(point, spec.kind)
            if spec.kind == "latency":
                time.sleep(spec.delay_ms / 1000.0)
                continue  # a latency spike doesn't shadow later error specs
            if spec.kind == "transient":
                raise TransientDispatchError(
                    f"injected transient fault at {point}")
            raise FaultInjected(f"injected fault at {point}",
                                scope=spec.scope)


_PLAN: FaultPlan | None = None


def fire(point: str, **ctx) -> None:
    """Injection-point hook: no-op (one None check) unless a plan is
    installed. Context kwargs are matched against each spec's `match`."""
    plan = _PLAN
    if plan is not None:
        plan.fire(point, **ctx)


def install(specs, seed: int = 0) -> FaultPlan:
    """Install a plan process-wide (replaces any previous plan). Accepts a
    ready FaultPlan or an iterable of FaultSpecs."""
    global _PLAN
    plan = specs if isinstance(specs, FaultPlan) else FaultPlan(specs,
                                                                seed=seed)
    _PLAN = plan
    return plan


def uninstall() -> None:
    global _PLAN
    _PLAN = None


def current() -> FaultPlan | None:
    return _PLAN


@contextmanager
def active(*specs, seed: int = 0):
    """Scoped activation for tests: installs the specs, uninstalls on exit
    (only if the plan is still this one — a nested install wins)."""
    plan = install(list(specs), seed=seed)
    try:
        yield plan
    finally:
        if _PLAN is plan:
            uninstall()


def parse_faults(text: str) -> list[FaultSpec]:
    """Parse the DLLAMA_FAULTS grammar:

        spec[,spec...]
        spec = point:kind[:prob[:count[:delay_ms[:duration_s]]]]

    `count` may be empty or "inf" for unlimited; `duration_s` (empty = none)
    arms the sustained-degradation window — the spec stops firing that many
    seconds after its first fire, e.g.
    `api.request:latency:1::800:45` = every request 800 ms slow for 45 s
    from the first hit, then recovered (the gray-failure chaos shape).
    Raises ValueError with the offending spec on malformed input (a typo'd
    chaos config must fail loud, not silently inject nothing)."""
    specs = []
    for raw in text.split(","):
        raw = raw.strip()
        if not raw:
            continue
        parts = raw.split(":")
        if len(parts) < 2 or len(parts) > 6:
            raise ValueError(
                f"bad fault spec {raw!r} "
                "(point:kind[:prob[:count[:delay_ms[:duration_s]]]])")
        point, kind = parts[0], parts[1]
        if kind not in KINDS:
            raise ValueError(f"bad fault kind {kind!r} in {raw!r} "
                             f"(one of {KINDS})")
        try:
            prob = float(parts[2]) if len(parts) > 2 and parts[2] else 1.0
            count = (None if len(parts) <= 3 or parts[3] in ("", "inf")
                     else int(parts[3]))
            delay = float(parts[4]) if len(parts) > 4 and parts[4] else 25.0
            duration = (float(parts[5]) if len(parts) > 5 and parts[5]
                        else None)
        except ValueError:
            raise ValueError(f"bad numeric field in fault spec {raw!r}")
        specs.append(FaultSpec(point=point, kind=kind, prob=prob, count=count,
                               delay_ms=delay, duration_s=duration))
    return specs


def install_from_env(environ=None) -> FaultPlan | None:
    """Install a plan from DLLAMA_FAULTS / DLLAMA_FAULT_SEED; None when the
    env is unset. Idempotent enough for multiple entry-point calls: an
    already-installed plan is kept (explicit install() wins over env)."""
    env = os.environ if environ is None else environ
    text = env.get("DLLAMA_FAULTS")
    if not text:
        return None
    if _PLAN is not None:
        return _PLAN
    seed = int(env.get("DLLAMA_FAULT_SEED", "0"))
    return install(parse_faults(text), seed=seed)
