"""QuietServer: a ThreadingHTTPServer that does not stack-trace routine
peer disconnects.

A streaming serving stack sees dropped sockets CONSTANTLY — clients abandon
SSE streams, and the durable fleet router (docs/FLEET.md) deliberately
aborts its upstream leg the moment it decides to resume a request
elsewhere. Each one used to print a full socketserver traceback to stderr;
at fleet scale that noise buries real errors. Anything that is not a
routine peer-went-away still reports normally.

Stdlib-only by design: both the api_server (jax-heavy) and the fleet router
(which must never import jax) serve HTTP through this one subclass, so the
suppressed-exception set cannot drift between them.
"""

from __future__ import annotations

import sys
from http.server import ThreadingHTTPServer

__all__ = ["QuietServer"]

_ROUTINE_DISCONNECTS = (BrokenPipeError, ConnectionResetError,
                        ConnectionAbortedError, TimeoutError)


class QuietServer(ThreadingHTTPServer):
    def handle_error(self, request, client_address):
        if isinstance(sys.exc_info()[1], _ROUTINE_DISCONNECTS):
            return
        super().handle_error(request, client_address)
