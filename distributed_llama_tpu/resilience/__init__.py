"""Resilience layer: fault injection + typed failure taxonomy
(docs/ROBUSTNESS.md).

- `faults.py` — named injection points (`faults.fire`), deterministic
  FaultSpec/FaultPlan machinery, DLLAMA_FAULTS env activation.
- `errors.py` — typed errors the serving stack raises and the HTTP layer
  maps to honest status codes, plus `classify()` (the scheduler's
  blast-radius switch: transient / request / engine) and `retriable()`
  (the durable router's mid-stream failover switch).
- `supervisor.py` — EngineSupervisor: escalates the dispatch-age watchdog
  from observation to action (fail in-flight retriable, re-initialize the
  backend, flip /healthz unhealthy so the fleet resumes elsewhere).

Consumers: runtime/batch_engine.py (retry + isolation), runtime/engine.py,
runtime/device_loop.py, runtime/paged_cache.py (injection points),
apps/api_server.py (error mapping, shedding, drain), perf/fault_matrix.py
and tests/test_resilience.py (chaos drivers).
"""

from . import faults
from .errors import (DeadlineExceeded, EngineClosed, EngineDraining,
                     EngineSaturated, EngineWedged, FaultInjected,
                     InvalidRequest, TransientDispatchError, classify,
                     retriable)

__all__ = ["faults", "DeadlineExceeded", "EngineClosed", "EngineDraining",
           "EngineSaturated", "EngineWedged", "FaultInjected",
           "InvalidRequest", "TransientDispatchError", "classify",
           "retriable"]
