"""Multi-tenant serving policy: quotas, weighted fairness, drain-rate hints.

"Millions of users" means tenants with different priorities, quotas, and
SLOs sharing one fleet — and before this module, admission control treated
every request identically: one abusive tenant could fill the wait queue and
starve everyone, and every backoff hint the stack emitted was a hardcoded
constant. This module is the policy layer both the BatchEngine scheduler
(runtime/batch_engine.py) and the fleet router (fleet/router.py) share
(docs/SERVING.md "Multi-tenant serving"):

- **TokenBucket / TenantRegistry** — per-tenant token-bucket quotas
  (configurable rate/burst). Exhaustion raises `QuotaExceeded`, which the
  HTTP layer maps to 429 + Retry-After derived from the bucket's own refill
  arithmetic. Unknown tenant ids resolve to the `default` policy (shared
  bucket and weight) so label cardinality and quota surface stay bounded no
  matter what clients put in `X-Tenant`.
- **WeightedFairQueue** — two-class (interactive > batch) start-time fair
  queueing over tenants: each item carries a virtual finish tag
  `max(V, F_tenant) + cost/weight`; dequeue serves the eligible head with
  the minimum tag, interactive class strictly before batch. Backlogged
  tenants receive service proportional to their weights over any window
  (the fluid-share property tests/test_tenancy.py checks against an
  oracle), so no tenant can starve another however hard it floods.
- **DrainRate** — a decayed-count EMA of service completions/sec. Honest
  backoff hints follow: `retry_after(depth) = depth / rate`, floored and
  capped, replacing the hardcoded `retry_after=1.0` / `poll_interval`
  constants the shed paths used to emit (the header now tracks load).
- **FairGate** — a capacity gate whose waiters are admitted in
  WeightedFairQueue order instead of lock-handoff order: the router-side
  fairness primitive (`--max-inflight`) bounding concurrent upstream
  proxies per tenant weights when the fleet is contended.

Dependency-free by design (threading/time/math only): the fleet router is a
stdlib-only process and imports this module directly.
"""

from __future__ import annotations

import math
import re
import threading
import time
from collections import deque
from dataclasses import dataclass, field

from .errors import QuotaExceeded

__all__ = ["TokenBucket", "TenantPolicy", "TenantRegistry",
           "WeightedFairQueue", "DrainRate", "FairGate", "CLASSES",
           "DEFAULT_TENANT", "sanitize_tenant"]

CLASSES = ("interactive", "batch")  # strict dequeue priority, left first
DEFAULT_TENANT = "default"

# X-Tenant values are client input: bound the charset/length BEFORE they
# reach flight records, journals, and log lines (metric labels are bounded
# separately by TenantRegistry.canonical)
_TENANT_RE = re.compile(r"[A-Za-z0-9._:-]{1,64}$")


def sanitize_tenant(raw: str | None) -> str:
    """Map a client-supplied tenant id (X-Tenant header) to the
    serving-local tenant id; unlabeled or garbage-labeled traffic is the
    default tenant."""
    raw = (raw or "").strip()
    return raw if raw and _TENANT_RE.match(raw) else DEFAULT_TENANT


class TokenBucket:
    """Classic token bucket on the monotonic clock. `rate` tokens/second
    refill up to `burst` capacity; `try_acquire(cost)` either debits and
    returns (True, 0.0) or returns (False, seconds-until-serviceable) for
    the Retry-After header. A cost above `burst` is clamped to it — a
    request larger than the bucket can ever hold still passes when the
    bucket is full (and drains it), instead of being unserviceable
    forever."""

    def __init__(self, rate: float, burst: float | None = None):
        assert rate > 0.0, "use no bucket at all for an unlimited tenant"
        self.rate = float(rate)
        self.burst = float(burst) if burst and burst > 0 else 2.0 * self.rate
        self._lock = threading.Lock()  # guards: _tokens, _t
        self._tokens = self.burst
        self._t = time.monotonic()

    def _refill(self, now: float) -> None:  # holds: self._lock
        self._tokens = min(self.burst,
                           self._tokens + (now - self._t) * self.rate)
        self._t = now

    def try_acquire(self, cost: float = 1.0) -> tuple[bool, float]:
        cost = min(max(float(cost), 0.0), self.burst)
        with self._lock:
            now = time.monotonic()
            self._refill(now)
            if self._tokens >= cost:
                self._tokens -= cost
                return True, 0.0
            return False, (cost - self._tokens) / self.rate

    def refund(self, cost: float) -> None:
        """Return a debit for work that received zero service (the request
        was shed after the quota check) — capped at burst, same clamp as
        the acquire side."""
        with self._lock:
            self._refill(time.monotonic())
            self._tokens = min(self.burst,
                               self._tokens + min(max(cost, 0.0),
                                                  self.burst))

    def available(self) -> float:
        with self._lock:
            self._refill(time.monotonic())
            return self._tokens


@dataclass
class TenantPolicy:
    """One tenant's configured policy. `weight` drives fair-share service;
    `rate`/`burst` (tokens/sec of prompt+decode work, 0 = unlimited) drive
    the admission quota."""

    name: str
    weight: float = 1.0
    rate: float = 0.0
    burst: float = 0.0
    bucket: TokenBucket | None = field(default=None, repr=False)
    # lifetime accounting (mutated only under the registry lock)
    admitted: int = 0
    throttled: int = 0

    def __post_init__(self):
        assert self.weight > 0.0, f"tenant {self.name!r}: weight must be > 0"
        if self.rate > 0.0 and self.bucket is None:
            self.bucket = TokenBucket(self.rate, self.burst or None)


class TenantRegistry:
    """The configured tenant set plus the `default` policy every unknown
    tenant id shares. Resolution never creates entries — arbitrary client
    `X-Tenant` values cannot grow the registry, the metric label space, or
    the quota table."""

    def __init__(self, policies: list[TenantPolicy] | None = None):
        self._policies: dict[str, TenantPolicy] = {}
        for p in (policies or []):
            self._policies[p.name] = p
        if DEFAULT_TENANT not in self._policies:
            self._policies[DEFAULT_TENANT] = TenantPolicy(DEFAULT_TENANT)
        self._lock = threading.Lock()  # guards: admitted/throttled counters

    @classmethod
    def parse(cls, spec: str) -> "TenantRegistry":
        """`"gold:weight=3,rate=200,burst=400;bronze:weight=1;default:rate=50"`
        — `;`-separated tenants, each `name[:k=v,...]` with keys weight /
        rate / burst. Malformed entries raise ValueError (configuration is
        operator input: fail loudly at startup, never guess)."""
        policies = []
        for part in (spec or "").split(";"):
            part = part.strip()
            if not part:
                continue
            name, _, kvs = part.partition(":")
            name = name.strip()
            if not name:
                raise ValueError(f"tenant entry without a name: {part!r}")
            kw: dict[str, float] = {}
            for kv in filter(None, (s.strip() for s in kvs.split(","))):
                k, eq, v = kv.partition("=")
                if not eq or k.strip() not in ("weight", "rate", "burst"):
                    raise ValueError(f"bad tenant option {kv!r} in {part!r} "
                                     "(want weight=/rate=/burst=)")
                kw[k.strip()] = float(v)
            policies.append(TenantPolicy(name, **kw))
        return cls(policies)

    def tenants(self) -> list[str]:
        return sorted(self._policies)

    def resolve(self, name: str | None) -> TenantPolicy:
        return self._policies.get(name or DEFAULT_TENANT,
                                  self._policies[DEFAULT_TENANT])

    def canonical(self, name: str | None) -> str:
        """The bounded metric-label identity: a configured tenant's own
        name, everything else collapsed to `default`."""
        n = name or DEFAULT_TENANT
        return n if n in self._policies else DEFAULT_TENANT

    def weight(self, name: str | None) -> float:
        return self.resolve(name).weight

    def set_quota(self, name: str, rate: float, burst: float = 0.0) -> None:
        """(Re)arm a tenant's token bucket at runtime: operators tune
        quotas live; the trace-driven load bench calibrates them against
        measured capacity. `rate <= 0` removes the quota."""
        pol = self.resolve(name)
        pol.rate = float(rate)
        pol.burst = float(burst)
        pol.bucket = (TokenBucket(pol.rate, pol.burst or None)
                      if rate > 0 else None)

    def acquire(self, name: str | None, cost: float = 1.0) -> TenantPolicy:
        """Debit `cost` from the tenant's quota bucket; raises QuotaExceeded
        (HTTP 429) with the bucket-derived Retry-After when exhausted."""
        pol = self.resolve(name)
        if pol.bucket is not None:
            ok, wait = pol.bucket.try_acquire(cost)
            if not ok:
                with self._lock:
                    pol.throttled += 1
                raise QuotaExceeded(
                    f"tenant {pol.name!r} quota exhausted "
                    f"({pol.rate:g} tokens/s, burst {pol.bucket.burst:g}); "
                    f"retry in {wait:.2f}s",
                    retry_after=max(wait, 0.05), tenant=pol.name)
        with self._lock:
            pol.admitted += 1
        return pol

    def refund(self, name: str | None, cost: float = 1.0) -> None:
        """Return a quota debit for a request shed with zero service."""
        pol = self.resolve(name)
        if pol.bucket is not None:
            pol.bucket.refund(cost)

    def stats(self) -> dict:
        with self._lock:
            return {p.name: {"weight": p.weight, "rate": p.rate,
                             "admitted": p.admitted, "throttled": p.throttled,
                             **({"bucket_tokens":
                                 round(p.bucket.available(), 1)}
                                if p.bucket is not None else {})}
                    for p in self._policies.values()}


class WeightedFairQueue:
    """Two-class start-time-fair queue over tenants (SFQ virtual time).

    NOT internally locked: the owner serializes access (the BatchEngine
    guards its instance with `_plock`; FairGate with its condition lock).
    Items are pushed with an explicit (tenant, klass, cost) or, via
    `append()`, with those read off the item's `tenant`/`klass`/`wfq_cost`
    attributes — the list-compatible surface the scheduler's drain/abort
    paths use. Per (tenant, class) FIFO order is preserved; across tenants
    the head with the minimum virtual finish tag is served; the interactive
    class is strictly served before batch (the documented shed/starve
    order: batch may wait behind interactive, tenants within a class may
    not starve each other)."""

    def __init__(self, registry: TenantRegistry | None = None):
        self._reg = registry
        # (tenant, klass) -> deque[(finish_tag, cost/weight, item)]
        self._q: dict[tuple[str, str], deque] = {}
        self._ftag: dict[tuple[str, str], float] = {}
        self._vt = {k: 0.0 for k in CLASSES}
        self._n = 0

    def _weight(self, tenant: str) -> float:
        return self._reg.weight(tenant) if self._reg is not None else 1.0

    @staticmethod
    def _item_key(item) -> tuple[str, str, float]:
        return (getattr(item, "tenant", DEFAULT_TENANT) or DEFAULT_TENANT,
                getattr(item, "klass", "interactive") or "interactive",
                float(getattr(item, "wfq_cost", 1.0) or 1.0))

    def push(self, item, tenant: str | None = None, klass: str | None = None,
             cost: float | None = None) -> None:
        dt, dk, dc = self._item_key(item)
        tenant = tenant if tenant is not None else dt
        klass = klass if klass is not None else dk
        cost = float(cost) if cost is not None else dc
        if klass not in CLASSES:
            klass = "interactive"
        key = (tenant, klass)
        cw = max(cost, 1e-9) / self._weight(tenant)
        tag = max(self._vt[klass], self._ftag.get(key, 0.0)) + cw
        self._ftag[key] = tag
        self._q.setdefault(key, deque()).append((tag, cw, item))
        self._n += 1

    def append(self, item) -> None:
        self.push(item)

    def _head_key(self) -> tuple[str, str] | None:
        for klass in CLASSES:
            best_key, best_tag = None, None
            for key, dq in self._q.items():
                if key[1] != klass or not dq:
                    continue
                if best_tag is None or dq[0][0] < best_tag:
                    best_key, best_tag = key, dq[0][0]
            if best_key is not None:
                return best_key
        return None

    def peek_next(self):
        key = self._head_key()
        return self._q[key][0][2] if key is not None else None

    def pop_next(self):
        key = self._head_key()
        if key is None:
            return None
        tag, _cw, item = self._q[key].popleft()
        self._vt[key[1]] = max(self._vt[key[1]], tag)
        self._n -= 1
        return item

    def entry_tag(self, tenant: str, klass: str, cost: float) -> float:
        """The virtual finish tag a push would receive, WITHOUT pushing —
        the weighted-shed comparison key: an arrival more entitled than
        the queue's worst resident (smaller tag) displaces it instead of
        being shed itself."""
        key = (tenant, klass)
        cw = max(cost, 1e-9) / self._weight(tenant)
        return max(self._vt[klass], self._ftag.get(key, 0.0)) + cw

    def last_tag(self, klass: str) -> float | None:
        """The maximum queued finish tag of `klass` (the least-entitled
        resident — what evict_last would remove), or None when empty."""
        tags = [dq[-1][0] for key, dq in self._q.items()
                if key[1] == klass and dq]
        return max(tags) if tags else None

    def evict_last(self, klass: str):
        """Remove and return the LEAST-entitled queued item of `klass` (the
        maximum finish tag — the newest arrival of the most-backlogged
        tenant), or None. The shed-batch-before-interactive lever: an
        interactive admission displacing queued batch work evicts the item
        fair queueing would have served last. The tenant's finish tag is
        rolled back so its next push is not charged for service it never
        received."""
        best_key, best_tag = None, None
        for key, dq in self._q.items():
            if key[1] != klass or not dq:
                continue
            if best_tag is None or dq[-1][0] > best_tag:
                best_key, best_tag = key, dq[-1][0]
        if best_key is None:
            return None
        tag, cw, item = self._q[best_key].pop()
        self._ftag[best_key] = tag - cw
        self._n -= 1
        return item

    def remove(self, item) -> bool:
        """Drop one specific queued item (cancel/expiry reaping)."""
        for key, dq in self._q.items():
            for entry in dq:
                if entry[2] is item:
                    dq.remove(entry)
                    self._n -= 1
                    if not dq:
                        # a mid-queue gap leaves later tags unchanged (they
                        # already embed this item's virtual service; the
                        # error is one item's cost, bounded and transient)
                        self._ftag[key] = max(self._ftag.get(key, 0.0),
                                              self._vt[key[1]])
                    return True
        return False

    def clear(self) -> None:
        """Abort-path reset (engine close / fail-all / wedge recovery):
        drops the items AND the per-tenant tags — after a recovery every
        request was failed, so carrying a tenant's pre-wedge virtual
        service forward would starve it against tenants that happened to
        be idle when the engine wedged."""
        self._q.clear()
        self._ftag.clear()
        self._vt = {k: 0.0 for k in CLASSES}
        self._n = 0

    def __iter__(self):
        for dq in self._q.values():
            for _tag, _cw, item in dq:
                yield item

    def __len__(self) -> int:
        return self._n

    def class_depth(self, klass: str) -> int:
        return sum(len(dq) for key, dq in self._q.items() if key[1] == klass)


class DrainRate:
    """Decayed-count EMA of service completions/sec → honest backoff hints.

    `note()` records one completion; the count decays with time constant
    `tau`, so `rate() ≈ completions/sec` over roughly the last `tau`
    seconds. `retry_after(depth)` is the measured time for the queue to
    drain `depth` items, floored (clients must not busy-spin on a fast
    queue) and capped (a stall must not quote an hour). Before any
    completion has been observed, `rate()` is 0 and `queue_wait()` returns
    0.0 — cold-start must never shed on a fabricated estimate — while
    `retry_after()` returns the floor."""

    def __init__(self, floor: float = 1.0, cap: float = 60.0,
                 tau: float = 10.0):
        self.floor = floor
        self.cap = cap
        self.tau = tau
        self._lock = threading.Lock()  # guards: _c, _t
        self._c = 0.0
        self._t: float | None = None

    def note(self, n: float = 1.0) -> None:
        with self._lock:
            now = time.monotonic()
            if self._t is not None:
                self._c *= math.exp(-(now - self._t) / self.tau)
            self._t = now
            self._c += n

    def rate(self) -> float:
        with self._lock:
            if self._t is None:
                return 0.0
            c = self._c * math.exp(-(time.monotonic() - self._t) / self.tau)
            return c / self.tau

    def queue_wait(self, depth: float) -> float:
        r = self.rate()
        return depth / r if r > 0.0 else 0.0

    def retry_after(self, depth: float) -> float:
        r = self.rate()
        if r <= 0.0:
            return self.floor
        return min(max(depth / r, self.floor), self.cap)


class FairGate:
    """Bounded concurrency gate admitting waiters in weighted-fair order.

    A plain semaphore hands capacity to whichever thread the OS wakes; under
    fleet saturation that lets one flooding tenant's handler threads take
    every slot. `acquire(tenant, klass, cost, timeout)` instead parks the
    caller in a WeightedFairQueue and admits strictly in its order —
    interactive before batch, tenants by weight — as `release()` frees
    capacity. `capacity <= 0` disables the gate (acquire always succeeds
    immediately). Returns False on timeout (the caller sheds with
    Retry-After)."""

    def __init__(self, capacity: int, registry: TenantRegistry | None = None):
        self.capacity = int(capacity)
        self._wfq = WeightedFairQueue(registry)
        self._cond = threading.Condition()  # guards: _active, _wfq
        self._active = 0

    def acquire(self, tenant: str = DEFAULT_TENANT,
                klass: str = "interactive", cost: float = 1.0,
                timeout: float | None = None) -> bool:
        if self.capacity <= 0:
            return True
        ticket = object()
        deadline = (time.monotonic() + timeout) if timeout is not None \
            else None
        with self._cond:
            if self._active < self.capacity and not len(self._wfq):
                self._active += 1
                return True
            self._wfq.push(ticket, tenant, klass, cost)
            while True:
                if (self._active < self.capacity
                        and self._wfq.peek_next() is ticket):
                    self._wfq.pop_next()
                    self._active += 1
                    return True
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0.0:
                        self._wfq.remove(ticket)
                        # a departing head must hand the evaluation to the
                        # next waiter, or free capacity could sit idle
                        self._cond.notify_all()
                        return False
                self._cond.wait(timeout=remaining)

    def release(self) -> None:
        if self.capacity <= 0:
            return
        with self._cond:
            self._active = max(self._active - 1, 0)
            self._cond.notify_all()

    def waiting(self) -> int:
        with self._cond:
            return len(self._wfq)
