"""dllama CLI — benchmark / generate / chat modes.

TPU-native counterpart of src/apps/dllama/dllama.cpp. The reference's `worker` mode
(dllama.cpp:205-221) has no equivalent: worker processes are replaced by SPMD shards of
one program, so a "worker" is just a mesh device. `--workers host:port` becomes `--tp N`;
`--nthreads` is meaningless (XLA owns the chip) and accepted-but-ignored for CLI
compatibility.

Modes (dllama.cpp:230-245):
    inference  — run prompt + --steps tokens, print per-token G/I/T-style stats
    generate   — stream tokens until EOS or --steps
    chat       — interactive REPL with chat template + stop detection (dllama.cpp:111-194)
"""

from __future__ import annotations

import argparse
import sys

from ..models.spec import ModelSpec
from ..quants import FloatType
from ..runtime.engine import Engine
from ..runtime.sampler import Sampler
from ..tokenizer import ChatItem, ChatTemplate, EosDetector, TemplateType
from ..tokenizer.eos import TokenStreamer


def build_parser(include_mode: bool = True) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="dllama", description=__doc__)
    if include_mode:
        p.add_argument("mode", choices=["inference", "generate", "chat"])
    p.add_argument("--model", required=True)
    p.add_argument("--tokenizer", required=True)
    p.add_argument("--prompt", default=None)
    p.add_argument("--steps", type=int, default=64)
    p.add_argument("--temperature", type=float, default=0.8)
    p.add_argument("--topp", type=float, default=0.9)
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--chat-template", default=None,
                   choices=[t.value for t in TemplateType])
    p.add_argument("--max-seq-len", type=int, default=0)
    p.add_argument("--weights-float-type", default=None,
                   choices=["f32", "f16", "q40", "q80"])
    p.add_argument("--buffer-float-type", default="q80",
                   choices=["f32", "f16", "q40", "q80"],
                   help="q80 enables int8-compressed collectives (the reference's "
                        "wire compression, tasks.cpp:96-135). Numerics are pinned by "
                        "tests and perf/microbench.py --section collectives; its TIME "
                        "on real multi-chip ICI is UNMEASURED (no multi-chip hardware "
                        "available) — expected to matter across DCN, likely a wash "
                        "on ICI")
    p.add_argument("--tp", type=int, default=None, help="tensor-parallel devices")
    p.add_argument("--sp", type=int, default=1,
                   help="sequence-parallel devices (ring attention over the KV cache)")
    p.add_argument("--pod", action="store_true",
                   help="join a multi-host pod job via jax.distributed and mesh over "
                        "every chip in the job — the SPMD replacement for the "
                        "reference's `dllama worker` + --workers bootstrap "
                        "(dllama.cpp:205-221). On Cloud TPU the coordinator is "
                        "auto-discovered; elsewhere pass --coordinator/--num-processes/"
                        "--process-id. Run the SAME command on every host.")
    p.add_argument("--coordinator", default=None, metavar="HOST:PORT",
                   help="jax.distributed coordinator for --pod off Cloud TPU")
    p.add_argument("--num-processes", type=int, default=None,
                   help="total processes in the --pod job (off Cloud TPU)")
    p.add_argument("--process-id", type=int, default=None,
                   help="this process's index in the --pod job (off Cloud TPU)")
    p.add_argument("--dtype", default="auto", choices=["auto", "float32", "bfloat16"],
                   help="auto = bfloat16 on TPU, float32 on CPU")
    p.add_argument("--no-pallas", action="store_true")
    p.add_argument("--moe-sharding", default="slice", choices=["slice", "expert"],
                   help="MoE expert placement over the tp axis: 'slice' TP-slices "
                        "every expert's hidden dim (the reference's scheme); "
                        "'expert' shards WHOLE experts (each chip owns E/tp experts "
                        "— the capacity axis for Grok-1-314B-class expert weights; "
                        "requires n_experts %% tp == 0)")
    p.add_argument("--cache-write", default=None,
                   choices=["deferred", "inscan"],
                   help="KV cache discipline (models/forward.py): 'deferred' keeps "
                        "the caches loop-invariant in the layer scan and commits new "
                        "rows in one top-level write (avoids XLA TPU's whole-cache "
                        "carry copies; works with --sp too); 'inscan' is the "
                        "per-layer in-place form")
    p.add_argument("--prologue", action="store_true", default=None,
                   help="fused rmsnorm+quantize prologue kernels on the decode "
                        "path (ops/pallas_prologue.py; also DLT_PROLOGUE=1) — "
                        "opt-in until the hardware A/B lands")
    p.add_argument("--prefill-kernel", action="store_true", default=None,
                   help="fused 4-bit dequant-matmul for prefill and batched "
                        "decode (ops/pallas_q4_mm.py; also DLT_PREFILL_KERNEL=1) "
                        "— opt-in until the hardware A/B lands")
    p.add_argument("--fused-matmul", action="store_true", default=None,
                   help="batched fused-epilogue kernels on the decode/verify/"
                        "drafter hot paths: --prefill-kernel plus residual-add "
                        "and silu·mul gate-pair epilogues, greedy-identical with "
                        "automatic XLA fallback (also DLT_FUSED_MATMUL=1; "
                        "docs/SERVING.md \"Kernel selection\") — opt-in until "
                        "the hardware A/B lands")
    p.add_argument("--pipeline", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="pipelined super-steps for batched serving (--batch "
                        "> 1, api_server/bench): eagerly chain decode "
                        "dispatch N+1 from device-resident state (last "
                        "token, positions, xorshift* RNG) while N's token "
                        "block transfers and is delivered host-side, so the "
                        "device never idles through EOS scans and callbacks; "
                        "output stays token-identical (a diverging block "
                        "flushes the in-flight dispatch). --no-pipeline "
                        "restores the serialized host<->device loop "
                        "(docs/SERVING.md \"Pipelined decode\")")
    p.add_argument("--device-loop", type=int, default=0, metavar="CHUNK",
                   help="decode CHUNK tokens per dispatch with the on-device scan loop "
                        "(runtime/device_loop.py); 0 = per-token host loop")
    p.add_argument("--speculative", type=int, default=0, metavar="K",
                   help="prompt-lookup speculative decoding: draft up to K "
                        "tokens from context n-gram matches and verify them "
                        "in one step. Sequential mode (--batch 1, "
                        "runtime/speculative.py) is greedy-only; with the "
                        "api_server's --batch > 1 the BatchEngine verifies "
                        "per-row draft blocks in one batched dispatch — "
                        "greedy AND seeded-stochastic, token-identical "
                        "either way (docs/SERVING.md \"Speculative "
                        "decoding\"). No reference counterpart")
    p.add_argument("--draft-model", default=None, metavar="PATH",
                   help="model-based speculative drafting (api_server "
                        "--batch > 1 only): load a second, small model from "
                        "PATH (same .m format/loaders as --model, vocab "
                        "must match), co-resident on the target's mesh, "
                        "drafting k tokens per row in one scan dispatch "
                        "with ADAPTIVE per-row k; n-gram lookup remains "
                        "the per-row fallback (docs/SERVING.md "
                        "\"Model-based drafting\"). Implies --speculative 8 "
                        "when K is unset")
    p.add_argument("--draft-k", type=int, default=0, metavar="K",
                   help="cap the model drafter's per-row draft length "
                        "(default: the --speculative K). The adaptive "
                        "controller picks each row's k from the bucketed "
                        "range [0, K]")
    p.add_argument("--trace", default=None, metavar="OUT.json",
                   help="record runtime spans (prefill chunks, decode "
                        "dispatches, super-steps, cold-attention callbacks) "
                        "and write a Chrome trace-event JSON at exit — load "
                        "it in Perfetto (ui.perfetto.dev) or chrome://tracing "
                        "(obs/trace.py; docs/OBSERVABILITY.md)")
    p.add_argument("--trace-annotate", action="store_true",
                   help="with --trace: also forward each span as a "
                        "jax.profiler TraceAnnotation so spans appear inside "
                        "an XLA device trace (perf/PROFILE.md workflow)")
    p.add_argument("--nthreads", type=int, default=None, help="ignored (XLA owns the chip)")
    p.add_argument("--kv-cache-storage", default=None,
                   choices=["ram", "host", "disc"],
                   help="'ram' (default): KV cache in HBM. 'host'/'disc': paged "
                        "out-of-core cache (runtime/paged_cache.py) — a device "
                        "hot ring of --kv-cache-resident recent positions plus "
                        "the full history in host RAM / an mmap'd disk file "
                        "pair (the reference's disc cache, transformer.cpp:"
                        "312-318, rebuilt flash-attention-style). Capacity "
                        "valve: exact attention over the whole context at "
                        "host-bandwidth speed; use --sp to go FAST instead")
    p.add_argument("--kv-cache-resident", type=int, default=1024, metavar="R",
                   help="paged mode: positions kept HBM-resident (rounded up "
                        "to a multiple of 64)")
    p.add_argument("--kv-cache-dir", default=None, metavar="DIR",
                   help="paged 'disc' mode: directory for the key/value cache "
                        "files (default: a fresh temp dir)")
    return p


def check_kv_storage(args) -> None:
    """The reference's `--kv-cache-storage disc` spills the KV cache to mmap'd disk
    files (src/transformer.cpp:312-318, utils.cpp:50-67) — an out-of-core valve for
    small-RAM CPU nodes. The paged cache (runtime/paged_cache.py) is the TPU-native
    equivalent: hot ring in HBM, full history on host/disk, exact merged attention.
    State the cost up front — every decoded token re-reads the cold history from
    host memory, so throughput falls with context length; --sp (ring attention over
    ICI) is the FAST long-context path when more chips are available."""
    if args.kv_cache_storage in ("host", "disc"):
        print(f"💡 paged KV cache ({args.kv_cache_storage}): hot ring of "
              f"{args.kv_cache_resident} positions in HBM, full history "
              f"{'on disk (mmap)' if args.kv_cache_storage == 'disc' else 'in host RAM'}."
              " Decode slows as the cold history grows; prefer --sp N when "
              "more chips are available (README §long-context).",
              file=sys.stderr)


_FT = {"f32": FloatType.F32, "f16": FloatType.F16, "q40": FloatType.Q40,
       "q80": FloatType.Q80}

_GRACEFUL_STOP = None  # threading.Event set by the first SIGTERM


def install_graceful_stop():
    """SIGTERM during a CLI generation stops cleanly after the current token
    (stats still print, the partial output is complete text) instead of
    killing the process mid-dispatch; a second SIGTERM hard-stops via
    KeyboardInterrupt. Returns the Event, or None where signal handlers
    can't be installed (non-main thread, e.g. under a test runner)."""
    global _GRACEFUL_STOP
    import signal
    import threading

    ev = threading.Event()

    def _on_term(signum, frame):
        if ev.is_set():
            raise KeyboardInterrupt
        ev.set()
        print("\n🟡 SIGTERM: finishing the current token, then stopping "
              "(send again to hard-stop)", file=sys.stderr)

    try:
        signal.signal(signal.SIGTERM, _on_term)
    except ValueError:  # not the main thread
        return None
    _GRACEFUL_STOP = ev
    return ev


def stop_requested() -> bool:
    """True once SIGTERM asked the CLI generation loop to wind down."""
    return _GRACEFUL_STOP is not None and _GRACEFUL_STOP.is_set()


def install_trace(args) -> bool:
    """--trace bootstrap (shared by dllama and api_server): install the
    process-wide tracer before any engine work so model-load/compile spans
    are captured too. Returns True when tracing is on."""
    if not getattr(args, "trace", None):
        return False
    from ..obs import trace

    trace.install(jax_annotations=getattr(args, "trace_annotate", False))
    return True


def dump_trace(args) -> None:
    """Write the Chrome trace to args.trace (no-op when --trace is unset)."""
    from ..obs import trace

    t = trace.current()
    if getattr(args, "trace", None) and t is not None:
        t.dump(args.trace)
        n = len(t.events())
        print(f"🧭 wrote {n} trace events to {args.trace} "
              f"({t.dropped_events} dropped) — open in ui.perfetto.dev",
              file=sys.stderr)


def init_pod(args) -> int:
    """--pod bootstrap: join the jax.distributed job before any device use.
    Returns this host's process index (0 when not a pod job)."""
    if not getattr(args, "pod", False):
        return 0
    from ..parallel.mesh import init_multihost

    idx = init_multihost(coordinator=args.coordinator,
                         num_processes=args.num_processes,
                         process_id=args.process_id)
    import jax

    print(f"🌐 Pod process {idx}/{jax.process_count()}: "
          f"{jax.local_device_count()} local / {jax.device_count()} global chips")
    return idx


def make_engine(args) -> Engine:
    import jax.numpy as jnp
    import time

    init_pod(args)
    t0 = time.perf_counter()
    engine = Engine.load(
        args.model, args.tokenizer, max_seq_len=args.max_seq_len,
        weights_ftype=_FT[args.weights_float_type] if args.weights_float_type else None,
        tp=args.tp, sp=args.sp, pod=getattr(args, "pod", False),
        dtype=(None if args.dtype == "auto"
               else jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32),
        use_pallas=False if args.no_pallas else None,
        compress_collectives=args.buffer_float_type == "q80" and (args.tp or 1) > 1,
        cache_write=args.cache_write, moe_sharding=args.moe_sharding,
        fused_prologue=args.prologue, prefill_kernel=args.prefill_kernel,
        fused_matmul=args.fused_matmul,
        kv_cache_storage=args.kv_cache_storage,
        kv_cache_resident=args.kv_cache_resident,
        kv_cache_dir=args.kv_cache_dir,
    )
    print(f"⏩ Loaded model in {time.perf_counter() - t0:.1f}s "
          f"(tp={engine.tp}, pallas={engine.use_pallas})")
    spec = engine.spec
    for k in ("dim", "hidden_dim", "n_layers", "n_heads", "n_kv_heads", "vocab_size",
              "seq_len"):
        print(f"💡 {k}: {getattr(spec, k)}")
    return engine


def make_sampler(args, spec: ModelSpec) -> Sampler:
    import time

    seed = args.seed if args.seed is not None else int(time.time())
    return Sampler(spec.vocab_size, args.temperature, args.topp, seed)


def mode_inference(args) -> None:
    engine = make_engine(args)
    sampler = make_sampler(args, engine.spec)
    tok = engine.tokenizer
    prompt = tok.encode(args.prompt or "Hello world", add_bos=True)
    pieces: list[bytes] = []
    if engine.tp > 1 or engine.sp > 1:
        # account the compiled step's actual collectives so the S/R columns are
        # measured (the reference counted socket bytes; dllama.cpp:76-93)
        mt = engine.collective_stats()
        counts = " ".join(f"{k}x{v}" for k, v in sorted(mt.counts.items()))
        print(f"🔷 Collectives/step: {counts} "
              f"({mt.total_payload_bytes / 1024:.0f} kB payload)")

    def on_token(t):
        piece = tok.decode_piece(prompt[-1] if not pieces else 0, t)
        pieces.append(piece)

    out, stats = engine.generate_with(prompt, args.steps, sampler, on_token=on_token,
                                      stop_check=lambda t: stop_requested(),
                                      device_loop_chunk=args.device_loop,
                         speculative_k=args.speculative)
    text = b"".join(pieces).decode("utf-8", errors="replace")
    print(text)
    # per-token stats table like dllama.cpp:76-93. The reference's columns are G(total),
    # I(inference), T(root socket transfer) (utils.cpp:215-218). Here I = the on-device
    # step INCLUDING the logits device->host copy (the only honest fence on the tunnel);
    # ICI collective time is fused into the compiled step and cannot be split out at
    # runtime, so the third column is H = host sampling/bookkeeping ms — labeled as
    # what it is rather than printed as "transfer".
    for i, (g, inf) in enumerate(zip(stats.token_ms, stats.infer_ms)):
        print(f"🔶 G {g:7.2f} ms I {inf:7.2f} ms H {g - inf:7.2f} ms "
              f"S {stats.sent_kbytes_per_token:8.0f} kB R {stats.recv_kbytes_per_token:8.0f} kB {pieces[i].decode('utf-8', 'replace')}")
    print("Columns: G total/token, I device step (incl. logits copy), H host sampling;")
    print(f"S/R source:          {stats.traffic_source} per-device ring bytes")
    print(f"Generated tokens:    {stats.generated_tokens}")
    print(f"Avg tokens / second: {stats.tokens_per_second:.2f}")
    print(f"Avg generation time: {stats.avg_token_ms:.2f} ms")
    print(f"Avg inference time:  {stats.avg_infer_ms:.2f} ms")
    if stats.avg_infer_ms > 0:
        gbps = engine.decode_weight_bytes / engine.tp / 1e9 / (stats.avg_infer_ms / 1e3)
        print(f"Weight stream:       {gbps:.1f} GB/s per chip "
              f"({engine.decode_weight_bytes / 1e9:.3f} GB/step global)")
    print(f"Prefill time:        {stats.prefill_ms:.2f} ms "
          f"({stats.prompt_tokens} tokens)")
    if getattr(stats, "spec_steps", 0):
        # speculative decoding: dispatches vs tokens is the whole story
        acc = stats.spec_accepted / max(stats.spec_drafted, 1)
        print(f"Speculative:         {stats.generated_tokens} tokens in "
              f"{stats.spec_steps} verify steps "
              f"({stats.spec_accepted}/{stats.spec_drafted} drafts accepted, "
              f"{acc:.0%})")


def mode_generate(args) -> None:
    engine = make_engine(args)
    sampler = make_sampler(args, engine.spec)
    tok = engine.tokenizer
    prompt = tok.encode(args.prompt or "", add_bos=True)
    prev = prompt[-1] if prompt else -1

    def on_token(t):
        nonlocal prev
        sys.stdout.buffer.write(tok.decode_piece(prev, t))
        sys.stdout.flush()
        prev = t

    engine.generate_with(prompt, args.steps, sampler, on_token=on_token,
                         stop_check=lambda t: t == tok.eos_id or stop_requested(),
                         device_loop_chunk=args.device_loop,
                         speculative_k=args.speculative)
    print()


def mode_chat(args) -> None:
    """Interactive REPL (Chat::chat, dllama.cpp:132-193): KV position persists across
    turns; generation stops on chat EOS or stop strings."""
    engine = make_engine(args)
    sampler = make_sampler(args, engine.spec)
    tok = engine.tokenizer
    template = ChatTemplate(args.chat_template or TemplateType.UNKNOWN,
                            tok.chat_template, tok.eos_piece())
    stops = tok.chat_stops()

    print("💻 System prompt (optional): ", end="", flush=True)
    system = sys.stdin.readline().strip()
    first = True
    while True:
        print("\n👱 User\n> ", end="", flush=True)
        user = sys.stdin.readline()
        if not user:
            break
        items = []
        if first and system:
            items.append(ChatItem("system", system))
        items.append(ChatItem("user", user.strip()))
        rendered = template.generate(items)
        prompt = tok.encode(rendered, add_bos=first)
        if engine.pos + len(prompt) >= engine.spec.seq_len:
            # next turn's prompt no longer fits the KV cache: hard stop at context
            # end like the reference (dllama.cpp:190-192) instead of overflowing
            print("\n(context end reached)")
            break
        first = False

        print("\n🤖 Assistant\n", flush=True)
        detector = EosDetector(tok.chat_eos_id, stops,
                               padding_left=2, padding_right=2)

        def emit(delta: bytes):
            sys.stdout.buffer.write(delta)
            sys.stdout.flush()

        streamer = TokenStreamer(detector, lambda t: tok.decode_piece(0, t), emit)
        engine.generate_with(prompt, engine.spec.seq_len - engine.pos - 1, sampler,
                             on_token=streamer.on_token,
                             stop_check=lambda t: (streamer.stop_check(t)
                                                   or stop_requested()),
                             device_loop_chunk=args.device_loop,
                         speculative_k=args.speculative)
        if stop_requested():
            print("\n(terminated)")
            break
        if engine.pos >= engine.spec.seq_len - 1:
            print("\n(context end reached)")
            break


def main(argv=None) -> None:
    from ..platform_env import apply_platform_env

    apply_platform_env()
    args = build_parser().parse_args(argv)
    if args.draft_model:
        import sys

        print("⚠️  --draft-model needs the batched verify path — it is an "
              "api_server --batch > 1 feature; the sequential CLI keeps "
              "prompt-lookup drafting (--speculative).", file=sys.stderr)
    check_kv_storage(args)
    install_trace(args)
    from ..resilience import faults

    faults.install_from_env()  # DLLAMA_FAULTS chaos config (resilience/)
    install_graceful_stop()  # SIGTERM: stop after the current token
    try:
        {"inference": mode_inference, "generate": mode_generate,
         "chat": mode_chat}[args.mode](args)
    finally:
        dump_trace(args)


if __name__ == "__main__":
    main()
