"""OpenAI-compatible HTTP API server.

TPU-native counterpart of src/apps/dllama-api/dllama-api.cpp: `POST /v1/chat/completions`
(streaming SSE via chunked transfer + non-streaming JSON), `GET /v1/models`, per-request
temperature/seed/max_tokens/stop overrides (dllama-api.cpp:351-380), and prefix KV reuse
through the shared-prefix cache subsystem (cache/, docs/PREFIX_CACHE.md), which subsumes
the reference's NaiveCache (dllama-api.cpp:187-232): the engine keeps the previous
conversation's KV and rewinds `pos` over the longest common token prefix, AND prefixes
harvested from past conversations are radix-indexed in a block pool, so returning to a
displaced conversation (or sharing its system prompt) seeds the cache instead of
re-prefilling.

With `--batch 1` (default) requests serialize behind a generation lock — the reference
is likewise a single-request-at-a-time accept loop (dllama-api.cpp:418-429). With
`--batch N` the server runs a continuous-batching scheduler (runtime/batch_engine.py):
up to N requests decode concurrently in one batched SPMD step, a capability the
reference lacks (its runtime has no batch dimension at all, funcs.cpp:424).
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlsplit

from ..obs import flight, metrics, reqctx, trace
from ..obs.process import install_process_metrics
from ..ops import matmul as matmul_ops
from ..resilience import faults
from ..resilience.errors import (DeadlineExceeded, EngineClosed,
                                 EngineDraining, EngineSaturated,
                                 EngineWedged, InvalidRequest, QuotaExceeded,
                                 retriable)
from ..resilience.tenancy import (CLASSES, DEFAULT_TENANT, TenantRegistry,
                                  sanitize_tenant)
from ..resilience.quiet_http import QuietServer
from ..runtime.engine import Engine
from ..runtime.sampler import Sampler
from ..tokenizer import ChatItem, ChatTemplate, EosDetector, TemplateType
from ..tokenizer.eos import TokenStreamer

# Per-request serving latencies (docs/OBSERVABILITY.md). TTFT is request
# arrival to the first text delta (the user-visible number: prefill + queue
# wait + first decode); TPOT the mean inter-token time after it; E2E the
# whole completion.
_TTFT = metrics.histogram(
    "api_request_ttft_seconds", "Request arrival to first streamed text delta")
_TPOT = metrics.histogram(
    "api_request_tpot_seconds",
    "Mean per-token time after the first token, per request")
_E2E = metrics.histogram(
    "api_request_e2e_seconds", "Request arrival to completion")
_HTTP = metrics.counter(
    "api_http_requests_total", "HTTP requests by route and status code",
    labelnames=("route", "code"))
# Durable-request resume admissions (docs/FLEET.md "Resume protocol"): how
# many mid-stream-failover re-submits this replica served, how much resumed
# generation they carried, and how much of each resume's prompt ⊕ delivered
# prefix the admission reused instead of re-prefilling (the "resume cost ≈
# one suffix prefill" health signal a chaos bench asserts is nonzero).
_RESUMED = metrics.counter(
    "api_resumed_requests_total",
    "Completions admitted with a resume payload (router failover re-submits)")
_RESUME_TOKENS = metrics.counter(
    "api_resume_tokens_total",
    "Delivered-elsewhere tokens carried by resume payloads (RNG coins "
    "fast-forwarded; tokens re-fed through the stop detector)")
_RESUME_PREFIX = metrics.counter(
    "api_resume_prefix_tokens_total",
    "Total prompt ⊕ delivered prefix length of resume admissions")
_RESUME_REUSED = metrics.counter(
    "api_resume_reused_tokens_total",
    "Resume prefix tokens whose prefill was skipped (slot rewind + radix "
    "prefix-cache seed) at resume admission")

_KNOWN_ROUTES = ("/v1/chat/completions", "/chat/completions", "/v1/models",
                 "/v1/stats", "/metrics", "/health", "/healthz",
                 "/v1/requests", "/v1/trace", "/v1/kv")

# Prefill-replica side of the disaggregation transfer (docs/DISAGG.md):
# /v1/kv prefill-only admissions and the chunked block export they feed.
_KV_PREFILLS = metrics.counter(
    "disagg_prefill_requests_total",
    "POST /v1/kv prefill-only admissions by outcome (ok, empty = prompt "
    "shorter than one full block, error)", labelnames=("outcome",))
_KV_EXPORT_BLOCKS = metrics.counter(
    "disagg_export_blocks_total",
    "KV blocks served to decode replicas over GET /v1/kv/<id>")
_KV_EXPORT_BYTES = metrics.counter(
    "disagg_export_bytes_total",
    "Wire bytes served to decode replicas (post-codec payload)")

def _class_from(body: dict) -> str:
    """Scheduling class from the body's `"class"` field (an X-Class header
    is folded into the body by do_POST before this runs; body wins).
    Unlabeled traffic is interactive — the safe default for
    latency-sensitive clients; garbage is a 400, never a silent guess."""
    raw = str(body.get("class") or "interactive").strip().lower()
    if raw not in CLASSES:
        raise InvalidRequest(
            f"'class' must be one of {CLASSES}, got {raw!r}")
    return raw


def _count_http(path: str, code: int) -> None:
    # unknown paths collapse to one label value so scrapes stay bounded;
    # per-request flight lookups collapse to their route prefix
    path = path.split("?", 1)[0]
    if path.startswith("/v1/requests/"):
        path = "/v1/requests"
    if path.startswith("/v1/kv/"):
        path = "/v1/kv"  # per-transfer chunk fetches share one label value
    route = path if path in _KNOWN_ROUTES else "other"
    _HTTP.labels(route=route, code=str(code)).inc()


def model_config_hash(spec) -> str:
    """Stable short hash of the model configuration — the replica-identity
    field fleet routers compare to catch a replica serving a different model
    than the rest of the fleet (docs/FLEET.md). Hashes the ModelSpec fields
    (enums stringified), not the weights: it identifies the config."""
    import dataclasses
    import hashlib

    d = {f.name: str(getattr(spec, f.name))
         for f in dataclasses.fields(spec)}
    return hashlib.sha1(json.dumps(d, sort_keys=True).encode()).hexdigest()[:12]


class ApiState:
    def __init__(self, engine: Engine, template_type: TemplateType,
                 default_sampler: Sampler, device_loop_chunk: int = 0,
                 batch_engine=None, speculative_k: int = 0,
                 prefix_cache=True, prefix_cache_blocks: int = 0,
                 prefix_block_tokens: int = 16, prefix_cache_q80: bool = False,
                 request_deadline: float = 0.0,
                 tenants: TenantRegistry | None = None,
                 role: str = "both", kv_wire_q80: bool = False,
                 kv_transfer_ttl: float = 120.0, kv_transfer_cap: int = 32):
        self.engine = engine
        # disaggregation (docs/DISAGG.md): the role this replica ADVERTISES
        # in its healthz load block (routing preference only — the engine
        # serves anything), the wire mode for KV exports, and the bounded
        # TTL'd table of host-snapshot transfers GET /v1/kv/<id> serves
        from ..fleet.disagg import ROLES, KVTransferTable

        if role not in ROLES:
            raise ValueError(f"role must be one of {ROLES}, got {role!r}")
        self.role = role
        self.kv_wire_q80 = kv_wire_q80
        self.kv_transfers = (KVTransferTable(cap=kv_transfer_cap,
                                             ttl=kv_transfer_ttl)
                             if batch_engine is not None else None)
        # multi-tenant policy (docs/SERVING.md "Multi-tenant serving"): the
        # registry the X-Tenant mapping resolves against. With a batch
        # engine the SAME object is the engine's quota/fairness authority
        # (enforced at submit); the --batch 1 path enforces the quota here.
        self.tenants = tenants
        # replica identity (docs/FLEET.md): set to host:port once the server
        # socket binds (serve()); what the router's membership poller reads
        self.replica_id = ""
        self.started_mono = time.monotonic()  # /healthz uptime_s
        self.batch_engine = batch_engine  # BatchEngine when --batch > 1, else None
        self.lock = threading.Lock()
        # graceful drain (docs/ROBUSTNESS.md): set by begin_drain/SIGTERM —
        # /healthz flips to 503 "draining", new completions are refused with
        # EngineDraining (503), in-flight requests finish
        self.draining = False
        # server-side wall-clock deadline applied to every batched request
        # (seconds; 0 = none) — the scheduler enforces it, finish "deadline"
        self.request_deadline = request_deadline
        # hung-engine supervisor (resilience/supervisor.py): set by serve()
        # when --supervisor-threshold > 0; /healthz folds its health in so
        # a wedged replica is ejected from fleet rotation while it recovers
        self.supervisor = None
        # single-slot prefix reuse (cache/single_slot.py, ex-NaiveCache): the
        # resident-conversation rewind plus the cross-conversation radix pool.
        # Batched mode needs neither — slot assignment and prefix reuse live
        # in the BatchEngine scheduler (which owns its own PrefixCache).
        self.cache = None
        if engine is not None:
            from ..cache import SingleSlotCache, make_prefix_cache

            pc = None
            if not engine.paged:
                pc = make_prefix_cache(
                    engine.k_cache.shape, engine.k_cache.dtype.itemsize,
                    slots=1, prefix_cache=prefix_cache,
                    blocks=prefix_cache_blocks,
                    block_tokens=prefix_block_tokens, q80=prefix_cache_q80)
            self.cache = SingleSlotCache(engine, pc)
        tok = (batch_engine or engine).tokenizer
        self.template = ChatTemplate(template_type, tok.chat_template, tok.eos_piece())
        self.default_sampler = default_sampler
        self.device_loop_chunk = device_loop_chunk
        self.speculative_k = speculative_k
        # constrained decoding (docs/SERVING.md "Constrained decoding"):
        # per-token byte pieces the grammar compiler lowers against,
        # resolved lazily on the first response_format request
        self.constrain_vocab: list[bytes] | None = None
        self.model_name = "distributed-llama-tpu"


def _now() -> int:
    return int(time.time())


def _completion_payload(state: ApiState, text: str, finish: str,
                        rid: str | None = None) -> dict:
    # `rid` is the serving request id (the flight-recorder key): reusing it
    # as the completion id makes GET /v1/requests/<id> reachable straight
    # from the client-visible response
    return {
        "id": rid or f"chatcmpl-{uuid.uuid4().hex[:12]}",
        "object": "chat.completion",
        "created": _now(),
        "model": state.model_name,
        "choices": [{
            "index": 0,
            "message": {"role": "assistant", "content": text},
            "finish_reason": finish,
        }],
    }


def _chunk_payload(state: ApiState, completion_id: str, delta: dict,
                   finish: str | None) -> dict:
    # one id across all chunks of a completion, per the OpenAI streaming contract
    return {
        "id": completion_id,
        "object": "chat.completion.chunk",
        "created": _now(),
        "model": state.model_name,
        "choices": [{"index": 0, "delta": delta, "finish_reason": finish}],
    }


def _load_block(state: "ApiState") -> dict:
    """Replica identity + load block served inside /healthz and /v1/stats —
    what a fleet router's membership poller consumes (fleet/membership.py):
    who this replica is (id, model config hash) and how loaded it is (slot
    count, free slots, queue depth, draining). Cheap: no device work."""
    be = state.batch_engine
    if be is not None:
        load = be.load_stats()
        draining = state.draining or be.draining
    else:
        # single-engine mode: one slot, "free" == the generation lock is
        # not held; there is no queue (requests serialize on the lock)
        locked = state.lock.locked()
        load = {"slots": 1, "free_slots": 0 if locked else 1,
                "queue_depth": 0}
        draining = state.draining
    spec = (be or state.engine).spec
    import os

    return {"id": state.replica_id, "model": state.model_name,
            "model_hash": model_config_hash(spec),
            # disaggregation role (docs/DISAGG.md): what role-aware routers
            # key on; role-less payloads read as "both" on their side
            "role": state.role,
            "batched": be is not None, "draining": bool(draining),
            # process identity/health for the fleet poller: pid matches the
            # replica's trace export, uptime catches restart loops
            "pid": os.getpid(),
            "uptime_s": round(time.monotonic() - state.started_mono, 1),
            **load}


def _stats_payload(state: "ApiState") -> dict:
    """GET /v1/stats: one JSON snapshot of every metric plus scheduler/engine
    state — the same numbers as /metrics, shaped for humans and scripts
    rather than a Prometheus scraper."""
    out: dict = {"model": state.model_name, "time": _now(),
                 "replica": _load_block(state),
                 "metrics": metrics.snapshot()}
    if state.supervisor is not None:
        out["supervisor"] = state.supervisor.stats()
    if state.kv_transfers is not None:
        out["disagg"] = {"role": state.role,
                         "kv_wire": "q80" if state.kv_wire_q80 else "raw",
                         "transfers": state.kv_transfers.stats()}
    if state.tenants is not None:
        out["tenants"] = state.tenants.stats()
    be = state.batch_engine
    pc = (be.prefix_cache if be is not None
          else state.cache.cache if state.cache is not None else None)
    if pc is not None:
        out["prefix_cache"] = pc.stats()
    if be is not None:
        out["batch_engine"] = {
            "slots": be.slots_n, "superstep": be.superstep,
            "pipeline": be.pipeline,
            "prefilled_tokens": be.prefilled_tokens,
            "decode_steps": be.decode_steps,
            "super_steps": be.super_steps,
            "mixed_steps": be.mixed_steps,
            "occupied": sum(1 for s in be._slots if s.req is not None),
            "scheduler_alive": be.scheduler_alive(),
            "draining": be.draining,
            "max_queue": be.max_queue,
            "queue_ttl": be.queue_ttl,
        }
        if be.kv_pool is not None:  # device-resident paged KV state
            out["batch_engine"]["paged_kv"] = dict(
                be.kv_pool.stats(), seed_bytes=be.seed_bytes,
                seed_ms=round(be.seed_ms, 3))
        spec_block = be.spec_stats()
        if spec_block is not None:
            # engine accept counters + proposer (model drafter health /
            # degradation) + per-row adaptive-k breakdown
            # (docs/SERVING.md "Model-based drafting")
            out["speculative"] = spec_block
        # constrained decoding (docs/SERVING.md "Constrained decoding"):
        # edge compile-cache health + engine table occupancy/degradations
        from ..constrain import compile_stats

        out["constrain"] = dict(be.constrain_stats(),
                                compile=compile_stats())
    elif state.engine is not None:
        eng = state.engine
        out["engine"] = {"pos": eng.pos, "tp": eng.tp, "sp": eng.sp,
                         "paged": eng.paged,
                         "seq_len": eng.spec.seq_len}
    # kernel-selection provenance (ops/matmul.py registry, docs/SERVING.md
    # "Kernel selection"): the resolved matmul policy and which lowering each
    # traced dispatch shape actually took — the human-readable view of
    # matmul_kernel_selected_total, and the place a silent xla-fallback under
    # --fused-matmul becomes visible without grepping Prometheus
    inner = be._eng if be is not None else state.engine
    if inner is not None:
        out["kernels"] = {"policy": str(inner.use_pallas),
                          "fused_matmul": bool(inner.fused_matmul),
                          "selections": matmul_ops.kernel_selections()}
    return out


def _opt(body: dict, key: str, default):
    """Request override with OpenAI null semantics: explicit null == unset."""
    v = body.get(key)
    return default if v is None else v


def _observe_done(t_start: float, ttft: list, n_tokens: int,
                  finish: str | None = None) -> None:
    dt = time.perf_counter() - t_start
    _E2E.observe(dt)
    tpot = None
    if ttft[0] is not None and n_tokens > 1:
        tpot = (dt - ttft[0]) / (n_tokens - 1)
        _TPOT.observe(tpot)
    # complete the flight-recorder timeline with the request-level numbers
    # only the HTTP layer knows (rid resolves from the bound trace context)
    flight.finish(
        None, finish,
        ttft_ms=round(ttft[0] * 1e3, 3) if ttft[0] is not None else None,
        tpot_ms=round(tpot * 1e3, 3) if tpot is not None else None,
        e2e_ms=round(dt * 1e3, 3), tokens=n_tokens)


def _parse_resume(body: dict, spec) -> list[int]:
    """Validate the durable-resume payload (docs/FLEET.md "Resume protocol"):
    `{"resume": {"tokens": [...]}}` — the generated tokens a failed replica
    already delivered, which this replica must treat as committed output:
    prefill them (mostly a prefix-cache hit), fast-forward the sampler past
    their coins, re-feed them through the stop detector (so a stop sequence
    spanning the failover boundary still fires), and continue generation
    byte-identical to the uninterrupted run."""
    raw = body.get("resume")
    if raw is None:
        return []
    if not isinstance(raw, dict) or not isinstance(raw.get("tokens"), list):
        raise InvalidRequest("'resume' must be {\"tokens\": [int, ...]}")
    toks = raw["tokens"]
    if not all(isinstance(t, int) and not isinstance(t, bool)
               and 0 <= t < spec.vocab_size for t in toks):
        raise InvalidRequest(
            f"'resume.tokens' must be token ids in [0, {spec.vocab_size})")
    return list(toks)


def _parse_response_format(state: "ApiState", body: dict, runner):
    """Validate + compile `response_format` at the edge (docs/SERVING.md
    "Constrained decoding") — BEFORE any queue work, so a malformed or
    unsupported grammar is an honest 400 invalid_request_error, never a
    stalled slot. Returns (TokenAutomaton, grammar_hash) or (None, "").

    Accepted forms (grammar source under its own key, OpenAI-style
    `{"json_schema": {"schema": {...}}}` nesting also honored):

      {"type": "json_schema", "json_schema": {...}}
      {"type": "regex",       "regex": "..."}
      {"type": "grammar",     "grammar": "root ::= ..."}
      {"type": "text"}   (explicit no-op)

    Compiles are LRU-cached by grammar hash (constrain/compiler.py), so a
    templated schema pays DFA construction once per process."""
    rf = body.get("response_format")
    if rf is None:
        return None, ""
    if not isinstance(rf, dict) or not isinstance(rf.get("type"), str):
        raise InvalidRequest(
            "'response_format' must be an object with a string 'type' "
            "(json_schema | regex | grammar | text)")
    kind = rf["type"]
    if kind == "text":
        return None, ""
    if kind not in ("json_schema", "regex", "grammar"):
        raise InvalidRequest(
            f"unsupported response_format type {kind!r} "
            "(want json_schema | regex | grammar | text)")
    if state.batch_engine is None:
        raise InvalidRequest(
            "response_format requires the batched engine (--batch >= 2); "
            "this server runs the sequential engine")
    tok = runner.tokenizer
    if tok is None:
        raise InvalidRequest(
            "response_format requires a tokenizer (token-level grammar "
            "masks are compiled against the served vocab)")
    source = rf.get(kind)
    if kind == "json_schema" and isinstance(source, dict) \
            and "schema" in source:
        source = source["schema"]  # OpenAI response_format nesting
    if source is None:
        raise InvalidRequest(
            f"response_format type {kind!r} needs the grammar under the "
            f"{kind!r} key")
    from ..constrain import CompileError, compile_grammar, vocab_bytes

    if state.constrain_vocab is None:
        state.constrain_vocab = vocab_bytes(tok)
    eos = getattr(tok, "chat_eos_id", None) or tok.eos_id
    try:
        aut, ghash = compile_grammar(kind, source, state.constrain_vocab,
                                     eos)
    except CompileError as e:
        raise InvalidRequest(f"invalid response_format: {e}") from None
    flight.event(None, "constrain_compiled", kind=kind, grammar=ghash,
                 states=aut.n_states)
    return aut, ghash


def run_completion(state: ApiState, body: dict, emit, *, journal=None,
                   deadline_s: float | None = None):
    """Shared completion core. `emit(text_delta)` streams; returns (text, finish).

    `journal` (durable routing, docs/FLEET.md): a mutable {"toks": [], "n": 0}
    the caller owns — every text delta's newly-flushed token ids are appended
    (and "n" advanced to the cumulative delivered count) BEFORE emit runs, so
    the streaming layer can stamp them onto the same SSE chunk as the text
    they produced. `deadline_s` is the remaining client deadline relayed via
    X-Deadline-Ms (min-combined with the server's --request-deadline).

    Raises typed resilience errors BEFORE any generation work so the HTTP
    layer can map them to honest status codes (InvalidRequest -> 400,
    EngineDraining/EngineSaturated -> 503, DeadlineExceeded -> 408)."""
    # the replica ctx lets a fault plan target ONE replica of an in-process
    # fleet (match={"replica": id}) — e.g. the gray-failure family's
    # sustained-latency injection (docs/ROBUSTNESS.md "Gray failures")
    faults.fire("api.request", replica=state.replica_id)
    if state.draining:
        raise EngineDraining("server is draining (shutting down)")
    rc = reqctx.current()
    # multi-tenant identity (docs/SERVING.md "Multi-tenant serving"): the
    # tenant rode in on the bound trace context (do_POST's X-Tenant
    # mapping); the class is a request option. Both raise 400 on garbage.
    tenant = (rc.tenant if rc is not None and rc.tenant else DEFAULT_TENANT)
    klass = _class_from(body)
    if rc is not None:
        # open the flight-recorder timeline at the HTTP boundary (the
        # BatchEngine enriches the same record from the scheduler side)
        flight.start(rc.request_id, rc.trace_id, replica=state.replica_id,
                     stream=bool(body.get("stream", False)),
                     **{"tenant": tenant, "class": klass})
    t_start = time.perf_counter()
    ttft: list = [None]
    user_emit = emit

    def emit(text):
        if ttft[0] is None:
            ttft[0] = time.perf_counter() - t_start
            _TTFT.observe(ttft[0])
        user_emit(text)

    runner = state.batch_engine or state.engine
    tok = runner.tokenizer
    spec = runner.spec
    messages = [ChatItem(m.get("role", "user"), m.get("content", ""))
                for m in body.get("messages", [])]
    rendered = state.template.generate(messages)
    prompt = tok.encode(rendered, add_bos=True)

    # request validation (docs/ROBUSTNESS.md): caller errors must be 400s,
    # never a 500 or a stall. A prompt at/over seq_len has no room to decode
    # even one token; max_tokens must be a non-negative integer (explicit 0 /
    # null keep the fill-the-context default, OpenAI null semantics).
    resume = _parse_resume(body, spec)
    if len(prompt) >= spec.seq_len:
        raise InvalidRequest(
            f"prompt is {len(prompt)} tokens but the model context is "
            f"{spec.seq_len}; reduce the conversation or raise --max-seq-len")
    if len(prompt) + len(resume) > spec.seq_len:
        # strictly MORE than the context could ever have generated: a
        # malformed payload, not a legitimate resume. == seq_len is the
        # legitimate edge — the original run ended at the context wall
        # after its last delivered token, so the resume re-emits the
        # delivered text and finishes "length" with zero new tokens.
        raise InvalidRequest(
            f"resume carries {len(resume)} tokens but the context has room "
            f"for {spec.seq_len - len(prompt)} past the prompt")
    mt_raw = _opt(body, "max_tokens", 0)
    if isinstance(mt_raw, bool) or not isinstance(mt_raw, int) or mt_raw < 0:
        raise InvalidRequest(
            f"'max_tokens' must be a non-negative integer, got {mt_raw!r}")
    # grammar compile at the edge (docs/SERVING.md "Constrained decoding"):
    # malformed/unsupported grammars 400 here, before any queue work; the
    # engine receives a ready automaton and never needs tokenizer bytes
    constraint, constraint_hash = _parse_response_format(state, body, runner)
    # disaggregated admission (docs/DISAGG.md): a router-injected kv_source
    # descriptor means a prefill replica already computed this prompt's KV —
    # pull the blocks into the prefix cache BEFORE admission so the radix
    # lookup remaps/seeds them instead of re-prefilling. Every failure mode
    # (dead prefill replica, truncated wire, mixed tokenizers) returns 0 and
    # the request admits with a plain local prefill: zero client impact.
    imported = 0
    ks = body.get("kv_source")
    if isinstance(ks, dict) and state.batch_engine is not None:
        from ..fleet.disagg import import_kv_source

        imported = import_kv_source(state.batch_engine, prompt, ks)
        if imported:
            flight.event(None, "kv_imported", tokens=imported)
    sampler = Sampler(
        spec.vocab_size,
        float(_opt(body, "temperature", state.default_sampler.temperature)),
        float(_opt(body, "top_p", state.default_sampler.topp)),
        int(_opt(body, "seed", _now())),
    )
    # the TOTAL budget is derived from the ORIGINAL prompt so a resumed
    # request stops at exactly the position the uninterrupted run would
    # have; the delivered tokens already spent part of it, and the context
    # wall caps it (a resume at the wall legitimately has zero budget)
    max_tokens = max(min((mt_raw or (spec.seq_len - len(prompt)))
                         - len(resume),
                         spec.seq_len - len(prompt) - len(resume)), 0)
    if resume:
        # the RNG half of byte-identical resume: every stochastic sample
        # drew exactly one xorshift* coin, greedy drew none — skip the
        # delivered tokens' coins so the continuation replays the
        # uninterrupted run's stream (runtime/sampler.py)
        sampler.fast_forward(len(resume))
        _RESUMED.inc()
        _RESUME_TOKENS.inc(len(resume))
        _RESUME_PREFIX.inc(len(prompt) + len(resume))
        flight.event(None, "resume_admitted", tokens=len(resume))
    # remaining-deadline propagation (docs/FLEET.md): the header-relayed
    # client deadline and the server-side --request-deadline compose by min
    # — a resumed request must never outlive the deadline the client set
    deadlines = [d for d in (state.request_deadline, deadline_s) if d]
    eff_deadline = min(deadlines) if deadlines else 0.0
    if state.batch_engine is None and state.tenants is not None:
        # --batch 1 (no scheduler to enforce policy): debit the tenant's
        # quota here — QuotaExceeded maps to 429 + Retry-After. The batched
        # path leaves enforcement to BatchEngine.submit (same registry
        # object; charging at both layers would double-bill every request).
        state.tenants.acquire(tenant, float(len(prompt) + max(max_tokens, 1)))

    stops = tok.chat_stops()
    stop_param = _opt(body, "stop", [])
    if isinstance(stop_param, str):  # OpenAI allows string-or-array
        stop_param = [stop_param]
    stops.extend(s.encode() for s in stop_param)
    detector = EosDetector(tok.chat_eos_id, stops, padding_left=2, padding_right=2)

    pieces: list[str] = []
    finish = ["length"]

    if state.batch_engine is not None:
        # continuous batching: slot assignment + per-slot prefix reuse live in the
        # BatchEngine scheduler; no server-side lock or pos bookkeeping. Socket writes
        # are decoupled from the scheduler thread through a queue — a slow client
        # backpressures only its own handler thread, never the shared decode loop.
        import queue as _queue

        deltas: "_queue.Queue[tuple | None]" = _queue.Queue()
        # token ids delivered since the last text flush: on_token appends on
        # the scheduler thread, and the streamer's synchronous emit drains
        # them into the SAME queue entry as the text they produced — the
        # token/text pairing the durable router's journal rides on
        pending_toks: list[int] = []

        def emit_queued(d: bytes):
            text = d.decode("utf-8", errors="replace")
            pieces.append(text)
            toks, pending_toks[:] = pending_toks[:], []
            deltas.put((text, toks))

        qstreamer = TokenStreamer(detector, lambda t: tok.decode_piece(0, t),
                                  emit_queued)

        def on_token(t: int):
            pending_toks.append(t)
            qstreamer.on_token(t)

        # resume re-feed (docs/FLEET.md): run the delivered tokens through
        # the SAME streamer before generation — their text re-emits (the
        # router splices by position, the client never sees a repeat) and
        # the stop detector ends up in the exact mid-stream state the failed
        # replica's was, so a stop sequence spanning the failover boundary
        # still fires
        for t in resume:
            if qstreamer.stopped:
                break
            on_token(t)
        req = None
        # a resume with zero remaining budget (the original run ended at
        # its token/context limit right after the last delivered token)
        # needs NO engine work: the re-fed text is the full completion
        if not qstreamer.stopped and not (resume and max_tokens == 0):
            req = state.batch_engine.submit(
                prompt + resume, max_tokens, sampler, on_token=on_token,
                stop_check=qstreamer.stop_check,
                deadline=eff_deadline or None,
                resume_tokens=len(resume), tenant=tenant, klass=klass,
                constraint=constraint, constraint_hash=constraint_hash)
            # sentinel closes the drain loop the moment the request completes
            # (the puts happen-before done.set(), so everything queued is
            # drained first)
            threading.Thread(target=lambda: (req.done.wait(),
                                             deltas.put(None)),
                             daemon=True).start()
        else:
            deltas.put(None)
        try:
            while (item := deltas.get()) is not None:
                text, toks = item
                if journal is not None:
                    journal["toks"].extend(toks)
                    journal["n"] += len(toks)
                emit(text)
        except Exception:
            # client went away mid-stream: free the slot instead of decoding the
            # abandoned request to max_tokens
            if req is not None:
                req.cancel()
            raise
        if req is not None and req.error is not None:
            raise req.error
        if qstreamer.stopped:
            finish[0] = "stop"
        elif req is not None and req.finish == "deadline":
            # deadline expired mid-generation WITH partial output: deliver
            # what exists, finish_reason says why it stopped early
            finish[0] = "deadline"
        gen_tokens = req.stats.generated_tokens if req is not None else 0
        if resume and req is not None:
            _RESUME_REUSED.inc(req.stats.reused_tokens)
        if imported and req is not None and req.error is None:
            # shipped-span accounting (docs/DISAGG.md): reuse must cover the
            # imported span minus the mandatory last-token inference; any
            # shortfall is a re-prefill of KV that crossed the wire for
            # nothing (the mixed-context bench asserts the sum stays 0)
            from ..fleet.disagg import note_reprefill

            note_reprefill(min(imported, len(prompt) - 1),
                           req.stats.reused_tokens)
        _observe_done(t_start, ttft, gen_tokens, finish[0])
        return "".join(pieces), finish[0]

    engine = state.engine
    jpending: list[int] = []  # tokens since the last flush (journal pairing)

    def emit_bytes(d: bytes):
        text = d.decode("utf-8", errors="replace")
        pieces.append(text)
        if journal is not None:
            journal["toks"].extend(jpending)
            journal["n"] += len(jpending)
        jpending.clear()
        emit(text)

    streamer = TokenStreamer(detector, lambda t: tok.decode_piece(0, t), emit_bytes)

    def on_token(t: int):
        jpending.append(t)
        streamer.on_token(t)

    # single-engine counterpart of the scheduler-enforced deadline: checked
    # per decoded token via stop_check, finish reason "deadline", partial
    # output delivered (granularity one token vs the scheduler's ~one
    # dispatch; generation time only — the do_POST lock wait precedes
    # t_start in this mode). eff_deadline folds in the X-Deadline-Ms
    # remaining-budget header a durable router relays across resumes.
    deadline_t = t_start + eff_deadline if eff_deadline else None

    def stop_or_deadline(t):
        if streamer.stop_check(t):
            return True
        if deadline_t is not None and time.perf_counter() >= deadline_t:
            finish[0] = "deadline"
            return True
        return False

    # resume re-feed: same contract as the batched path — delivered tokens
    # re-emit their text and arm the stop detector's cross-boundary state
    for t in resume:
        if streamer.stopped:
            break
        on_token(t)
    prompt_full = prompt + resume
    if streamer.stopped:
        _observe_done(t_start, ttft, 0, "stop")
        return "".join(pieces), "stop"
    if resume and max_tokens == 0:
        # original run ended at its limit right after the last delivered
        # token: the re-fed text IS the completion — no engine work
        _observe_done(t_start, ttft, 0, "length")
        return "".join(pieces), "length"

    # Prefix reuse (cache/single_slot.py): rewind pos over the resident
    # conversation's common prefix (for paged engines, begin() also restores
    # the hot ring from the host store via Engine.seek) and/or seed cache rows
    # from the cross-conversation block pool — prefill covers only the rest.
    # A resumed request reuses against prompt ⊕ delivered: the prompt half is
    # usually cached, so resume cost ≈ one delivered-suffix prefill.
    reuse = state.cache.begin(prompt_full)
    delta_prompt = prompt_full[reuse:]
    if resume:
        _RESUME_REUSED.inc(reuse)

    try:
        out, _stats = engine.generate_with(delta_prompt, max_tokens, sampler,
                                           on_token=on_token,
                                           stop_check=stop_or_deadline,
                                           device_loop_chunk=state.device_loop_chunk,
                                           speculative_k=state.speculative_k,
                                           # full conversation (incl. the reused
                                           # prefix) for the n-gram proposer —
                                           # delta_prompt alone would starve
                                           # prompt-lookup of exactly the
                                           # repetitive history it draws from
                                           history_tokens=prompt_full)
    except Exception:
        # KV may hold a half-written new conversation; drop the reuse index entirely
        state.cache.invalidate()
        raise
    if streamer.stopped:
        finish[0] = "stop"
    # only tokens whose KV was actually written are reusable (a final stop token is
    # sampled but never inferred, so engine.pos may be one short of prompt+out)
    state.cache.end((prompt_full + out)[: engine.pos])
    _observe_done(t_start, ttft, len(out), finish[0])
    return "".join(pieces), finish[0]


def _flight_error(rid: str, e: Exception) -> None:
    """Complete (or discard) the flight record of a failed completion.
    Admission sheds (saturated/draining/closed 503s) and caller errors
    (ValueError covers InvalidRequest and template/encode failures — the
    400 class) are DROPPED: both arrive at client-request rate, and
    finishing each one would flood --slow-log and churn every real
    timeline out of the ring exactly when the recorder matters most.
    Server-side failures (500s, deadline expiries) stay exemplars."""
    if isinstance(e, (EngineSaturated, EngineClosed, QuotaExceeded,
                      ValueError)):
        flight.drop(rid)
    else:
        flight.finish(rid, None, error=str(e))


def _map_error(e: Exception) -> tuple[int, str, float | None]:
    """Typed resilience error -> (status, OpenAI error type, Retry-After).

    InvalidRequest subclasses ValueError, so the isinstance order matters:
    the specific mappings come first and a bare ValueError (template/encode
    failures on caller input) stays a 400."""
    if isinstance(e, QuotaExceeded):
        # the tenant's own token bucket, not server load: 429, and the
        # Retry-After comes from the bucket's refill arithmetic
        return 429, "rate_limit_error", getattr(e, "retry_after", 1.0)
    if isinstance(e, EngineSaturated):
        return 503, "overloaded_error", getattr(e, "retry_after", 1.0)
    if isinstance(e, EngineWedged):
        # the supervisor failed this request while recovering a hung engine:
        # retriable by contract — a durable router resumes it elsewhere, a
        # plain client may simply retry after the recovery window
        return 503, "server_wedged", 1.0
    if isinstance(e, EngineClosed):  # covers EngineDraining
        return 503, "server_shutting_down", None
    if isinstance(e, DeadlineExceeded):
        return 408, "timeout_error", None
    if isinstance(e, ValueError):  # covers InvalidRequest
        return 400, "invalid_request_error", None
    return 500, "server_error", None


class Handler(BaseHTTPRequestHandler):
    state: ApiState  # injected

    def log_message(self, fmt, *args):  # quieter logs, reference prints per request
        print(f"🔷 {self.command} {self.path}")

    def _raw(self, code: int, content_type: str, data: bytes,
             extra_headers: dict | None = None):
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)
        _count_http(self.path, code)

    def _json(self, code: int, payload: dict,
              extra_headers: dict | None = None):
        self._raw(code, "application/json", json.dumps(payload).encode(),
                  extra_headers)

    def _error(self, code: int, message: str, etype: str,
               retry_after: float | None = None,
               extra_headers: dict | None = None):
        """OpenAI-style error body: {"error": {"message", "type"}} — clients
        built against the OpenAI SDK parse this shape, not bare strings.
        Load-shed 503s carry Retry-After so clients back off instead of
        hammering a saturated queue."""
        hdrs = dict(extra_headers or {})
        if retry_after is not None:
            hdrs["Retry-After"] = str(max(int(retry_after + 0.5), 1))
        self._json(code, {"error": {"message": message, "type": etype}},
                   hdrs or None)

    def _mapped_error(self, e: Exception, rid: str | None = None):
        # errored requests are the flight recorder's PRIMARY exemplars:
        # the error response must reveal the lookup key (X-Request-Id)
        # or the operator can never reach GET /v1/requests/<id> for it
        code, etype, retry_after = _map_error(e)
        hdrs = ({"X-Request-Id": rid, "X-Replica": self._replica_addr()}
                if rid else None)
        self._error(code, str(e), etype, retry_after, hdrs)

    def _replica_addr(self) -> str:
        """Routable replica address for the X-Replica header. A server bound
        to 0.0.0.0 would advertise an unroutable wildcard; the address the
        CLIENT actually connected to (this connection's local sockname) is
        reachable by that client by construction."""
        rid = self.state.replica_id
        if not rid.startswith("0.0.0.0:"):
            return rid
        try:
            host, port = self.connection.getsockname()[:2]
            return f"{host}:{port}"
        except (OSError, ValueError):
            return rid

    def do_GET(self):
        if self.path == "/v1/models":
            self._json(200, {"object": "list", "data": [
                {"id": self.state.model_name, "object": "model",
                 "created": _now(), "owned_by": "user"}]})
        elif self.path in ("/health", "/healthz"):
            # load-balancer probe: cheap, no device work. 200 while serving;
            # 503 "draining" once SIGTERM/begin_drain flips the state (the
            # LB stops routing while in-flight requests finish) and 503
            # "unhealthy" when the batch scheduler thread died.
            be = self.state.batch_engine
            alive = be is None or be.scheduler_alive()
            sup = self.state.supervisor
            replica = _load_block(self.state)  # identity+load for routers
            if self.state.draining or (be is not None and be.draining):
                self._json(503, {"status": "draining", "replica": replica})
            elif not alive:
                self._json(503, {"status": "unhealthy",
                                 "reason": "scheduler thread dead",
                                 "replica": replica})
            elif sup is not None and not sup.healthy:
                # the supervisor caught a wedged engine: stay out of fleet
                # rotation for the recovery window (or permanently, state
                # "failed") so the router resumes this replica's journaled
                # requests elsewhere (docs/ROBUSTNESS.md)
                self._json(503, {"status": "unhealthy",
                                 "reason": f"supervisor: engine {sup.state}",
                                 "replica": replica})
            else:
                self._json(200, {"status": "ok", "replica": replica})
        elif self.path == "/metrics":
            self._raw(200, "text/plain; version=0.0.4; charset=utf-8",
                      metrics.render().encode())
        elif self.path == "/v1/stats":
            self._json(200, _stats_payload(self.state))
        elif self.path.split("?", 1)[0] == "/v1/requests" \
                or self.path.startswith("/v1/requests/"):
            self._get_requests()
        elif self.path.startswith("/v1/kv/"):
            self._get_kv()
        elif self.path == "/v1/trace":
            # this replica's live Chrome trace (the fleet router's /v1/trace
            # pulls these from every replica and merges them)
            t = trace.current()
            if t is None:
                self._error(404, "tracing is not enabled on this replica "
                            "(start with --trace)", "invalid_request_error")
            else:
                self._json(200, t.to_chrome_trace())
        else:
            self._error(404, f"Unknown route: {self.path}", "invalid_request_error")

    def _get_requests(self):
        """GET /v1/requests[?slowest=K] | /v1/requests/<id>: the flight
        recorder's per-request timelines (docs/OBSERVABILITY.md)."""
        rec = flight.current()
        if rec is None:
            self._error(404, "flight recorder is not enabled",
                        "invalid_request_error")
            return
        parts = urlsplit(self.path)
        if parts.path.startswith("/v1/requests/"):
            key = parts.path[len("/v1/requests/"):]
            r = rec.get(key)
            if r is None:
                self._error(404, f"no flight record for {key!r} (ring keeps "
                            f"the last {rec.capacity} completed requests)",
                            "invalid_request_error")
            else:
                self._json(200, r)
            return
        qs = parse_qs(parts.query)
        try:
            slowest = int(qs.get("slowest", ["0"])[0])
        except ValueError:
            self._error(400, "'slowest' must be an integer",
                        "invalid_request_error")
            return
        tenant = qs.get("tenant", [None])[0]  # per-tenant filter
        self._json(200, rec.requests(slowest=slowest, tenant=tenant))

    def _post_kv(self):
        """POST /v1/kv (docs/DISAGG.md): prefill-only admission for the
        disaggregation transfer. Tokenizes the messages like a completion,
        runs the prefill through the batch scheduler (one throwaway greedy
        token — the decode replica generates from token zero with ITS
        sampler), and registers the host-snapshot blocks in the transfer
        table. The response is the descriptor the router injects as
        ``kv_source``; n_blocks 0 tells the planner the prompt was too
        short to ship (it routes monolithic)."""
        state = self.state
        try:
            length = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(length) or b"{}")
            if not isinstance(body.get("messages"), list) \
                    or not body["messages"]:
                raise ValueError("'messages' must be a non-empty array")
        except (ValueError, json.JSONDecodeError):
            self._error(400, "Request body is not valid JSON with a "
                        "non-empty 'messages' array", "invalid_request_error")
            return
        be = state.batch_engine
        if be is None or state.kv_transfers is None:
            self._error(501, "KV transfer requires a batched engine "
                        "(--batch > 1)", "invalid_request_error")
            return
        try:
            faults.fire("disagg.prefill")
            if state.draining:
                raise EngineDraining("server is draining (shutting down)")
            tok = be.tokenizer
            messages = [ChatItem(m.get("role", "user"), m.get("content", ""))
                        for m in body["messages"] if isinstance(m, dict)]
            prompt = tok.encode(state.template.generate(messages),
                                add_bos=True)
            if len(prompt) >= be.spec.seq_len:
                raise InvalidRequest(
                    f"prompt is {len(prompt)} tokens but the model context "
                    f"is {be.spec.seq_len}")
            # tenant/class relayed by the planner (docs/DISAGG.md): the
            # remote prefill is charged to the REQUESTING tenant at its
            # real class — a batch tenant's split prefills must not jump
            # the prefill replica's queue as anonymous interactive work
            tenant = sanitize_tenant(self.headers.get("X-Tenant"))
            klass = str(self.headers.get("X-Class")
                        or "interactive").strip().lower()
            if klass not in CLASSES:
                klass = "interactive"
            req = be.submit(prompt, 1,
                            Sampler(be.spec.vocab_size, 0.0, 0.9, 0),
                            export_kv=True, tenant=tenant, klass=klass)
            req.wait(timeout=300)
        except Exception as e:
            _KV_PREFILLS.labels(outcome="error").inc()
            self._mapped_error(e)
            return
        exp = req.kv_export
        if not exp or not exp[1]:
            _KV_PREFILLS.labels(outcome="empty").inc()
            self._json(200, {"xfer_id": None, "n_tokens": 0, "n_blocks": 0})
            return
        tokens, blocks, bt = exp
        desc = state.kv_transfers.open(
            tokens, blocks, bt, "q80" if state.kv_wire_q80 else "raw")
        _KV_PREFILLS.labels(outcome="ok").inc()
        self._json(200, desc)

    def _get_kv(self):
        """GET /v1/kv/<xfer_id>?from=F&n=N (docs/DISAGG.md): serve wire-
        encoded blocks [F, F+N) of a registered transfer. Every range is an
        independent request against the host snapshot, so a decode replica
        resumes a broken transfer by simply re-fetching the range — and an
        expired/unknown id is an honest 404 its fallback handles."""
        state = self.state
        parts = urlsplit(self.path)
        xfer_id = parts.path[len("/v1/kv/"):]
        t = (state.kv_transfers.get(xfer_id)
             if state.kv_transfers is not None else None)
        if t is None:
            self._error(404, f"no KV transfer {xfer_id!r} (unknown or "
                        "expired)", "invalid_request_error")
            return
        qs = parse_qs(parts.query)
        try:
            frm = int(qs.get("from", ["0"])[0])
            n = int(qs.get("n", [str(len(t.blocks) - max(frm, 0))])[0])
        except ValueError:
            self._error(400, "'from' and 'n' must be integers",
                        "invalid_request_error")
            return
        if frm < 0 or n < 0 or frm + n > len(t.blocks):
            self._error(400, f"range [{frm}, {frm + n}) outside "
                        f"[0, {len(t.blocks)})", "invalid_request_error")
            return
        try:
            faults.fire("disagg.export", xfer=xfer_id)
            from ..cache.wire import encode_blocks

            payload = encode_blocks(t.blocks[frm:frm + n],
                                    q80=state.kv_wire_q80)
        except Exception as e:
            self._error(500, f"export failed: {e}", "server_error")
            return
        _KV_EXPORT_BLOCKS.inc(n)
        _KV_EXPORT_BYTES.inc(len(payload))
        # a range covering the final block marks the transfer consumed —
        # its table slot frees after a short retry grace instead of the
        # full TTL (capped table, docs/DISAGG.md)
        state.kv_transfers.note_served(t, frm, n)
        self._raw(200, "application/octet-stream", payload,
                  {"X-KV-From": str(frm), "X-KV-Count": str(n)})

    def do_POST(self):
        if self.path == "/v1/kv":
            self._post_kv()
            return
        if self.path not in ("/v1/chat/completions", "/chat/completions"):
            self._error(404, f"Unknown route: {self.path}", "invalid_request_error")
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(length) or b"{}")
        except (ValueError, json.JSONDecodeError):
            self._error(400, "Request body is not valid JSON",
                        "invalid_request_error")
            return
        if not isinstance(body.get("messages"), list) or not body["messages"]:
            self._error(400, "'messages' must be a non-empty array",
                        "invalid_request_error")
            return
        stream = bool(body.get("stream", False))
        state = self.state
        # remaining client deadline (docs/FLEET.md): a durable router relays
        # the ORIGINAL X-Deadline-Ms minus elapsed time across every retry
        # and resume, so the request can never silently outlive the budget
        # the client set; an already-expired budget is an immediate 408
        deadline_s = None
        hdr = self.headers.get("X-Deadline-Ms")
        if hdr is not None:
            try:
                v = float(hdr)
                if v != v or v in (float("inf"), float("-inf")):
                    raise ValueError(hdr)  # NaN/inf pass <=0 checks below
                deadline_s = max(v, 0.0) / 1000.0
            except ValueError:
                self._error(400, "X-Deadline-Ms must be a finite number "
                            "(ms)", "invalid_request_error")
                return
            if deadline_s <= 0.0:
                self._error(408, "client deadline already expired",
                            "timeout_error")
                return
        # durable journal mode (docs/FLEET.md "Resume protocol"): the router
        # asks for token ids alongside each SSE text delta so its journal
        # can re-submit the request mid-stream; OpenAI clients ignore the
        # extra field, and it is absent without the header
        jstate = ({"toks": [], "n": 0}
                  if self.headers.get("X-Dllama-Journal") else None)
        # request identity (docs/OBSERVABILITY.md "Request tracing"): adopt
        # the inbound W3C traceparent (the fleet router stamps one on every
        # proxied hop; any W3C-speaking client works too) or originate a
        # trace here; the completion id doubles as the flight-recorder key
        rid = f"chatcmpl-{uuid.uuid4().hex[:12]}"
        # tenant identity (docs/SERVING.md "Multi-tenant serving"): the
        # X-Tenant header (relayed by the fleet router on every proxy try
        # and durable resume) rides the request context into the engine's
        # quota/fairness accounting and the flight-recorder timeline; an
        # X-Class header composes with the body's "class" field (body wins)
        ctx = reqctx.adopt(self.headers.get("traceparent"), request_id=rid,
                           tenant=sanitize_tenant(self.headers.get("X-Tenant")))
        if "class" not in body and self.headers.get("X-Class"):
            body["class"] = self.headers.get("X-Class")
        # batched mode: the scheduler serializes device access itself, so concurrent
        # requests proceed without the server-side lock (they share decode steps)
        import contextlib
        guard = contextlib.nullcontext() if state.batch_engine is not None else state.lock
        with guard, reqctx.use(ctx):
            if stream:
                # SSE headers are DEFERRED to the first delta: an error
                # raised before any output (validation, load shed, drain,
                # queue-TTL expiry) gets its real status code (400/503/408)
                # instead of a 200 stream carrying an error event
                completion_id = rid
                started = [False]

                def _start_stream():
                    self.send_response(200)
                    self.send_header("Content-Type", "text/event-stream")
                    self.send_header("Cache-Control", "no-cache")
                    self.send_header("Transfer-Encoding", "chunked")
                    self.send_header("X-Request-Id", rid)
                    self.send_header("X-Replica", self._replica_addr())
                    self.end_headers()
                    _count_http(self.path, 200)
                    started[0] = True

                def emit(text):
                    if not started[0]:
                        _start_stream()
                    payload = _chunk_payload(state, completion_id, {"content": text}, None)
                    if jstate is not None:
                        # token ids whose text THIS chunk carries + the
                        # cumulative delivered count — the durable router's
                        # journal entry (stripped before client relay)
                        payload["dllama"] = {"n": jstate["n"],
                                             "toks": jstate["toks"]}
                        jstate["toks"] = []
                    self._write_chunk(f"data: {json.dumps(payload)}\n\n".encode())

                try:
                    _text, finish = run_completion(state, body, emit,
                                                   journal=jstate,
                                                   deadline_s=deadline_s)
                except Exception as e:
                    _flight_error(rid, e)
                    if not started[0]:  # nothing sent: honest status code
                        self._mapped_error(e, rid)
                        return
                    # mid-stream: error as SSE event, then terminate. The
                    # `retriable` flag is the durable router's failover
                    # switch (docs/FLEET.md): True = the replica failed
                    # around an innocent request (wedged/closed/engine
                    # fault) and the journal may resume it elsewhere;
                    # False = deterministic, resuming would fail again.
                    code, etype, _ra = _map_error(e)
                    self._write_chunk(
                        ("data: " + json.dumps({"error": {
                            "message": str(e), "type": etype,
                            "code": code,
                            "retriable": retriable(e)}})
                         + "\n\n").encode())
                    self._write_chunk(b"data: [DONE]\n\n")
                    self._write_chunk(b"")
                    return
                if not started[0]:  # zero-delta completion still streams
                    _start_stream()
                self._write_chunk(
                    ("data: " + json.dumps(
                        _chunk_payload(state, completion_id, {}, finish))
                     + "\n\n").encode())
                # always terminate the chunked stream so clients don't hang
                self._write_chunk(b"data: [DONE]\n\n")
                self._write_chunk(b"")
            else:
                try:
                    text, finish = run_completion(state, body,
                                                  lambda _t: None,
                                                  deadline_s=deadline_s)
                    self._json(200, _completion_payload(state, text, finish,
                                                        rid),
                               {"X-Request-Id": rid,
                                "X-Replica": self._replica_addr()})
                except Exception as e:
                    _flight_error(rid, e)
                    self._mapped_error(e, rid)

    def _write_chunk(self, data: bytes):
        self.wfile.write(f"{len(data):X}\r\n".encode() + data + b"\r\n")
        self.wfile.flush()


def serve(engine: Engine, host: str = "0.0.0.0", port: int = 9990,
          template_type: TemplateType = TemplateType.UNKNOWN,
          default_sampler: Sampler | None = None,
          device_loop_chunk: int = 0, batch_engine=None,
          speculative_k: int = 0, prefix_cache=True,
          prefix_cache_blocks: int = 0, prefix_block_tokens: int = 16,
          prefix_cache_q80: bool = False,
          request_deadline: float = 0.0, flight_requests: int = 256,
          slow_log: str | None = None,
          slow_threshold: float = 1.0,
          supervisor_threshold: float = 0.0,
          supervisor_poll: float = 1.0,
          tenants: TenantRegistry | None = None,
          role: str = "both", kv_wire_q80: bool = False,
          kv_transfer_ttl: float = 120.0,
          kv_transfer_cap: int = 32) -> ThreadingHTTPServer:
    # batched speculative decoding lives in the BatchEngine scheduler
    # (construct it with speculative=K); speculative_k here drives only the
    # sequential engine's per-request verify loop. Guard EVERY caller, not
    # just the CLI: an engine built WITHOUT speculation plus speculative_k>0
    # would otherwise be silently inert.
    if (batch_engine is not None and speculative_k > 0
            and not getattr(batch_engine, "spec_k", 0)):
        raise ValueError(
            "speculative_k > 0 with a batch_engine requires the engine to "
            "be constructed with speculative=K (BatchEngine owns the "
            "batched draft-verify path)")
    runner = batch_engine or engine
    # one policy authority per replica: prefer the batch engine's own
    # registry (quota enforced at submit) so the HTTP mapping and the
    # scheduler agree on every tenant's weight and bucket
    if tenants is None and batch_engine is not None:
        tenants = getattr(batch_engine, "tenants", None)
    state = ApiState(engine, template_type,
                     default_sampler or Sampler(runner.spec.vocab_size, 0.7, 0.9, 0),
                     device_loop_chunk, batch_engine=batch_engine,
                     speculative_k=speculative_k, prefix_cache=prefix_cache,
                     prefix_cache_blocks=prefix_cache_blocks,
                     prefix_block_tokens=prefix_block_tokens,
                     prefix_cache_q80=prefix_cache_q80,
                     request_deadline=request_deadline, tenants=tenants,
                     role=role, kv_wire_q80=kv_wire_q80,
                     kv_transfer_ttl=kv_transfer_ttl,
                     kv_transfer_cap=kv_transfer_cap)
    handler = type("BoundHandler", (Handler,), {"state": state, "protocol_version": "HTTP/1.1"})
    server = QuietServer((host, port), handler)
    server.api_state = state  # drain controller / tests reach the state here
    # bound port is only known now (port=0 binds ephemeral in tests/benches)
    state.replica_id = f"{host}:{server.server_address[1]}"
    # flight recorder (docs/OBSERVABILITY.md "Request tracing"): always on —
    # a bounded ring of recent request timelines costs a few dict appends
    # per request, and GET /v1/requests must answer "why was THIS slow"
    # without a restart. A pre-installed recorder (tests, shared processes)
    # is kept ONLY when this server asked for defaults; explicit flight
    # flags must win, not silently no-op against the older instance.
    if (flight.current() is None or slow_log is not None
            or flight_requests != 256 or slow_threshold != 1.0):
        flight.install(flight_requests, slow_log=slow_log,
                       slow_threshold=slow_threshold)
    install_process_metrics()
    trace.set_process_name(f"api_server {state.replica_id}")
    if supervisor_threshold > 0 and batch_engine is not None:
        # hung-engine supervision (docs/ROBUSTNESS.md): act on the dispatch
        # watchdog instead of only exporting it — wedged past the threshold
        # ⇒ fail in-flight retriable, re-initialize the backend, and keep
        # /healthz unhealthy for the window so the fleet resumes elsewhere
        from ..resilience.supervisor import EngineSupervisor

        state.supervisor = EngineSupervisor(
            batch_engine, threshold=supervisor_threshold,
            poll=supervisor_poll).start()
        print(f"🛡️  supervisor armed: dispatch hang > "
              f"{supervisor_threshold:.0f}s fails in-flight (retriable) and "
              "re-initializes the backend")
    print(f"🟢 dllama-api listening on {host}:{port}")
    return server


def begin_drain(server: ThreadingHTTPServer, state: ApiState,
                drain_timeout: float = 30.0) -> None:
    """Graceful drain (the SIGTERM body; docs/ROBUSTNESS.md):

    1. flip state.draining — `/healthz` answers 503 "draining" (the LB stops
       routing) and new completions are refused with 503;
    2. let in-flight AND already-queued requests finish, bounded by
       drain_timeout (BatchEngine.close(drain=True); single-engine mode
       waits for the generation lock);
    3. stop accepting connections and return.

    Idempotent: a second call (double SIGTERM) skips straight to shutdown.
    """
    already = state.draining
    state.draining = True
    be = state.batch_engine
    if not already:
        print(f"🟡 draining: letting in-flight requests finish "
              f"(timeout {drain_timeout:.0f}s)")
        if be is not None:
            be.close(drain=True, timeout=drain_timeout)
        else:
            # single-engine mode: in-flight == the generation lock is held;
            # handlers queued behind it observe draining and 503 immediately
            deadline = time.monotonic() + drain_timeout
            while time.monotonic() < deadline:
                if state.lock.acquire(timeout=0.1):
                    state.lock.release()
                    break
    server.shutdown()
    print("🔴 drained, server stopped")


def install_sigterm_drain(server: ThreadingHTTPServer, state: ApiState,
                          drain_timeout: float = 30.0) -> bool:
    """Install the SIGTERM -> begin_drain handler (main thread only; returns
    False where signals can't be installed). The handler runs the drain on a
    worker thread so the signal frame returns immediately — serve_forever()
    unblocks when the drain calls server.shutdown()."""
    import signal

    def _on_term(signum, frame):
        threading.Thread(target=begin_drain,
                         args=(server, state, drain_timeout),
                         name="drain", daemon=True).start()

    try:
        signal.signal(signal.SIGTERM, _on_term)
    except ValueError:  # not the main thread
        return False
    return True


def main(argv=None) -> None:
    from ..platform_env import apply_platform_env

    apply_platform_env()
    from .dllama import build_parser, make_engine, make_sampler

    p = build_parser(include_mode=False)
    p.add_argument("--port", type=int, default=9990)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--batch", type=int, default=1,
                   help="continuous-batching slots: up to N requests decode "
                        "concurrently in one batched step (1 = reference-style "
                        "serialized serving)")
    p.add_argument("--superstep", type=int, default=8,
                   help="K-step device decode loop for --batch > 1: forward + "
                        "sampling scan K tokens on device per dispatch (1 host "
                        "sync per K tokens); the scheduler drops to single "
                        "steps while a new request waits, so admission latency "
                        "stays ~1 step. 1 = host-side sampling every token")
    p.add_argument("--dp", type=int, default=1,
                   help="data-parallel mesh axis: shard the --batch cache rows over "
                        "N device groups (requires --batch divisible by N)")
    p.add_argument("--no-prefix-cache", action="store_true",
                   help="disable the cross-request shared-prefix KV cache "
                        "(docs/PREFIX_CACHE.md); prefix reuse falls back to "
                        "the reference-style resident/slot rewind only")
    p.add_argument("--prefix-cache-blocks", type=int, default=0, metavar="N",
                   help="prefix-cache pool capacity in blocks (0 = auto: 4 "
                        "contexts per slot set, capped at ~1 GiB host RAM)")
    p.add_argument("--prefix-cache-block-tokens", type=int, default=16,
                   metavar="T", help="tokens per prefix-cache block (reuse "
                        "granularity; smaller = finer matches, more nodes)")
    p.add_argument("--prefix-cache-q80", action="store_true",
                   help="Q80-compress cold prefix-cache blocks (~3.8x denser "
                        "than f32) — capacity over bit-exactness: a cold hit "
                        "is a near-lossless dequantized seed, not an exact "
                        "replay (docs/PREFIX_CACHE.md cost model)")
    p.add_argument("--no-paged-kv", action="store_true",
                   help="escape hatch: revert --batch engines to the dense "
                        "per-slot contiguous KV caches instead of the "
                        "device-resident block pool + block tables "
                        "(docs/PAGED_KV.md); prefix hits then SCATTER pool "
                        "rows host→device instead of remapping tables")
    p.add_argument("--kv-block-tokens", type=int, default=16, metavar="T",
                   help="paged KV: tokens per device pool block (rounded "
                        "down to divide seq_len; also the radix directory's "
                        "reuse granularity — docs/PAGED_KV.md)")
    p.add_argument("--kv-pool-blocks", type=int, default=0, metavar="N",
                   help="paged KV: device pool capacity in blocks (0 = auto: "
                        "slots x blocks-per-context + headroom). Sizing it "
                        "BELOW slots x contexts oversubscribes KV — longer "
                        "contexts fit, pool pressure evicts/demotes the "
                        "directory (docs/PAGED_KV.md)")
    p.add_argument("--max-queue", type=int, default=0, metavar="N",
                   help="admission control (--batch > 1 only): refuse new "
                        "requests with 503 + Retry-After once N are waiting "
                        "for a slot (0 = unbounded; docs/ROBUSTNESS.md)")
    p.add_argument("--queue-ttl", type=float, default=0.0, metavar="S",
                   help="(--batch > 1 only) expire requests that waited more "
                        "than S seconds for a slot: 408 timeout_error, finish "
                        "reason 'deadline' (0 = no TTL)")
    p.add_argument("--request-deadline", type=float, default=0.0, metavar="S",
                   help="wall-clock deadline per request: generation past S "
                        "seconds stops with finish reason 'deadline' (partial "
                        "output delivered); with --batch > 1 the scheduler "
                        "enforces it over queue + generation and expiry "
                        "before the first token is a 408; with --batch 1 it "
                        "bounds generation per token (0 = none)")
    p.add_argument("--drain-timeout", type=float, default=30.0, metavar="S",
                   help="SIGTERM graceful drain: /healthz flips to 503 "
                        "'draining', admissions stop, in-flight requests get "
                        "up to S seconds to finish before the server closes")
    p.add_argument("--flight-requests", type=int, default=256, metavar="N",
                   help="flight recorder ring: keep the last N completed "
                        "request timelines for GET /v1/requests "
                        "(docs/OBSERVABILITY.md)")
    p.add_argument("--slow-log", default=None, metavar="OUT.jsonl",
                   help="append every request slower than --slow-threshold "
                        "as one JSON line (its full flight-recorder "
                        "timeline) — durable exemplars after the ring "
                        "rotates")
    p.add_argument("--slow-threshold", type=float, default=1.0, metavar="S",
                   help="E2E seconds over which a request lands in "
                        "--slow-log (default 1.0)")
    p.add_argument("--supervisor-threshold", type=float, default=0.0,
                   metavar="S",
                   help="hung-engine supervisor (--batch > 1;"
                        " docs/ROBUSTNESS.md): when no device dispatch "
                        "completes for S seconds while work is in flight, "
                        "fail in-flight requests with a RETRIABLE error, "
                        "re-initialize the backend, and flip /healthz "
                        "unhealthy so a fleet router resumes the requests "
                        "elsewhere (0 = observe-only watchdog, the "
                        "pre-supervisor behavior). Size well above the "
                        "slowest legitimate dispatch incl. cold compiles")
    p.add_argument("--supervisor-poll", type=float, default=1.0, metavar="S",
                   help="supervisor watchdog sampling period (detection "
                        "latency is threshold + poll)")
    p.add_argument("--role", choices=("prefill", "decode", "both"),
                   default="both",
                   help="disaggregation role advertised in /healthz "
                        "(docs/DISAGG.md): a role-aware router sends "
                        "long-prompt admissions to 'prefill' replicas "
                        "(which ship the resulting KV blocks out over "
                        "/v1/kv) and decode chains to 'decode' replicas. "
                        "A routing preference, not a capability — the "
                        "engine serves anything regardless")
    p.add_argument("--kv-wire-q80", action="store_true",
                   help="Q80-compress KV blocks on the /v1/kv export wire "
                        "(~3.8x fewer bytes than f32; bounded error, not "
                        "bit-exact — docs/DISAGG.md \"Wire format\")")
    p.add_argument("--kv-transfer-ttl", type=float, default=120.0,
                   metavar="S",
                   help="how long an exported KV transfer stays servable "
                        "for decode-replica fetches before it expires "
                        "(fully-fetched transfers free their slot after a "
                        "short retry grace instead)")
    p.add_argument("--kv-transfer-cap", type=int, default=32, metavar="N",
                   help="max concurrently-held KV export transfers (each "
                        "holds a host snapshot of one prompt's KV blocks); "
                        "beyond N the oldest is evicted — size it above "
                        "the expected concurrent long-prompt admissions")
    p.add_argument("--tenants", default=None, metavar="SPEC",
                   help="multi-tenant policy (docs/SERVING.md \"Multi-tenant"
                        " serving\"): ';'-separated "
                        "name[:weight=W,rate=R,burst=B] entries — W drives "
                        "weighted-fair scheduling, R/B a token-bucket quota "
                        "in tokens/sec (429 + Retry-After on exhaustion; "
                        "0/absent = unlimited). Requests pick their tenant "
                        "via the X-Tenant header; unknown ids share the "
                        "'default' entry. Example: "
                        "'gold:weight=4;free:weight=1,rate=50,burst=100'")
    p.add_argument("--slo-ttft-interactive", type=float, default=0.0,
                   metavar="S",
                   help="SLO-aware shedding (--batch > 1): refuse an "
                        "interactive admission when the measured queue "
                        "drain rate projects its wait past S seconds — "
                        "after first evicting queued batch-class work "
                        "(batch sheds before interactive); 0 = off")
    p.add_argument("--slo-ttft-batch", type=float, default=0.0, metavar="S",
                   help="batch-class TTFT target: refuse batch admissions "
                        "whose projected queue wait exceeds S seconds "
                        "(503 + drain-derived Retry-After); 0 = off")
    p.add_argument("--slo-tpot", type=float, default=0.0, metavar="S",
                   help="interactive TPOT target in seconds/token: while "
                        "the measured decode pace exceeds it, new "
                        "batch-class admissions are refused (they would "
                        "widen every shared dispatch further); 0 = off")
    args = p.parse_args(argv)
    from .dllama import dump_trace, install_trace

    install_trace(args)
    faults.install_from_env()  # DLLAMA_FAULTS chaos config (resilience/)
    # tenant policy is operator configuration: parse failures abort startup
    tenants = TenantRegistry.parse(args.tenants) if args.tenants else None
    batch_engine = None
    if args.dp > 1 and args.batch <= 1:
        p.error("--dp requires --batch > 1 (data parallelism shards batched cache rows)")
    if args.batch > 1:
        if args.sp > 1:
            p.error("--batch > 1 requires --sp 1: per-row cache positions are "
                    "incompatible with the sequence-sharded (ring) cache")
        if args.kv_cache_storage in ("host", "disc"):
            # refuse loudly rather than silently allocating the full-seq_len
            # HBM cache in exactly the overflow scenario the flag exists for
            p.error("--kv-cache-storage host|disc requires --batch 1: the "
                    "paged cache is single-sequence. For long-context serving "
                    "use --sp (more chips) or --batch 1.")
        import jax.numpy as jnp

        from ..runtime.batch_engine import BatchEngine
        from .dllama import _FT, init_pod

        init_pod(args)
        batch_engine = BatchEngine.load(
            args.model, args.tokenizer, max_seq_len=args.max_seq_len,
            weights_ftype=_FT[args.weights_float_type] if args.weights_float_type
            else None,
            slots=args.batch, superstep=max(args.superstep, 1),
            pipeline=args.pipeline,
            # --draft-model without --speculative K engages the default
            # verify width (the drafter is useless without the verify path)
            speculative=(args.speculative
                         or (args.draft_k or 8 if args.draft_model else 0)),
            draft_model=args.draft_model, draft_k=args.draft_k,
            prefix_cache=not args.no_prefix_cache,
            prefix_cache_blocks=args.prefix_cache_blocks,
            prefix_block_tokens=args.prefix_cache_block_tokens,
            prefix_cache_q80=args.prefix_cache_q80,
            paged_kv=not args.no_paged_kv,
            kv_block_tokens=args.kv_block_tokens,
            kv_pool_blocks=args.kv_pool_blocks,
            max_queue=args.max_queue, queue_ttl=args.queue_ttl,
            tenants=tenants,
            slo_ttft_interactive=args.slo_ttft_interactive,
            slo_ttft_batch=args.slo_ttft_batch,
            slo_tpot_interactive=args.slo_tpot,
            tp=args.tp, dp=args.dp, pod=args.pod,
            cache_write=args.cache_write, moe_sharding=args.moe_sharding,
            fused_prologue=args.prologue, prefill_kernel=args.prefill_kernel,
            fused_matmul=args.fused_matmul,
            dtype=(None if args.dtype == "auto"
                   else jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32),
            use_pallas=False if args.no_pallas else None,
            compress_collectives=args.buffer_float_type == "q80" and (args.tp or 1) > 1)
        engine = None
        sampler = make_sampler(args, batch_engine.spec)
        print(f"⏩ Continuous batching: {args.batch} slots, "
              f"super-step K={batch_engine.superstep}, pipelined decode "
              f"{'on' if batch_engine.pipeline else 'off'}"
              + (f", speculative k={batch_engine.spec_k}"
                 if batch_engine.spec_k else "")
              + (" (model drafter co-resident)"
                 if batch_engine.drafter is not None else ""))
    else:
        from .dllama import check_kv_storage

        if args.draft_model:
            import sys

            print("⚠️  --draft-model needs the batched verify path: add "
                  "--batch N (N > 1). Serving WITHOUT model-based drafting.",
                  file=sys.stderr)
        check_kv_storage(args)  # paged-mode cost notice (same as the CLI)
        engine = make_engine(args)
        sampler = make_sampler(args, engine.spec)
    server = serve(engine, args.host, args.port,
                   TemplateType(args.chat_template) if args.chat_template
                   else TemplateType.UNKNOWN, sampler, args.device_loop,
                   batch_engine=batch_engine, speculative_k=args.speculative,
                   prefix_cache=not args.no_prefix_cache,
                   prefix_cache_blocks=args.prefix_cache_blocks,
                   prefix_block_tokens=args.prefix_cache_block_tokens,
                   prefix_cache_q80=args.prefix_cache_q80,
                   request_deadline=args.request_deadline,
                   flight_requests=args.flight_requests,
                   slow_log=args.slow_log,
                   slow_threshold=args.slow_threshold,
                   supervisor_threshold=args.supervisor_threshold,
                   supervisor_poll=args.supervisor_poll,
                   tenants=tenants, role=args.role,
                   kv_wire_q80=args.kv_wire_q80,
                   kv_transfer_ttl=args.kv_transfer_ttl,
                   kv_transfer_cap=args.kv_transfer_cap)
    # SIGTERM -> graceful drain (docs/ROBUSTNESS.md): /healthz flips to
    # draining, admissions stop, in-flight requests finish, then shutdown
    install_sigterm_drain(server, server.api_state, args.drain_timeout)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        if server.api_state.supervisor is not None:
            server.api_state.supervisor.stop()
        if batch_engine is not None:
            # idempotent after a SIGTERM drain (close() re-entry is a no-op
            # walk over already-freed slots); a Ctrl-C exit aborts in-flight
            # requests with EngineClosed instead of leaking the scheduler
            batch_engine.close()
        dump_trace(args)  # --trace: flush the span buffer on shutdown


if __name__ == "__main__":
    main()
