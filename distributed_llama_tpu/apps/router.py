"""Fleet router entry point — front N api_server replicas with one process.

    python -m distributed_llama_tpu.apps.router \
        --replica 10.0.0.1:9990 --replica 10.0.0.2:9990 --port 9900

No model, no device, no jax work: the router only needs the fleet/ package
(stdlib HTTP + the shared radix trie). Replicas are ordinary api_server
processes; their SIGTERM graceful drain (docs/ROBUSTNESS.md) composes with
the router's membership poller into zero-downtime rolling restarts — drain a
replica, the router stops routing to it, restart it, it rejoins. See
docs/FLEET.md for the topology and routing policy.
"""

from __future__ import annotations

import argparse
import signal
import threading

from ..fleet.router import close_router, serve_router
from ..resilience import faults


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="dllama-router", description=__doc__)
    p.add_argument("--replica", action="append", required=True,
                   metavar="HOST:PORT", dest="replicas",
                   help="api_server replica address (repeat per replica)")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=9900)
    p.add_argument("--routing", choices=("affinity", "random"),
                   default="affinity",
                   help="replica selection: 'affinity' prefers the replica "
                        "whose recent routes share the longest prompt "
                        "block-prefix (prefix-cache locality), least-loaded "
                        "fallback; 'random' is the A/B control")
    p.add_argument("--poll-interval", type=float, default=2.0, metavar="S",
                   help="membership /healthz poll period")
    p.add_argument("--poll-timeout", type=float, default=2.0, metavar="S")
    p.add_argument("--block-bytes", type=int, default=64, metavar="B",
                   help="affinity-map block granularity in prompt bytes "
                        "(~ the replicas' --prefix-cache-block-tokens in "
                        "bytes; smaller = finer matches, more trie nodes)")
    p.add_argument("--affinity-nodes", type=int, default=8192, metavar="N",
                   help="affinity trie capacity (LRU-evicted beyond N)")
    p.add_argument("--retries", type=int, default=2, metavar="N",
                   help="max failover tries on a DIFFERENT replica for "
                        "requests that failed before their first byte; with "
                        "durable routing also the consecutive-fruitless-try "
                        "budget per mid-stream resume round")
    p.add_argument("--no-durable", action="store_true",
                   help="disable durable requests (docs/FLEET.md \"Resume "
                        "protocol\"): by default every completion is "
                        "journaled (params + pinned seed + delivered "
                        "tokens) and a mid-stream replica failure is "
                        "survived by resuming on a surviving replica with "
                        "byte-identical continuation and exactly-once "
                        "delivery; this flag reverts to verbatim "
                        "pass-through where mid-stream failures surface as "
                        "SSE error events")
    p.add_argument("--proxy-timeout", type=float, default=120.0, metavar="S",
                   help="per-try socket timeout (connect and each read)")
    p.add_argument("--tenants", default=None, metavar="SPEC",
                   help="router-level multi-tenant policy (docs/SERVING.md "
                        "\"Multi-tenant serving\"): ';'-separated "
                        "name[:weight=W,rate=R,burst=B] entries — R/B a "
                        "token-bucket quota (429 + Retry-After before any "
                        "proxy work), W the fair-share weight the "
                        "--max-inflight gate uses. Tenants are picked via "
                        "the X-Tenant header and relayed to replicas on "
                        "every try and durable resume")
    p.add_argument("--max-inflight", type=int, default=0, metavar="N",
                   help="bound concurrent completion proxies fleet-wide; "
                        "contended capacity is granted in weighted-fair "
                        "order (interactive class first, tenants by "
                        "weight) instead of thread-wakeup order (0 = "
                        "unbounded, the pre-tenancy behavior)")
    p.add_argument("--gate-timeout", type=float, default=30.0, metavar="S",
                   help="how long a request may wait in the --max-inflight "
                        "fair gate before shedding with 503 + "
                        "drain-derived Retry-After")
    p.add_argument("--disagg-threshold", type=int, default=0, metavar="T",
                   help="prefill/decode disaggregation (docs/DISAGG.md): "
                        "completions whose estimated prompt length (chars/4) "
                        "is at least T tokens run their prefill on a "
                        "prefill-capable replica (--role prefill on the "
                        "api_server) and ship the KV blocks to a decode "
                        "replica over /v1/kv; routing becomes role-aware. "
                        "0 = off (monolithic fleet, the default)")
    p.add_argument("--disagg-timeout", type=float, default=60.0, metavar="S",
                   help="timeout of the planner's /v1/kv prefill POST; on "
                        "expiry the request routes monolithic")
    p.add_argument("--seed", type=int, default=0,
                   help="random-routing RNG seed (A/B reproducibility)")
    p.add_argument("--trace", default=None, metavar="OUT.json",
                   help="record router spans (proxy tries, stamped with each "
                        "request's W3C trace id) and write a Chrome trace at "
                        "exit; also enables GET /v1/trace — the fleet-merged "
                        "Perfetto file joining this router's spans with every "
                        "replica's (docs/OBSERVABILITY.md)")
    return p


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)
    faults.install_from_env()  # DLLAMA_FAULTS chaos config (resilience/)
    tracer = None
    if args.trace:
        from ..obs import trace as obs_trace

        tracer = obs_trace.install(process_name="router")
    server = serve_router(
        args.replicas, host=args.host, port=args.port, policy=args.routing,
        poll_interval=args.poll_interval, poll_timeout=args.poll_timeout,
        block_bytes=args.block_bytes, affinity_nodes=args.affinity_nodes,
        retries=args.retries, try_timeout=args.proxy_timeout, seed=args.seed,
        durable=not args.no_durable, tenants=args.tenants,
        max_inflight=args.max_inflight, gate_timeout=args.gate_timeout,
        disagg_threshold=args.disagg_threshold,
        disagg_timeout=args.disagg_timeout)

    def _on_term(signum, frame):
        # the router holds no request state worth draining beyond in-flight
        # proxies; shutdown() lets those finish their handler threads
        threading.Thread(target=close_router, args=(server,),
                         name="router-drain", daemon=True).start()

    try:
        signal.signal(signal.SIGTERM, _on_term)
    except ValueError:
        pass  # not the main thread
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        close_router(server)
        if tracer is not None:
            tracer.dump(args.trace)
            print(f"🧭 wrote {len(tracer.events())} router trace events to "
                  f"{args.trace}")
        print("🔴 router stopped")


if __name__ == "__main__":
    main()
