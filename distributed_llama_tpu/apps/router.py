"""Fleet router entry point — front N api_server replicas with one process.

    python -m distributed_llama_tpu.apps.router \
        --replica 10.0.0.1:9990 --replica 10.0.0.2:9990 --port 9900

No model, no device, no jax work: the router only needs the fleet/ package
(stdlib HTTP + the shared radix trie). Replicas are ordinary api_server
processes; their SIGTERM graceful drain (docs/ROBUSTNESS.md) composes with
the router's membership poller into zero-downtime rolling restarts — drain a
replica, the router stops routing to it, restart it, it rejoins. See
docs/FLEET.md for the topology and routing policy.
"""

from __future__ import annotations

import argparse
import signal
import threading

from ..fleet.latency import GrayConfig
from ..fleet.router import close_router, serve_router
from ..resilience import faults


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="dllama-router", description=__doc__)
    p.add_argument("--replica", action="append", required=True,
                   metavar="HOST:PORT", dest="replicas",
                   help="api_server replica address (repeat per replica)")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=9900)
    p.add_argument("--routing", choices=("affinity", "random"),
                   default="affinity",
                   help="replica selection: 'affinity' prefers the replica "
                        "whose recent routes share the longest prompt "
                        "block-prefix (prefix-cache locality), least-loaded "
                        "fallback; 'random' is the A/B control")
    p.add_argument("--poll-interval", type=float, default=2.0, metavar="S",
                   help="membership /healthz poll period")
    p.add_argument("--poll-timeout", type=float, default=2.0, metavar="S")
    p.add_argument("--block-bytes", type=int, default=64, metavar="B",
                   help="affinity-map block granularity in prompt bytes "
                        "(~ the replicas' --prefix-cache-block-tokens in "
                        "bytes; smaller = finer matches, more trie nodes)")
    p.add_argument("--affinity-nodes", type=int, default=8192, metavar="N",
                   help="affinity trie capacity (LRU-evicted beyond N)")
    p.add_argument("--retries", type=int, default=2, metavar="N",
                   help="max failover tries on a DIFFERENT replica for "
                        "requests that failed before their first byte; with "
                        "durable routing also the consecutive-fruitless-try "
                        "budget per mid-stream resume round")
    p.add_argument("--no-durable", action="store_true",
                   help="disable durable requests (docs/FLEET.md \"Resume "
                        "protocol\"): by default every completion is "
                        "journaled (params + pinned seed + delivered "
                        "tokens) and a mid-stream replica failure is "
                        "survived by resuming on a surviving replica with "
                        "byte-identical continuation and exactly-once "
                        "delivery; this flag reverts to verbatim "
                        "pass-through where mid-stream failures surface as "
                        "SSE error events")
    p.add_argument("--proxy-timeout", type=float, default=120.0, metavar="S",
                   help="per-try timeout CEILING: the adaptive "
                        "pre-first-byte timeout and the stream idle-gap "
                        "timeout are both clamped to at most this "
                        "(docs/FLEET.md \"Gray-failure resilience\")")
    # gray-failure resilience (docs/FLEET.md "Gray-failure resilience"):
    # adaptive timeouts, bounded hedging, probation, retry budget
    p.add_argument("--ttfb-timeout-floor", type=float, default=5.0,
                   metavar="S",
                   help="lower clamp of the adaptive pre-first-byte "
                        "timeout (derived from observed fleet TTFB p95; "
                        "the --proxy-timeout cap applies until enough "
                        "samples exist)")
    p.add_argument("--ttfb-timeout-cap", type=float, default=None,
                   metavar="S",
                   help="upper clamp of the adaptive pre-first-byte "
                        "timeout (default: --proxy-timeout). Set equal to "
                        "the floor to pin a fixed TTFB timeout")
    p.add_argument("--idle-timeout", type=float, default=0.0, metavar="S",
                   help="stream idle-gap timeout: how long one body read "
                        "may block mid-stream before the replica counts as "
                        "wedged (durable routing resumes the stream "
                        "elsewhere). 0 = adaptive from observed per-event "
                        "pace, floored at 10 s, capped at --proxy-timeout")
    p.add_argument("--no-hedge", action="store_true",
                   help="disable pre-first-byte request hedging (by "
                        "default a try quiet past ~fleet TTFB p95 races a "
                        "budget-bounded duplicate on another replica; "
                        "first byte wins, the loser is canceled)")
    p.add_argument("--hedge-delay", type=float, default=0.0, metavar="S",
                   help="fixed hedge delay; 0 = adaptive (~observed fleet "
                        "TTFB p95). Pin it in tiny fleets where a slow "
                        "replica carries a large share of the samples and "
                        "the adaptive p95 would defer the hedge past the "
                        "latency it exists to cut")
    p.add_argument("--hedge-budget-pct", type=float, default=5.0,
                   metavar="PCT",
                   help="hedge spend bound: duplicate tries may not exceed "
                        "this percentage of proxied tries (plus a small "
                        "burst) — hedging can never melt an overloaded "
                        "fleet")
    p.add_argument("--retry-budget-ratio", type=float, default=0.5,
                   metavar="R",
                   help="failover retry budget: tokens added per delivered "
                        "completion (each failover retry spends one; an "
                        "empty bucket sheds instead of storming)")
    p.add_argument("--retry-budget-cap", type=float, default=16.0,
                   metavar="N",
                   help="failover retry budget burst cap (the bucket "
                        "starts full)")
    p.add_argument("--eject-multiple", type=float, default=4.0, metavar="X",
                   help="gray-failure probation: a replica whose observed "
                        "TTFB p50 is at least X times its peers' median "
                        "leaves rotation for canary-only probation")
    p.add_argument("--eject-min-samples", type=int, default=20, metavar="N",
                   help="per-replica TTFB samples required before the "
                        "outlier detector may judge it")
    p.add_argument("--probation-canaries", type=int, default=3, metavar="N",
                   help="consecutive in-band canary responses required for "
                        "a degraded replica to rejoin rotation")
    p.add_argument("--canary-every", type=int, default=8, metavar="N",
                   help="route every Nth pick to a degraded replica "
                        "(the probation canary trickle)")
    p.add_argument("--quorum-frac", type=float, default=0.5, metavar="F",
                   help="never eject below ceil(F x healthy replicas): a "
                        "uniformly slow fleet degrades honestly instead of "
                        "ejecting everyone")
    p.add_argument("--tenants", default=None, metavar="SPEC",
                   help="router-level multi-tenant policy (docs/SERVING.md "
                        "\"Multi-tenant serving\"): ';'-separated "
                        "name[:weight=W,rate=R,burst=B] entries — R/B a "
                        "token-bucket quota (429 + Retry-After before any "
                        "proxy work), W the fair-share weight the "
                        "--max-inflight gate uses. Tenants are picked via "
                        "the X-Tenant header and relayed to replicas on "
                        "every try and durable resume")
    p.add_argument("--max-inflight", type=int, default=0, metavar="N",
                   help="bound concurrent completion proxies fleet-wide; "
                        "contended capacity is granted in weighted-fair "
                        "order (interactive class first, tenants by "
                        "weight) instead of thread-wakeup order (0 = "
                        "unbounded, the pre-tenancy behavior)")
    p.add_argument("--gate-timeout", type=float, default=30.0, metavar="S",
                   help="how long a request may wait in the --max-inflight "
                        "fair gate before shedding with 503 + "
                        "drain-derived Retry-After")
    p.add_argument("--disagg-threshold", type=int, default=0, metavar="T",
                   help="prefill/decode disaggregation (docs/DISAGG.md): "
                        "completions whose estimated prompt length (chars/4) "
                        "is at least T tokens run their prefill on a "
                        "prefill-capable replica (--role prefill on the "
                        "api_server) and ship the KV blocks to a decode "
                        "replica over /v1/kv; routing becomes role-aware. "
                        "0 = off (monolithic fleet, the default)")
    p.add_argument("--disagg-timeout", type=float, default=60.0, metavar="S",
                   help="timeout of the planner's /v1/kv prefill POST; on "
                        "expiry the request routes monolithic")
    p.add_argument("--seed", type=int, default=0,
                   help="random-routing RNG seed (A/B reproducibility)")
    p.add_argument("--trace", default=None, metavar="OUT.json",
                   help="record router spans (proxy tries, stamped with each "
                        "request's W3C trace id) and write a Chrome trace at "
                        "exit; also enables GET /v1/trace — the fleet-merged "
                        "Perfetto file joining this router's spans with every "
                        "replica's (docs/OBSERVABILITY.md)")
    return p


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)
    faults.install_from_env()  # DLLAMA_FAULTS chaos config (resilience/)
    tracer = None
    if args.trace:
        from ..obs import trace as obs_trace

        tracer = obs_trace.install(process_name="router")
    gray = GrayConfig(
        eject_multiple=args.eject_multiple,
        min_samples=args.eject_min_samples,
        probation_exits=args.probation_canaries,
        quorum_frac=args.quorum_frac,
        canary_every=args.canary_every,
        ttfb_floor=args.ttfb_timeout_floor,
        ttfb_cap=args.ttfb_timeout_cap,
        idle_timeout=args.idle_timeout,
        hedge=not args.no_hedge,
        hedge_delay=args.hedge_delay,
        hedge_pct=args.hedge_budget_pct / 100.0,
        retry_ratio=args.retry_budget_ratio,
        retry_cap=args.retry_budget_cap)
    server = serve_router(
        args.replicas, host=args.host, port=args.port, policy=args.routing,
        poll_interval=args.poll_interval, poll_timeout=args.poll_timeout,
        block_bytes=args.block_bytes, affinity_nodes=args.affinity_nodes,
        retries=args.retries, try_timeout=args.proxy_timeout, seed=args.seed,
        durable=not args.no_durable, tenants=args.tenants,
        max_inflight=args.max_inflight, gate_timeout=args.gate_timeout,
        disagg_threshold=args.disagg_threshold,
        disagg_timeout=args.disagg_timeout, gray=gray)

    def _on_term(signum, frame):
        # the router holds no request state worth draining beyond in-flight
        # proxies; shutdown() lets those finish their handler threads
        threading.Thread(target=close_router, args=(server,),
                         name="router-drain", daemon=True).start()

    try:
        signal.signal(signal.SIGTERM, _on_term)
    except ValueError:
        pass  # not the main thread
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        close_router(server)
        if tracer is not None:
            tracer.dump(args.trace)
            print(f"🧭 wrote {len(tracer.events())} router trace events to "
                  f"{args.trace}")
        print("🔴 router stopped")


if __name__ == "__main__":
    main()
