"""Fleet router: cache-affinity HTTP front for N api_server replicas.

One dependency-free process (stdlib http only, same discipline as
apps/api_server.py) that turns the single-replica serving stack into a
horizontal fleet:

- **routing** — `pick()` prefers the replica whose recent routes share the
  longest byte-block prefix with the request (fleet/affinity.py over the
  cache/radix.py trie), so shared system prompts hit the replica whose
  prefix cache already holds their KV; misses fall back to least-loaded by
  the polled queue-depth/free-slot load block plus the router's own
  in-flight counts. `policy="random"` is the A/B control
  (`bench.py --routing random`).
- **proxying** — streaming SSE and non-streaming bodies pass through
  verbatim with a per-try socket timeout. A try that fails BEFORE the first
  byte reaches the client (connect error, injected `router.proxy` fault,
  replica 503) retries on a different replica — completions are idempotent
  until output is delivered — bounded by `retries`; once bytes have flowed
  the failure is surfaced as an SSE error event, never a silent re-issue.
  When every candidate is exhausted or the rotation is empty the client
  gets 503 + Retry-After (the fleet-level analog of the replica's
  admission-control shed).
- **observability** — `GET /metrics` merges every replica's Prometheus
  exposition under a `replica="host:port"` label with the router's own
  counters (routes by reason, proxy errors, per-replica inflight);
  `GET /v1/stats` serves the JSON equivalent; `GET /healthz` reports
  rotation so the router itself can sit behind a dumb L4 balancer.

Topology/flags: docs/FLEET.md. Entry point: apps/router.py.
"""

from __future__ import annotations

import json
import random
import threading
import time
from http.client import HTTPConnection
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs

from ..obs import metrics, reqctx, trace
from ..obs.process import install_process_metrics
from ..resilience import faults
from ..resilience.errors import QuotaExceeded
from ..resilience.quiet_http import QuietServer
from ..resilience.tenancy import (DrainRate, FairGate, TenantRegistry,
                                  sanitize_tenant)
from .affinity import AffinityMap
from .disagg import DisaggPlanner
from .journal import RequestJournal, iter_sse_data, parse_chunk
from .latency import GrayConfig, GrayFailureDetector, LatencyStat, TokenBudget
from .membership import Membership, Replica

__all__ = ["RouterState", "serve_router", "close_router", "merge_prometheus",
           "fleet_trace"]

_ROUTES = metrics.counter(
    "router_routes_total",
    "Requests routed, by decision reason (docs/FLEET.md)",
    labelnames=("reason",))
_PROXY_ERRORS = metrics.counter(
    "router_proxy_errors_total", "Proxy-path failures by kind",
    labelnames=("kind",))
_INFLIGHT = metrics.gauge(
    "router_replica_inflight", "Router-side in-flight proxies per replica",
    labelnames=("replica",))
_HTTP = metrics.counter(
    "router_http_requests_total", "Router HTTP responses by route and code",
    labelnames=("route", "code"))
_RETRIES = metrics.counter(
    "router_retried_requests_total",
    "Requests that needed at least one failover try")
_SCRAPE_ERRORS = metrics.counter(
    "router_scrape_errors_total",
    "Replica /metrics//v1/stats fetches that failed during aggregation")
_PROXY_SECONDS = metrics.histogram(
    "router_proxy_seconds", "Per-try proxy wall time (successful tries)")
# Multi-tenant policy at the fleet edge (docs/SERVING.md "Multi-tenant
# serving"): router-level quota throttles and fairness-gate sheds. Labels
# stay bounded — unknown tenant ids collapse to the canonical "default".
_THROTTLED = metrics.counter(
    "router_throttled_total",
    "Requests refused with 429: the tenant's router-level token bucket "
    "was exhausted", labelnames=("tenant",))
_GATE_SHED = metrics.counter(
    "router_gate_shed_total",
    "Requests shed because the weighted-fair inflight gate "
    "(--max-inflight) stayed full past the gate timeout")
_GATE_WAITING = metrics.gauge(
    "router_gate_waiting",
    "Handler threads currently parked in the weighted-fair inflight gate")
_DRAIN_RATE = metrics.gauge(
    "router_drain_rate",
    "Measured fleet completions/sec through this router (decayed EMA) — "
    "the denominator of the router's drain-derived Retry-After hints")
# Gray-failure resilience (docs/FLEET.md "Gray-failure resilience"):
# outcome-driven TTFB tracking, bounded hedging, retry budgets, adaptive
# timeouts, and Retry-After cooldowns.
_TTFB = metrics.histogram(
    "router_ttfb_seconds",
    "Per-try time from issuing the upstream request to response headers "
    "(api_server defers SSE headers to the first delta, so this is "
    "first-byte time, replica queue wait included) — feeds the adaptive "
    "pre-first-byte timeout and the hedge delay")
_TTFB_TIMEOUT = metrics.gauge(
    "router_ttfb_timeout_seconds",
    "Current adaptive pre-first-byte timeout (mult x observed fleet TTFB "
    "p95, clamped to the configured floor/cap; the cap until enough "
    "samples exist)")
_HEDGES = metrics.counter(
    "router_hedges_total",
    "Pre-first-byte request hedging by outcome: launched (duplicate try "
    "issued after the hedge delay), won (the hedge delivered first byte "
    "before the primary), denied (the hedge token budget was empty — "
    "spend stays bounded under overload), canary (budget-exempt hedge of "
    "a canary pick into a probation replica — its rate is bounded by "
    "canary_every instead)", labelnames=("outcome",))
_RETRY_DENIED = metrics.counter(
    "router_retry_budget_denied_total",
    "Failover retries suppressed because the global retry budget (token "
    "bucket refilled by delivered completions) was exhausted — the "
    "anti-retry-storm governor")
_RETRY_AFTER_HONORED = metrics.counter(
    "router_retry_after_honored_total",
    "Replica 503 Retry-After hints honored as pick() cooldowns (the "
    "failover loop no longer immediately re-hammers a replica that just "
    "said it was saturated)")

_KNOWN_ROUTES = ("/v1/chat/completions", "/chat/completions", "/v1/models",
                 "/v1/stats", "/metrics", "/health", "/healthz", "/v1/trace",
                 "/v1/requests")


class RouterState:
    def __init__(self, membership: Membership, policy: str = "affinity",
                 block_bytes: int = 64, affinity_nodes: int = 8192,
                 retries: int = 2, try_timeout: float = 120.0,
                 scrape_timeout: float = 3.0, key_bytes: int = 4096,
                 seed: int = 0, durable: bool = True,
                 journal_inflight: int = 4096,
                 tenants: TenantRegistry | None = None,
                 max_inflight: int = 0, gate_timeout: float = 30.0,
                 disagg_threshold: int = 0, disagg_timeout: float = 60.0,
                 gray: GrayConfig | None = None):
        assert policy in ("affinity", "random"), policy
        self.membership = membership
        # gray-failure resilience (docs/FLEET.md "Gray-failure resilience"):
        # outcome-driven fleet latency stats feed adaptive timeouts and the
        # hedge delay; the detector runs probation; the budgets bound hedge
        # and retry spend so failover can never amplify an overload
        self.gray = gray or GrayConfig()
        self.detector = GrayFailureDetector(self.gray)
        self.fleet_ttfb = LatencyStat(window=256)
        self.fleet_pace = LatencyStat(window=512)
        self.hedge_budget = TokenBudget(self.gray.hedge_pct,
                                        self.gray.hedge_burst)
        self.retry_budget = TokenBudget(self.gray.retry_ratio,
                                        self.gray.retry_cap)
        # prefill/decode disaggregation (docs/DISAGG.md): when the threshold
        # is armed, long-prompt completions run their prefill on a
        # prefill-capable replica, whose KV blocks the decode replica then
        # imports — and routing becomes role-aware (short chains prefer
        # decode replicas, unsplit long prompts prefer prefill ones)
        self.disagg = DisaggPlanner(disagg_threshold, timeout=disagg_timeout)
        # Multi-tenant fleet edge (docs/SERVING.md "Multi-tenant serving"):
        # optional router-level token-bucket quotas (429 before any proxy
        # work) and a weighted-fair inflight gate replacing the implicit
        # FIFO of handler-thread scheduling — when `max_inflight` > 0,
        # concurrent completion proxies are bounded and contended capacity
        # is handed out interactive-first, tenants by weight. The drain
        # estimator feeds every fleet-saturation Retry-After hint (measured
        # completions/sec vs depth, never the poll-interval constant).
        self.tenants = tenants
        self.gate = FairGate(max_inflight, tenants)
        self.gate_timeout = gate_timeout
        self.drain = DrainRate()
        self.affinity = AffinityMap(block_bytes=block_bytes,
                                    max_nodes=affinity_nodes)
        self.policy = policy
        self.retries = max(retries, 0)
        self.try_timeout = try_timeout
        self.scrape_timeout = scrape_timeout
        self.key_bytes = key_bytes
        # durable requests (docs/FLEET.md "Resume protocol"): journal every
        # in-flight completion so a mid-stream replica failure is survived by
        # resuming on another replica instead of surfaced as an SSE error
        self.durable = durable
        self.journal = RequestJournal(max_inflight=journal_inflight)
        self._rng = random.Random(seed)
        self._rr = 0  # round-robin clock for least-loaded ties
        self._canary_clock = 0  # every Nth pick canaries a degraded replica
        self._lock = threading.Lock()  # guards: _rng, _rr, _canary_clock

    # ------------------------------------------------------------------
    # routing decision
    # ------------------------------------------------------------------

    def affinity_key(self, body: dict) -> bytes:
        """Deterministic byte key of the prompt prefix: the messages in
        render order, role and content separated by sentinels so
        ("ab","c") cannot collide with ("a","bc"). Capped — affinity only
        needs the leading blocks, not the whole conversation."""
        parts = []
        for m in body.get("messages", []):
            if not isinstance(m, dict):
                continue
            parts.append(str(m.get("role", "user")).encode("utf-8", "replace")
                         + b"\x00"
                         + str(m.get("content", "")).encode("utf-8", "replace")
                         + b"\x1e")
            if sum(len(p) for p in parts) >= self.key_bytes:
                break
        return b"".join(parts)[:self.key_bytes]

    def pick(self, key: bytes, tried: set[str],
             prefer_roles: tuple | None = None
             ) -> tuple[Replica | None, str]:
        """(replica, reason) for the next try; (None, "saturated") when no
        routable replica remains. Reasons: affinity | least_loaded | random
        on the first try, failover afterwards. `prefer_roles` (docs/
        DISAGG.md) narrows the candidates to replicas advertising one of
        those roles when any match — a SOFT preference: an empty match
        falls back to the whole rotation, because roles are routing
        affinities, not capabilities, and serving beats shedding."""
        rotation = [r for r in self.membership.in_rotation()
                    if r.id not in tried]
        if not rotation:
            # serving beats shedding: with nothing healthy left, a
            # probation replica (slow, not dead) still beats a 503 — this
            # is also how the quorum-floor promise composes with failover
            cands = self.membership.canary_candidates(tried)
            if cands:
                return min(cands, key=Replica.load_score), "canary"
            return None, "saturated"
        if prefer_roles is not None:
            preferred = [r for r in rotation if r.role in prefer_roles]
            if preferred:
                rotation = preferred
        if tried:
            return min(rotation, key=Replica.load_score), "failover"
        # canary trickle (docs/FLEET.md "Gray-failure resilience"): every
        # canary_every-th first-try pick routes to a probation replica so
        # rejoin evidence keeps flowing without it serving real share
        cands = self.membership.canary_candidates(tried)
        if cands:
            with self._lock:
                self._canary_clock += 1
                take = (self._canary_clock
                        % max(self.gray.canary_every, 1) == 0)
            if take:
                return min(cands, key=Replica.load_score), "canary"
        if self.policy == "random":
            with self._lock:
                return self._rng.choice(rotation), "random"
        rep_id, _depth = self.affinity.lookup(key, {r.id for r in rotation})
        if rep_id is not None:
            return self.membership.by_id(rep_id), "affinity"
        # cold prefix: least-loaded, with ROUND-ROBIN among load ties — a
        # fixed tie-break (e.g. lowest id) would send every cold prefix of a
        # quiet fleet to one replica, and affinity would then pin all their
        # future traffic there too (observed: one replica served ~everything
        # until the fleet warmed unevenly into saturation)
        load = lambda r: (r.queue_depth + r.inflight, -r.free_slots)  # noqa: E731
        best = min(load(r) for r in rotation)
        ties = [r for r in rotation if load(r) == best]
        with self._lock:
            pick = ties[self._rr % len(ties)]
            self._rr += 1
        return pick, "least_loaded"

    def note_done(self) -> None:
        """One completion fully relayed: feed the drain estimator (the
        denominator of every fleet-saturation Retry-After hint) and refill
        the global retry budget — successes are what entitle failover to
        keep spending tries under stress."""
        self.drain.note()
        _DRAIN_RATE.set(self.drain.rate())
        self.retry_budget.note()

    # ------------------------------------------------------------------
    # gray-failure signals: adaptive timeouts, hedge delay, budgets
    # ------------------------------------------------------------------

    def note_ttfb(self, rep: Replica, ttfb_s: float,
                  ok: bool = True) -> None:
        """Fold one upstream open's first-byte time into the replica's and
        the fleet's stats. Only a SUCCESSFUL open (`ok`) is judged by the
        detector for probation exit — a censored timeout sample records
        "at least this slow", and when the effective TTFB timeout sits
        below the ejection threshold that value would READ as in-band: a
        degraded replica whose canaries never produced headers must reset
        the rejoin streak, not extend it."""
        rep.lat.ttfb.note(ttfb_s)
        self.fleet_ttfb.note(ttfb_s)
        _TTFB.observe(ttfb_s)
        if ok:
            self.detector.note_outcome(rep, ttfb_s,
                                       self.membership.replicas)
        elif rep.degraded:
            rep.canary_note(False)  # a timed-out canary is still-bad evidence

    def note_pace(self, rep: Replica, gap_s: float) -> None:
        """One relayed stream event's inter-arrival gap (the idle-gap
        timeout's evidence base)."""
        rep.lat.pace.note(gap_s)
        self.fleet_pace.note(gap_s)

    def ttfb_timeout(self) -> float:
        """Adaptive pre-first-byte timeout: mult x observed fleet TTFB p95,
        clamped to [floor, cap]; the cap (the old fixed --proxy-timeout
        behavior) until enough samples exist to trust the estimate."""
        g = self.gray
        cap = g.ttfb_cap if g.ttfb_cap is not None else self.try_timeout
        if self.fleet_ttfb.count() < g.min_lat_samples:
            _TTFB_TIMEOUT.set(cap)
            return cap
        p95 = self.fleet_ttfb.quantile(0.95) or cap
        t = min(max(g.ttfb_mult * p95, g.ttfb_floor), cap)
        _TTFB_TIMEOUT.set(t)
        return t

    def idle_timeout(self) -> float:
        """Stream idle-gap timeout: how long one body read may block. Split
        from the TTFB timeout so a healthy long generation (steady token
        gaps) is distinguishable from a mid-stream wedge. Fixed when
        configured, else mult x observed pace p99 clamped to
        [idle_floor, --proxy-timeout]."""
        g = self.gray
        if g.idle_timeout > 0.0:
            return g.idle_timeout
        if self.fleet_pace.count() < g.min_lat_samples:
            return self.try_timeout
        p99 = self.fleet_pace.quantile(0.99) or self.try_timeout
        return min(max(g.idle_mult * p99, g.idle_floor), self.try_timeout)

    def hedge_delay(self) -> float | None:
        """How long a pre-first-byte open may stay quiet before a duplicate
        try is raced against it (a fixed --hedge-delay, else ~observed TTFB
        p95); None = hedging off (disabled, or adaptive without enough
        samples to place the delay)."""
        g = self.gray
        if not g.hedge:
            return None
        if g.hedge_delay > 0.0:
            return g.hedge_delay
        if self.fleet_ttfb.count() < g.min_lat_samples:
            return None
        p95 = self.fleet_ttfb.quantile(0.95)
        return None if p95 is None else max(p95, g.hedge_floor)

    def allow_retry(self) -> bool:
        """Gate one failover retry on the global retry budget (refilled by
        delivered completions): under a fleet-wide outage the budget drains
        and the router stops amplifying load into a retry storm."""
        if self.retry_budget.spend():
            return True
        _RETRY_DENIED.inc()
        return False

    def retry_after_hint(self) -> float:
        """Drain-derived Retry-After for fleet-saturation refusals: the
        measured time for the fleet to work off its current backlog
        (polled queue depth + router in-flight across replicas, plus gate
        waiters), floored and capped (resilience/tenancy.py DrainRate) —
        the header tracks load instead of relaying the membership
        poll-interval constant."""
        depth = sum(r.queue_depth + r.inflight
                    for r in self.membership.replicas) + self.gate.waiting()
        return self.drain.retry_after(depth + 1)


# ----------------------------------------------------------------------
# Prometheus merge
# ----------------------------------------------------------------------

def _inject_label(sample: str, label: str) -> str:
    """Add `label` (e.g. replica="h:p") to one exposition sample line."""
    brace = sample.find("{")
    space = sample.find(" ")
    if brace != -1 and (space == -1 or brace < space):
        return sample[:brace + 1] + label + "," + sample[brace + 1:]
    return sample[:space] + "{" + label + "}" + sample[space:]


def merge_prometheus(texts: list[tuple[str | None, str]]) -> str:
    """Merge expositions into one: `texts` is [(replica id or None, text)].
    Samples from labeled sources get `replica="<id>"` injected; HELP/TYPE
    headers are emitted once per family (first source wins). Families are
    attributed by the running header like our own renderer emits them, with
    a name-prefix fallback for any foreign layout."""
    families: dict[str, dict] = {}
    order: list[str] = []

    def fam_for(name: str) -> dict:
        if name not in families:
            families[name] = {"help": None, "type": None, "samples": []}
            order.append(name)
        return families[name]

    for rep_id, text in texts:
        label = f'replica="{rep_id}"' if rep_id is not None else None
        current: str | None = None
        for line in text.splitlines():
            if not line.strip():
                continue
            if line.startswith("# HELP ") or line.startswith("# TYPE "):
                kind = "help" if line[2] == "H" else "type"
                rest = line[7:].split(" ", 1)
                current = rest[0]
                fam = fam_for(current)
                if fam[kind] is None:
                    fam[kind] = rest[1] if len(rest) > 1 else ""
                continue
            if line.startswith("#"):
                continue
            mname = line.split("{", 1)[0].split(" ", 1)[0]
            name = (current if current is not None and mname.startswith(current)
                    else mname)
            fam_for(name)["samples"].append(
                _inject_label(line, label) if label else line)
    out = []
    for name in order:
        fam = families[name]
        if fam["help"] is not None:
            out.append(f"# HELP {name} {fam['help']}")
        if fam["type"] is not None:
            out.append(f"# TYPE {name} {fam['type']}")
        out.extend(fam["samples"])
    return "\n".join(out) + "\n"


def _fetch(rep: Replica, path: str, timeout: float) -> tuple[int, bytes]:
    conn = HTTPConnection(rep.host, rep.port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def _scrape_all(state: RouterState, path: str) -> list[tuple[Replica, object]]:
    """Fetch `path` from every replica CONCURRENTLY (one thread each, joined
    at scrape_timeout): a serial loop would block an aggregation request up
    to scrape_timeout PER unreachable replica — exactly during the rolling
    restarts and incidents monitoring exists for. Returns (replica, result)
    pairs where result is (status, body) or the raised exception."""
    results: list = [None] * len(state.membership.replicas)

    def fetch(i: int, rep: Replica) -> None:
        try:
            results[i] = _fetch(rep, path, state.scrape_timeout)
        except Exception as e:
            results[i] = e

    threads = [threading.Thread(target=fetch, args=(i, rep), daemon=True)
               for i, rep in enumerate(state.membership.replicas)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + state.scrape_timeout + 1.0
    for t in threads:
        t.join(timeout=max(deadline - time.monotonic(), 0.0))
    out = []
    for rep, res in zip(state.membership.replicas, results):
        out.append((rep, res if res is not None
                    else TimeoutError("scrape timed out")))
    return out


def fleet_metrics(state: RouterState) -> str:
    """Router-own exposition + every reachable replica's, replica-labeled."""
    texts: list[tuple[str | None, str]] = [(None, metrics.render())]
    for rep, res in _scrape_all(state, "/metrics"):
        if isinstance(res, tuple) and res[0] == 200:
            texts.append((rep.id, res[1].decode("utf-8", "replace")))
        else:
            _SCRAPE_ERRORS.inc()
    return merge_prometheus(texts)


def fleet_stats(state: RouterState) -> dict:
    out = {
        "time": int(time.time()),
        "router": {
            "policy": state.policy,
            "affinity_nodes": state.affinity.nodes(),
            "replicas": {r.id: r.snapshot()
                         for r in state.membership.replicas},
            # gray-failure spend governors + current adaptive timeouts
            # (docs/FLEET.md "Gray-failure resilience")
            "gray": {"hedge_budget": state.hedge_budget.stats(),
                     "retry_budget": state.retry_budget.stats(),
                     "ttfb_timeout_s": round(state.ttfb_timeout(), 3),
                     "idle_timeout_s": round(state.idle_timeout(), 3)},
            "metrics": metrics.snapshot(),
        },
        "replicas": {},
    }
    for rep, res in _scrape_all(state, "/v1/stats"):
        if isinstance(res, tuple):
            status, body = res
            try:
                # a 200 with a non-JSON body (wrong process on the port, an
                # LB error page) must degrade to THIS replica's error entry,
                # not crash the whole aggregation
                out["replicas"][rep.id] = (json.loads(body) if status == 200
                                           else {"error": f"status {status}"})
            except ValueError as e:
                _SCRAPE_ERRORS.inc()
                out["replicas"][rep.id] = {"error": f"non-JSON body: {e}"}
        else:
            _SCRAPE_ERRORS.inc()
            out["replicas"][rep.id] = {"error": repr(res)}
    return out


def fleet_trace(state: RouterState) -> dict:
    """GET /v1/trace: ONE Perfetto-loadable Chrome trace for the whole fleet
    — the router's own proxy spans plus every replica's `/v1/trace` export,
    merged onto a wall-clock-aligned timeline with one pid (and a
    process_name label) per process. A request's `router.proxy` span and its
    replica-side engine spans share the `trace_id` arg the traceparent
    propagation stamped, so following one request across processes is a
    Perfetto args search (docs/OBSERVABILITY.md "Fleet trace merge")."""
    sources: list[tuple[str, dict]] = []
    own = trace.current()
    if own is not None:
        sources.append(("router", own.to_chrome_trace()))
    for rep, res in _scrape_all(state, "/v1/trace"):
        if isinstance(res, tuple):
            status, body = res
            if status == 200:
                try:
                    sources.append((f"replica {rep.id}", json.loads(body)))
                    continue
                except ValueError:
                    pass  # a 200 with a non-JSON body IS a scrape error
            elif status == 404:
                # replica running without --trace: documented-normal — absent
                # from the merge, never counted as a scrape failure
                continue
        _SCRAPE_ERRORS.inc()
    return trace.merge_chrome_traces(sources)


# ----------------------------------------------------------------------
# HTTP handler
# ----------------------------------------------------------------------

class RouterHandler(BaseHTTPRequestHandler):
    state: RouterState  # injected by serve_router

    def log_message(self, fmt, *args):
        print(f"🔶 {self.command} {self.path}")

    def _count(self, code: int) -> None:
        path = self.path.split("?", 1)[0]
        if path.startswith("/v1/requests/"):
            path = "/v1/requests"  # per-id lookups share one label value
        route = path if path in _KNOWN_ROUTES else "other"
        _HTTP.labels(route=route, code=str(code)).inc()

    def _raw(self, code: int, content_type: str, data: bytes,
             extra_headers: dict | None = None):
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)
        self._count(code)

    def _json(self, code: int, payload: dict,
              extra_headers: dict | None = None):
        self._raw(code, "application/json", json.dumps(payload).encode(),
                  extra_headers)

    def _error(self, code: int, message: str, etype: str,
               retry_after: float | None = None):
        hdrs = ({"Retry-After": str(max(int(retry_after + 0.5), 1))}
                if retry_after is not None else None)
        self._json(code, {"error": {"message": message, "type": etype}}, hdrs)

    # -------------------------------------------------------------- GET

    def do_GET(self):
        state = self.state
        if self.path in ("/health", "/healthz"):
            rotation = state.membership.in_rotation()
            payload = {
                "status": "ok" if rotation else "no_healthy_replicas",
                "role": "router",
                "in_rotation": len(rotation),
                # gray-failure probation roster (docs/FLEET.md): degraded
                # replicas are alive but canary-only — operators (and the
                # chaos bench) watch entry/exit here
                "degraded": [r.id for r in state.membership.replicas
                             if r.degraded],
                "replicas": {r.id: r.snapshot()
                             for r in state.membership.replicas},
            }
            self._json(200 if rotation else 503, payload)
        elif self.path == "/metrics":
            self._raw(200, "text/plain; version=0.0.4; charset=utf-8",
                      fleet_metrics(state).encode())
        elif self.path == "/v1/stats":
            self._json(200, fleet_stats(state))
        elif self.path == "/v1/trace":
            self._json(200, fleet_trace(state))
        elif self.path.startswith("/v1/requests/"):
            # the slow-request workflow must work for clients that can only
            # reach the router (replicas on an internal network): the id
            # lives on exactly one replica's flight recorder — ask them all
            # concurrently, relay the hit verbatim (replica 404s are the
            # expected misses, never scrape errors)
            path = self.path.split("?", 1)[0]
            unreachable = 0
            for rep, res in _scrape_all(state, path):
                if isinstance(res, tuple):
                    if res[0] == 200:
                        self._raw(200, "application/json", res[1])
                        return
                    # a replica 404 is a definitive miss THERE; any other
                    # status is indeterminate, like an exception below
                    if res[0] == 404:
                        continue
                unreachable += 1
                _SCRAPE_ERRORS.inc()
            key = path[len("/v1/requests/"):]
            if unreachable:
                # a 404 here would claim the record doesn't exist anywhere
                # while the replica that may hold it simply didn't answer
                # (rolling restart) — report the uncertainty honestly
                self._error(502, f"no flight record for {key!r} on the "
                            f"replicas that answered, but {unreachable} "
                            "replica(s) were unreachable", "server_error")
            else:
                self._error(404, f"no flight record for {key!r} on any "
                            "replica", "invalid_request_error")
        elif self.path.split("?", 1)[0] == "/v1/requests":
            # merged listing: each replica's summaries nested under its id,
            # query string (?slowest=K) validated HERE (a caller error must
            # be a 400, not N replica 400s masquerading as scrape failures)
            q = self.path.partition("?")[2]
            try:
                int(parse_qs(q).get("slowest", ["0"])[0])
            except ValueError:
                self._error(400, "'slowest' must be an integer",
                            "invalid_request_error")
                return
            out: dict = {"replicas": {}}
            for rep, res in _scrape_all(
                    state, "/v1/requests" + (f"?{q}" if q else "")):
                # same degradation contract as fleet_stats: a failing
                # replica gets an explicit error entry, never a silent drop
                if isinstance(res, tuple):
                    status, body = res
                    try:
                        out["replicas"][rep.id] = (
                            json.loads(body) if status == 200
                            else {"error": f"status {status}"})
                        continue
                    except ValueError as e:
                        _SCRAPE_ERRORS.inc()
                        out["replicas"][rep.id] = {
                            "error": f"non-JSON body: {e}"}
                        continue
                _SCRAPE_ERRORS.inc()
                out["replicas"][rep.id] = {"error": repr(res)}
            self._json(200, out)
        elif self.path == "/v1/models":
            rep = state.membership.least_loaded()
            if rep is None:
                self._error(503, "no healthy replica", "overloaded_error",
                            retry_after=state.retry_after_hint())
                return
            try:
                status, body = _fetch(rep, self.path, state.try_timeout)
                self._raw(status, "application/json", body)
            except Exception as e:
                self._error(502, f"replica {rep.id} unreachable: {e}",
                            "server_error")
        else:
            self._error(404, f"Unknown route: {self.path}",
                        "invalid_request_error")

    # ------------------------------------------------------------- POST

    def _deadline_ms(self) -> float | None:
        """Parse the client's X-Deadline-Ms budget (None = no deadline;
        ValueError surfaces as a 400 in the caller). Non-finite values must
        be rejected HERE: a NaN would pass every downstream `<= 0` check
        and then blow up int() conversions inside the failover loop, where
        the blast radius is replica ejections, not a clean 400."""
        hdr = self.headers.get("X-Deadline-Ms")
        if hdr is None:
            return None
        v = float(hdr)
        if v != v or v in (float("inf"), float("-inf")):
            raise ValueError(f"non-finite deadline {hdr!r}")
        return max(v, 0.0)

    def do_POST(self):
        if self.path not in ("/v1/chat/completions", "/chat/completions"):
            self._error(404, f"Unknown route: {self.path}",
                        "invalid_request_error")
            return
        state = self.state
        try:
            length = int(self.headers.get("Content-Length", 0))
            raw = self.rfile.read(length) or b"{}"
            body = json.loads(raw)
            if not isinstance(body, dict):
                raise ValueError("body is not an object")
        except (ValueError, json.JSONDecodeError):
            self._error(400, "Request body is not valid JSON",
                        "invalid_request_error")
            return
        try:
            deadline_ms = self._deadline_ms()
        except ValueError:
            self._error(400, "X-Deadline-Ms must be a number (ms)",
                        "invalid_request_error")
            return
        # multi-tenant fleet edge (docs/SERVING.md "Multi-tenant serving"):
        # resolve the tenant/class once; the router-level quota refuses
        # with 429 BEFORE any proxy work, and the X-Tenant/X-Class headers
        # are relayed on every try (and durable resume) so replica-side
        # accounting survives failover
        tenant = sanitize_tenant(self.headers.get("X-Tenant"))
        klass = str(body.get("class") or self.headers.get("X-Class")
                    or "interactive").strip().lower()
        if klass not in ("interactive", "batch"):
            klass = "interactive"
        tenant_hdrs = {"X-Tenant": tenant, "X-Class": klass}
        cost = 0.0
        if state.tenants is not None:
            # router-level cost estimate: the router never tokenizes, so
            # charge ~chars/4 of rendered content plus the decode budget
            chars = sum(len(str(m.get("content", "")))
                        for m in body.get("messages", [])
                        if isinstance(m, dict))
            cost = chars / 4.0 + float(body.get("max_tokens") or 64)
            try:
                state.tenants.acquire(tenant, cost)
            except QuotaExceeded as e:
                _THROTTLED.labels(
                    tenant=state.tenants.canonical(tenant)).inc()
                self._error(429, str(e), "rate_limit_error",
                            retry_after=e.retry_after)
                return
        # weighted-fair inflight gate (--max-inflight): contended capacity
        # is handed out interactive-first, tenants by weight — a flooding
        # tenant's handler threads can no longer take every slot
        if not state.gate.acquire(tenant, klass,
                                  timeout=state.gate_timeout):
            if state.tenants is not None:
                # zero service rendered: a gate shed must not also drain
                # the tenant's bucket (the retry would be double-punished)
                state.tenants.refund(tenant, cost)
            _GATE_SHED.inc()
            self._error(503, "fleet at --max-inflight and the fair gate "
                        "timed out", "overloaded_error",
                        retry_after=state.retry_after_hint())
            return
        _GATE_WAITING.set(state.gate.waiting())
        try:
            self._post_completion(body, raw, deadline_ms, tenant_hdrs)
        finally:
            state.gate.release()
            _GATE_WAITING.set(state.gate.waiting())

    def _post_completion(self, body: dict, raw: bytes, deadline_ms,
                         tenant_hdrs: dict) -> None:
        state = self.state
        # kv_source is ROUTER-OWNED (docs/DISAGG.md "Trust model"): a
        # client-supplied descriptor would make the decode replica fetch
        # from an arbitrary attacker host (SSRF) and insert the result
        # into the SHARED prefix cache (cross-request poisoning) — strip
        # it at the edge unconditionally; only the planner below may
        # inject one. Durable resumes are unaffected (they re-submit the
        # journaled entry.body, which keeps the planner's descriptor).
        if "kv_source" in body:
            body = dict(body)
            body.pop("kv_source")
            raw = json.dumps(body).encode()
        # prefill/decode disaggregation (docs/DISAGG.md): split BEFORE the
        # journal opens so the injected kv_source rides the durable body —
        # a mid-stream failover's resume then re-imports from the prefill
        # replica (or falls back to a local prefill if it died too). Plan
        # failures are silent: the request routes monolithic, untouched.
        if state.disagg.enabled:
            ks = state.disagg.plan(state.membership, body, tenant_hdrs,
                                   state.affinity, state.affinity_key(body))
            if ks is not None:
                body = dict(body)
                body["kv_source"] = ks
                raw = json.dumps(body).encode()
        # trace origination (docs/OBSERVABILITY.md "Request tracing"): adopt
        # the client's W3C traceparent or start a new trace; every proxy try
        # is its own hop (fresh span id, same trace id) stamped onto the
        # upstream request, so the replica's engine spans and this router's
        # proxy span share one trace id in the merged fleet trace
        ctx = reqctx.adopt(self.headers.get("traceparent"),
                           tenant=tenant_hdrs.get("X-Tenant", ""))
        if state.durable and "resume" not in body:
            # durable path (docs/FLEET.md "Resume protocol"): journal the
            # request and survive mid-stream replica failures by resuming on
            # another replica with exactly-once splicing. A client-supplied
            # resume payload is passed through the plain path untouched (the
            # caller IS a durability layer; double-journaling would fight
            # it). A full journal degrades to the plain path too — served,
            # just not failover-protected.
            entry = state.journal.open(
                body, stream=bool(body.get("stream", False)),
                deadline_ms=deadline_ms,
                tenant=tenant_hdrs.get("X-Tenant", ""),
                klass=tenant_hdrs.get("X-Class", ""))
            if entry is not None:
                self._durable_post(entry, ctx)
                return
        self._plain_post(body, raw, ctx, deadline_ms, tenant_hdrs)

    def _plain_post(self, body: dict, raw: bytes, ctx, deadline_ms,
                    tenant_hdrs: dict | None = None):
        """The pre-durable proxy loop: verbatim pass-through, pre-first-byte
        failover only, mid-stream failures surfaced honestly."""
        state = self.state
        t0 = time.perf_counter()
        key = state.affinity_key(body)
        prefer = state.disagg.prefer_roles(body, state.membership,
                                           state.affinity, key)
        tried: set[str] = set()
        last_503: tuple[bytes, str, str | None] | None = None
        for attempt in range(1 + state.retries):
            if attempt and not state.allow_retry():
                break  # retry budget drained: shed instead of storming
            extra = dict(tenant_hdrs) if tenant_hdrs else None
            if deadline_ms is not None:
                # propagate the REMAINING budget, not the original: a retry
                # that re-sent the full deadline would let the fleet spend
                # attempts × deadline on a request the client abandoned
                rem = deadline_ms - (time.perf_counter() - t0) * 1000.0
                if rem <= 0.0:
                    self._error(408, "client deadline expired during "
                                "failover", "timeout_error")
                    return
                extra = dict(extra or {})
                extra["X-Deadline-Ms"] = str(int(rem) or 1)
            rep, reason = state.pick(key, tried, prefer)
            if rep is None:
                break
            tried.add(rep.id)
            _ROUTES.labels(reason=reason).inc()
            if attempt == 1:
                _RETRIES.inc()
            hop = ctx.child()
            with reqctx.use(hop), \
                    trace.span("router.proxy",
                               {"replica": rep.id, "reason": reason,
                                "attempt": attempt}):
                outcome, info = self._proxy_try(
                    rep, raw, key, tried, hop, extra, prefer,
                    canary=reason == "canary",
                    stream=bool(body.get("stream")))
            if outcome == "delivered" or outcome == "aborted":
                return
            if info is not None:  # a relayable 503 from this replica
                last_503 = info
        # every candidate exhausted (or rotation empty): fleet-level shed.
        # A replica's own 503 body is the most honest thing to relay; either
        # way the client ALWAYS gets Retry-After — derived from the
        # MEASURED fleet drain rate vs backlog (retry_after_hint), not a
        # constant — so it backs off in proportion to real load.
        retry_after = state.retry_after_hint()
        if last_503 is not None:
            data, ctype, ra = last_503
            self._raw(503, ctype, data,
                      {"Retry-After": ra or str(max(int(retry_after), 1))})
        else:
            self._error(503, "no replica available "
                        f"({len(tried)} tried, "
                        f"{len(state.membership.in_rotation())} in rotation)",
                        "overloaded_error", retry_after=retry_after)

    # ---------------------------------------------------- durable proxy

    def _durable_post(self, entry, ctx) -> None:
        """Journaled proxy loop (docs/FLEET.md "Resume protocol"): the
        upstream leg ALWAYS streams with in-band token journaling, whatever
        the client asked for, so every delivered token is recorded the
        moment it flows. A mid-stream replica failure re-submits the journal
        to a surviving replica with a `resume` payload; splice() gives the
        client exactly-once delivery, so the failover is invisible. The
        failover budget is `retries` tries per no-progress round — a try
        that advanced the stream resets the round (a long generation may
        outlive several replicas), so only consecutive fruitless tries give
        up."""
        try:
            self._durable_post_inner(entry, ctx)
        finally:
            # a client that dropped the connection mid-relay unwinds the
            # handler through a write error before any close() — reclaim
            # the entry (no-op after a normal close) or abandoned streams
            # would fill the journal and silently disable durability
            self.state.journal.abandon(entry)

    def _durable_post_inner(self, entry, ctx) -> None:
        state = self.state
        key = state.affinity_key(entry.body)
        prefer = state.disagg.prefer_roles(entry.body, state.membership,
                                           state.affinity, key)
        client_started = [False]
        tried: set[str] = set()
        fruitless = 0
        last_503: tuple[bytes, str, str | None] | None = None
        attempt = 0
        while fruitless <= state.retries:
            rem = entry.remaining_deadline_ms()
            if rem is not None and rem <= 0.0:
                self._durable_fail(entry, client_started, 408,
                                   "client deadline expired during failover",
                                   "timeout_error")
                return
            if attempt and not state.allow_retry():
                break  # retry budget drained: surface instead of storming
            rep, reason = state.pick(key, tried, prefer)
            if rep is None:
                break
            tried.add(rep.id)
            _ROUTES.labels(reason=reason).inc()
            if attempt == 1:
                _RETRIES.inc()
            attempt += 1
            progress0 = (len(entry.tokens), entry.sent_chars)
            hop = ctx.child()
            with reqctx.use(hop), \
                    trace.span("router.proxy",
                               {"replica": rep.id, "reason": reason,
                                "attempt": attempt - 1, "durable": True,
                                "resume_tokens": len(entry.tokens)}):
                outcome, info = self._durable_try(rep, entry, key, tried,
                                                  hop, client_started,
                                                  prefer,
                                                  canary=reason == "canary")
            if outcome in ("done", "fatal"):
                state.journal.close(
                    entry, entry.finish if outcome == "done" else "error")
                return
            if info is not None:
                last_503 = info
            if (len(entry.tokens), entry.sent_chars) != progress0:
                # the replica served this request for a while before dying:
                # new failover round — every OTHER replica is a candidate
                # again (it may have rejoined rotation since). The replica
                # to keep excluding is the one that actually SERVED the try
                # (a hedge may have won the open away from `rep`).
                fruitless = 1
                tried = {entry.replicas[-1] if entry.replicas else rep.id}
            else:
                fruitless += 1
        # candidates exhausted with no completion: surface honestly, with
        # the drain-derived backoff hint (docs/SERVING.md)
        state.journal.close(entry, "failed")
        retry_after = state.retry_after_hint()
        if client_started[0]:
            self._sse_error_event(
                f"no replica could resume the stream ({len(tried)} tried)",
                "server_error")
        elif last_503 is not None:
            data, ctype, ra = last_503
            self._raw(503, ctype, data,
                      {"Retry-After": ra or str(max(int(retry_after), 1))})
        else:
            self._error(503, "no replica available "
                        f"({len(tried)} tried, "
                        f"{len(state.membership.in_rotation())} in rotation)",
                        "overloaded_error", retry_after=retry_after)

    def _durable_try(self, rep: Replica, entry, key: bytes, tried: set,
                     hop, client_started: list, prefer=None,
                     canary: bool = False):
        """One journaled upstream try (hedged pre-first-byte via
        `_open_raced` — the journal is only ever fed from the WINNING
        response, on this handler thread, so a canceled hedge loser can
        never fold tokens in). Returns (outcome, relayable_503):
        "done" — the completion reached the client (stream terminated or
        JSON sent); "fatal" — a deterministic error was relayed, do not
        retry; "retry" — the replica failed around the request (connect,
        read, 503, or a retriable in-stream error); anything already
        delivered stays journaled for the next candidate."""
        state = self.state
        mem = state.membership
        if entry.tokens or entry.sent_chars:
            state.journal.note_resume(entry)
        headers = {"Content-Type": "application/json",
                   "X-Dllama-Journal": "1",
                   "traceparent": hop.to_traceparent()}
        # tenant identity survives failover: every try (first AND
        # resume) re-stamps the journaled tenant/class so the new
        # replica's quota/fairness accounting stays attributed
        if entry.tenant:
            headers["X-Tenant"] = entry.tenant
        if entry.klass:
            headers["X-Class"] = entry.klass
        rem = entry.remaining_deadline_ms()
        if rem is not None:
            headers["X-Deadline-Ms"] = str(max(int(rem), 1))
        payload = json.dumps(entry.upstream_body()).encode()
        t0 = time.perf_counter()
        # the durable upstream leg ALWAYS streams (X-Dllama-Journal: 1 —
        # headers arrive at the first delta even for a non-stream client),
        # so the adaptive pre-first-byte timeout applies unconditionally
        win, conn, resp = self._open_raced(rep, payload, headers, key,
                                           tried, prefer, canary=canary)
        if win is None:
            return "retry", None
        try:
            entry.replicas.append(win.id)
            if resp.status == 503:
                data = resp.read()
                _PROXY_ERRORS.labels(kind="status_503").inc()
                if b"server_shutting_down" in data or b"draining" in data:
                    win.draining = True
                ra = resp.getheader("Retry-After")
                self._note_retry_after(win, ra)
                return "retry", (data,
                                 resp.getheader("Content-Type",
                                                "application/json"),
                                 ra)
            ctype = resp.getheader("Content-Type", "")
            if "text/event-stream" not in ctype:
                # pre-stream deterministic error (400/408...): relay with
                # its real status — resuming a caller error elsewhere would
                # fail identically (the replica validated the journal body)
                try:
                    data = resp.read()
                except Exception:
                    _PROXY_ERRORS.labels(kind="read").inc()
                    mem.mark_failed(win)
                    return "retry", None
                if client_started[0]:
                    self._sse_error_event(
                        f"replica {win.id} refused the resume with status "
                        f"{resp.status}", "server_error")
                else:
                    extra = {h: v for h in self._RELAY_HEADERS
                             if (v := resp.getheader(h))}
                    self._raw(resp.status, ctype or "application/json",
                              data, extra or None)
                return "fatal", None
            outcome = self._durable_relay(win, entry, resp, client_started,
                                          key)
            if outcome == "done":
                _PROXY_SECONDS.observe(time.perf_counter() - t0)
            return outcome, None
        finally:
            conn.close()
            mem.inflight_dec(win)
            _INFLIGHT.labels(replica=win.id).set(win.inflight)

    def _durable_relay(self, rep: Replica, entry, resp,
                       client_started: list, key: bytes):
        """Parse the upstream SSE stream event-by-event, fold token journal
        fields into the entry, splice content past what the client already
        has, and relay. The upstream counts content from generated-token
        zero (a resumed replica re-emits the delivered prefix), so splicing
        is a pure cumulative-position comparison. The affinity record for
        `key` lands BEFORE the final client write: a client that reads the
        completion and immediately consults routing state (tests, a
        follow-up request on a warm connection) must observe the route."""
        up_chars = 0
        saw_done = False
        events = iter_sse_data(resp)
        t_last = time.perf_counter()
        while True:
            try:
                data = next(events)
            except StopIteration:
                break
            except Exception:
                # includes the idle-gap socket timeout: a wedged replica
                # stops producing and the durable path resumes elsewhere
                _PROXY_ERRORS.labels(kind="read").inc()
                self.state.membership.mark_failed(rep)
                return "retry"
            now = time.perf_counter()
            self.state.note_pace(rep, now - t_last)  # idle-gap evidence
            t_last = now
            if data == "[DONE]":
                saw_done = True
                break
            payload = parse_chunk(data)
            if payload is None:
                continue
            if "error" in payload:
                err = payload.get("error") or {}
                if err.get("retriable"):
                    # the replica failed AROUND the request (wedged engine,
                    # drain, engine-scope fault) and says so: resume
                    # elsewhere; nothing new reached the client this event
                    _PROXY_ERRORS.labels(kind="upstream_retriable").inc()
                    return "retry"
                if client_started[0] or entry.stream:
                    self._durable_start_stream(entry, resp, client_started)
                    self._sse_error_event(
                        err.get("message", "upstream error"),
                        err.get("type", "server_error"))
                else:
                    self._error(int(err.get("code") or 500),
                                err.get("message", "upstream error"),
                                err.get("type", "server_error"))
                return "fatal"
            if "dllama" in payload:
                entry.record_tokens(payload.pop("dllama"))
            if entry.completion_id is None:
                entry.completion_id = payload.get("id")
            if entry.model is None:
                entry.model = payload.get("model")
            choices = payload.get("choices") or [{}]
            delta = choices[0].get("delta") or {}
            text = delta.get("content") or ""
            finish = choices[0].get("finish_reason")
            new = ""
            if text:
                up_chars += len(text)
                new = entry.splice(text, up_chars)
            if new or finish is not None:
                if not entry.stream:
                    if new:
                        entry.parts.append(new)
                    if finish is not None:
                        entry.finish = finish
                    continue
                self._durable_start_stream(entry, resp, client_started)
                payload["id"] = entry.completion_id or payload.get("id")
                delta["content"] = new
                if not new:
                    delta.pop("content", None)
                if finish is not None:
                    entry.finish = finish
                self._write_chunk(
                    f"data: {json.dumps(payload)}\n\n".encode())
        if entry.finish is None and not saw_done:
            # the stream ended without a finish chunk or [DONE]: the replica
            # died mid-stream (or produced a malformed empty stream) — the
            # journal holds everything delivered; resume elsewhere
            _PROXY_ERRORS.labels(
                kind="empty_stream" if up_chars == 0 else "read").inc()
            self.state.membership.mark_failed(rep)
            return "retry"
        self.state.affinity.record(key, rep.id)  # happens-before completion
        if entry.stream:
            # zero-delta completions still stream (parity with api_server)
            self._durable_start_stream(entry, resp, client_started)
            self._write_chunk(b"data: [DONE]\n\n")
            self._write_chunk(b"")
        else:
            extra = {h: v for h in self._RELAY_HEADERS
                     if (v := resp.getheader(h))}
            self._json(200, {
                "id": entry.completion_id or "chatcmpl-durable",
                "object": "chat.completion",
                "created": int(time.time()),
                "model": entry.model or "distributed-llama-tpu",
                "choices": [{
                    "index": 0,
                    "message": {"role": "assistant",
                                "content": "".join(entry.parts)},
                    "finish_reason": entry.finish or "stop",
                }],
            }, extra or None)
        self.state.note_done()  # feeds the drain-derived Retry-After
        return "done"

    def _durable_start_stream(self, entry, resp, client_started: list):
        if client_started[0]:
            return
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Transfer-Encoding", "chunked")
        for h in self._RELAY_HEADERS:
            v = resp.getheader(h)
            if v:
                self.send_header(h, v)
        self.end_headers()
        self._count(200)
        client_started[0] = True

    def _sse_error_event(self, message: str, etype: str) -> None:
        """Honest mid-stream termination (client already has bytes)."""
        self._write_chunk(
            ("data: " + json.dumps({"error": {
                "message": message, "type": etype}}) + "\n\n").encode())
        self._write_chunk(b"data: [DONE]\n\n")
        self._write_chunk(b"")

    def _durable_fail(self, entry, client_started: list, code: int,
                      message: str, etype: str) -> None:
        self.state.journal.close(entry, "failed")
        if client_started[0]:
            self._sse_error_event(message, etype)
        else:
            self._error(code, message, etype)

    # ------------------------------------------------------------ proxy

    # Retry-After rides along so a replica's own 429 (tenant quota) and
    # other backoff-bearing statuses keep their hint through the proxy
    _RELAY_HEADERS = ("X-Request-Id", "X-Replica", "Retry-After")

    def _open_raced(self, primary: Replica, payload: bytes, headers: dict,
                    key: bytes, tried: set, prefer, canary: bool = False,
                    stream: bool = True):
        """Pre-first-byte phase of one failover round, with bounded hedging
        (docs/FLEET.md "Gray-failure resilience"): open the upstream leg
        (connect -> request -> response headers) against `primary` under the
        ADAPTIVE pre-first-byte timeout; if no headers arrive within the
        hedge delay (~fleet TTFB p95) and the hedge token budget allows,
        race one duplicate open on a different replica — first headers win,
        the loser is closed before any body byte of it is read. The
        pre-first-byte phase is idempotent (PR 6/9 semantics), so the
        duplicate can never double-deliver; the budget caps hedge spend so
        hedging can never melt an overloaded fleet.

        A 503 is a REFUSAL, not a first byte: while a rival attempt is
        still in flight it is stashed as the round's fallback instead of
        crowning it — a saturated hedge target must not cancel a primary
        that is about to deliver. It is promoted to winner only when no
        attempt produced a real response (the caller then relays/cools it
        exactly as before).

        Returns (winner, conn, resp) — winner None when every attempt
        failed (each already mark_failed + counted). The winner's id and
        every failed attempt's id are added to `tried` (handler thread
        only); the winner's inflight count stays held for the caller's
        relay (released in the caller's finally), losers release their own.
        The winner's socket is switched to the idle-gap timeout before
        return, so each body read may block at most idle_timeout(). When
        no hedge can possibly arm this round (delay None), the open runs
        INLINE on the handler thread — the common no-hedging path pays no
        thread spawn or cv polling."""
        state = self.state
        mem = state.membership
        # the ADAPTIVE pre-first-byte timeout is a STREAMING instrument:
        # api_server defers stream headers to the first delta, so stream
        # TTFB is genuinely first-byte time. A non-streaming response's
        # first byte only arrives after the FULL generation — judging it
        # by the fleet's (stream-dominated) TTFB p95 would kill every
        # legitimately long non-stream completion, so those keep the cap
        # (the pre-adaptive fixed behavior)
        if stream:
            ttfb_to = state.ttfb_timeout()
        else:
            g = state.gray
            ttfb_to = (g.ttfb_cap if g.ttfb_cap is not None
                       else state.try_timeout)
        idle_to = state.idle_timeout()
        state.hedge_budget.note()  # budget accrues per round, spent per hedge
        cv = threading.Condition()
        race = {"win": None, "soft": None, "lost": 0, "failed_ids": [],
                "started": 1, "hedge_id": None}

        def settled() -> bool:
            # a stashed 503 settles the round only once no rival is left
            return (race["win"] is not None
                    or race["lost"] + (1 if race["soft"] else 0)
                    >= race["started"])

        def attempt(rep: Replica) -> None:
            mem.inflight_inc(rep)
            _INFLIGHT.labels(replica=rep.id).set(rep.inflight)
            won = False
            held = False
            conn = None
            t0 = time.perf_counter()
            try:
                faults.fire("router.proxy", replica=rep.id)
                conn = HTTPConnection(rep.host, rep.port, timeout=ttfb_to)
                conn.request("POST", self.path, payload, headers)
                resp = conn.getresponse()
            except Exception:
                elapsed = time.perf_counter() - t0
                if stream and elapsed >= 0.9 * ttfb_to:
                    # timeout-shaped failure: record the CENSORED latency
                    # (at least this slow) so a replica whose tries never
                    # finish still accumulates outlier evidence for the
                    # detector; connect refusals fail fast and are not
                    # latency samples
                    state.note_ttfb(rep, elapsed, ok=False)
                _PROXY_ERRORS.labels(kind="connect").inc()
                mem.mark_failed(rep)
                if conn is not None:
                    try:
                        conn.close()
                    except Exception:
                        pass
                with cv:
                    race["lost"] += 1
                    race["failed_ids"].append(rep.id)
                    cv.notify_all()
            else:
                if resp.status >= 400:
                    # an error/refusal is NOT a first byte: no TTFB
                    # evidence (a fast 503/429 would mask a slow replica,
                    # drag the adaptive timeout down during overload, and
                    # walk a still-slow replica out of probation); a 503
                    # canary resets the rejoin streak — saturated is not
                    # recovered. Other errors say nothing about latency.
                    if resp.status == 503 and rep.degraded:
                        rep.canary_note(False)
                elif stream:
                    # only STREAM first-byte times are service-latency
                    # evidence — a non-stream try's headers arrive after
                    # the full generation and would read as an outlier
                    state.note_ttfb(rep, time.perf_counter() - t0)
                with cv:
                    if race["win"] is None and resp.status < 400:
                        race["win"] = (rep, conn, resp)
                        won = True
                        # switch to the idle-gap timeout INSIDE the critical
                        # section: the handler thread cannot observe the win
                        # (and start relaying / closing the conn) until cv
                        # is released, so this can never race conn.close()
                        if conn.sock is not None:
                            conn.sock.settimeout(idle_to)
                    elif race["win"] is None and race["soft"] is None:
                        # an error while a rival may still deliver: stash,
                        # do not crown (a refusing hedge target must not
                        # cancel a viable primary with a 503/429 the
                        # primary would never have issued); the handler
                        # promotes or releases it
                        race["soft"] = (rep, conn, resp)
                        held = True
                    else:
                        race["lost"] += 1
                        if resp.status == 503:
                            # an uncrowned refusal still means "saturated":
                            # cool the replica down and exclude it from
                            # this round's remaining failover candidates
                            race["failed_ids"].append(rep.id)
                    cv.notify_all()
                if not won and not held and resp.status == 503:
                    self._note_retry_after(rep,
                                           resp.getheader("Retry-After"))
                if won:
                    if rep.id == race["hedge_id"]:
                        _HEDGES.labels(outcome="won").inc()
                elif not held:
                    try:  # lost the race; nothing of it was relayed
                        conn.close()
                    except Exception:
                        pass
            finally:
                if not won and not held:
                    mem.inflight_dec(rep)
                    _INFLIGHT.labels(replica=rep.id).set(rep.inflight)

        # hedging is also a STREAMING instrument here: the delay derives
        # from stream first-byte times, and hedging a non-stream try whose
        # generation simply outlasts that delay would systematically
        # duplicate the longest generations (the always-streaming durable
        # leg — the default path — still hedges non-stream CLIENTS)
        delay = state.hedge_delay() if stream else None
        if canary and state.gray.hedge and stream:
            # a canary pick deliberately routes INTO a known-slow replica:
            # hedge it almost immediately and OUTSIDE the budget (the
            # canary rate is already bounded by canary_every), so probation
            # probing never costs the client the victim's latency — the
            # canary attempt still completes and records its outcome as
            # the race loser
            delay = max(state.gray.hedge_floor, 0.05)
        if delay is None:
            # hedging cannot arm this round: open inline, no thread spawn
            attempt(primary)
        else:
            threading.Thread(target=attempt, args=(primary,), daemon=True,
                             name="proxy-try").start()
        with cv:
            if delay is not None:
                cv.wait_for(settled, timeout=delay)
                if not settled():
                    # primary quiet past the hedge delay: try to race it
                    hedge, _ = state.pick(key, tried | {primary.id}, prefer)
                    if hedge is not None and hedge.id != primary.id:
                        if canary or state.hedge_budget.spend():
                            race["hedge_id"] = hedge.id
                            race["started"] += 1
                            _HEDGES.labels(
                                outcome="canary" if canary
                                else "launched").inc()
                            threading.Thread(target=attempt, args=(hedge,),
                                             daemon=True,
                                             name="proxy-hedge").start()
                        else:
                            _HEDGES.labels(outcome="denied").inc()
            while not settled():
                cv.wait(timeout=1.0)
            win = race["win"]
            soft = race["soft"]
            failed_ids = list(race["failed_ids"])
        if win is None and soft is not None:
            win = soft  # every rival failed: the 503 is the round's answer
        elif soft is not None:
            # a real winner emerged; release the stashed error — but a
            # 503 still means "saturated": honor the cooldown and exclude
            # the replica from this round's remaining candidates
            rep_s, conn_s, resp_s = soft
            if resp_s.status == 503:
                self._note_retry_after(rep_s,
                                       resp_s.getheader("Retry-After"))
                tried.add(rep_s.id)
            try:
                conn_s.close()
            except Exception:
                pass
            mem.inflight_dec(rep_s)
            _INFLIGHT.labels(replica=rep_s.id).set(rep_s.inflight)
        for rid in failed_ids:
            tried.add(rid)
        if win is None:
            return None, None, None
        tried.add(win[0].id)
        return win

    def _note_retry_after(self, rep: Replica, ra_header) -> None:
        """Honor a replica 503's Retry-After as a pick() cooldown: the
        failover loop must not immediately re-hammer a replica that just
        said it was saturated (absent/garbage headers read as 1 s)."""
        try:
            secs = float(ra_header) if ra_header else 1.0
        except (TypeError, ValueError):
            secs = 1.0
        rep.note_retry_after(secs)
        _RETRY_AFTER_HONORED.inc()

    def _proxy_try(self, rep: Replica, raw: bytes, key: bytes, tried: set,
                   hop=None, extra_headers: dict | None = None, prefer=None,
                   canary: bool = False, stream: bool = True):
        """One proxy attempt against `rep` (plus, past the hedge delay, a
        budget-bounded duplicate on another replica — `_open_raced`).
        Returns (outcome, relayable): outcome "delivered" (response fully
        relayed), "aborted" (failed after client bytes — already
        terminated, never retry), or "retry" (nothing reached the client;
        relayable = (body, ctype, retry_after) when the failure was a
        replica 503 worth relaying). `hop` is this try's trace context,
        stamped upstream as `traceparent`; the replica's
        X-Request-Id/X-Replica response headers are relayed so the client
        can reach GET /v1/requests/<id> on the serving replica.
        `extra_headers` carries per-try headers (remaining X-Deadline-Ms)."""
        state = self.state
        mem = state.membership
        headers = {"Content-Type": "application/json"}
        if extra_headers:
            headers.update(extra_headers)
        if hop is not None:
            headers["traceparent"] = hop.to_traceparent()
        t0 = time.perf_counter()
        win, conn, resp = self._open_raced(rep, raw, headers, key, tried,
                                           prefer, canary=canary,
                                           stream=stream)
        if win is None:
            return "retry", None
        try:
            if resp.status == 503:
                # shed (overloaded, Retry-After) or drain — in both cases
                # another replica may serve this request right now. Reflect
                # a drain in membership immediately; the poller confirms.
                # Either way honor the Retry-After as a pick() cooldown so
                # failover doesn't re-hammer the saturated replica.
                data = resp.read()
                _PROXY_ERRORS.labels(kind="status_503").inc()
                if b"server_shutting_down" in data or b"draining" in data:
                    win.draining = True
                ra = resp.getheader("Retry-After")
                self._note_retry_after(win, ra)
                return "retry", (data,
                                 resp.getheader("Content-Type",
                                                "application/json"),
                                 ra)
            ctype = resp.getheader("Content-Type", "application/json")
            if "text/event-stream" in ctype:
                return self._relay_stream(win, resp, key)
            # non-streaming (includes pre-stream errors with real status
            # codes — api_server defers SSE headers to the first delta, so a
            # 400/408 arrives here as plain JSON): relay verbatim, no retry
            # of non-503 errors (they are deterministic caller errors). A
            # body-read failure is retriable — nothing reached the client,
            # completions are idempotent until output is delivered.
            try:
                data = resp.read()
            except Exception:
                _PROXY_ERRORS.labels(kind="read").inc()
                mem.mark_failed(win)
                return "retry", None
            extra = {h: v for h in self._RELAY_HEADERS
                     if (v := resp.getheader(h))}
            if resp.status == 200:
                # record BEFORE relaying: the client must not observe the
                # completion while the route is still unrecorded
                state.affinity.record(key, win.id)
            self._raw(resp.status, ctype, data, extra or None)
            if resp.status == 200:
                _PROXY_SECONDS.observe(time.perf_counter() - t0)
                state.note_done()  # feeds the drain-derived Retry-After
            return "delivered", None
        finally:
            conn.close()
            mem.inflight_dec(win)
            _INFLIGHT.labels(replica=win.id).set(win.inflight)

    def _relay_stream(self, rep: Replica, resp, key: bytes):
        """SSE pass-through. Client headers are deferred to the first
        upstream byte so an upstream that dies before producing anything is
        still retryable on another replica."""
        state = self.state
        sent_any = False
        t0 = time.perf_counter()
        t_last = t0
        while True:
            try:
                chunk = resp.read1(65536)
                now = time.perf_counter()
                state.note_pace(rep, now - t_last)  # idle-gap evidence
                t_last = now
            except Exception:
                # includes the idle-gap socket timeout (a mid-stream wedge);
                # without durable routing the only honest move after bytes
                # flowed is the SSE error below
                _PROXY_ERRORS.labels(kind="read").inc()
                if not sent_any:
                    state.membership.mark_failed(rep)
                    return "retry", None
                # mid-stream: the client already has partial output — a
                # retry would double-deliver. Honest termination instead.
                self._sse_error_event(
                    f"upstream replica {rep.id} failed mid-stream",
                    "server_error")
                return "aborted", None
            if not chunk:
                break
            if not sent_any:
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Transfer-Encoding", "chunked")
                for h in self._RELAY_HEADERS:
                    v = resp.getheader(h)
                    if v:
                        self.send_header(h, v)
                self.end_headers()
                self._count(200)
                sent_any = True
            self._write_chunk(chunk)
        if not sent_any:
            # 200 event-stream with an empty body is a malformed upstream;
            # nothing reached the client, so another replica may try
            _PROXY_ERRORS.labels(kind="empty_stream").inc()
            return "retry", None
        # record BEFORE the stream terminator: the client must not be able
        # to observe completion while the route is still unrecorded
        state.affinity.record(key, rep.id)
        self._write_chunk(b"")  # terminate the chunked response
        _PROXY_SECONDS.observe(time.perf_counter() - t0)
        state.note_done()  # feeds the drain-derived Retry-After
        return "delivered", None

    def _write_chunk(self, data: bytes):
        self.wfile.write(f"{len(data):X}\r\n".encode() + data + b"\r\n")
        self.wfile.flush()


# ----------------------------------------------------------------------
# server plumbing
# ----------------------------------------------------------------------

def serve_router(replicas: list[str], host: str = "0.0.0.0",
                 port: int = 9900, policy: str = "affinity",
                 poll_interval: float = 2.0, poll_timeout: float = 2.0,
                 block_bytes: int = 64, affinity_nodes: int = 8192,
                 retries: int = 2, try_timeout: float = 120.0,
                 seed: int = 0, durable: bool = True,
                 tenants: "TenantRegistry | str | None" = None,
                 max_inflight: int = 0,
                 gate_timeout: float = 30.0,
                 disagg_threshold: int = 0,
                 disagg_timeout: float = 60.0,
                 gray: GrayConfig | None = None) -> ThreadingHTTPServer:
    """Build + bind the router (does NOT serve_forever — caller's thread
    choice). Membership is polled once synchronously so the first request
    already has a rotation. `server.router_state` exposes the state.
    `durable=False` reverts completions to the PR-6 verbatim pass-through
    (mid-stream failures surfaced, not resumed). `tenants` (a registry or
    the parseable spec string) enables router-level quotas; `max_inflight`
    > 0 arms the weighted-fair inflight gate (docs/SERVING.md
    "Multi-tenant serving"). `gray` tunes the gray-failure resilience layer
    (probation, hedging, adaptive timeouts, retry budget — docs/FLEET.md
    "Gray-failure resilience"); None = GrayConfig() defaults."""
    if isinstance(tenants, str):
        tenants = TenantRegistry.parse(tenants) if tenants else None
    membership = Membership(replicas, poll_interval=poll_interval,
                            poll_timeout=poll_timeout)
    state = RouterState(membership, policy=policy, block_bytes=block_bytes,
                        affinity_nodes=affinity_nodes, retries=retries,
                        try_timeout=try_timeout, seed=seed, durable=durable,
                        tenants=tenants, max_inflight=max_inflight,
                        gate_timeout=gate_timeout,
                        disagg_threshold=disagg_threshold,
                        disagg_timeout=disagg_timeout, gray=gray)
    # probation entry runs on the poll thread; the detector must be attached
    # BEFORE the synchronous first poll inside start()
    membership.detector = state.detector
    membership.start()
    handler = type("BoundRouterHandler", (RouterHandler,),
                   {"state": state, "protocol_version": "HTTP/1.1"})
    server = QuietServer((host, port), handler)
    server.router_state = state
    install_process_metrics()  # uptime/RSS/threads/build info on /metrics
    trace.set_process_name(f"router {host}:{server.server_address[1]}")
    print(f"🟢 fleet router listening on {host}:{server.server_address[1]} "
          f"({len(membership.replicas)} replicas, policy={policy})")
    return server


def close_router(server: ThreadingHTTPServer) -> None:
    """Stop serving and the membership poller (idempotent)."""
    server.shutdown()
    server.server_close()
    server.router_state.membership.stop()
