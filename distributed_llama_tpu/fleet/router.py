"""Fleet router: cache-affinity HTTP front for N api_server replicas.

One dependency-free process (stdlib http only, same discipline as
apps/api_server.py) that turns the single-replica serving stack into a
horizontal fleet:

- **routing** — `pick()` prefers the replica whose recent routes share the
  longest byte-block prefix with the request (fleet/affinity.py over the
  cache/radix.py trie), so shared system prompts hit the replica whose
  prefix cache already holds their KV; misses fall back to least-loaded by
  the polled queue-depth/free-slot load block plus the router's own
  in-flight counts. `policy="random"` is the A/B control
  (`bench.py --routing random`).
- **proxying** — streaming SSE and non-streaming bodies pass through
  verbatim with a per-try socket timeout. A try that fails BEFORE the first
  byte reaches the client (connect error, injected `router.proxy` fault,
  replica 503) retries on a different replica — completions are idempotent
  until output is delivered — bounded by `retries`; once bytes have flowed
  the failure is surfaced as an SSE error event, never a silent re-issue.
  When every candidate is exhausted or the rotation is empty the client
  gets 503 + Retry-After (the fleet-level analog of the replica's
  admission-control shed).
- **observability** — `GET /metrics` merges every replica's Prometheus
  exposition under a `replica="host:port"` label with the router's own
  counters (routes by reason, proxy errors, per-replica inflight);
  `GET /v1/stats` serves the JSON equivalent; `GET /healthz` reports
  rotation so the router itself can sit behind a dumb L4 balancer.

Topology/flags: docs/FLEET.md. Entry point: apps/router.py.
"""

from __future__ import annotations

import json
import random
import threading
import time
from http.client import HTTPConnection
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs

from ..obs import metrics, reqctx, trace
from ..obs.process import install_process_metrics
from ..resilience import faults
from ..resilience.errors import QuotaExceeded
from ..resilience.quiet_http import QuietServer
from ..resilience.tenancy import (DrainRate, FairGate, TenantRegistry,
                                  sanitize_tenant)
from .affinity import AffinityMap
from .disagg import DisaggPlanner
from .journal import RequestJournal, iter_sse_data, parse_chunk
from .membership import Membership, Replica

__all__ = ["RouterState", "serve_router", "close_router", "merge_prometheus",
           "fleet_trace"]

_ROUTES = metrics.counter(
    "router_routes_total",
    "Requests routed, by decision reason (docs/FLEET.md)",
    labelnames=("reason",))
_PROXY_ERRORS = metrics.counter(
    "router_proxy_errors_total", "Proxy-path failures by kind",
    labelnames=("kind",))
_INFLIGHT = metrics.gauge(
    "router_replica_inflight", "Router-side in-flight proxies per replica",
    labelnames=("replica",))
_HTTP = metrics.counter(
    "router_http_requests_total", "Router HTTP responses by route and code",
    labelnames=("route", "code"))
_RETRIES = metrics.counter(
    "router_retried_requests_total",
    "Requests that needed at least one failover try")
_SCRAPE_ERRORS = metrics.counter(
    "router_scrape_errors_total",
    "Replica /metrics//v1/stats fetches that failed during aggregation")
_PROXY_SECONDS = metrics.histogram(
    "router_proxy_seconds", "Per-try proxy wall time (successful tries)")
# Multi-tenant policy at the fleet edge (docs/SERVING.md "Multi-tenant
# serving"): router-level quota throttles and fairness-gate sheds. Labels
# stay bounded — unknown tenant ids collapse to the canonical "default".
_THROTTLED = metrics.counter(
    "router_throttled_total",
    "Requests refused with 429: the tenant's router-level token bucket "
    "was exhausted", labelnames=("tenant",))
_GATE_SHED = metrics.counter(
    "router_gate_shed_total",
    "Requests shed because the weighted-fair inflight gate "
    "(--max-inflight) stayed full past the gate timeout")
_GATE_WAITING = metrics.gauge(
    "router_gate_waiting",
    "Handler threads currently parked in the weighted-fair inflight gate")
_DRAIN_RATE = metrics.gauge(
    "router_drain_rate",
    "Measured fleet completions/sec through this router (decayed EMA) — "
    "the denominator of the router's drain-derived Retry-After hints")

_KNOWN_ROUTES = ("/v1/chat/completions", "/chat/completions", "/v1/models",
                 "/v1/stats", "/metrics", "/health", "/healthz", "/v1/trace",
                 "/v1/requests")


class RouterState:
    def __init__(self, membership: Membership, policy: str = "affinity",
                 block_bytes: int = 64, affinity_nodes: int = 8192,
                 retries: int = 2, try_timeout: float = 120.0,
                 scrape_timeout: float = 3.0, key_bytes: int = 4096,
                 seed: int = 0, durable: bool = True,
                 journal_inflight: int = 4096,
                 tenants: TenantRegistry | None = None,
                 max_inflight: int = 0, gate_timeout: float = 30.0,
                 disagg_threshold: int = 0, disagg_timeout: float = 60.0):
        assert policy in ("affinity", "random"), policy
        self.membership = membership
        # prefill/decode disaggregation (docs/DISAGG.md): when the threshold
        # is armed, long-prompt completions run their prefill on a
        # prefill-capable replica, whose KV blocks the decode replica then
        # imports — and routing becomes role-aware (short chains prefer
        # decode replicas, unsplit long prompts prefer prefill ones)
        self.disagg = DisaggPlanner(disagg_threshold, timeout=disagg_timeout)
        # Multi-tenant fleet edge (docs/SERVING.md "Multi-tenant serving"):
        # optional router-level token-bucket quotas (429 before any proxy
        # work) and a weighted-fair inflight gate replacing the implicit
        # FIFO of handler-thread scheduling — when `max_inflight` > 0,
        # concurrent completion proxies are bounded and contended capacity
        # is handed out interactive-first, tenants by weight. The drain
        # estimator feeds every fleet-saturation Retry-After hint (measured
        # completions/sec vs depth, never the poll-interval constant).
        self.tenants = tenants
        self.gate = FairGate(max_inflight, tenants)
        self.gate_timeout = gate_timeout
        self.drain = DrainRate()
        self.affinity = AffinityMap(block_bytes=block_bytes,
                                    max_nodes=affinity_nodes)
        self.policy = policy
        self.retries = max(retries, 0)
        self.try_timeout = try_timeout
        self.scrape_timeout = scrape_timeout
        self.key_bytes = key_bytes
        # durable requests (docs/FLEET.md "Resume protocol"): journal every
        # in-flight completion so a mid-stream replica failure is survived by
        # resuming on another replica instead of surfaced as an SSE error
        self.durable = durable
        self.journal = RequestJournal(max_inflight=journal_inflight)
        self._rng = random.Random(seed)
        self._rr = 0  # round-robin clock for least-loaded ties
        self._lock = threading.Lock()  # guards: _rng, _rr

    # ------------------------------------------------------------------
    # routing decision
    # ------------------------------------------------------------------

    def affinity_key(self, body: dict) -> bytes:
        """Deterministic byte key of the prompt prefix: the messages in
        render order, role and content separated by sentinels so
        ("ab","c") cannot collide with ("a","bc"). Capped — affinity only
        needs the leading blocks, not the whole conversation."""
        parts = []
        for m in body.get("messages", []):
            if not isinstance(m, dict):
                continue
            parts.append(str(m.get("role", "user")).encode("utf-8", "replace")
                         + b"\x00"
                         + str(m.get("content", "")).encode("utf-8", "replace")
                         + b"\x1e")
            if sum(len(p) for p in parts) >= self.key_bytes:
                break
        return b"".join(parts)[:self.key_bytes]

    def pick(self, key: bytes, tried: set[str],
             prefer_roles: tuple | None = None
             ) -> tuple[Replica | None, str]:
        """(replica, reason) for the next try; (None, "saturated") when no
        routable replica remains. Reasons: affinity | least_loaded | random
        on the first try, failover afterwards. `prefer_roles` (docs/
        DISAGG.md) narrows the candidates to replicas advertising one of
        those roles when any match — a SOFT preference: an empty match
        falls back to the whole rotation, because roles are routing
        affinities, not capabilities, and serving beats shedding."""
        rotation = [r for r in self.membership.in_rotation()
                    if r.id not in tried]
        if not rotation:
            return None, "saturated"
        if prefer_roles is not None:
            preferred = [r for r in rotation if r.role in prefer_roles]
            if preferred:
                rotation = preferred
        if tried:
            return min(rotation, key=Replica.load_score), "failover"
        if self.policy == "random":
            with self._lock:
                return self._rng.choice(rotation), "random"
        rep_id, _depth = self.affinity.lookup(key, {r.id for r in rotation})
        if rep_id is not None:
            return self.membership.by_id(rep_id), "affinity"
        # cold prefix: least-loaded, with ROUND-ROBIN among load ties — a
        # fixed tie-break (e.g. lowest id) would send every cold prefix of a
        # quiet fleet to one replica, and affinity would then pin all their
        # future traffic there too (observed: one replica served ~everything
        # until the fleet warmed unevenly into saturation)
        load = lambda r: (r.queue_depth + r.inflight, -r.free_slots)  # noqa: E731
        best = min(load(r) for r in rotation)
        ties = [r for r in rotation if load(r) == best]
        with self._lock:
            pick = ties[self._rr % len(ties)]
            self._rr += 1
        return pick, "least_loaded"

    def note_done(self) -> None:
        """One completion fully relayed: feed the drain estimator (the
        denominator of every fleet-saturation Retry-After hint)."""
        self.drain.note()
        _DRAIN_RATE.set(self.drain.rate())

    def retry_after_hint(self) -> float:
        """Drain-derived Retry-After for fleet-saturation refusals: the
        measured time for the fleet to work off its current backlog
        (polled queue depth + router in-flight across replicas, plus gate
        waiters), floored and capped (resilience/tenancy.py DrainRate) —
        the header tracks load instead of relaying the membership
        poll-interval constant."""
        depth = sum(r.queue_depth + r.inflight
                    for r in self.membership.replicas) + self.gate.waiting()
        return self.drain.retry_after(depth + 1)


# ----------------------------------------------------------------------
# Prometheus merge
# ----------------------------------------------------------------------

def _inject_label(sample: str, label: str) -> str:
    """Add `label` (e.g. replica="h:p") to one exposition sample line."""
    brace = sample.find("{")
    space = sample.find(" ")
    if brace != -1 and (space == -1 or brace < space):
        return sample[:brace + 1] + label + "," + sample[brace + 1:]
    return sample[:space] + "{" + label + "}" + sample[space:]


def merge_prometheus(texts: list[tuple[str | None, str]]) -> str:
    """Merge expositions into one: `texts` is [(replica id or None, text)].
    Samples from labeled sources get `replica="<id>"` injected; HELP/TYPE
    headers are emitted once per family (first source wins). Families are
    attributed by the running header like our own renderer emits them, with
    a name-prefix fallback for any foreign layout."""
    families: dict[str, dict] = {}
    order: list[str] = []

    def fam_for(name: str) -> dict:
        if name not in families:
            families[name] = {"help": None, "type": None, "samples": []}
            order.append(name)
        return families[name]

    for rep_id, text in texts:
        label = f'replica="{rep_id}"' if rep_id is not None else None
        current: str | None = None
        for line in text.splitlines():
            if not line.strip():
                continue
            if line.startswith("# HELP ") or line.startswith("# TYPE "):
                kind = "help" if line[2] == "H" else "type"
                rest = line[7:].split(" ", 1)
                current = rest[0]
                fam = fam_for(current)
                if fam[kind] is None:
                    fam[kind] = rest[1] if len(rest) > 1 else ""
                continue
            if line.startswith("#"):
                continue
            mname = line.split("{", 1)[0].split(" ", 1)[0]
            name = (current if current is not None and mname.startswith(current)
                    else mname)
            fam_for(name)["samples"].append(
                _inject_label(line, label) if label else line)
    out = []
    for name in order:
        fam = families[name]
        if fam["help"] is not None:
            out.append(f"# HELP {name} {fam['help']}")
        if fam["type"] is not None:
            out.append(f"# TYPE {name} {fam['type']}")
        out.extend(fam["samples"])
    return "\n".join(out) + "\n"


def _fetch(rep: Replica, path: str, timeout: float) -> tuple[int, bytes]:
    conn = HTTPConnection(rep.host, rep.port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def _scrape_all(state: RouterState, path: str) -> list[tuple[Replica, object]]:
    """Fetch `path` from every replica CONCURRENTLY (one thread each, joined
    at scrape_timeout): a serial loop would block an aggregation request up
    to scrape_timeout PER unreachable replica — exactly during the rolling
    restarts and incidents monitoring exists for. Returns (replica, result)
    pairs where result is (status, body) or the raised exception."""
    results: list = [None] * len(state.membership.replicas)

    def fetch(i: int, rep: Replica) -> None:
        try:
            results[i] = _fetch(rep, path, state.scrape_timeout)
        except Exception as e:
            results[i] = e

    threads = [threading.Thread(target=fetch, args=(i, rep), daemon=True)
               for i, rep in enumerate(state.membership.replicas)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + state.scrape_timeout + 1.0
    for t in threads:
        t.join(timeout=max(deadline - time.monotonic(), 0.0))
    out = []
    for rep, res in zip(state.membership.replicas, results):
        out.append((rep, res if res is not None
                    else TimeoutError("scrape timed out")))
    return out


def fleet_metrics(state: RouterState) -> str:
    """Router-own exposition + every reachable replica's, replica-labeled."""
    texts: list[tuple[str | None, str]] = [(None, metrics.render())]
    for rep, res in _scrape_all(state, "/metrics"):
        if isinstance(res, tuple) and res[0] == 200:
            texts.append((rep.id, res[1].decode("utf-8", "replace")))
        else:
            _SCRAPE_ERRORS.inc()
    return merge_prometheus(texts)


def fleet_stats(state: RouterState) -> dict:
    out = {
        "time": int(time.time()),
        "router": {
            "policy": state.policy,
            "affinity_nodes": state.affinity.nodes(),
            "replicas": {r.id: r.snapshot()
                         for r in state.membership.replicas},
            "metrics": metrics.snapshot(),
        },
        "replicas": {},
    }
    for rep, res in _scrape_all(state, "/v1/stats"):
        if isinstance(res, tuple):
            status, body = res
            try:
                # a 200 with a non-JSON body (wrong process on the port, an
                # LB error page) must degrade to THIS replica's error entry,
                # not crash the whole aggregation
                out["replicas"][rep.id] = (json.loads(body) if status == 200
                                           else {"error": f"status {status}"})
            except ValueError as e:
                _SCRAPE_ERRORS.inc()
                out["replicas"][rep.id] = {"error": f"non-JSON body: {e}"}
        else:
            _SCRAPE_ERRORS.inc()
            out["replicas"][rep.id] = {"error": repr(res)}
    return out


def fleet_trace(state: RouterState) -> dict:
    """GET /v1/trace: ONE Perfetto-loadable Chrome trace for the whole fleet
    — the router's own proxy spans plus every replica's `/v1/trace` export,
    merged onto a wall-clock-aligned timeline with one pid (and a
    process_name label) per process. A request's `router.proxy` span and its
    replica-side engine spans share the `trace_id` arg the traceparent
    propagation stamped, so following one request across processes is a
    Perfetto args search (docs/OBSERVABILITY.md "Fleet trace merge")."""
    sources: list[tuple[str, dict]] = []
    own = trace.current()
    if own is not None:
        sources.append(("router", own.to_chrome_trace()))
    for rep, res in _scrape_all(state, "/v1/trace"):
        if isinstance(res, tuple):
            status, body = res
            if status == 200:
                try:
                    sources.append((f"replica {rep.id}", json.loads(body)))
                    continue
                except ValueError:
                    pass  # a 200 with a non-JSON body IS a scrape error
            elif status == 404:
                # replica running without --trace: documented-normal — absent
                # from the merge, never counted as a scrape failure
                continue
        _SCRAPE_ERRORS.inc()
    return trace.merge_chrome_traces(sources)


# ----------------------------------------------------------------------
# HTTP handler
# ----------------------------------------------------------------------

class RouterHandler(BaseHTTPRequestHandler):
    state: RouterState  # injected by serve_router

    def log_message(self, fmt, *args):
        print(f"🔶 {self.command} {self.path}")

    def _count(self, code: int) -> None:
        path = self.path.split("?", 1)[0]
        if path.startswith("/v1/requests/"):
            path = "/v1/requests"  # per-id lookups share one label value
        route = path if path in _KNOWN_ROUTES else "other"
        _HTTP.labels(route=route, code=str(code)).inc()

    def _raw(self, code: int, content_type: str, data: bytes,
             extra_headers: dict | None = None):
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)
        self._count(code)

    def _json(self, code: int, payload: dict,
              extra_headers: dict | None = None):
        self._raw(code, "application/json", json.dumps(payload).encode(),
                  extra_headers)

    def _error(self, code: int, message: str, etype: str,
               retry_after: float | None = None):
        hdrs = ({"Retry-After": str(max(int(retry_after + 0.5), 1))}
                if retry_after is not None else None)
        self._json(code, {"error": {"message": message, "type": etype}}, hdrs)

    # -------------------------------------------------------------- GET

    def do_GET(self):
        state = self.state
        if self.path in ("/health", "/healthz"):
            rotation = state.membership.in_rotation()
            payload = {
                "status": "ok" if rotation else "no_healthy_replicas",
                "role": "router",
                "in_rotation": len(rotation),
                "replicas": {r.id: r.snapshot()
                             for r in state.membership.replicas},
            }
            self._json(200 if rotation else 503, payload)
        elif self.path == "/metrics":
            self._raw(200, "text/plain; version=0.0.4; charset=utf-8",
                      fleet_metrics(state).encode())
        elif self.path == "/v1/stats":
            self._json(200, fleet_stats(state))
        elif self.path == "/v1/trace":
            self._json(200, fleet_trace(state))
        elif self.path.startswith("/v1/requests/"):
            # the slow-request workflow must work for clients that can only
            # reach the router (replicas on an internal network): the id
            # lives on exactly one replica's flight recorder — ask them all
            # concurrently, relay the hit verbatim (replica 404s are the
            # expected misses, never scrape errors)
            path = self.path.split("?", 1)[0]
            unreachable = 0
            for rep, res in _scrape_all(state, path):
                if isinstance(res, tuple):
                    if res[0] == 200:
                        self._raw(200, "application/json", res[1])
                        return
                    # a replica 404 is a definitive miss THERE; any other
                    # status is indeterminate, like an exception below
                    if res[0] == 404:
                        continue
                unreachable += 1
                _SCRAPE_ERRORS.inc()
            key = path[len("/v1/requests/"):]
            if unreachable:
                # a 404 here would claim the record doesn't exist anywhere
                # while the replica that may hold it simply didn't answer
                # (rolling restart) — report the uncertainty honestly
                self._error(502, f"no flight record for {key!r} on the "
                            f"replicas that answered, but {unreachable} "
                            "replica(s) were unreachable", "server_error")
            else:
                self._error(404, f"no flight record for {key!r} on any "
                            "replica", "invalid_request_error")
        elif self.path.split("?", 1)[0] == "/v1/requests":
            # merged listing: each replica's summaries nested under its id,
            # query string (?slowest=K) validated HERE (a caller error must
            # be a 400, not N replica 400s masquerading as scrape failures)
            q = self.path.partition("?")[2]
            try:
                int(parse_qs(q).get("slowest", ["0"])[0])
            except ValueError:
                self._error(400, "'slowest' must be an integer",
                            "invalid_request_error")
                return
            out: dict = {"replicas": {}}
            for rep, res in _scrape_all(
                    state, "/v1/requests" + (f"?{q}" if q else "")):
                # same degradation contract as fleet_stats: a failing
                # replica gets an explicit error entry, never a silent drop
                if isinstance(res, tuple):
                    status, body = res
                    try:
                        out["replicas"][rep.id] = (
                            json.loads(body) if status == 200
                            else {"error": f"status {status}"})
                        continue
                    except ValueError as e:
                        _SCRAPE_ERRORS.inc()
                        out["replicas"][rep.id] = {
                            "error": f"non-JSON body: {e}"}
                        continue
                _SCRAPE_ERRORS.inc()
                out["replicas"][rep.id] = {"error": repr(res)}
            self._json(200, out)
        elif self.path == "/v1/models":
            rep = state.membership.least_loaded()
            if rep is None:
                self._error(503, "no healthy replica", "overloaded_error",
                            retry_after=state.retry_after_hint())
                return
            try:
                status, body = _fetch(rep, self.path, state.try_timeout)
                self._raw(status, "application/json", body)
            except Exception as e:
                self._error(502, f"replica {rep.id} unreachable: {e}",
                            "server_error")
        else:
            self._error(404, f"Unknown route: {self.path}",
                        "invalid_request_error")

    # ------------------------------------------------------------- POST

    def _deadline_ms(self) -> float | None:
        """Parse the client's X-Deadline-Ms budget (None = no deadline;
        ValueError surfaces as a 400 in the caller). Non-finite values must
        be rejected HERE: a NaN would pass every downstream `<= 0` check
        and then blow up int() conversions inside the failover loop, where
        the blast radius is replica ejections, not a clean 400."""
        hdr = self.headers.get("X-Deadline-Ms")
        if hdr is None:
            return None
        v = float(hdr)
        if v != v or v in (float("inf"), float("-inf")):
            raise ValueError(f"non-finite deadline {hdr!r}")
        return max(v, 0.0)

    def do_POST(self):
        if self.path not in ("/v1/chat/completions", "/chat/completions"):
            self._error(404, f"Unknown route: {self.path}",
                        "invalid_request_error")
            return
        state = self.state
        try:
            length = int(self.headers.get("Content-Length", 0))
            raw = self.rfile.read(length) or b"{}"
            body = json.loads(raw)
            if not isinstance(body, dict):
                raise ValueError("body is not an object")
        except (ValueError, json.JSONDecodeError):
            self._error(400, "Request body is not valid JSON",
                        "invalid_request_error")
            return
        try:
            deadline_ms = self._deadline_ms()
        except ValueError:
            self._error(400, "X-Deadline-Ms must be a number (ms)",
                        "invalid_request_error")
            return
        # multi-tenant fleet edge (docs/SERVING.md "Multi-tenant serving"):
        # resolve the tenant/class once; the router-level quota refuses
        # with 429 BEFORE any proxy work, and the X-Tenant/X-Class headers
        # are relayed on every try (and durable resume) so replica-side
        # accounting survives failover
        tenant = sanitize_tenant(self.headers.get("X-Tenant"))
        klass = str(body.get("class") or self.headers.get("X-Class")
                    or "interactive").strip().lower()
        if klass not in ("interactive", "batch"):
            klass = "interactive"
        tenant_hdrs = {"X-Tenant": tenant, "X-Class": klass}
        cost = 0.0
        if state.tenants is not None:
            # router-level cost estimate: the router never tokenizes, so
            # charge ~chars/4 of rendered content plus the decode budget
            chars = sum(len(str(m.get("content", "")))
                        for m in body.get("messages", [])
                        if isinstance(m, dict))
            cost = chars / 4.0 + float(body.get("max_tokens") or 64)
            try:
                state.tenants.acquire(tenant, cost)
            except QuotaExceeded as e:
                _THROTTLED.labels(
                    tenant=state.tenants.canonical(tenant)).inc()
                self._error(429, str(e), "rate_limit_error",
                            retry_after=e.retry_after)
                return
        # weighted-fair inflight gate (--max-inflight): contended capacity
        # is handed out interactive-first, tenants by weight — a flooding
        # tenant's handler threads can no longer take every slot
        if not state.gate.acquire(tenant, klass,
                                  timeout=state.gate_timeout):
            if state.tenants is not None:
                # zero service rendered: a gate shed must not also drain
                # the tenant's bucket (the retry would be double-punished)
                state.tenants.refund(tenant, cost)
            _GATE_SHED.inc()
            self._error(503, "fleet at --max-inflight and the fair gate "
                        "timed out", "overloaded_error",
                        retry_after=state.retry_after_hint())
            return
        _GATE_WAITING.set(state.gate.waiting())
        try:
            self._post_completion(body, raw, deadline_ms, tenant_hdrs)
        finally:
            state.gate.release()
            _GATE_WAITING.set(state.gate.waiting())

    def _post_completion(self, body: dict, raw: bytes, deadline_ms,
                         tenant_hdrs: dict) -> None:
        state = self.state
        # kv_source is ROUTER-OWNED (docs/DISAGG.md "Trust model"): a
        # client-supplied descriptor would make the decode replica fetch
        # from an arbitrary attacker host (SSRF) and insert the result
        # into the SHARED prefix cache (cross-request poisoning) — strip
        # it at the edge unconditionally; only the planner below may
        # inject one. Durable resumes are unaffected (they re-submit the
        # journaled entry.body, which keeps the planner's descriptor).
        if "kv_source" in body:
            body = dict(body)
            body.pop("kv_source")
            raw = json.dumps(body).encode()
        # prefill/decode disaggregation (docs/DISAGG.md): split BEFORE the
        # journal opens so the injected kv_source rides the durable body —
        # a mid-stream failover's resume then re-imports from the prefill
        # replica (or falls back to a local prefill if it died too). Plan
        # failures are silent: the request routes monolithic, untouched.
        if state.disagg.enabled:
            ks = state.disagg.plan(state.membership, body, tenant_hdrs,
                                   state.affinity, state.affinity_key(body))
            if ks is not None:
                body = dict(body)
                body["kv_source"] = ks
                raw = json.dumps(body).encode()
        # trace origination (docs/OBSERVABILITY.md "Request tracing"): adopt
        # the client's W3C traceparent or start a new trace; every proxy try
        # is its own hop (fresh span id, same trace id) stamped onto the
        # upstream request, so the replica's engine spans and this router's
        # proxy span share one trace id in the merged fleet trace
        ctx = reqctx.adopt(self.headers.get("traceparent"),
                           tenant=tenant_hdrs.get("X-Tenant", ""))
        if state.durable and "resume" not in body:
            # durable path (docs/FLEET.md "Resume protocol"): journal the
            # request and survive mid-stream replica failures by resuming on
            # another replica with exactly-once splicing. A client-supplied
            # resume payload is passed through the plain path untouched (the
            # caller IS a durability layer; double-journaling would fight
            # it). A full journal degrades to the plain path too — served,
            # just not failover-protected.
            entry = state.journal.open(
                body, stream=bool(body.get("stream", False)),
                deadline_ms=deadline_ms,
                tenant=tenant_hdrs.get("X-Tenant", ""),
                klass=tenant_hdrs.get("X-Class", ""))
            if entry is not None:
                self._durable_post(entry, ctx)
                return
        self._plain_post(body, raw, ctx, deadline_ms, tenant_hdrs)

    def _plain_post(self, body: dict, raw: bytes, ctx, deadline_ms,
                    tenant_hdrs: dict | None = None):
        """The pre-durable proxy loop: verbatim pass-through, pre-first-byte
        failover only, mid-stream failures surfaced honestly."""
        state = self.state
        t0 = time.perf_counter()
        key = state.affinity_key(body)
        prefer = state.disagg.prefer_roles(body, state.membership,
                                           state.affinity, key)
        tried: set[str] = set()
        last_503: tuple[bytes, str, str | None] | None = None
        for attempt in range(1 + state.retries):
            extra = dict(tenant_hdrs) if tenant_hdrs else None
            if deadline_ms is not None:
                # propagate the REMAINING budget, not the original: a retry
                # that re-sent the full deadline would let the fleet spend
                # attempts × deadline on a request the client abandoned
                rem = deadline_ms - (time.perf_counter() - t0) * 1000.0
                if rem <= 0.0:
                    self._error(408, "client deadline expired during "
                                "failover", "timeout_error")
                    return
                extra = {"X-Deadline-Ms": str(int(rem) or 1)}
            rep, reason = state.pick(key, tried, prefer)
            if rep is None:
                break
            tried.add(rep.id)
            _ROUTES.labels(reason=reason).inc()
            if attempt == 1:
                _RETRIES.inc()
            hop = ctx.child()
            with reqctx.use(hop), \
                    trace.span("router.proxy",
                               {"replica": rep.id, "reason": reason,
                                "attempt": attempt}):
                outcome, info = self._proxy_try(rep, raw, key, hop, extra)
            if outcome == "delivered" or outcome == "aborted":
                return
            if info is not None:  # a relayable 503 from this replica
                last_503 = info
        # every candidate exhausted (or rotation empty): fleet-level shed.
        # A replica's own 503 body is the most honest thing to relay; either
        # way the client ALWAYS gets Retry-After — derived from the
        # MEASURED fleet drain rate vs backlog (retry_after_hint), not a
        # constant — so it backs off in proportion to real load.
        retry_after = state.retry_after_hint()
        if last_503 is not None:
            data, ctype, ra = last_503
            self._raw(503, ctype, data,
                      {"Retry-After": ra or str(max(int(retry_after), 1))})
        else:
            self._error(503, "no replica available "
                        f"({len(tried)} tried, "
                        f"{len(state.membership.in_rotation())} in rotation)",
                        "overloaded_error", retry_after=retry_after)

    # ---------------------------------------------------- durable proxy

    def _durable_post(self, entry, ctx) -> None:
        """Journaled proxy loop (docs/FLEET.md "Resume protocol"): the
        upstream leg ALWAYS streams with in-band token journaling, whatever
        the client asked for, so every delivered token is recorded the
        moment it flows. A mid-stream replica failure re-submits the journal
        to a surviving replica with a `resume` payload; splice() gives the
        client exactly-once delivery, so the failover is invisible. The
        failover budget is `retries` tries per no-progress round — a try
        that advanced the stream resets the round (a long generation may
        outlive several replicas), so only consecutive fruitless tries give
        up."""
        try:
            self._durable_post_inner(entry, ctx)
        finally:
            # a client that dropped the connection mid-relay unwinds the
            # handler through a write error before any close() — reclaim
            # the entry (no-op after a normal close) or abandoned streams
            # would fill the journal and silently disable durability
            self.state.journal.abandon(entry)

    def _durable_post_inner(self, entry, ctx) -> None:
        state = self.state
        key = state.affinity_key(entry.body)
        prefer = state.disagg.prefer_roles(entry.body, state.membership,
                                           state.affinity, key)
        client_started = [False]
        tried: set[str] = set()
        fruitless = 0
        last_503: tuple[bytes, str, str | None] | None = None
        attempt = 0
        while fruitless <= state.retries:
            rem = entry.remaining_deadline_ms()
            if rem is not None and rem <= 0.0:
                self._durable_fail(entry, client_started, 408,
                                   "client deadline expired during failover",
                                   "timeout_error")
                return
            rep, reason = state.pick(key, tried, prefer)
            if rep is None:
                break
            tried.add(rep.id)
            _ROUTES.labels(reason=reason).inc()
            if attempt == 1:
                _RETRIES.inc()
            attempt += 1
            progress0 = (len(entry.tokens), entry.sent_chars)
            hop = ctx.child()
            with reqctx.use(hop), \
                    trace.span("router.proxy",
                               {"replica": rep.id, "reason": reason,
                                "attempt": attempt - 1, "durable": True,
                                "resume_tokens": len(entry.tokens)}):
                outcome, info = self._durable_try(rep, entry, key, hop,
                                                  client_started)
            if outcome in ("done", "fatal"):
                state.journal.close(
                    entry, entry.finish if outcome == "done" else "error")
                return
            if info is not None:
                last_503 = info
            if (len(entry.tokens), entry.sent_chars) != progress0:
                # the replica served this request for a while before dying:
                # new failover round — every OTHER replica is a candidate
                # again (it may have rejoined rotation since)
                fruitless = 1
                tried = {rep.id}
            else:
                fruitless += 1
        # candidates exhausted with no completion: surface honestly, with
        # the drain-derived backoff hint (docs/SERVING.md)
        state.journal.close(entry, "failed")
        retry_after = state.retry_after_hint()
        if client_started[0]:
            self._sse_error_event(
                f"no replica could resume the stream ({len(tried)} tried)",
                "server_error")
        elif last_503 is not None:
            data, ctype, ra = last_503
            self._raw(503, ctype, data,
                      {"Retry-After": ra or str(max(int(retry_after), 1))})
        else:
            self._error(503, "no replica available "
                        f"({len(tried)} tried, "
                        f"{len(state.membership.in_rotation())} in rotation)",
                        "overloaded_error", retry_after=retry_after)

    def _durable_try(self, rep: Replica, entry, key: bytes, hop,
                     client_started: list):
        """One journaled upstream try. Returns (outcome, relayable_503):
        "done" — the completion reached the client (stream terminated or
        JSON sent); "fatal" — a deterministic error was relayed, do not
        retry; "retry" — the replica failed around the request (connect,
        read, 503, or a retriable in-stream error); anything already
        delivered stays journaled for the next candidate."""
        state = self.state
        mem = state.membership
        mem.inflight_inc(rep)
        _INFLIGHT.labels(replica=rep.id).set(rep.inflight)
        if entry.tokens or entry.sent_chars:
            state.journal.note_resume(entry)
        conn = None
        t0 = time.perf_counter()
        try:
            try:
                faults.fire("router.proxy", replica=rep.id)
                headers = {"Content-Type": "application/json",
                           "X-Dllama-Journal": "1",
                           "traceparent": hop.to_traceparent()}
                # tenant identity survives failover: every try (first AND
                # resume) re-stamps the journaled tenant/class so the new
                # replica's quota/fairness accounting stays attributed
                if entry.tenant:
                    headers["X-Tenant"] = entry.tenant
                if entry.klass:
                    headers["X-Class"] = entry.klass
                rem = entry.remaining_deadline_ms()
                if rem is not None:
                    headers["X-Deadline-Ms"] = str(max(int(rem), 1))
                conn = HTTPConnection(rep.host, rep.port,
                                      timeout=state.try_timeout)
                conn.request("POST", self.path,
                             json.dumps(entry.upstream_body()).encode(),
                             headers)
                resp = conn.getresponse()
            except Exception:
                _PROXY_ERRORS.labels(kind="connect").inc()
                mem.mark_failed(rep)
                return "retry", None
            entry.replicas.append(rep.id)
            if resp.status == 503:
                data = resp.read()
                _PROXY_ERRORS.labels(kind="status_503").inc()
                if b"server_shutting_down" in data or b"draining" in data:
                    rep.draining = True
                return "retry", (data,
                                 resp.getheader("Content-Type",
                                                "application/json"),
                                 resp.getheader("Retry-After"))
            ctype = resp.getheader("Content-Type", "")
            if "text/event-stream" not in ctype:
                # pre-stream deterministic error (400/408...): relay with
                # its real status — resuming a caller error elsewhere would
                # fail identically (the replica validated the journal body)
                try:
                    data = resp.read()
                except Exception:
                    _PROXY_ERRORS.labels(kind="read").inc()
                    mem.mark_failed(rep)
                    return "retry", None
                if client_started[0]:
                    self._sse_error_event(
                        f"replica {rep.id} refused the resume with status "
                        f"{resp.status}", "server_error")
                else:
                    extra = {h: v for h in self._RELAY_HEADERS
                             if (v := resp.getheader(h))}
                    self._raw(resp.status, ctype or "application/json",
                              data, extra or None)
                return "fatal", None
            outcome = self._durable_relay(rep, entry, resp, client_started,
                                          key)
            if outcome == "done":
                _PROXY_SECONDS.observe(time.perf_counter() - t0)
            return outcome, None
        finally:
            if conn is not None:
                conn.close()
            mem.inflight_dec(rep)
            _INFLIGHT.labels(replica=rep.id).set(rep.inflight)

    def _durable_relay(self, rep: Replica, entry, resp,
                       client_started: list, key: bytes):
        """Parse the upstream SSE stream event-by-event, fold token journal
        fields into the entry, splice content past what the client already
        has, and relay. The upstream counts content from generated-token
        zero (a resumed replica re-emits the delivered prefix), so splicing
        is a pure cumulative-position comparison. The affinity record for
        `key` lands BEFORE the final client write: a client that reads the
        completion and immediately consults routing state (tests, a
        follow-up request on a warm connection) must observe the route."""
        up_chars = 0
        saw_done = False
        events = iter_sse_data(resp)
        while True:
            try:
                data = next(events)
            except StopIteration:
                break
            except Exception:
                _PROXY_ERRORS.labels(kind="read").inc()
                self.state.membership.mark_failed(rep)
                return "retry"
            if data == "[DONE]":
                saw_done = True
                break
            payload = parse_chunk(data)
            if payload is None:
                continue
            if "error" in payload:
                err = payload.get("error") or {}
                if err.get("retriable"):
                    # the replica failed AROUND the request (wedged engine,
                    # drain, engine-scope fault) and says so: resume
                    # elsewhere; nothing new reached the client this event
                    _PROXY_ERRORS.labels(kind="upstream_retriable").inc()
                    return "retry"
                if client_started[0] or entry.stream:
                    self._durable_start_stream(entry, resp, client_started)
                    self._sse_error_event(
                        err.get("message", "upstream error"),
                        err.get("type", "server_error"))
                else:
                    self._error(int(err.get("code") or 500),
                                err.get("message", "upstream error"),
                                err.get("type", "server_error"))
                return "fatal"
            if "dllama" in payload:
                entry.record_tokens(payload.pop("dllama"))
            if entry.completion_id is None:
                entry.completion_id = payload.get("id")
            if entry.model is None:
                entry.model = payload.get("model")
            choices = payload.get("choices") or [{}]
            delta = choices[0].get("delta") or {}
            text = delta.get("content") or ""
            finish = choices[0].get("finish_reason")
            new = ""
            if text:
                up_chars += len(text)
                new = entry.splice(text, up_chars)
            if new or finish is not None:
                if not entry.stream:
                    if new:
                        entry.parts.append(new)
                    if finish is not None:
                        entry.finish = finish
                    continue
                self._durable_start_stream(entry, resp, client_started)
                payload["id"] = entry.completion_id or payload.get("id")
                delta["content"] = new
                if not new:
                    delta.pop("content", None)
                if finish is not None:
                    entry.finish = finish
                self._write_chunk(
                    f"data: {json.dumps(payload)}\n\n".encode())
        if entry.finish is None and not saw_done:
            # the stream ended without a finish chunk or [DONE]: the replica
            # died mid-stream (or produced a malformed empty stream) — the
            # journal holds everything delivered; resume elsewhere
            _PROXY_ERRORS.labels(
                kind="empty_stream" if up_chars == 0 else "read").inc()
            self.state.membership.mark_failed(rep)
            return "retry"
        self.state.affinity.record(key, rep.id)  # happens-before completion
        if entry.stream:
            # zero-delta completions still stream (parity with api_server)
            self._durable_start_stream(entry, resp, client_started)
            self._write_chunk(b"data: [DONE]\n\n")
            self._write_chunk(b"")
        else:
            extra = {h: v for h in self._RELAY_HEADERS
                     if (v := resp.getheader(h))}
            self._json(200, {
                "id": entry.completion_id or "chatcmpl-durable",
                "object": "chat.completion",
                "created": int(time.time()),
                "model": entry.model or "distributed-llama-tpu",
                "choices": [{
                    "index": 0,
                    "message": {"role": "assistant",
                                "content": "".join(entry.parts)},
                    "finish_reason": entry.finish or "stop",
                }],
            }, extra or None)
        self.state.note_done()  # feeds the drain-derived Retry-After
        return "done"

    def _durable_start_stream(self, entry, resp, client_started: list):
        if client_started[0]:
            return
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Transfer-Encoding", "chunked")
        for h in self._RELAY_HEADERS:
            v = resp.getheader(h)
            if v:
                self.send_header(h, v)
        self.end_headers()
        self._count(200)
        client_started[0] = True

    def _sse_error_event(self, message: str, etype: str) -> None:
        """Honest mid-stream termination (client already has bytes)."""
        self._write_chunk(
            ("data: " + json.dumps({"error": {
                "message": message, "type": etype}}) + "\n\n").encode())
        self._write_chunk(b"data: [DONE]\n\n")
        self._write_chunk(b"")

    def _durable_fail(self, entry, client_started: list, code: int,
                      message: str, etype: str) -> None:
        self.state.journal.close(entry, "failed")
        if client_started[0]:
            self._sse_error_event(message, etype)
        else:
            self._error(code, message, etype)

    # ------------------------------------------------------------ proxy

    # Retry-After rides along so a replica's own 429 (tenant quota) and
    # other backoff-bearing statuses keep their hint through the proxy
    _RELAY_HEADERS = ("X-Request-Id", "X-Replica", "Retry-After")

    def _proxy_try(self, rep: Replica, raw: bytes, key: bytes, hop=None,
                   extra_headers: dict | None = None):
        """One proxy attempt against `rep`. Returns (outcome, relayable):
        outcome "delivered" (response fully relayed), "aborted" (failed
        after client bytes — already terminated, never retry), or "retry"
        (nothing reached the client; relayable = (body, ctype, retry_after)
        when the failure was a replica 503 worth relaying). `hop` is this
        try's trace context, stamped upstream as `traceparent`; the
        replica's X-Request-Id/X-Replica response headers are relayed so
        the client can reach GET /v1/requests/<id> on the serving replica.
        `extra_headers` carries per-try headers (remaining X-Deadline-Ms)."""
        state = self.state
        mem = state.membership
        mem.inflight_inc(rep)
        _INFLIGHT.labels(replica=rep.id).set(rep.inflight)
        conn = None
        t0 = time.perf_counter()
        try:
            try:
                faults.fire("router.proxy", replica=rep.id)
                headers = {"Content-Type": "application/json"}
                if extra_headers:
                    headers.update(extra_headers)
                if hop is not None:
                    headers["traceparent"] = hop.to_traceparent()
                conn = HTTPConnection(rep.host, rep.port,
                                      timeout=state.try_timeout)
                conn.request("POST", self.path, raw, headers)
                resp = conn.getresponse()
            except Exception:
                _PROXY_ERRORS.labels(kind="connect").inc()
                mem.mark_failed(rep)
                return "retry", None
            if resp.status == 503:
                # shed (overloaded, Retry-After) or drain — in both cases
                # another replica may serve this request right now. Reflect
                # a drain in membership immediately; the poller confirms.
                data = resp.read()
                _PROXY_ERRORS.labels(kind="status_503").inc()
                if b"server_shutting_down" in data or b"draining" in data:
                    rep.draining = True
                return "retry", (data,
                                 resp.getheader("Content-Type",
                                                "application/json"),
                                 resp.getheader("Retry-After"))
            ctype = resp.getheader("Content-Type", "application/json")
            if "text/event-stream" in ctype:
                return self._relay_stream(rep, resp, key)
            # non-streaming (includes pre-stream errors with real status
            # codes — api_server defers SSE headers to the first delta, so a
            # 400/408 arrives here as plain JSON): relay verbatim, no retry
            # of non-503 errors (they are deterministic caller errors). A
            # body-read failure is retriable — nothing reached the client,
            # completions are idempotent until output is delivered.
            try:
                data = resp.read()
            except Exception:
                _PROXY_ERRORS.labels(kind="read").inc()
                mem.mark_failed(rep)
                return "retry", None
            extra = {h: v for h in self._RELAY_HEADERS
                     if (v := resp.getheader(h))}
            if resp.status == 200:
                # record BEFORE relaying: the client must not observe the
                # completion while the route is still unrecorded
                state.affinity.record(key, rep.id)
            self._raw(resp.status, ctype, data, extra or None)
            if resp.status == 200:
                _PROXY_SECONDS.observe(time.perf_counter() - t0)
                state.note_done()  # feeds the drain-derived Retry-After
            return "delivered", None
        finally:
            if conn is not None:
                conn.close()
            mem.inflight_dec(rep)
            _INFLIGHT.labels(replica=rep.id).set(rep.inflight)

    def _relay_stream(self, rep: Replica, resp, key: bytes):
        """SSE pass-through. Client headers are deferred to the first
        upstream byte so an upstream that dies before producing anything is
        still retryable on another replica."""
        state = self.state
        sent_any = False
        t0 = time.perf_counter()
        while True:
            try:
                chunk = resp.read1(65536)
            except Exception:
                _PROXY_ERRORS.labels(kind="read").inc()
                if not sent_any:
                    state.membership.mark_failed(rep)
                    return "retry", None
                # mid-stream: the client already has partial output — a
                # retry would double-deliver. Honest termination instead.
                self._sse_error_event(
                    f"upstream replica {rep.id} failed mid-stream",
                    "server_error")
                return "aborted", None
            if not chunk:
                break
            if not sent_any:
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Transfer-Encoding", "chunked")
                for h in self._RELAY_HEADERS:
                    v = resp.getheader(h)
                    if v:
                        self.send_header(h, v)
                self.end_headers()
                self._count(200)
                sent_any = True
            self._write_chunk(chunk)
        if not sent_any:
            # 200 event-stream with an empty body is a malformed upstream;
            # nothing reached the client, so another replica may try
            _PROXY_ERRORS.labels(kind="empty_stream").inc()
            return "retry", None
        # record BEFORE the stream terminator: the client must not be able
        # to observe completion while the route is still unrecorded
        state.affinity.record(key, rep.id)
        self._write_chunk(b"")  # terminate the chunked response
        _PROXY_SECONDS.observe(time.perf_counter() - t0)
        state.note_done()  # feeds the drain-derived Retry-After
        return "delivered", None

    def _write_chunk(self, data: bytes):
        self.wfile.write(f"{len(data):X}\r\n".encode() + data + b"\r\n")
        self.wfile.flush()


# ----------------------------------------------------------------------
# server plumbing
# ----------------------------------------------------------------------

def serve_router(replicas: list[str], host: str = "0.0.0.0",
                 port: int = 9900, policy: str = "affinity",
                 poll_interval: float = 2.0, poll_timeout: float = 2.0,
                 block_bytes: int = 64, affinity_nodes: int = 8192,
                 retries: int = 2, try_timeout: float = 120.0,
                 seed: int = 0, durable: bool = True,
                 tenants: "TenantRegistry | str | None" = None,
                 max_inflight: int = 0,
                 gate_timeout: float = 30.0,
                 disagg_threshold: int = 0,
                 disagg_timeout: float = 60.0) -> ThreadingHTTPServer:
    """Build + bind the router (does NOT serve_forever — caller's thread
    choice). Membership is polled once synchronously so the first request
    already has a rotation. `server.router_state` exposes the state.
    `durable=False` reverts completions to the PR-6 verbatim pass-through
    (mid-stream failures surfaced, not resumed). `tenants` (a registry or
    the parseable spec string) enables router-level quotas; `max_inflight`
    > 0 arms the weighted-fair inflight gate (docs/SERVING.md
    "Multi-tenant serving")."""
    if isinstance(tenants, str):
        tenants = TenantRegistry.parse(tenants) if tenants else None
    membership = Membership(replicas, poll_interval=poll_interval,
                            poll_timeout=poll_timeout)
    state = RouterState(membership, policy=policy, block_bytes=block_bytes,
                        affinity_nodes=affinity_nodes, retries=retries,
                        try_timeout=try_timeout, seed=seed, durable=durable,
                        tenants=tenants, max_inflight=max_inflight,
                        gate_timeout=gate_timeout,
                        disagg_threshold=disagg_threshold,
                        disagg_timeout=disagg_timeout)
    membership.start()
    handler = type("BoundRouterHandler", (RouterHandler,),
                   {"state": state, "protocol_version": "HTTP/1.1"})
    server = QuietServer((host, port), handler)
    server.router_state = state
    install_process_metrics()  # uptime/RSS/threads/build info on /metrics
    trace.set_process_name(f"router {host}:{server.server_address[1]}")
    print(f"🟢 fleet router listening on {host}:{server.server_address[1]} "
          f"({len(membership.replicas)} replicas, policy={policy})")
    return server


def close_router(server: ThreadingHTTPServer) -> None:
    """Stop serving and the membership poller (idempotent)."""
    server.shutdown()
    server.server_close()
    server.router_state.membership.stop()
