"""Durable-request journal: the router-side half of mid-stream failover.

A replica death used to be survivable only BEFORE output flowed — once the
client had bytes, the router's only honest move was an SSE error event
(fleet/router.py PR 6). The journal removes that cliff (docs/FLEET.md
"Resume protocol"): every in-flight completion the durable router proxies is
recorded here — the request body with its sampling seed PINNED, the adopted
trace context, the delivered generated-token ids the serving replica reports
in-band (the `dllama` field `X-Dllama-Journal` asks for), and the exact
number of content characters relayed to the client. When a replica dies
mid-stream the router re-submits the entry to a surviving replica with a
`resume` payload; the replica prefills prompt ⊕ delivered-tokens (mostly a
radix prefix-cache hit), fast-forwards its sampler past the consumed coins,
and re-emits the stream from generated-token zero — byte-identical to the
uninterrupted run by the engine's RNG/prefill guarantees — while the router
splices: it skips exactly `sent_chars` characters before relaying again, so
the client sees one uninterrupted stream with exactly-once delivery.

The journal is in-memory: the DURABILITY DOMAIN is "requests outlive the
replica serving them", not the router process itself (a router crash drops
the TCP connections it fronts regardless of any journal). Entries live only
while their request is in flight and are dropped at completion.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field

from ..obs import metrics

__all__ = ["JournalEntry", "RequestJournal", "iter_sse_data", "parse_chunk",
           "pin_seed"]

_INFLIGHT = metrics.gauge(
    "router_journal_inflight",
    "Durable requests currently journaled (in flight through the router)")
_RESUMED = metrics.counter(
    "router_resumed_requests_total",
    "Requests resumed on another replica after a mid-stream failure "
    "(counted once per request, however many times it moved)")
_RESUME_ATTEMPTS = metrics.counter(
    "router_resume_attempts_total",
    "Mid-stream failover re-submits issued (one per replica move)")
_RESUME_TOKENS = metrics.counter(
    "router_resume_tokens_total",
    "Journaled generated tokens carried by resume re-submits")
_DURABLE_FAILED = metrics.counter(
    "router_durable_failed_total",
    "Durable requests that exhausted every resume candidate and surfaced a "
    "client-visible failure")


def pin_seed(body: dict) -> dict:
    """Pin the sampling seed BEFORE the first proxy try: the replica defaults
    a missing/null seed to wall-clock time, so a retried or resumed request
    would draw a different xorshift* stream and diverge. One journal-owned
    seed makes every re-submit byte-deterministic (greedy requests are
    deterministic regardless; the pin is harmless there)."""
    if body.get("seed") is None:
        body = dict(body)
        body["seed"] = int.from_bytes(os.urandom(4), "big") >> 1
    return body


@dataclass
class JournalEntry:
    """One in-flight durable request. Mutated only by its handler thread."""

    rid: str                      # router-side journal key
    body: dict                    # seed-pinned request (WITHOUT resume field)
    stream: bool                  # client asked for SSE
    deadline_ms: float | None     # original X-Deadline-Ms budget, if any
    # multi-tenant identity (docs/SERVING.md "Multi-tenant serving"):
    # re-stamped as X-Tenant/X-Class on every upstream try INCLUDING
    # mid-stream resumes, so failover preserves tenant accounting
    tenant: str = ""
    klass: str = ""
    t0: float = field(default_factory=time.perf_counter)
    tokens: list[int] = field(default_factory=list)  # delivered token ids
    sent_chars: int = 0           # content chars relayed to the client
    # accumulated spliced content for NON-streaming clients (nothing reaches
    # the client until completion, so the text must survive replica moves)
    parts: list[str] = field(default_factory=list)
    completion_id: str | None = None  # first upstream id, kept across moves
    model: str | None = None      # first upstream model (final payloads)
    replicas: list[str] = field(default_factory=list)  # serving history
    resumes: int = 0              # successful mid-stream moves
    finish: str | None = None

    def upstream_body(self) -> dict:
        """Body for the next upstream try: always streaming (the journal
        needs in-band tokens even for non-streaming clients) plus the resume
        payload once anything was delivered."""
        b = dict(self.body)
        b["stream"] = True
        if self.tokens:
            b["resume"] = {"tokens": list(self.tokens)}
        return b

    def record_tokens(self, info: dict) -> None:
        """Fold one chunk's `dllama` journal field in. `n` is the cumulative
        delivered count INCLUDING the chunk's `toks`; a resumed upstream
        re-counts from zero over tokens this journal already holds, so only
        the tail beyond the current length is appended (idempotent under
        replays)."""
        toks = info.get("toks") or []
        try:
            n = int(info.get("n", 0))
        except (TypeError, ValueError):
            return
        have = len(self.tokens)
        if n > have and len(toks) >= n - have:
            self.tokens.extend(int(t) for t in toks[len(toks) - (n - have):])

    def splice(self, text: str, upstream_chars: int) -> str:
        """Exactly-once delivery: `text` is one upstream delta whose content
        ends at cumulative position `upstream_chars` in the upstream's
        from-zero stream; return only the part the client has not seen."""
        start = upstream_chars - len(text)
        if upstream_chars <= self.sent_chars:
            return ""
        new = text[max(self.sent_chars - start, 0):]
        self.sent_chars += len(new)
        return new

    def remaining_deadline_ms(self) -> float | None:
        """X-Deadline-Ms for the NEXT hop: the client's original budget minus
        elapsed wall time — a resumed request must not outlive the deadline
        the client set (0 = already expired; caller fails the request)."""
        if self.deadline_ms is None:
            return None
        rem = self.deadline_ms - (time.perf_counter() - self.t0) * 1000.0
        return max(rem, 0.0)


class RequestJournal:
    """Bounded live table of in-flight durable requests."""

    def __init__(self, max_inflight: int = 4096):
        self.max_inflight = max_inflight
        self._live: dict[str, JournalEntry] = {}
        self._lock = threading.Lock()  # guards: _live, _seq
        self._seq = 0

    def open(self, body: dict, stream: bool, deadline_ms: float | None,
             tenant: str = "", klass: str = "") -> JournalEntry | None:
        """Journal a new request (seed pinned here). None when the table is
        full — the caller should fall back to the non-durable proxy path
        rather than shed (an unjournaled request is still served, it just
        cannot survive a mid-stream failure)."""
        with self._lock:
            if len(self._live) >= self.max_inflight:
                return None
            self._seq += 1
            rid = f"jrn-{self._seq:08d}"
            entry = JournalEntry(rid, pin_seed(body), stream, deadline_ms,
                                 tenant=tenant, klass=klass)
            self._live[rid] = entry
            _INFLIGHT.set(len(self._live))
        return entry

    def note_resume(self, entry: JournalEntry) -> None:
        if entry.resumes == 0:
            _RESUMED.inc()
        entry.resumes += 1
        _RESUME_ATTEMPTS.inc()
        _RESUME_TOKENS.inc(len(entry.tokens))

    def close(self, entry: JournalEntry, finish: str | None) -> None:
        entry.finish = finish
        if finish == "failed":
            _DURABLE_FAILED.inc()
        with self._lock:
            self._live.pop(entry.rid, None)
            _INFLIGHT.set(len(self._live))

    def abandon(self, entry: JournalEntry) -> None:
        """Last-resort cleanup for an entry whose handler unwound without
        reaching a close() — typically the CLIENT dropped the connection
        mid-relay (a write raised out of the proxy loop). Idempotent and a
        no-op after a real close; without it every abandoned SSE stream
        would leak its entry until the table filled and durability silently
        disabled fleet-wide."""
        with self._lock:
            if self._live.pop(entry.rid, None) is None:
                return
            _INFLIGHT.set(len(self._live))
        if entry.finish is None:
            entry.finish = "abandoned"

    def inflight(self) -> int:
        with self._lock:
            return len(self._live)


def iter_sse_data(resp):
    """Incrementally yield the payload string of every `data: ...` SSE event
    from an http.client response (readline honors chunked decoding, so each
    event is surfaced the moment its bytes arrive — the router relays tokens
    with no end-of-stream buffering). Multi-line data fields are joined per
    the SSE spec; [DONE] is yielded verbatim for the caller to recognize."""
    data_lines: list[str] = []
    while True:
        line = resp.readline()
        if not line:
            break
        line = line.decode("utf-8", "replace").rstrip("\r\n")
        if line == "":
            if data_lines:
                yield "\n".join(data_lines)
                data_lines = []
            continue
        if line.startswith("data:"):
            data_lines.append(line[5:].lstrip(" "))
    if data_lines:  # stream cut mid-event: surface what arrived
        yield "\n".join(data_lines)


def parse_chunk(data: str):
    """Parse one SSE data payload into a dict, or None for [DONE]/garbage."""
    if data == "[DONE]":
        return None
    try:
        obj = json.loads(data)
    except ValueError:
        return None
    return obj if isinstance(obj, dict) else None
