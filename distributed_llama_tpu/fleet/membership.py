"""Fleet membership: a static replica list kept live by a /healthz poller.

The replica set is configuration (`--replica host:port`, repeated) — there is
no discovery protocol — but *rotation* is dynamic: a background poller GETs
every replica's `/healthz` (the identity/load block api_server publishes) on
an interval — unreachable replicas on a per-replica exponential backoff
with jitter instead, with a capped down log — and replicas leave rotation
the moment they report `draining`
(SIGTERM graceful drain, docs/ROBUSTNESS.md), report `unhealthy` (scheduler
thread dead), or stop answering; they rejoin automatically on the first clean
poll after recovery. The proxy path can also eject a replica synchronously
(`mark_failed`) when a connect fails mid-request — rotation must not wait a
poll interval to stop routing into a dead socket.

The same poll carries the load block (free slots, queue depth) that feeds
least-loaded routing, and the model-config hash that catches a replica
serving a different model than the rest of the fleet (warned + counted, not
fatal: the operator may be mid-rolling-upgrade).

Polling is the `router.health` fault-injection point (resilience/faults.py):
an injected error marks the replica unreachable for that round — the poller
thread itself must survive anything a poll raises.
"""

from __future__ import annotations

import json
import random
import threading
import time
from dataclasses import dataclass, field
from http.client import HTTPConnection

from ..obs import metrics
from ..resilience import faults
from .latency import ReplicaLatency

__all__ = ["Replica", "Membership"]

_IN_ROTATION = metrics.gauge(
    "router_replicas_in_rotation",
    "Replicas currently healthy and not draining (routable)")
_POLLS = metrics.counter(
    "router_health_polls_total", "Membership /healthz polls by outcome",
    labelnames=("outcome",))
_HASH_MISMATCH = metrics.counter(
    "router_model_hash_mismatch_total",
    "Polls observing a replica whose model config hash differs from the fleet's")


@dataclass
class Replica:
    """One api_server behind the router. Health/load fields are the last
    poll's reading; `inflight` is the router's own live proxy count.

    The health/load block is mutated from TWO thread families — the
    background poller (`Membership._poll`) and every proxy handler thread
    (`Membership.mark_failed`, inflight counting) — so all mutation goes
    through the `_lock`-holding methods below and readers that combine
    several fields (`load_score`, `snapshot`) take the lock too. The
    pre-fix code mutated fields bare: concurrent `mark_failed`s could lose
    `consecutive_failures` increments (feeding the backoff exponent), and a
    reader could observe a half-applied poll (e.g. `healthy=True` already
    set while `status` still said `"unreachable"`). Found by the
    lock-guard pass (docs/ANALYSIS.md); pinned by
    tests/test_fleet.py::test_replica_status_mutation_is_atomic."""

    host: str
    port: int
    id: str = ""
    healthy: bool = False
    draining: bool = False
    status: str = "unpolled"   # ok | draining | unhealthy | unreachable | unpolled
    # disaggregation role (docs/DISAGG.md): what the replica ADVERTISES in
    # its healthz load block — "prefill" (long-prompt admissions land here,
    # KV shipped out), "decode" (imports KV, runs decode chains), or "both"
    # (the monolithic default). Roles are routing preferences, not hard
    # capabilities: every replica runs the full engine, so a degraded fleet
    # can still serve anything anywhere. Replicas predating the role field
    # (an old healthz payload) read as "both" — back-compat pinned by
    # tests/test_disagg.py.
    role: str = "both"
    model_hash: str | None = None
    slots: int = 0
    free_slots: int = 0
    queue_depth: int = 0
    inflight: int = 0
    # process identity from the replica's healthz block: pid matches the
    # os_pid recorded per process in the fleet-merged Perfetto trace's
    # otherData.processes (merged events themselves carry remapped index
    # pids); a shrinking uptime between polls means the replica restarted
    # (crash loop) even if every poll happened to land on a healthy window
    pid: int = 0
    uptime_s: float = 0.0
    consecutive_failures: int = 0
    last_ok: float = 0.0
    hash_warned: bool = False  # rate-limits the model-mismatch warning
    # gray-failure resilience (docs/FLEET.md "Gray-failure resilience"):
    # `degraded` is ROUTER-SIDE probation state — the replica answers healthz
    # ok but its observed TTFB is an outlier vs its peers, so it leaves
    # normal rotation and serves canary traffic only until `canary_ok`
    # consecutive in-band outcomes clear it (fleet/latency.py detector).
    # `retry_after_until` is the Retry-After cooldown a replica's own 503
    # asked for: pick() skips the replica until the window passes (or a
    # clean idle poll shows the saturation cleared).
    degraded: bool = False
    canary_ok: int = 0
    retry_after_until: float = 0.0  # monotonic; 0 = no cooldown
    # outcome-driven latency signals (TTFB / stream pace / healthz RTT);
    # the stats self-lock, only the reference lives here
    lat: ReplicaLatency = field(default_factory=ReplicaLatency, repr=False)
    # per-replica poll backoff (unreachable replicas only): the background
    # poller skips this replica until next_poll_t — exponential with jitter,
    # so a dead replica costs ~one timed-out connect per backoff_cap instead
    # of one per poll_interval (and N dead replicas don't re-probe in sync)
    next_poll_t: float = 0.0       # monotonic; 0 = poll normally
    down_since: float = 0.0        # monotonic of the first failed poll
    last_down_log: float = 0.0     # rate-limits the "still down" line
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)  # guards: healthy, draining, status, consecutive_failures, slots, free_slots, queue_depth, model_hash, pid, uptime_s, inflight, last_ok, role, degraded, canary_ok, retry_after_until

    def __post_init__(self):
        if not self.id:
            self.id = f"{self.host}:{self.port}"

    def load_score(self) -> tuple:
        """Least-loaded ordering: fewest waiting+in-flight first, then most
        free slots, then the polled healthz round-trip in 10 ms buckets (a
        latency signal that exists before any traffic flows — two idle
        replicas tie-break toward the faster network/process, and the
        bucketing keeps micro-jitter from destabilizing the order), then id
        for determinism."""
        with self._lock:
            return (self.queue_depth + self.inflight, -self.free_slots,
                    int(self.lat.health_rtt.ewma() * 100.0), self.id)

    def snapshot(self) -> dict:
        with self._lock:
            out = {"id": self.id, "healthy": self.healthy,
                   "draining": self.draining,
                   "status": ("degraded" if self.degraded and self.healthy
                              and self.status == "ok" else self.status),
                   "degraded": self.degraded,
                   "role": self.role,
                   "model_hash": self.model_hash, "slots": self.slots,
                   "free_slots": self.free_slots,
                   "queue_depth": self.queue_depth,
                   "inflight": self.inflight,
                   "pid": self.pid, "uptime_s": self.uptime_s,
                   "cooldown_s": round(
                       max(self.retry_after_until - time.monotonic(), 0.0),
                       2)}
        out.update(self.lat.snapshot_ms())
        return out

    # -- gray-failure state (fleet/latency.py detector) -----------------

    def set_degraded(self, flag: bool) -> bool:
        """Enter/exit probation atomically; returns True when the flag
        actually changed (the caller counts transitions exactly once)."""
        with self._lock:
            if self.degraded == flag:
                return False
            self.degraded = flag
            self.canary_ok = 0
            return True

    def canary_note(self, in_band: bool) -> int:
        """Fold one canary outcome in; returns the consecutive in-band
        streak (an out-of-band canary resets it)."""
        with self._lock:
            self.canary_ok = self.canary_ok + 1 if in_band else 0
            return self.canary_ok

    def note_retry_after(self, seconds: float, cap: float = 30.0) -> None:
        """Honor the replica's own Retry-After: keep it out of pick() for
        the window (capped — a pathological header must not eject a replica
        for minutes). A clean idle poll clears the cooldown early
        (apply_poll): the saturation the 503 reported has drained."""
        until = time.monotonic() + min(max(seconds, 0.0), cap)
        with self._lock:
            self.retry_after_until = max(self.retry_after_until, until)

    def in_cooldown(self) -> bool:
        with self._lock:
            return self.retry_after_until > time.monotonic()

    def mark_unreachable(self, clear_draining: bool = False) -> int:
        """Atomic ejection bookkeeping (poller failure path AND proxy-path
        `mark_failed`): returns the new consecutive-failure count for the
        caller's backoff math."""
        with self._lock:
            self.healthy = False
            if clear_draining:
                self.draining = False
            self.status = "unreachable"
            self.consecutive_failures += 1
            return self.consecutive_failures

    def apply_poll(self, status: str, ok: bool, block: dict) -> float:
        """Fold one successful /healthz response in atomically; returns the
        PREVIOUS uptime reading (the caller's restart detection)."""
        with self._lock:
            self.status = status
            self.healthy = ok
            self.draining = (status == "draining"
                             or bool(block.get("draining")))
            self.slots = int(block.get("slots", self.slots) or 0)
            self.free_slots = int(block.get("free_slots",
                                            self.free_slots) or 0)
            self.queue_depth = int(block.get("queue_depth",
                                             self.queue_depth) or 0)
            self.model_hash = block.get("model_hash", self.model_hash)
            # role-less payloads (pre-disagg replicas, rolling upgrades)
            # read as "both" — the monolithic behavior they implement
            self.role = str(block.get("role") or "both")
            prev_uptime = self.uptime_s
            self.pid = int(block.get("pid", self.pid) or 0)
            self.uptime_s = float(block.get("uptime_s",
                                            self.uptime_s) or 0.0)
            if ok:
                self.consecutive_failures = 0
                self.last_ok = time.monotonic()
                if (self.retry_after_until and self.queue_depth == 0
                        and self.free_slots > 0):
                    # the saturation the Retry-After reported has drained
                    # (idle queue, free slots): end the cooldown early so a
                    # recovered replica rejoins within one poll instead of
                    # sitting out the full advisory window
                    self.retry_after_until = 0.0
            return prev_uptime


def parse_addr(addr: str) -> tuple[str, int]:
    host, _, port = addr.rpartition(":")
    if not host or not port.isdigit():
        raise ValueError(f"bad replica address {addr!r} (want host:port)")
    return host, int(port)


class Membership:
    def __init__(self, addrs: list[str], poll_interval: float = 2.0,
                 poll_timeout: float = 2.0, backoff_cap: float = 30.0,
                 down_log_interval: float = 30.0):
        if not addrs:
            raise ValueError("router needs at least one --replica host:port")
        self.replicas = [Replica(*parse_addr(a)) for a in addrs]
        if len({r.id for r in self.replicas}) != len(self.replicas):
            raise ValueError("duplicate replica addresses")
        self.poll_interval = poll_interval
        self.poll_timeout = poll_timeout
        # exponential poll backoff for unreachable replicas, jittered so a
        # fleet of routers (or several dead replicas) never re-probes in
        # lockstep; capped so a recovered replica rejoins within backoff_cap
        self.backoff_cap = backoff_cap
        self.down_log_interval = down_log_interval
        # gray-failure detector (fleet/latency.py): evaluated once per poll
        # round on this thread — probation ENTRY is poll-driven, EXIT is
        # canary-outcome-driven on the proxy path. None = detection off;
        # serve_router attaches its RouterState's detector before start().
        self.detector = None
        self._backoff_rng = random.Random(0xD11A)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._fleet_hash: str | None = None

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def start(self) -> None:
        """Synchronous first poll (the router can route immediately after
        start() returns), then the background refresh loop."""
        self.poll_once()
        self._thread = threading.Thread(target=self._run, name="fleet-poll",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.poll_timeout + 1.0)

    def _run(self) -> None:
        while not self._stop.wait(self.poll_interval):
            self.poll_once(force=False)

    # ------------------------------------------------------------------
    # polling
    # ------------------------------------------------------------------

    def poll_once(self, force: bool = True) -> None:
        """Poll the fleet. `force=True` (the default — explicit callers mean
        "poll NOW") ignores per-replica backoff; the background loop passes
        False so unreachable replicas are probed on their backoff schedule
        instead of every interval."""
        now = time.monotonic()
        for rep in self.replicas:
            if not force and rep.next_poll_t > now:
                continue  # unreachable replica inside its backoff window
            self._poll(rep)
        if self.detector is not None:
            self.detector.evaluate(self.replicas)
        _IN_ROTATION.set(len(self.in_rotation()))

    def _poll(self, rep: Replica) -> None:
        t0 = time.perf_counter()
        try:
            faults.fire("router.health", replica=rep.id)
            conn = HTTPConnection(rep.host, rep.port,
                                  timeout=self.poll_timeout)
            try:
                conn.request("GET", "/healthz")
                resp = conn.getresponse()
                body = json.loads(resp.read() or b"{}")
            finally:
                conn.close()
            # healthz round-trip: a latency signal that exists before any
            # traffic flows — load_score tie-break + snapshot()/router
            # /healthz visibility (docs/FLEET.md "Gray-failure resilience")
            rep.lat.health_rtt.note(time.perf_counter() - t0)
        except Exception:
            rep.mark_unreachable(clear_draining=True)
            _POLLS.labels(outcome="unreachable").inc()
            self._note_unreachable(rep)
            return
        if rep.down_since > 0.0:  # reachable again: reset backoff, say so once
            print(f"🟢 replica {rep.id} reachable again after "
                  f"{time.monotonic() - rep.down_since:.0f}s down")
        rep.next_poll_t = 0.0
        rep.down_since = 0.0
        rep.last_down_log = 0.0
        status = body.get("status",
                          "ok" if resp.status == 200 else "unhealthy")
        block = body.get("replica") or {}
        ok = resp.status == 200 and status == "ok"
        # one atomic application: a concurrent load_score/snapshot (proxy
        # threads routing) must never see a half-applied poll
        prev_uptime = rep.apply_poll(status, ok, block)
        if prev_uptime and rep.uptime_s and rep.uptime_s < prev_uptime:
            print(f"⚠️  replica {rep.id} restarted between polls "
                  f"(uptime {prev_uptime:.0f}s -> {rep.uptime_s:.0f}s)")
        if ok:
            if rep.model_hash:
                if self._fleet_hash is None:
                    self._fleet_hash = rep.model_hash
                elif rep.model_hash != self._fleet_hash:
                    _HASH_MISMATCH.inc()
                    if not rep.hash_warned:  # once per mismatch episode
                        rep.hash_warned = True
                        print(f"⚠️  replica {rep.id} serves model hash "
                              f"{rep.model_hash}, fleet is "
                              f"{self._fleet_hash} — mid-rolling-upgrade or "
                              "a misdeployed checkpoint")
                else:
                    rep.hash_warned = False
        _POLLS.labels(outcome=status).inc()

    def _note_unreachable(self, rep: Replica) -> None:
        """Failure bookkeeping: exponential backoff with jitter on the next
        background poll (2^k × poll_interval, capped, ×uniform[0.5, 1.0)) and
        a CAPPED down log — first failure logs immediately, then at most one
        line per down_log_interval, so a dead replica cannot spam one line
        per poll for hours."""
        now = time.monotonic()
        # exponent capped BEFORE exponentiating: a replica down for hours
        # reaches failure counts where 2**k overflows float multiplication
        # and would kill the poller thread (2**32 × any interval is already
        # far past every cap)
        exp = min(max(rep.consecutive_failures - 1, 0), 32)
        backoff = min(self.poll_interval * (2 ** exp), self.backoff_cap)
        rep.next_poll_t = now + backoff * (0.5 + 0.5
                                           * self._backoff_rng.random())
        if rep.down_since == 0.0:
            rep.down_since = now
            rep.last_down_log = now
            print(f"🔴 replica {rep.id} unreachable; polling with backoff "
                  f"(cap {self.backoff_cap:.0f}s)")
        elif now - rep.last_down_log >= self.down_log_interval:
            rep.last_down_log = now
            print(f"🔴 replica {rep.id} still unreachable "
                  f"({now - rep.down_since:.0f}s, "
                  f"{rep.consecutive_failures} failed polls)")

    # ------------------------------------------------------------------
    # rotation / selection
    # ------------------------------------------------------------------

    def in_rotation(self) -> list[Replica]:
        """Replicas eligible for NORMAL routing: healthy, not draining, not
        in gray-failure probation, and outside any Retry-After cooldown
        their own 503 asked for."""
        now = time.monotonic()
        return [r for r in self.replicas
                if r.healthy and not r.draining and not r.degraded
                and r.retry_after_until <= now]

    def canary_candidates(self, exclude: set[str] = frozenset()
                          ) -> list[Replica]:
        """Degraded-but-alive replicas eligible for canary traffic (and for
        the serving-beats-shedding fallback when normal rotation empties)."""
        now = time.monotonic()
        return [r for r in self.replicas
                if r.healthy and not r.draining and r.degraded
                and r.retry_after_until <= now and r.id not in exclude]

    def by_id(self, rep_id: str) -> Replica | None:
        for r in self.replicas:
            if r.id == rep_id:
                return r
        return None

    def mark_failed(self, rep: Replica) -> None:
        """Proxy-path ejection: a connect/read failure takes the replica out
        of rotation NOW; the poller re-admits it on the next clean poll."""
        rep.mark_unreachable()
        _IN_ROTATION.set(len(self.in_rotation()))

    def least_loaded(self, exclude: set[str] = frozenset()
                     ) -> Replica | None:
        cands = [r for r in self.in_rotation() if r.id not in exclude]
        return min(cands, key=Replica.load_score) if cands else None

    def inflight_inc(self, rep: Replica) -> None:
        with rep._lock:
            rep.inflight += 1

    def inflight_dec(self, rep: Replica) -> None:
        with rep._lock:
            rep.inflight = max(rep.inflight - 1, 0)
