"""Gray-failure resilience primitives: latency tracking, probation, budgets.

The hard failures PR 9 survives (SIGKILL, wedged engine) announce themselves:
a dead socket, an unhealthy /healthz. The *gray* failure does not — a replica
that answers every health poll while decoding 10x slow (GC pauses, thermal
throttling, a lossy NIC, one contended core) passes membership's checks and
silently drags fleet-wide tail latency, because routing reads only the polled
queue-depth block and every proxy try shares one fixed 120 s socket timeout.
This module is the dependency-free measurement + policy layer the router
threads through the fleet tier (docs/FLEET.md "Gray-failure resilience"):

- **LatencyStat** — a windowed streaming estimator (ring of the last N
  samples for on-demand quantiles, plus an EWMA) fed by REAL proxy outcomes:
  TTFB per try, per-token pace per relayed stream event, healthz round-trip
  per membership poll. No numpy — the router process stays stdlib-only.
- **GrayFailureDetector** — outlier ejection with probation: a replica whose
  observed TTFB is a configurable multiple of its PEERS' median leaves
  normal rotation into a `degraded` state, keeps receiving a trickle of
  canary traffic, and rejoins only after N consecutive in-band canaries.
  A quorum floor stops the detector from ejecting a uniformly-slow fleet
  below `quorum_frac` of its healthy replicas — uniform slowness degrades
  honestly instead of shedding everything.
- **TokenBudget** — the spend governor behind request hedging and failover
  retries: tokens accrue from observed work (a fraction per try / per
  success) up to a cap, and each hedge/retry spends one. Under overload the
  budget drains and the failover machinery stops amplifying load into a
  retry storm; under normal traffic it is never the binding constraint.

Policy knobs live in **GrayConfig** (one object, wired from apps/router.py
flags) so the fault matrix and the chaos bench can arm aggressive variants
without growing serve_router's signature per knob.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

from ..obs import metrics

__all__ = ["LatencyStat", "ReplicaLatency", "TokenBudget", "GrayConfig",
           "GrayFailureDetector"]

_DEGRADED = metrics.gauge(
    "router_replicas_degraded",
    "Replicas currently in gray-failure probation (out of normal rotation, "
    "receiving canary traffic only)")
_PROBATION = metrics.counter(
    "router_probation_total",
    "Gray-failure probation transitions (docs/FLEET.md): enter = TTFB "
    "outlier ejected from rotation, exit = rejoined after consecutive "
    "in-band canaries", labelnames=("event",))
_QUORUM_HELD = metrics.counter(
    "router_probation_quorum_held_total",
    "Ejections the detector SKIPPED because they would drop rotation below "
    "the quorum floor (a uniformly slow fleet must degrade honestly, not "
    "shed itself empty)")


class LatencyStat:
    """Windowed streaming latency estimator: a ring of the last `window`
    samples (quantiles computed on demand over a snapshot) plus a decayed
    EWMA. Sample counts are monotonic; the window bounds memory and keeps
    quantiles RECENT — a replica that recovered an hour ago must not be
    judged on last hour's tail."""

    def __init__(self, window: int = 128, alpha: float = 0.2):
        assert window >= 4 and 0.0 < alpha <= 1.0
        self._window = window
        self._alpha = alpha
        self._lock = threading.Lock()  # guards: _ring, _n, _ewma
        self._ring: list[float] = []
        self._n = 0          # total samples ever noted
        self._ewma = 0.0

    def note(self, v: float) -> None:
        v = float(v)
        with self._lock:
            if len(self._ring) < self._window:
                self._ring.append(v)
            else:
                self._ring[self._n % self._window] = v
            self._n += 1
            self._ewma = (v if self._n == 1
                          else self._ewma + self._alpha * (v - self._ewma))

    def count(self) -> int:
        with self._lock:
            return self._n

    def ewma(self) -> float:
        with self._lock:
            return self._ewma

    def quantile(self, q: float) -> float | None:
        """q-quantile over the current window; None before any sample."""
        with self._lock:
            if not self._ring:
                return None
            data = sorted(self._ring)
        idx = min(int(q * len(data)), len(data) - 1)
        return data[max(idx, 0)]

    def reset(self) -> None:
        with self._lock:
            self._ring = []
            self._n = 0
            self._ewma = 0.0


class ReplicaLatency:
    """Per-replica outcome signals, one LatencyStat each (each stat carries
    its own lock, so note paths from proxy handler threads and the poller
    never contend on a shared structure):

    - `ttfb`: seconds from issuing the upstream request to its response
      headers (api_server defers SSE headers to the first delta, so this IS
      first-byte time, queue wait included) — the primary gray signal;
    - `pace`: per-event inter-arrival gap while relaying a stream — catches
      the replica that starts fast and decodes slow;
    - `health_rtt`: /healthz round-trip from the membership poller — a
      latency signal that exists BEFORE any traffic flows (load_score
      tie-break), and the tie-break between two idle replicas."""

    __slots__ = ("ttfb", "pace", "health_rtt")

    def __init__(self):
        self.ttfb = LatencyStat(window=128)
        self.pace = LatencyStat(window=256)
        self.health_rtt = LatencyStat(window=32)

    def snapshot_ms(self) -> dict:
        """Rounded-ms view for /healthz // /v1/stats (None = no samples)."""
        def ms(v):
            return None if v is None else round(v * 1000.0, 2)
        return {"ttfb_p50_ms": ms(self.ttfb.quantile(0.5)),
                "ttfb_p95_ms": ms(self.ttfb.quantile(0.95)),
                "pace_p95_ms": ms(self.pace.quantile(0.95)),
                "health_rtt_ms": (None if self.health_rtt.count() == 0
                                  else ms(self.health_rtt.ewma()))}


class TokenBudget:
    """Work-proportional spend governor (hedges, failover retries). Tokens
    accrue at `rate` per note() up to `cap`; each spend() takes one whole
    token. Starts FULL: a cold router must still be able to fail over (the
    budget bounds storms, it does not ration the first incident)."""

    def __init__(self, rate: float, cap: float):
        assert rate >= 0.0 and cap >= 1.0
        self.rate = rate
        self.cap = float(cap)
        self._lock = threading.Lock()  # guards: _tokens, _spent, _noted
        self._tokens = float(cap)
        self._spent = 0
        self._noted = 0

    def note(self, n: float = 1.0) -> None:
        with self._lock:
            self._noted += 1
            self._tokens = min(self._tokens + self.rate * n, self.cap)

    def spend(self) -> bool:
        with self._lock:
            if self._tokens < 1.0:
                return False
            self._tokens -= 1.0
            self._spent += 1
            return True

    def level(self) -> float:
        with self._lock:
            return self._tokens

    def stats(self) -> dict:
        with self._lock:
            return {"tokens": round(self._tokens, 3), "cap": self.cap,
                    "rate": self.rate, "spent": self._spent,
                    "noted": self._noted}


@dataclass
class GrayConfig:
    """Gray-failure policy knobs (docs/FLEET.md "Gray-failure resilience").
    One object instead of a dozen serve_router parameters; apps/router.py
    builds it from flags, the fault matrix and chaos bench arm aggressive
    variants directly."""

    # outlier ejection / probation
    eject_multiple: float = 4.0   # degraded when TTFB p50 >= this x peer median
    min_samples: int = 20         # per-replica TTFB samples before judging
    probation_exits: int = 3      # consecutive in-band canaries to rejoin
    quorum_frac: float = 0.5      # never eject below ceil(frac x healthy)
    canary_every: int = 8         # every Nth first-try pick canaries a degraded replica
    # adaptive timeouts (pre-first-byte vs stream idle gap)
    ttfb_floor: float = 5.0       # adaptive TTFB timeout lower clamp (s)
    ttfb_cap: float | None = None  # upper clamp; None = the --proxy-timeout cap
    ttfb_mult: float = 6.0        # timeout = mult x observed fleet TTFB p95
    idle_timeout: float = 0.0     # fixed stream idle-gap timeout; 0 = adaptive
    idle_floor: float = 10.0      # adaptive idle-gap lower clamp (s)
    idle_mult: float = 50.0       # idle = mult x observed fleet pace p99
    min_lat_samples: int = 32     # fleet samples before timeouts/hedges adapt
    # bounded request hedging (pre-first-byte duplicate try)
    hedge: bool = True
    hedge_pct: float = 0.05       # budget accrual: extra tries <= pct of tries (+burst)
    hedge_burst: float = 4.0      # budget cap (also the cold-start allowance)
    hedge_floor: float = 0.05     # minimum hedge delay (s)
    # fixed hedge delay override (s); 0 = adaptive (~fleet TTFB p95). The
    # adaptive delay is right when slow replicas are a small minority; in a
    # tiny fleet where one of two replicas is slow, HALF the samples are
    # slow and p95-based hedging defers itself — pin the delay instead.
    hedge_delay: float = 0.0
    # global failover retry budget (refilled by successes)
    retry_ratio: float = 0.5      # tokens added per delivered completion
    retry_cap: float = 16.0


class GrayFailureDetector:
    """Outlier ejection with probation over Membership's replicas.

    `evaluate` runs on the membership poll thread (periodic, low rate);
    `note_outcome` runs on proxy handler threads after every successful
    upstream open. Both read per-replica LatencyStat objects (self-locked)
    and mutate replica probation state through the Replica's own lock-held
    methods, so there is no detector-owned shared mutable state beyond the
    metrics counters."""

    def __init__(self, cfg: GrayConfig):
        self.cfg = cfg

    # -- fleet statistics ----------------------------------------------

    def _peer_median_ttfb(self, rep, replicas) -> float | None:
        """Median of the OTHER candidate replicas' TTFB p50s. Peers exclude
        the judged replica (with 2 replicas a self-inclusive median could
        never flag anything: no member exceeds 2x a median it is half of)
        and exclude already-degraded replicas (their slowness must not
        drag the baseline toward them)."""
        p50s = []
        for r in replicas:
            if r is rep or not r.healthy or r.draining or r.degraded:
                continue
            if r.lat.ttfb.count() >= self.cfg.min_samples:
                q = r.lat.ttfb.quantile(0.5)
                if q is not None:
                    p50s.append(q)
        if not p50s:
            return None
        p50s.sort()
        return p50s[len(p50s) // 2]

    def _quorum_floor(self, replicas) -> int:
        healthy = sum(1 for r in replicas if r.healthy and not r.draining)
        return max(int(self.cfg.quorum_frac * healthy + 0.999), 1)

    # -- probation entry (poll thread) ---------------------------------

    def evaluate(self, replicas) -> None:
        """One detection pass: flag TTFB outliers, respecting the quorum
        floor. Exit is canary-driven (note_outcome), never time-driven — a
        replica rejoins because it MEASURED healthy, not because it waited."""
        floor = self._quorum_floor(replicas)
        for rep in replicas:
            if rep.degraded or not rep.healthy or rep.draining:
                continue
            if rep.lat.ttfb.count() < self.cfg.min_samples:
                continue
            peer_median = self._peer_median_ttfb(rep, replicas)
            if peer_median is None or peer_median <= 0.0:
                continue
            p50 = rep.lat.ttfb.quantile(0.5)
            if p50 is None or p50 < self.cfg.eject_multiple * peer_median:
                continue
            # count what is actually ROUTABLE right now: a replica sitting
            # out a Retry-After cooldown is healthy but not in rotation,
            # and the floor's promise is about where traffic can GO
            in_rotation = sum(1 for r in replicas
                              if r.healthy and not r.draining
                              and not r.degraded and not r.in_cooldown())
            if in_rotation - 1 < floor:
                _QUORUM_HELD.inc()
                continue
            if rep.set_degraded(True):
                _PROBATION.labels(event="enter").inc()
                print(f"🟡 replica {rep.id} entering gray-failure probation "
                      f"(TTFB p50 {p50 * 1000:.0f}ms >= "
                      f"{self.cfg.eject_multiple:g}x peer median "
                      f"{peer_median * 1000:.0f}ms); canary traffic only")
        _DEGRADED.set(sum(1 for r in replicas if r.degraded))

    # -- probation exit (proxy outcome path) ---------------------------

    def note_outcome(self, rep, ttfb_s: float, replicas) -> None:
        """Fold one successful upstream open's TTFB into probation state:
        for a degraded replica, an in-band canary (TTFB back under the
        ejection threshold vs its peers) counts toward rejoin; an
        out-of-band one resets the streak."""
        if not rep.degraded:
            return
        peer_median = self._peer_median_ttfb(rep, replicas)
        if peer_median is None:
            # no peer baseline (peers draining/unjudged): the canary can't
            # be JUDGED, so it must not advance the rejoin streak — a
            # still-slow replica would otherwise walk out of probation the
            # moment its peers stop being comparable. (An emptied rotation
            # still serves: pick() falls back to canary_candidates.)
            return
        in_band = ttfb_s < self.cfg.eject_multiple * peer_median
        streak = rep.canary_note(in_band)
        if in_band and streak >= self.cfg.probation_exits:
            # rejoin: the window still holds probation-era samples, so a
            # fresh detection pass must start from the replica's NEW
            # behavior, not re-eject it on stale tail
            rep.lat.ttfb.reset()
            rep.lat.pace.reset()
            if rep.set_degraded(False):
                _PROBATION.labels(event="exit").inc()
                print(f"🟢 replica {rep.id} rejoined from probation "
                      f"({streak} consecutive in-band canaries)")
            _DEGRADED.set(sum(1 for r in replicas if r.degraded))
