"""Prefill/decode disaggregation: role-aware planning + KV-block streaming.

The topology (docs/DISAGG.md): replicas advertise a ROLE in their healthz
load block — ``prefill`` (long-prompt admissions land here), ``decode``
(short chains and the decode half of split requests), or ``both`` (the
monolithic default). The router splits a long-prompt completion in two:

1. **plan** (`DisaggPlanner.plan`, router side, stdlib-only): estimate the
   prompt length (chars/4 — the router never tokenizes, same arithmetic as
   the tenancy cost model); at/over the threshold, POST the request's
   messages to a prefill-capable replica's ``/v1/kv``. That replica runs
   the prefill into its own device pool, snapshots the committed
   prompt-prefix KV blocks to host, and answers with a transfer
   descriptor (xfer id, token count, block geometry, token hash, wire
   mode). The descriptor is injected into the request body as
   ``kv_source`` and the request routes onward preferring decode-capable
   replicas.

2. **import** (`import_kv_source`, decode-replica side): before admission,
   the decode replica verifies the descriptor against ITS OWN tokenization
   (token-hash mismatch = different tokenizer/model — skip, local
   prefill), then pulls the blocks over HTTP in bounded chunks
   (``GET /v1/kv/<id>?from=F&n=N`` — every range is independently
   re-fetchable, so a flaky connection retries per chunk) and inserts them
   into the engine's prefix cache as HOST blocks (`BatchEngine.
   import_kv_blocks`): a paged directory adopts them as COLD nodes and the
   existing admission path promotes them to device; a dense cache inserts
   them into its host pool and the existing seed path scatters them. The
   import is therefore pure host bookkeeping — no device array is ever
   touched off the scheduler thread — and admission then reuses the
   shipped span instead of re-prefilling ("resume at token 0 with shipped
   KV", the degenerate case of PR 9's resume protocol).

**Failure semantics**: every failure in the split path degrades to the
monolithic behavior with zero client-visible effect — a failed plan or
prefill POST routes the untouched request normally; a mid-transfer death
(prefill replica killed, truncated wire buffer, chunk fetch exhausting its
retry) abandons the import and the decode replica simply prefills locally.
The fault matrix pins this (perf/fault_matrix.py disagg family).

This module is imported by the stdlib-only router process: numpy and the
wire codec (cache/wire.py) load lazily inside the decode-replica-side
functions only.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
import uuid
from http.client import HTTPConnection

from ..obs import metrics, trace
from ..resilience import faults
from .latency import LatencyStat
from .membership import Membership, Replica, parse_addr

__all__ = ["ROLES", "PREFILL_ROLES", "DECODE_ROLES", "DisaggPlanner",
           "KVTransferTable", "tokens_hash", "estimate_prompt_tokens",
           "fetch_kv_blocks", "import_kv_source"]

ROLES = ("prefill", "decode", "both")
PREFILL_ROLES = ("prefill", "both")
DECODE_ROLES = ("decode", "both")

# Router-side disaggregation telemetry (docs/OBSERVABILITY.md).
_PLANNED = metrics.counter(
    "router_disagg_requests_total",
    "Long-prompt completions considered for prefill/decode splitting, by "
    "outcome: split (KV shipped), warm (a decode-capable replica already "
    "holds the prefix — routed there, no transfer), no_topology (no "
    "distinct prefill+decode pair in rotation), empty (prompt too short "
    "for one full block), or prefill_error (the prefill POST failed — "
    "routed monolithic)", labelnames=("outcome",))
_PREFILL_SECONDS = metrics.histogram(
    "router_disagg_prefill_seconds",
    "Wall time of the planner's /v1/kv prefill POST (remote prefill + "
    "host KV snapshot, before the decode leg is routed)")

# Decode-replica-side import telemetry.
_IMPORTS = metrics.counter(
    "disagg_import_requests_total",
    "kv_source imports attempted at the decode replica, by outcome: "
    "imported, config_mismatch (block geometry differs), hash_mismatch "
    "(tokenizations disagree), error (fetch/decode failed -> local "
    "prefill), empty (descriptor carried no blocks)",
    labelnames=("outcome",))
_IMPORT_TOKENS = metrics.counter(
    "disagg_import_tokens_total",
    "Prompt tokens whose KV arrived over the wire and entered the prefix "
    "cache (the span admission reuses instead of re-prefilling)")
_IMPORT_BYTES = metrics.counter(
    "disagg_import_bytes_total",
    "Wire bytes fetched from prefill replicas (post-codec payload)")
_IMPORT_SECONDS = metrics.histogram(
    "disagg_import_seconds",
    "Wall time of one kv_source import (all chunk fetches + host insert)")
_FETCH_HEDGES = metrics.counter(
    "disagg_fetch_hedges_total",
    "Duplicate KV-chunk fetches raced against a chunk quiet past the "
    "adaptive soft deadline (ranges are independently re-fetchable and "
    "idempotent, so first result wins — the gray-failure hedging idiom "
    "applied to the transfer leg)")
_REPREFILL = metrics.counter(
    "disagg_reprefill_tokens_total",
    "Shipped-span tokens a disaggregated admission re-prefilled anyway "
    "(0 in a healthy fleet — the mixed-context bench asserts it in-run; "
    "nonzero means the imported blocks missed the radix lookup)")


def tokens_hash(tokens) -> str:
    """Short stable hash of a token-id sequence. The decode replica compares
    it against its OWN tokenization of the prompt before importing: a
    mismatch means the fleet is serving mixed tokenizers/models (rolling
    upgrade) and the shipped KV would seed garbage."""
    h = hashlib.sha1()
    for t in tokens:
        h.update(int(t).to_bytes(4, "little"))
    return h.hexdigest()[:16]


def estimate_prompt_tokens(body: dict) -> float:
    """Router-side prompt-length estimate: rendered chars / 4 plus a few
    per-message template tokens (the router never tokenizes — same
    arithmetic as the tenancy cost model)."""
    chars = 0
    msgs = 0
    for m in body.get("messages", []):
        if isinstance(m, dict):
            chars += len(str(m.get("content", "")))
            msgs += 1
    return chars / 4.0 + 4.0 * msgs


# ----------------------------------------------------------------------
# router side: the planner
# ----------------------------------------------------------------------

class DisaggPlanner:
    """Decides which completions split and executes the prefill leg.
    Stateless beyond config — every decision reads the live membership."""

    def __init__(self, threshold_tokens: int = 0, timeout: float = 60.0):
        self.threshold = max(int(threshold_tokens), 0)
        self.timeout = timeout

    @property
    def enabled(self) -> bool:
        return self.threshold > 0

    # -- role-aware routing preference ---------------------------------

    def warm_decode(self, membership: Membership, affinity,
                    key: bytes) -> str | None:
        """Replica id of a decode-capable replica whose recorded routes
        cover EVERY full block of the request's affinity key — the prefix
        is already hot there, so shipping KV it already holds would waste
        a whole transfer (`insert` would discard the copies). Same
        staleness caveat as affinity routing itself: a restarted replica's
        stale record costs one cold prefill, never correctness."""
        if affinity is None or not key:
            return None
        decode_ids = {r.id for r in membership.in_rotation()
                      if r.role in DECODE_ROLES}
        if not decode_ids:
            return None
        rep, depth = affinity.lookup(key, decode_ids)
        if rep is not None and depth >= max(
                len(key) // affinity.block_bytes, 1):
            return rep
        return None

    def prefer_roles(self, body: dict, membership: Membership,
                     affinity=None, key: bytes = b"") -> tuple | None:
        """Role filter for pick(): requests carrying shipped KV (or short
        decode chains) prefer decode-capable replicas; long prompts that
        did NOT split prefer prefill-capable ones — UNLESS a decode
        replica already holds the prefix (warm_decode), in which case the
        request should follow the warm cache. None when the fleet is
        homogeneous (all "both") — role preference must not perturb
        monolithic fleets."""
        if not self.enabled:
            return None
        if not any(r.role != "both" for r in membership.replicas):
            return None
        if "kv_source" in body:
            return DECODE_ROLES
        if estimate_prompt_tokens(body) >= self.threshold:
            if self.warm_decode(membership, affinity, key) is not None:
                return DECODE_ROLES
            return PREFILL_ROLES
        return DECODE_ROLES

    # -- the split ------------------------------------------------------

    def plan(self, membership: Membership, body: dict,
             tenant_hdrs: dict | None = None, affinity=None,
             key: bytes = b"") -> dict | None:
        """Attempt the split: returns the ``kv_source`` descriptor to inject
        into the request body, or None for the monolithic path. Never
        raises — every failure degrades to monolithic routing.
        `tenant_hdrs` (X-Tenant/X-Class) are relayed onto the prefill leg
        so the prefill replica's quota/fairness accounting attributes the
        remote prefill to the requesting tenant at its real class."""
        # a body already carrying kv_source or resume went through a first
        # pass (client-side durability layer, or a journaled failover whose
        # entry kept the injected descriptor) — never re-split it
        if (not self.enabled or "kv_source" in body or "resume" in body
                or estimate_prompt_tokens(body) < self.threshold):
            return None
        rotation = membership.in_rotation()
        if not any(r.role != "both" for r in rotation):
            # homogeneous fleet (all "both" — including role-less replicas
            # mid-rolling-upgrade, which parse as "both"): never split.
            # Splitting here would pay a remote prefill for zero isolation
            # gain, and pre-role replicas don't even serve /v1/kv — the
            # same heterogeneity gate prefer_roles() applies.
            _PLANNED.labels(outcome="no_topology").inc()
            return None
        if self.warm_decode(membership, affinity, key) is not None:
            # the prefix is already hot on a decode-capable replica:
            # routing there (prefer_roles follows the same signal) beats
            # shipping KV its cache would discard as already-covered
            _PLANNED.labels(outcome="warm").inc()
            return None
        dedicated = [r for r in rotation if r.role == "prefill"]
        prefills = dedicated or [r for r in rotation if r.role == "both"]
        decodes = [r for r in rotation if r.role in DECODE_ROLES]
        if not prefills:
            _PLANNED.labels(outcome="no_topology").inc()
            return None
        pre = min(prefills, key=Replica.load_score)
        if not any(d.id != pre.id for d in decodes):
            # no DISTINCT decode candidate: shipping KV back to the same
            # replica is strictly worse than serving it monolithic
            _PLANNED.labels(outcome="no_topology").inc()
            return None
        t0 = time.perf_counter()
        try:
            faults.fire("disagg.plan", replica=pre.id)
            with trace.span("disagg.plan", {"replica": pre.id}):
                desc = self._start_transfer(pre, body, tenant_hdrs)
        except Exception:
            _PLANNED.labels(outcome="prefill_error").inc()
            return None
        _PREFILL_SECONDS.observe(time.perf_counter() - t0)
        if not desc or not desc.get("n_blocks"):
            _PLANNED.labels(outcome="empty").inc()
            return None
        desc["replica"] = pre.id
        _PLANNED.labels(outcome="split").inc()
        return desc

    def _start_transfer(self, rep: Replica, body: dict,
                        tenant_hdrs: dict | None = None) -> dict | None:
        """POST /v1/kv on the prefill replica: run the prefill, snapshot
        the blocks, get the transfer descriptor back."""
        payload = {"messages": body.get("messages", [])}
        headers = {"Content-Type": "application/json"}
        if tenant_hdrs:
            headers.update(tenant_hdrs)
        conn = HTTPConnection(rep.host, rep.port, timeout=self.timeout)
        try:
            conn.request("POST", "/v1/kv", json.dumps(payload).encode(),
                         headers)
            resp = conn.getresponse()
            data = resp.read()
            if resp.status != 200:
                # a refusing prefill replica (4xx/5xx) is a failed PLAN,
                # not an empty transfer — count it as prefill_error
                raise RuntimeError(f"/v1/kv -> {resp.status}")
            desc = json.loads(data)
            if not isinstance(desc, dict):
                raise RuntimeError("/v1/kv returned a non-object body")
            return desc
        finally:
            conn.close()


# ----------------------------------------------------------------------
# prefill-replica side: the transfer table
# ----------------------------------------------------------------------

class _Transfer:
    __slots__ = ("xfer_id", "tokens", "blocks", "block_tokens", "created")

    def __init__(self, xfer_id, tokens, blocks, block_tokens):
        self.xfer_id = xfer_id
        self.tokens = tokens          # token ids the blocks cover
        self.blocks = blocks          # [(k, v)] host arrays per block
        self.block_tokens = block_tokens
        self.created = time.monotonic()


class KVTransferTable:
    """Bounded TTL'd table of exportable prefill transfers on a replica.
    Entries hold HOST snapshots of the committed prompt blocks (taken on
    the scheduler thread at request finish), so an export range is
    re-servable for the whole TTL whatever the device pool does meanwhile
    — that is what makes the chunked transfer resumable. Once a fetch
    covers the FINAL block the transfer is CONSUMED: its remaining
    lifetime drops to `consumed_ttl` (late retries can still re-fetch
    briefly) so completed transfers stop crowding the capped table out
    from under still-pending ones."""

    def __init__(self, cap: int = 32, ttl: float = 120.0,
                 consumed_ttl: float = 10.0):
        self.cap = max(int(cap), 1)
        self.ttl = ttl
        self.consumed_ttl = max(consumed_ttl, 0.0)
        self._lock = threading.Lock()  # guards: _live
        self._live: dict[str, _Transfer] = {}

    def _sweep_locked(self) -> None:  # holds: self._lock
        """TTL expiry only — cap-eviction lives in open() (get()/stats()
        sweep too, and must never evict a LIVE entry to 'make room')."""
        now = time.monotonic()
        dead = [x for x, t in self._live.items()
                if now - t.created > self.ttl]
        for x in dead:
            del self._live[x]

    def open(self, tokens: list[int], blocks: list,
             block_tokens: int, wire: str) -> dict:
        """Register a transfer; returns the descriptor the planner injects
        as ``kv_source`` (sans the replica address, which the ROUTER fills
        in — the replica may be bound to 0.0.0.0)."""
        xfer_id = f"kv-{uuid.uuid4().hex[:12]}"
        with self._lock:
            self._sweep_locked()
            while len(self._live) >= self.cap:  # room for the NEW entry
                oldest = min(self._live.values(), key=lambda t: t.created)
                del self._live[oldest.xfer_id]
            self._live[xfer_id] = _Transfer(xfer_id, list(tokens),
                                            list(blocks), block_tokens)
        return {"xfer_id": xfer_id, "n_tokens": len(tokens),
                "n_blocks": len(blocks), "block_tokens": block_tokens,
                "tokens_hash": tokens_hash(tokens), "wire": wire}

    def get(self, xfer_id: str) -> _Transfer | None:
        with self._lock:
            # full sweep, not just this id: expiry must not be open()-lazy,
            # or an idle prefill replica pins up to `cap` host KV snapshots
            # long past their TTL (and stats() would overstate pressure)
            self._sweep_locked()
            return self._live.get(xfer_id)

    def note_served(self, t: _Transfer, frm: int, n: int) -> None:
        """Consumption tracking: a range covering the final block marks the
        transfer consumed — rebase its clock so only `consumed_ttl` of
        lifetime remains (never EXTENDS a transfer's life)."""
        if frm + n < len(t.blocks):
            return
        with self._lock:
            t.created = min(
                t.created,
                time.monotonic() - max(self.ttl - self.consumed_ttl, 0.0))

    def stats(self) -> dict:
        with self._lock:
            self._sweep_locked()
            return {"live": len(self._live), "cap": self.cap,
                    "ttl_s": self.ttl}


# ----------------------------------------------------------------------
# decode-replica side: fetch + import
# ----------------------------------------------------------------------

def fetch_kv_blocks(host: str, port: int, xfer_id: str, frm: int, n: int,
                    timeout: float = 30.0) -> list:
    """Fetch blocks [frm, frm+n) of a transfer and decode them to host
    (K, V) pairs. One HTTP request per call — any range is independently
    re-fetchable (the resumability primitive). Lazy-imports the wire codec
    (numpy): the stdlib-only router imports this module but never calls
    this."""
    from ..cache.wire import decode_blocks

    faults.fire("disagg.fetch", xfer=xfer_id)
    conn = HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("GET", f"/v1/kv/{xfer_id}?from={frm}&n={n}")
        resp = conn.getresponse()
        data = resp.read()
        if resp.status != 200:
            raise RuntimeError(
                f"kv fetch {xfer_id}[{frm}:{frm + n}] -> {resp.status}")
    finally:
        conn.close()
    _IMPORT_BYTES.inc(len(data))
    blocks = decode_blocks(data)
    if len(blocks) != n:
        raise RuntimeError(
            f"kv fetch {xfer_id}[{frm}:{frm + n}] returned {len(blocks)} "
            "blocks")
    return blocks


def _fetch_chunk_hedged(host: str, port: int, xfer_id: str, frm: int,
                        n: int, *, timeout: float,
                        chunk_lat: LatencyStat) -> list:
    """One chunk fetch with the gray-failure treatment (docs/FLEET.md
    "Gray-failure resilience") applied to the transfer leg: once earlier
    chunks of this import have landed, the per-chunk timeout TIGHTENS to a
    multiple of the observed chunk time (capped at the configured
    `timeout` — a prefill replica that served chunk 1 in 30 ms should not
    get 30 s to wedge on chunk 2), and a fetch quiet past the adaptive
    soft deadline races ONE duplicate fetch of the same range — ranges are
    independently re-fetchable and idempotent, so first result wins and
    the loser is discarded. The first chunk (no evidence yet) runs plain.
    Raises when every attempt failed; the caller's per-chunk retry and the
    local-prefill fallback keep the failure semantics unchanged."""
    if chunk_lat.count() == 0:
        t0 = time.perf_counter()
        out = fetch_kv_blocks(host, port, xfer_id, frm, n, timeout=timeout)
        chunk_lat.note(time.perf_counter() - t0)
        return out
    soft = min(max(4.0 * chunk_lat.ewma(), 0.25), timeout)
    hard = min(max(4.0 * soft, 1.0), timeout)
    cv = threading.Condition()
    state: dict = {"ok": None, "errs": 0, "started": 1, "err": None}

    def settled() -> bool:
        return state["ok"] is not None or state["errs"] >= state["started"]

    def attempt():
        t0 = time.perf_counter()
        try:
            got = fetch_kv_blocks(host, port, xfer_id, frm, n, timeout=hard)
            chunk_lat.note(time.perf_counter() - t0)
            with cv:
                if state["ok"] is None:
                    state["ok"] = got
                cv.notify_all()
        except Exception as e:
            with cv:
                state["errs"] += 1
                state["err"] = e
                cv.notify_all()

    threading.Thread(target=attempt, daemon=True, name="kv-fetch").start()
    with cv:
        cv.wait_for(settled, timeout=soft)
        hedge = not settled()
        if hedge:
            state["started"] += 1
    if hedge:
        _FETCH_HEDGES.inc()
        threading.Thread(target=attempt, daemon=True,
                         name="kv-fetch-hedge").start()
    with cv:
        # final wait bounded by the CONFIGURED cap, not the tightened
        # per-socket-op deadline: `hard` bounds each read/connect inside
        # fetch_kv_blocks, but a multi-read chunk making steady progress
        # may legitimately take longer in total than one op's budget
        if not cv.wait_for(settled, timeout=timeout + 1.0):
            raise TimeoutError(f"kv fetch {xfer_id}[{frm}:{frm + n}] "
                               f"timed out after {timeout:.1f}s")
        if state["ok"] is not None:
            return state["ok"]
        raise state["err"]


def import_kv_source(engine, prompt: list[int], ks: dict, *,
                     timeout: float = 30.0, chunk_blocks: int = 4) -> int:
    """Pull a ``kv_source`` transfer into `engine`'s prefix cache; returns
    the token span now servable from cache (0 on ANY failure — the caller
    simply admits with a local prefill, docs/DISAGG.md "Failure
    semantics"). Each chunk gets one retry before the import is abandoned:
    a transient connection blip resumes mid-transfer, a dead prefill
    replica fails both attempts and degrades."""
    t0 = time.perf_counter()
    try:
        n_tokens = int(ks["n_tokens"])
        n_blocks = int(ks["n_blocks"])
        bt = int(ks["block_tokens"])
        host, port = parse_addr(str(ks["replica"]))
        xfer_id = str(ks["xfer_id"])
    except (KeyError, TypeError, ValueError):
        _IMPORTS.labels(outcome="error").inc()
        return 0
    if n_blocks <= 0:
        _IMPORTS.labels(outcome="empty").inc()
        return 0
    pc = getattr(engine, "prefix_cache", None)
    if pc is None or bt != pc.block_tokens or n_tokens > len(prompt) \
            or n_tokens != n_blocks * bt:
        _IMPORTS.labels(outcome="config_mismatch").inc()
        return 0
    if tokens_hash(prompt[:n_tokens]) != ks.get("tokens_hash"):
        # different tokenization (mixed fleet / rolling upgrade): the
        # shipped rows would seed KV for tokens this replica never saw
        _IMPORTS.labels(outcome="hash_mismatch").inc()
        return 0
    blocks: list = []
    chunk_lat = LatencyStat(window=16)  # per-import chunk-time evidence
    try:
        with trace.span("disagg.import",
                        {"xfer": xfer_id, "blocks": n_blocks}):
            for frm in range(0, n_blocks, max(chunk_blocks, 1)):
                want = min(max(chunk_blocks, 1), n_blocks - frm)
                for attempt in (0, 1):  # per-chunk retry: resumable ranges
                    try:
                        if attempt == 0:
                            blocks.extend(_fetch_chunk_hedged(
                                host, port, xfer_id, frm, want,
                                timeout=timeout, chunk_lat=chunk_lat))
                        else:
                            # the retry runs UN-tightened, with the full
                            # configured timeout: the hedged attempt's
                            # EWMA-derived deadline may be exactly why the
                            # first try failed (a transient server-side
                            # stall after fast chunks), and a retry that
                            # can only repeat the same deadline could
                            # never succeed where slowness failed
                            blocks.extend(fetch_kv_blocks(
                                host, port, xfer_id, frm, want,
                                timeout=timeout))
                        break
                    except Exception:
                        if attempt:
                            raise
            imported = engine.import_kv_blocks(prompt[:n_tokens], blocks)
    except Exception:
        _IMPORTS.labels(outcome="error").inc()
        return 0
    if imported <= 0:
        _IMPORTS.labels(outcome="empty").inc()
        return 0
    _IMPORTS.labels(outcome="imported").inc()
    _IMPORT_TOKENS.inc(imported)
    _IMPORT_SECONDS.observe(time.perf_counter() - t0)
    return imported


def note_reprefill(shipped: int, reused: int) -> int:
    """Admission accounting for a streamed-KV request: how many shipped
    tokens were re-prefilled anyway (reuse fell short of the shipped
    span). The mixed-context bench asserts the fleet-wide sum stays 0."""
    missed = max(shipped - reused, 0)
    if missed:
        _REPREFILL.inc(missed)
    return missed
