"""Router-side prefix-affinity map: request prefix -> the replica that has it hot.

The prefix cache (cache/, docs/PREFIX_CACHE.md) makes KV reuse *computable*
inside one replica; this map makes it *routable* across a fleet. Every routed
completion records (prompt-prefix, replica) here; a new request looks up the
replica whose recent routes share the longest block-prefix with it and is sent
there, so the shared prefix hits that replica's radix pool instead of being
re-prefilled on whichever replica a load balancer happened to pick.

Structure: the SAME block-granular radix trie as the replica-side cache index
(cache/radix.py — reused, not reimplemented), with two differences of use:

- keys are the raw UTF-8 bytes of the rendered messages, not token ids. The
  router is deliberately tokenizer-free (it proxies for any model the replicas
  load); byte-block prefix equality is a conservative proxy for token-block
  prefix equality — two prompts sharing `block_bytes` leading bytes share
  their leading token blocks for any deterministic tokenizer. `block_bytes`
  should approximate the replica's `--prefix-cache-block-tokens` granularity
  in bytes (default 64 bytes ~ 16 tokens x ~4 bytes/token).
- node handles carry the id of the LAST replica routed through that prefix
  (latest-wins) instead of block-pool handles; `refs` stay 0 so the LRU cap
  can always evict.

Bounded: `max_nodes` caps the trie; over-cap inserts evict LRU leaves via the
radix index's own cascade. Thread-safe: one lock (handler threads race).
"""

from __future__ import annotations

import threading

from ..cache.radix import RadixIndex

__all__ = ["AffinityMap"]


class AffinityMap:
    def __init__(self, block_bytes: int = 64, max_nodes: int = 8192):
        assert block_bytes >= 1 and max_nodes >= 1
        self.block_bytes = block_bytes
        self.max_nodes = max_nodes
        self._radix = RadixIndex(block_tokens=block_bytes)
        self._lock = threading.Lock()  # guards: _radix

    def lookup(self, key: bytes, alive: set[str]) -> tuple[str | None, int]:
        """(replica id, shared full blocks) for the deepest recorded route
        whose replica is in `alive`; (None, 0) on no usable match.

        Walking UP from the deepest matched node trades prefix depth for
        availability: an ancestor's replica shares a shorter — but still
        non-zero — prefix, which still beats a cold least-loaded pick."""
        with self._lock:
            nodes = self._radix.match(key)
            for depth in range(len(nodes), 0, -1):
                rep = nodes[depth - 1].handle
                if rep in alive:
                    return rep, depth
        return None, 0

    def record(self, key: bytes, replica: str) -> None:
        """The request keyed by `key` was served by `replica`: stamp every
        block of the prefix with it (latest-wins along the whole chain, so a
        failover re-route redirects the prefix's future traffic too)."""
        with self._lock:
            chain = self._radix.insert(key, lambda _i: replica)
            for node in chain:
                node.handle = replica
            if self._radix.nodes > self.max_nodes:
                self._radix.evict(self._radix.nodes - self.max_nodes)

    def nodes(self) -> int:
        with self._lock:
            return self._radix.nodes
