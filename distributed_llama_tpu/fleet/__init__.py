"""Fleet serving tier: prefix-locality router + membership over N replicas.

`membership` polls replica `/healthz` identity/load blocks (with per-replica
backoff for unreachable ones), `affinity` maps request prefixes to the
replica whose KV cache already holds them (the cache/radix.py trie re-used
router-side), `journal` records every in-flight durable request so a
mid-stream replica failure is resumed instead of surfaced (docs/FLEET.md
"Resume protocol"), `disagg` splits long-prompt completions across
prefill/decode roles with KV-block streaming between replicas
(docs/DISAGG.md), `router` fronts the fleet with durable failover
proxying and replica-labeled aggregated metrics. docs/FLEET.md.
"""

from .affinity import AffinityMap  # noqa: F401
from .disagg import DisaggPlanner, KVTransferTable  # noqa: F401
from .journal import JournalEntry, RequestJournal  # noqa: F401
from .membership import Membership, Replica  # noqa: F401
from .router import (close_router, fleet_metrics, fleet_stats,  # noqa: F401
                     merge_prometheus, serve_router)
