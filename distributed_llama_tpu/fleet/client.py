"""Minimal stdlib completion client — the ONE request/SSE-read driver shared
by bench.py's fleet workloads and perf/fault_matrix.py's family runners.

Before this module the repo carried seven near-identical copies of the same
loop (three fault-matrix request helpers, four bench SSE readers), each with
its own drift opportunities around error events, chunked decoding, and
header relay (churn explicitly deferred from PR 14). The driver reads the
stream INCREMENTALLY (readline honors chunked decoding) so first-delta time
is a true arrival time, and never raises: every failure mode lands in the
returned dict's "error" field, which is what every caller wants — benches
and fault cells assert on outcomes, they don't handle transport exceptions.

Returned dict (fields None when not applicable):
  status   HTTP status (None when the connection itself failed)
  text     joined completion text ("" for an empty stream; None on failure)
  finish   finish_reason (stream: last seen; non-stream: choice field)
  error    None on success; SSE error payload / body / repr(exc) otherwise
  rid      X-Request-Id response header (serving identity)
  replica  X-Replica response header
  ttft     seconds from request start to the FIRST delta (stream only)
  e2e      seconds from request start to stream end
  tpot     mean inter-delta gap seconds (stream, >= 2 deltas)
  deltas   content-bearing SSE events seen
"""

from __future__ import annotations

import http.client
import json
import time


def completion_request(port: int, body: dict, *, host: str = "127.0.0.1",
                       path: str = "/v1/chat/completions",
                       timeout: float = 120.0, headers: dict | None = None,
                       on_delta=None) -> dict:
    """POST one chat completion and drain it (streaming when
    body["stream"] is true). `on_delta(n, replica)` fires per
    content-bearing SSE event with the running delta count — the hook the
    chaos bench's mid-stream replica killer rides."""
    out = {"status": None, "text": None, "finish": None, "error": None,
           "rid": None, "replica": None, "ttft": None, "e2e": None,
           "tpot": None, "deltas": 0}
    t0 = time.perf_counter()
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        hdrs = {"Content-Type": "application/json"}
        if headers:
            hdrs.update(headers)
        conn.request("POST", path, json.dumps(body), hdrs)
        resp = conn.getresponse()
        out["status"] = resp.status
        out["rid"] = resp.getheader("X-Request-Id")
        out["replica"] = resp.getheader("X-Replica")
        if not body.get("stream"):
            data = resp.read()
            if resp.status != 200:
                try:
                    out["error"] = json.loads(data or b"{}")
                except ValueError:
                    out["error"] = data.decode(errors="replace")
                return out
            payload = json.loads(data or b"{}")
            choice = payload["choices"][0]
            out["text"] = choice["message"]["content"]
            out["finish"] = choice.get("finish_reason")
            out["e2e"] = time.perf_counter() - t0
            return out
        if resp.status != 200:
            out["error"] = resp.read().decode(errors="replace")
            return out
        text: list[str] = []
        t_first = t_last = None
        while True:
            line = resp.readline()
            if not line:
                break
            line = line.decode().strip()
            if not line.startswith("data: ") or line == "data: [DONE]":
                continue
            payload = json.loads(line[6:])
            if "error" in payload:
                out["error"] = payload["error"]
                out["text"] = "".join(text)
                return out
            choice = payload["choices"][0]
            if choice.get("finish_reason"):
                out["finish"] = choice["finish_reason"]
            d = choice["delta"].get("content")
            if d:
                now = time.perf_counter()
                text.append(d)
                out["deltas"] += 1
                if t_first is None:
                    t_first = now
                    out["ttft"] = now - t0
                t_last = now
                if on_delta is not None:
                    on_delta(out["deltas"], out["replica"])
        out["text"] = "".join(text)
        out["e2e"] = time.perf_counter() - t0
        if out["deltas"] > 1:
            out["tpot"] = (t_last - t_first) / (out["deltas"] - 1)
        return out
    except Exception as e:
        out["error"] = repr(e)
        return out
    finally:
        try:
            conn.close()
        except Exception:
            pass
