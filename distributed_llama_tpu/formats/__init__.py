from .mfile import load_model, write_model  # noqa: F401
from .tfile import TokenizerData, load_tokenizer, write_tokenizer  # noqa: F401
