"""`.t` tokenizer file format — byte-compatible reader/writer.

Format (reference: src/tokenizer.cpp:39-138 for parsing, converter/tokenizer-writer.py):

    [magic 0x567124 i32][header_size i32][(key i32, value i32) * nKv]
    [chat_template bytes][chat_stop bytes]
    per token i in 0..vocab_size: [score f32][len i32][bytes]

Header keys (tokenizer.hpp:24-34): version=0, vocab_size=1, max_token_length=2, bos_id=3,
eos_id=4, pad_id=5, chat_eos_id=6, chat_template(len)=7, chat_stop(len)=8. The legacy
magic 0x567123 uses a fixed struct header.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

MAGIC = 0x567124
LEGACY_MAGIC = 0x567123

KEY_VERSION = 0
KEY_VOCAB_SIZE = 1
KEY_MAX_TOKEN_LENGTH = 2
KEY_BOS_ID = 3
KEY_EOS_ID = 4
KEY_PAD_ID = 5
KEY_CHAT_EOS_ID = 6
KEY_CHAT_TEMPLATE = 7
KEY_CHAT_STOP = 8


@dataclass
class TokenizerData:
    vocab: list[bytes]
    scores: list[float]
    bos_id: int = -1
    eos_id: int = -1
    chat_eos_id: int = -1
    max_token_length: int = 0
    chat_template: str | None = None
    chat_stop: str | None = None
    pad_id: int = -1

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)


def load_tokenizer(path: str) -> TokenizerData:
    with open(path, "rb") as f:
        magic = struct.unpack("<i", f.read(4))[0]
        chat_template = chat_stop = None
        chat_eos_id = -1
        pad_id = -1
        if magic == LEGACY_MAGIC:
            # TokenizerOldHeader: vocabSize, maxTokenLength, bosId, eosId, padId
            vocab_size, max_len, bos_id, eos_id, pad_id = struct.unpack("<5i", f.read(20))
        elif magic == MAGIC:
            header_size = struct.unpack("<i", f.read(4))[0]
            n = (header_size - 8) // 4
            ints = struct.unpack(f"<{n}i", f.read(n * 4))
            kv = {ints[i]: ints[i + 1] for i in range(0, n, 2)}
            if kv.get(KEY_VERSION) != 1:
                raise ValueError("old tokenizer version, please regenerate")
            vocab_size = kv[KEY_VOCAB_SIZE]
            max_len = kv[KEY_MAX_TOKEN_LENGTH]
            bos_id = kv.get(KEY_BOS_ID, -1)
            eos_id = kv.get(KEY_EOS_ID, -1)
            chat_eos_id = kv.get(KEY_CHAT_EOS_ID, -1)
            pad_id = kv.get(KEY_PAD_ID, -1)
            tpl_len = kv.get(KEY_CHAT_TEMPLATE, 0)
            stop_len = kv.get(KEY_CHAT_STOP, 0)
            if tpl_len > 0:
                chat_template = f.read(tpl_len).decode("utf-8", errors="replace")
                # reference stores the template WITH its NUL terminator included in len
                chat_template = chat_template.rstrip("\x00")
            if stop_len > 0:
                chat_stop = f.read(stop_len).decode("utf-8", errors="replace").rstrip("\x00")
        else:
            raise ValueError(f"invalid tokenizer file magic {magic:#x}")

        vocab: list[bytes] = []
        scores: list[float] = []
        for _ in range(vocab_size):
            score = struct.unpack("<f", f.read(4))[0]
            ln = struct.unpack("<i", f.read(4))[0]
            vocab.append(f.read(ln))
            scores.append(score)

    return TokenizerData(vocab=vocab, scores=scores, bos_id=bos_id, eos_id=eos_id,
                         chat_eos_id=chat_eos_id, max_token_length=max_len,
                         chat_template=chat_template, chat_stop=chat_stop, pad_id=pad_id)


def write_tokenizer(path: str, t: TokenizerData) -> None:
    kv: list[tuple[int, int]] = [
        (KEY_VERSION, 1),
        (KEY_VOCAB_SIZE, t.vocab_size),
        (KEY_MAX_TOKEN_LENGTH, t.max_token_length or max(len(v) for v in t.vocab)),
    ]
    if t.bos_id >= 0:
        kv.append((KEY_BOS_ID, t.bos_id))
    if t.eos_id >= 0:
        kv.append((KEY_EOS_ID, t.eos_id))
    if t.pad_id >= 0:
        kv.append((KEY_PAD_ID, t.pad_id))
    if t.chat_eos_id >= 0:
        kv.append((KEY_CHAT_EOS_ID, t.chat_eos_id))
    # no NUL terminator — reference converters write the raw utf-8 bytes
    tpl = t.chat_template.encode() if t.chat_template else b""
    stop = t.chat_stop.encode() if t.chat_stop else b""
    if tpl:
        kv.append((KEY_CHAT_TEMPLATE, len(tpl)))
    if stop:
        kv.append((KEY_CHAT_STOP, len(stop)))
    data = b"".join(struct.pack("<ii", k, v) for k, v in kv)
    with open(path, "wb") as f:
        f.write(struct.pack("<i", MAGIC))
        f.write(struct.pack("<i", 8 + len(data)))
        f.write(data)
        f.write(tpl)
        f.write(stop)
        for score, piece in zip(t.scores, t.vocab):
            f.write(struct.pack("<f", float(score)))
            f.write(struct.pack("<i", len(piece)))
            f.write(piece)
