"""`.m` model file format — byte-compatible reader/writer.

Format (reference: src/transformer.cpp:12-148 for parsing, converter/writer.py:109-143
for writing):

    [magic 0xA00ABCD i32][header_size i32][ (key i32, value i32) * nKv ]
    then raw tensors in fixed order (transformer.cpp:494-529):
        embedding (vocab, dim) F32
        per layer: wq, wk, wv, wo; dense: w1, w2, w3 | moe: router + per-expert
                   (up, gate, down); rms_att F32, rms_ffn F32
                   [+ grok1: rms_moe, rms_ffn2 F32]
        rms_final (dim,) F32
        wcls (vocab, dim) [weights ftype]

    header_size counts magic+size+kv bytes; tensors start at byte header_size. Matmul
    tensors use the header's weights ftype (F32/F16/Q40/Q80 block streams); norms and
    embedding are always F32. Legacy magics 0xABCD00/01 use a fixed 9-int header
    (transformer.cpp:28-43).

The loader memory-maps the file and returns the params dict of models/params.py with
per-layer tensors stacked along a leading n_layers axis.
"""

from __future__ import annotations

import struct
from typing import BinaryIO

import numpy as np

from ..models.params import Params, block_tensor_shapes
from ..models.spec import ArchType, HeaderKey, HiddenAct, ModelSpec, RopeType
from ..quants import (
    FloatType,
    QTensor,
    batch_bytes,
    q40_from_bytes,
    q40_to_bytes,
    q80_from_bytes,
    q80_to_bytes,
    quantize_q40,
    quantize_q80,
)

MAGIC = 0xA00ABCD
LEGACY_MAGICS = {0xABCD00: ArchType.LLAMA, 0xABCD01: ArchType.GROK1}


def read_spec(path: str, max_seq_len: int = 0,
              weights_ftype: FloatType | None = None) -> tuple[ModelSpec, FloatType, int]:
    """Parse the header. Returns (spec, weights_ftype, header_size)."""
    with open(path, "rb") as f:
        magic = struct.unpack("<i", f.read(4))[0]
        fields: dict[str, int] = {}
        if magic in LEGACY_MAGICS:
            vals = struct.unpack("<9i", f.read(36))
            (fields["dim"], fields["hidden_dim"], fields["n_layers"], fields["n_heads"],
             fields["n_kv_heads"], fields["n_experts"], fields["n_active_experts"],
             fields["vocab_size"], fields["seq_len"]) = vals
            arch = LEGACY_MAGICS[magic]
            header_size = 4 + 36
            kv: dict[int, int] = {}
        elif magic == MAGIC:
            header_size = struct.unpack("<i", f.read(4))[0]
            n_kv_bytes = header_size - 8
            raw = f.read(n_kv_bytes)
            ints = struct.unpack(f"<{n_kv_bytes // 4}i", raw)
            kv = {ints[i]: ints[i + 1] for i in range(0, len(ints), 2)}
            arch = ArchType(kv[HeaderKey.ARCH_TYPE])
            for name, key in (("dim", HeaderKey.DIM), ("hidden_dim", HeaderKey.HIDDEN_DIM),
                              ("n_layers", HeaderKey.N_LAYERS),
                              ("n_heads", HeaderKey.N_HEADS),
                              ("n_kv_heads", HeaderKey.N_KV_HEADS),
                              ("n_experts", HeaderKey.N_EXPERTS),
                              ("n_active_experts", HeaderKey.N_ACTIVE_EXPERTS),
                              ("vocab_size", HeaderKey.VOCAB_SIZE),
                              ("seq_len", HeaderKey.SEQ_LEN)):
                if key in kv:
                    fields[name] = kv[key]
        else:
            raise ValueError(f"unsupported model file magic {magic:#x}")

    if weights_ftype is None:
        if HeaderKey.WEIGHTS_FLOAT_TYPE not in kv:
            raise ValueError("weights float type not in header and not specified")
        weights_ftype = FloatType(kv[HeaderKey.WEIGHTS_FLOAT_TYPE])

    spec = ModelSpec(
        arch_type=arch,
        hidden_act=HiddenAct(kv.get(HeaderKey.HIDDEN_ACT, HiddenAct.SILU)),
        rope_theta=float(kv.get(HeaderKey.ROPE_THETA, 10000)),
        rope_type=RopeType(kv.get(HeaderKey.ROPE_TYPE, RopeType.UNKNOWN)),
        rope_scaling_factor=float(kv.get(HeaderKey.ROPE_SCALING_FACTOR, 0)),
        rope_scaling_low_freq_factor=float(
            kv.get(HeaderKey.ROPE_SCALING_LOW_FREQ_FACTOR, 0)),
        rope_scaling_high_freq_factor=float(
            kv.get(HeaderKey.ROPE_SCALING_HIGH_FREQ_FACTOR, 0)),
        rope_scaling_orig_max_seq_len=kv.get(HeaderKey.ROPE_SCALING_ORIG_MAX_SEQ_LEN, 0),
        version=kv.get(HeaderKey.VERSION, 0),
        **fields,
    ).resolved(max_seq_len)
    return spec, weights_ftype, header_size


def model_tensor_bytes(spec: ModelSpec, wft: FloatType) -> int:
    """Total tensor bytes after the header (mirrors the reference's missedBytes check,
    transformer.cpp:531-535)."""
    total = batch_bytes(FloatType.F32, spec.dim, spec.vocab_size)  # embedding
    shapes = block_tensor_shapes(spec)
    for name, (shape, quantized) in shapes.items():
        ft = wft if quantized else FloatType.F32
        d = int(np.prod(shape[:-1], initial=1))
        total += spec.n_layers * batch_bytes(ft, shape[-1], d)
    total += batch_bytes(FloatType.F32, spec.dim, 1)  # rms_final
    total += batch_bytes(wft, spec.dim, spec.vocab_size)  # wcls
    return total


def _tensor_from_bytes(buf: memoryview, shape: tuple[int, ...],
                       ftype: FloatType) -> QTensor:
    if ftype == FloatType.F32:
        return QTensor(ftype, np.frombuffer(buf, "<f4").reshape(shape).copy())
    if ftype == FloatType.F16:
        return QTensor(ftype, np.frombuffer(buf, "<f2").reshape(shape).copy())
    if ftype == FloatType.Q40:
        packed, scales = q40_from_bytes(buf, shape)
        return QTensor(ftype, packed, scales)
    if ftype == FloatType.Q80:
        vals, scales = q80_from_bytes(buf, shape)
        return QTensor(ftype, vals, scales)
    raise ValueError(ftype)


def _stack(tensors: list[QTensor]) -> QTensor:
    data = np.stack([t.data for t in tensors])
    scales = None if tensors[0].scales is None else np.stack([t.scales for t in tensors])
    return QTensor(tensors[0].ftype, data, scales)


def load_model(path: str, max_seq_len: int = 0,
               weights_ftype: FloatType | None = None) -> tuple[ModelSpec, Params]:
    """Load a `.m` file into (spec, params). Equivalent of Transformer::loadRootFromFile
    (transformer.cpp:467-539) — mmap + per-tensor parse, no socket distribution (sharding
    happens later via parallel.shard_params)."""
    spec, wft, header_size = read_spec(path, max_seq_len, weights_ftype)
    mm = np.memmap(path, dtype=np.uint8, mode="r")
    view = memoryview(mm)
    off = header_size

    expected = header_size + model_tensor_bytes(spec, wft)
    if expected != len(mm):
        raise ValueError(
            f"model file size mismatch: expected {expected} bytes for "
            f"{wft.name} weights, file has {len(mm)} (wrong weights float type?)")

    def take(shape: tuple[int, ...], ftype: FloatType) -> QTensor:
        nonlocal off
        nbytes = batch_bytes(ftype, shape[-1], int(np.prod(shape[:-1], initial=1)))
        t = _tensor_from_bytes(view[off:off + nbytes], shape, ftype)
        off += nbytes
        return t

    # NOTE: seq-len clamping must not affect tensor layout; file tensors are independent
    # of seq_len, so no adjustment needed.
    embedding = take((spec.vocab_size, spec.dim), FloatType.F32)

    shapes = block_tensor_shapes(spec)
    per_layer: dict[str, list[QTensor]] = {name: [] for name in shapes}
    for _ in range(spec.n_layers):
        layer: dict[str, QTensor] = {}
        for name in ("wq", "wk", "wv", "wo"):
            layer[name] = take(shapes[name][0], wft)
        if spec.is_moe:
            layer["router"] = take(shapes["router"][0], wft)
            ups, gates, downs = [], [], []
            e, h, d = spec.n_experts, spec.hidden_dim, spec.dim
            for _e in range(e):
                ups.append(take((h, d), wft))
                gates.append(take((h, d), wft))
                downs.append(take((d, h), wft))
            layer["moe_up"] = _stack(ups)
            layer["moe_gate"] = _stack(gates)
            layer["moe_down"] = _stack(downs)
        else:
            layer["w1"] = take(shapes["w1"][0], wft)
            layer["w2"] = take(shapes["w2"][0], wft)
            layer["w3"] = take(shapes["w3"][0], wft)
        layer["rms_att"] = take((spec.dim,), FloatType.F32)
        layer["rms_ffn"] = take((spec.dim,), FloatType.F32)
        if spec.arch_type == ArchType.GROK1:
            layer["rms_moe"] = take((spec.dim,), FloatType.F32)
            layer["rms_ffn2"] = take((spec.dim,), FloatType.F32)
        for name, t in layer.items():
            per_layer[name].append(t)

    rms_final = take((spec.dim,), FloatType.F32)
    wcls = take((spec.vocab_size, spec.dim), wft)

    if off != len(mm):
        raise ValueError(f"model file size mismatch: consumed {off}, file {len(mm)} "
                         "(missing/extra bytes — wrong weights float type?)")

    blocks: Params = {}
    for name, tensors in per_layer.items():
        stacked = _stack(tensors)
        blocks[name] = (stacked if shapes[name][1] else
                        np.asarray(stacked.data, dtype=np.float32))
    params: Params = {
        "embedding": np.asarray(embedding.data),
        "blocks": blocks,
        "rms_final": np.asarray(rms_final.data),
        "wcls": wcls,
    }
    return spec, params


# ---------------------------------------------------------------------------
# writer (converter back-end; byte-compatible with converter/writer.py)
# ---------------------------------------------------------------------------


def write_header(f: BinaryIO, spec: ModelSpec, weights_ftype: FloatType) -> None:
    kv: list[tuple[int, int]] = [
        (HeaderKey.VERSION, 0),
        (HeaderKey.ARCH_TYPE, int(spec.arch_type)),
        (HeaderKey.DIM, spec.dim),
        (HeaderKey.HIDDEN_DIM, spec.hidden_dim),
        (HeaderKey.N_LAYERS, spec.n_layers),
        (HeaderKey.N_HEADS, spec.n_heads),
        (HeaderKey.N_KV_HEADS, spec.n_kv_heads),
        (HeaderKey.N_EXPERTS, spec.n_experts),
        (HeaderKey.N_ACTIVE_EXPERTS, spec.n_active_experts),
        (HeaderKey.VOCAB_SIZE, spec.vocab_size),
        (HeaderKey.SEQ_LEN, spec.seq_len),
        (HeaderKey.HIDDEN_ACT, int(spec.hidden_act)),
        (HeaderKey.ROPE_THETA, int(spec.rope_theta)),
        (HeaderKey.WEIGHTS_FLOAT_TYPE, int(weights_ftype)),
    ]
    if spec.rope_type != RopeType.UNKNOWN:
        kv.append((HeaderKey.ROPE_TYPE, int(spec.rope_type)))
    if spec.rope_scaling_factor:
        kv += [
            (HeaderKey.ROPE_SCALING_FACTOR, int(spec.rope_scaling_factor)),
            (HeaderKey.ROPE_SCALING_LOW_FREQ_FACTOR, int(spec.rope_scaling_low_freq_factor)),
            (HeaderKey.ROPE_SCALING_HIGH_FREQ_FACTOR,
             int(spec.rope_scaling_high_freq_factor)),
            (HeaderKey.ROPE_SCALING_ORIG_MAX_SEQ_LEN, spec.rope_scaling_orig_max_seq_len),
        ]
    data = b"".join(struct.pack("<ii", k, v) for k, v in kv)
    f.write(struct.pack("<i", MAGIC))
    f.write(struct.pack("<i", 8 + len(data)))
    f.write(data)


def write_tensor(f: BinaryIO, x: np.ndarray, ftype: FloatType) -> int:
    """Flattened tensor -> reference byte stream (converter/writer.py:96-107)."""
    flat = np.asarray(x, dtype=np.float32).reshape(-1)
    if ftype == FloatType.F32:
        buf = flat.astype("<f4").tobytes()
    elif ftype == FloatType.F16:
        buf = flat.astype("<f2").tobytes()
    elif ftype == FloatType.Q40:
        buf = q40_to_bytes(*quantize_q40(flat))
    elif ftype == FloatType.Q80:
        buf = q80_to_bytes(*quantize_q80(flat))
    else:
        raise ValueError(ftype)
    f.write(buf)
    return len(buf)


def write_model(path: str, spec: ModelSpec, tensors_iter, weights_ftype: FloatType) -> None:
    """Write a `.m` from an iterator of (name, np.ndarray) in file order.

    `tensors_iter` must yield tensors in the exact order documented in load_model; norms
    and embedding are forced F32 regardless of weights_ftype (convert-llama.py:79-85).
    """
    norm_names = {"embedding", "rms_att", "rms_ffn", "rms_moe", "rms_ffn2", "rms_final"}
    with open(path, "wb") as f:
        write_header(f, spec, weights_ftype)
        for name, tensor in tensors_iter:
            ftype = FloatType.F32 if name in norm_names else weights_ftype
            write_tensor(f, tensor, ftype)


def params_file_order(spec: ModelSpec, params: Params):
    """Yield (name, array) in `.m` order from a params dict (testing / re-export)."""
    yield "embedding", params["embedding"]
    blocks = params["blocks"]

    def as_np(t, idx):
        return t.to_numpy()[idx] if isinstance(t, QTensor) else np.asarray(t)[idx]

    for l in range(spec.n_layers):
        for name in ("wq", "wk", "wv", "wo"):
            yield name, as_np(blocks[name], l)
        if spec.is_moe:
            yield "router", as_np(blocks["router"], l)
            for e in range(spec.n_experts):
                yield "moe_up", as_np(blocks["moe_up"], (l, e))
                yield "moe_gate", as_np(blocks["moe_gate"], (l, e))
                yield "moe_down", as_np(blocks["moe_down"], (l, e))
        else:
            for name in ("w1", "w2", "w3"):
                yield name, as_np(blocks[name], l)
        yield "rms_att", as_np(blocks["rms_att"], l)
        yield "rms_ffn", as_np(blocks["rms_ffn"], l)
        if spec.arch_type == ArchType.GROK1:
            yield "rms_moe", as_np(blocks["rms_moe"], l)
            yield "rms_ffn2", as_np(blocks["rms_ffn2"], l)
    yield "rms_final", params["rms_final"]
    wcls = params["wcls"]
    yield "wcls", wcls.to_numpy() if isinstance(wcls, QTensor) else np.asarray(wcls)
