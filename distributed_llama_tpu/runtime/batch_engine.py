"""Continuous-batching engine: concurrent sequences share one batched SPMD step.

The reference API server is a single-request accept loop (dllama-api.cpp:418-429) and
its whole runtime is batch=1 (no batch dim anywhere, funcs.cpp:424). On TPU a decode
step is HBM-bandwidth-bound — the weights stream past the MXU once per step regardless
of how many sequences ride along — so batching B requests costs nearly the same wall
time as one and multiplies throughput. This module is therefore a capability extension
beyond reference parity, built on the per-row `start_pos` support in models/forward.py:
each KV-cache row advances at its own position (continuous batching).

Design:
- B cache "slots", each holding one sequence's KV rows + host-side state.
- One scheduler thread owns the device. The decode hot path is a K-step
  SUPER-STEP (runtime/device_loop.py make_batched_decode_loop): forward +
  sampling scan K steps entirely on device and the host gets a (K, B) token
  block back in ONE transfer — 1 host sync per K decoded tokens instead of 1
  per token. K adapts: when a new request is waiting (or a row is within K of
  finishing) the scheduler falls back to single T=1 batched steps so admission
  latency stays bounded by one step, not K.
- Prefill never stalls decode: a prefill chunk dispatches TOGETHER with the
  active decode rows in one mixed (B, chunk) step — the prefill row carries
  chunk real tokens, each decode row carries its next token at index 0 (its
  remaining positions are scratch writes on masked future slots), and each
  decode row's logits read from index 0. One dispatch advances the prefill
  chunk AND every active sequence by one token.
- Idle rows ride along with their start_pos parked at their current position: their
  cache writes land at future positions that are masked now and overwritten when those
  positions actually decode, so no masking program is needed.
- EOS/stop detection stays host-side, applied to the returned token block; a
  row that stops mid-block simply keeps its position at the verified frontier
  (the over-decoded rows beyond it sit on masked slots and are overwritten by
  the slot's next writes — the same free-rollback property speculative
  decoding relies on).
- PIPELINED super-steps (docs/SERVING.md "Pipelined decode"): the decode loop
  returns its final carry (last token, positions, xorshift* state) as device
  arrays, so super-step N+1 is issued CHAINED from N's device state before
  N's (K, B) block has even reached the host — the device runs N+1 while the
  host delivers N (EOS/stop scan, callbacks, sampler resync). When delivery
  shows the speculated schedule diverged (a row stopped/cancelled/errored
  mid-block, so N+1 decoded past the real frontier), the in-flight dispatch
  is FLUSHED: its tokens are discarded via the same free frontier-rewind
  rollback, clamp_pos keeps a context-end park from poisoning the prefix
  harvest, and the next dispatch re-uploads host state (the sampler RNG
  round-trips bit-exactly through a flush). Admission breaks the chain
  instead of riding it, bounding admission latency at one in-flight window.
- Sampling runs ON DEVICE inside the super-step with the host Sampler's
  xorshift* stream (state uploaded before, written back after), host-side
  elsewhere (prefill boundaries, single-step mode). Greedy super-steps emit
  bit-exactly the host loop's tokens.
- Per-slot NaiveCache prefix reuse (dllama-api.cpp:187-232): a new request lands on the
  free slot sharing the longest token prefix and rewinds instead of re-prefilling.
- CROSS-REQUEST prefix reuse (cache/, docs/PREFIX_CACHE.md): a finished slot's
  committed prefix is harvested into a radix-indexed block pool; a new request
  whose prompt shares cached blocks — on ANY slot — seeds its cache rows + pos
  from the pool and prefills only the uncached suffix. The same-slot rewind
  above remains as the token-granular (and copy-free) fast path.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Callable

import jax.numpy as jnp
import numpy as np

from ..models.spec import ModelSpec
from ..obs import flight, metrics, reqctx, trace
from ..resilience import faults
from ..resilience.errors import (DeadlineExceeded, EngineClosed,
                                 EngineDraining, EngineSaturated,
                                 EngineWedged, InvalidRequest, classify)
from ..resilience.tenancy import (CLASSES, DEFAULT_TENANT, DrainRate,
                                  TenantRegistry, WeightedFairQueue)
from .engine import PREFILL_CHUNKS, GenerationStats
from .speculative import (AdaptiveK, NgramProposer, ProposerMux,
                          verify_block_bucket)

__all__ = ["BatchEngine", "BatchRequest"]

# Scheduler telemetry (docs/OBSERVABILITY.md). The super-step scheduler was a
# black box: admission latency, dispatch mix, rollback volume, and slot
# occupancy were all invisible outside one-off bench runs.
_QUEUE_WAIT = metrics.histogram(
    "batch_queue_wait_seconds",
    "submit() to slot assignment (admission latency incl. queueing)")
_QUEUE_DEPTH = metrics.gauge(
    "batch_queue_depth", "Requests waiting for a free slot")
_SLOTS_TOTAL = metrics.gauge(
    "batch_slots_total", "Configured cache slots (--batch)")
_SLOTS_OCCUPIED = metrics.gauge(
    "batch_slots_occupied", "Cache slots holding a live request")
_DISPATCH_SECONDS = metrics.histogram(
    "batch_dispatch_seconds",
    "Wall time of one scheduler device dispatch, by shape",
    labelnames=("kind",))
_DISP_PREFILL = _DISPATCH_SECONDS.labels(kind="prefill")
_DISP_MIXED = _DISPATCH_SECONDS.labels(kind="mixed")
_DISP_SINGLE = _DISPATCH_SECONDS.labels(kind="single_step")
_DISP_SUPER = _DISPATCH_SECONDS.labels(kind="super_step")
_DISP_VERIFY = _DISPATCH_SECONDS.labels(kind="verify")
_SUPERSTEP_TOKENS = metrics.histogram(
    "batch_superstep_tokens",
    "Tokens decoded per super-step dispatch (sum of row budgets)",
    buckets=metrics.DEFAULT_SIZE_BUCKETS)
_ROLLBACK_TOKENS = metrics.counter(
    "batch_rollback_tokens_total",
    "Device-decoded tokens discarded by host-side stop/cancel frontier rewind")
_PARKED_ROW_STEPS = metrics.counter(
    "batch_parked_row_steps_total",
    "Row-steps spent parked (rows riding a dispatch without advancing)")
_PREFILL_TOKENS = metrics.counter(
    "batch_prefill_tokens_total", "Prompt tokens prefilled by the scheduler")
_DECODE_TOKENS = metrics.counter(
    "batch_decode_tokens_total", "Tokens delivered to requests by the scheduler")
_REQUESTS = metrics.counter(
    "batch_requests_total", "Completed requests by finish reason",
    labelnames=("finish",))
_PREFIX_SEEDED = metrics.counter(
    "batch_prefix_seeded_tokens_total",
    "Cache rows copied from the prefix-cache pool at admission "
    "(prompt tokens whose prefill was skipped beyond the same-slot rewind)")
# Resilience telemetry (docs/ROBUSTNESS.md): every unhappy-path decision the
# scheduler makes — error blast radius, transient retries, shed admissions,
# expired deadlines — is a counter, and scheduler liveness is a gauge pair
# (alive flag + seconds since the last successful dispatch) so a hung or dead
# scheduler is visible on /metrics before clients notice.
_ENGINE_ERRORS = metrics.counter(
    "engine_errors_total",
    "Dispatch/scheduler errors by blast radius "
    "(transient=retried, request=failed one request, engine=failed all)",
    labelnames=("kind",))
_RETRIES = metrics.counter(
    "engine_retries_total",
    "Transient dispatch failures retried with backoff")
_SHED = metrics.counter(
    "engine_shed_requests_total",
    "Admissions refused because the queue was at --max-queue")
_DEADLINE_EXPIRED = metrics.counter(
    "engine_deadline_expired_total",
    "Requests expired by queue TTL or generation deadline, by where",
    labelnames=("where",))
_SCHED_ALIVE = metrics.gauge(
    "batch_scheduler_alive",
    "1 while the BatchEngine scheduler thread is running (0 = dead/idle)")
_DISPATCH_AGE = metrics.gauge(
    "batch_dispatch_age_seconds",
    "Dispatch watchdog: seconds since the scheduler last completed a device "
    "dispatch, 0 while idle (read at scrape time)")
# Pipelined super-step telemetry (docs/SERVING.md "Pipelined decode"): the
# gap histogram is the win (device-idle time between decode dispatches ->
# ~0 when chained), the flush counter the cost (speculated device work
# discarded when the host schedule diverged).
_DISPATCH_GAP = metrics.histogram(
    "batch_dispatch_gap_seconds",
    "Device-idle gap before a decode super-step: host time between the "
    "previous dispatch's results landing and this dispatch being issued "
    "(0 when chained from device state while the predecessor is in flight)")
_PIPELINE_DEPTH = metrics.gauge(
    "batch_pipeline_depth",
    "Decode super-steps currently in flight on device (2 = overlapped: one "
    "executing while its predecessor's block is delivered host-side)")
_PIPELINE_FLUSHES = metrics.counter(
    "batch_pipeline_flushes_total",
    "Pipeline breaks by reason: an eagerly chained super-step was discarded "
    "before delivery (stop/cancel/error/finish — its rows diverged from the "
    "speculated schedule) or chaining was declined (admission/close, or "
    "'spec': the accept-aware policy preferred a host-drafted verify "
    "dispatch over extending the scan chain)",
    labelnames=("reason",))
# Batched speculative decoding (docs/SERVING.md "Speculative decoding"):
# per-engine spec telemetry next to the sequential path's spec_* family —
# drafted/accepted volumes and the verify-dispatch count are THE health
# signals for the batched draft-verify path (accept rate ~0 means the
# workload is paying wide dispatches for nothing).
_SPEC_VERIFY_STEPS = metrics.counter(
    "batch_spec_verify_steps_total",
    "Batched draft-verify super-step dispatches")
_SPEC_DRAFTED = metrics.counter(
    "batch_spec_drafted_tokens_total",
    "Draft tokens proposed to batched verify dispatches (per row)")
_SPEC_ACCEPTED = metrics.counter(
    "batch_spec_accepted_tokens_total",
    "Draft tokens batched verify dispatches accepted")
_SPEC_ACCEPT_RATE = metrics.gauge(
    "batch_spec_accept_rate",
    "Cumulative batched accepted/drafted ratio (process lifetime)")
# Durable-request resume (docs/FLEET.md "Resume protocol"): requests
# re-admitted mid-generation after a replica failure, and how much of their
# prompt ⊕ delivered-tokens prefix the admission re-prefill actually skipped
# (same-slot rewind + radix pool seed) — the resume-cost health signal.
_RESUMED = metrics.counter(
    "batch_resumed_requests_total",
    "Requests admitted with a resume prefix (mid-stream failover re-submits)")
_RESUME_TOKENS = metrics.counter(
    "batch_resume_prefix_tokens_total",
    "Delivered-elsewhere tokens carried by resume admissions (the suffix the "
    "new replica must re-prefill or reuse)")
# Multi-tenant serving (docs/SERVING.md "Multi-tenant serving"): per-tenant
# service accounting (labels stay bounded — unknown tenant ids collapse to
# the canonical "default" policy), fairness preemptions, SLO-driven sheds,
# quota throttles, and the measured drain rate every Retry-After hint is
# derived from (resilience/tenancy.py).
_TENANT_TOKENS = metrics.counter(
    "batch_tenant_tokens_total",
    "Decode tokens delivered, by canonical tenant", labelnames=("tenant",))
_TENANT_REQUESTS = metrics.counter(
    "batch_tenant_requests_total",
    "Completed requests by canonical tenant and class",
    labelnames=("tenant", "class"))
_PREEMPTED = metrics.counter(
    "batch_preempted_total",
    "Batch-class rows preempted at a super-step boundary so a waiting "
    "interactive request could take the slot (the preempted request is "
    "re-queued and later resumes byte-identical)")
_SLO_SHED = metrics.counter(
    "engine_slo_shed_total",
    "Admissions refused (or queued batch work evicted for an interactive "
    "arrival) because the projected queue wait exceeded the class's TTFT "
    "target or measured TPOT exceeded the interactive target, by class",
    labelnames=("class",))
_QUOTA_THROTTLED = metrics.counter(
    "engine_quota_throttled_total",
    "Admissions refused with 429: the tenant's token-bucket quota was "
    "exhausted", labelnames=("tenant",))
_DRAIN_RATE = metrics.gauge(
    "engine_drain_rate",
    "Measured request completions/sec (decayed EMA, resilience/tenancy.py "
    "DrainRate) — the denominator of drain-derived Retry-After hints")
# Hung-engine supervision (resilience/supervisor.py): the watchdog gauge
# escalated to action — recoveries attempted and the requests they failed.
_WEDGE_RECOVERIES = metrics.counter(
    "engine_wedge_recoveries_total",
    "Supervisor escalations: a wedged scheduler was abandoned and the engine "
    "re-initialized, by outcome", labelnames=("outcome",))
_WEDGE_FAILED = metrics.counter(
    "engine_wedge_failed_requests_total",
    "In-flight/queued requests failed with EngineWedged by a supervisor "
    "recovery (retriable: a durable router resumes them elsewhere)")
# Grammar-constrained decoding (constrain/, docs/SERVING.md "Constrained
# decoding"): rows with an attached TokenAutomaton, masked dispatches
# issued, and rows degraded to unconstrained output (mask fault or table
# capacity — a service condition, never a client-visible failure).
_CONSTRAIN_ROWS = metrics.gauge(
    "constrain_rows",
    "Batch rows currently decoding under an attached grammar automaton")
_CONSTRAIN_DISPATCHES = metrics.counter(
    "constrain_masked_dispatches_total",
    "Batched decode/verify dispatches issued through the masked program "
    "variants (>= 1 live constrained row in the batch)")
_CONSTRAIN_DEGRADED = metrics.counter(
    "constrain_degraded_total",
    "Constrained rows degraded to unconstrained decoding, by reason "
    "(capacity = constraint table full, mask = masking fault, "
    "divergence = delivered token left the grammar)",
    labelnames=("reason",))


# Donated single-block pool updates (docs/PAGED_KV.md copy-on-write and
# cold promotion): an eager `pool.at[:, b].set(...)` would materialize a
# whole new pool array per block touched — O(pool) HBM traffic and 2x peak
# memory. Donating the pool lets XLA update the one block in place.
import jax  # noqa: E402  (after the module docstring's import block)

_pool_block_copy = jax.jit(lambda c, src, dst: c.at[:, dst].set(c[:, src]),
                           donate_argnums=(0,))
_pool_block_set = jax.jit(lambda c, dst, rows: c.at[:, dst].set(rows),
                          donate_argnums=(0,))


class _StaleEpoch(BaseException):
    """Raised inside an ABANDONED scheduler thread (recover_wedged bumped the
    engine epoch while this thread was stuck in a device call): the thread
    must unwind without touching engine state — the slots/queue it knew were
    replaced, so its _fail_all/_deliver paths would corrupt the NEW epoch's
    requests. BaseException so no blanket `except Exception` net keeps the
    zombie serving."""


@dataclass
class BatchRequest:
    prompt: list[int]
    max_tokens: int
    sampler: object
    on_token: Callable[[int], None] | None = None
    stop_check: Callable[[int], bool] | None = None
    # results
    out: list[int] = field(default_factory=list)
    finish: str = "length"
    error: Exception | None = None
    done: threading.Event = field(default_factory=threading.Event)
    stats: GenerationStats = field(default_factory=GenerationStats)

    cancelled: bool = False
    submit_t: float = 0.0  # perf_counter at submit(), feeds batch_queue_wait
    # multi-tenant identity (docs/SERVING.md "Multi-tenant serving"):
    # `tenant` is the serving-local tenant id (quota + fair-share key),
    # `klass` the scheduling class — "interactive" (strict queue priority,
    # may preempt batch rows at super-step boundaries) or "batch" (absorbs
    # slack, shed first under overload). `wfq_cost` is the virtual-service
    # cost the fair queue charges (≈ total token positions the request
    # consumes); `preemptions` counts slot losses to interactive arrivals.
    tenant: str = DEFAULT_TENANT
    klass: str = "interactive"
    wfq_cost: float = 1.0
    preemptions: int = 0
    # durable resume (docs/FLEET.md): the last `resume_tokens` entries of
    # `prompt` are generated-and-delivered-elsewhere tokens, not user prompt —
    # admission counts them separately and the sampler arrives fast-forwarded
    resume_tokens: int = 0
    # disaggregation export (docs/DISAGG.md): when set, _finish snapshots
    # the slot's committed prompt-prefix KV blocks to HOST arrays (on the
    # scheduler thread — the only thread allowed to read device caches)
    # into kv_export = (tokens, [(k, v) per block], block_tokens) before
    # done.set(), so the waiting /v1/kv handler can serve them
    export_kv: bool = False
    kv_export: tuple | None = None
    # request identity (docs/OBSERVABILITY.md "Request tracing"): `rid` keys
    # the flight-recorder timeline; `ctx` is the W3C trace context captured
    # at submit() — the scheduler thread re-enters it (reqctx.use) around
    # per-request work so engine-side spans/events carry this request's
    # trace id even though one super-step serves many requests
    rid: str = ""
    ctx: object = None  # obs.reqctx.TraceContext | None
    # absolute perf_counter deadline for the WHOLE request (queue + decode);
    # 0 = none. The scheduler enforces it once per loop pass (finish reason
    # "deadline"), so granularity is one dispatch (~K token-times).
    deadline_t: float = 0.0
    # absolute perf_counter bound on QUEUE time only (expired before a slot
    # was assigned -> finish "deadline" without ever prefilling); 0 = none
    queue_ttl_t: float = 0.0
    # grammar-constrained decoding (constrain/, docs/SERVING.md "Constrained
    # decoding"): a compiled TokenAutomaton the OUTPUT must satisfy, plus
    # the grammar hash the api edge logged. The engine allocates a region
    # in its device constraint table at admission and masks sampling (host
    # and device) to the automaton's allowed set; compile happens at the
    # edge so the engine never needs tokenizer bytes.
    constraint: object = None  # constrain.TokenAutomaton | None
    constraint_hash: str = ""

    def cancel(self) -> None:
        """Ask the scheduler to stop decoding this request (client went away)."""
        self.cancelled = True

    def wait(self, timeout=None) -> list[int]:
        if not self.done.wait(timeout):
            # auto-cancel: a timed-out waiter previously walked away while
            # the request kept decoding to max_tokens with its slot (and any
            # prefix-cache lease) pinned — the scheduler reaps a cancelled
            # request on its next pass through the existing _finish path
            self.cancel()
            raise TimeoutError(
                f"generation not finished within {timeout}s (auto-cancelled)")
        if self.error is not None:
            raise self.error
        return self.out


class _SlotConstraint:
    """Per-slot grammar state (scheduler-thread-only, constrain/).

    `state` is the LOCAL automaton state, the host mirror of the device
    carry — advanced in _emit per DELIVERED token, so after any full
    delivery host and device agree exactly (integer bookkeeping, no
    resync needed; a flushed/partial dispatch re-uploads from here, same
    discipline as the sampler rng). `offset` rebases local states into
    the engine's stacked ConstraintTable; `degraded` parks the row on the
    universal state 0 (unconstrained) after a mask fault or capacity
    miss — visible in metrics and the flight timeline, never to the
    client."""

    __slots__ = ("automaton", "state", "offset", "ghash", "degraded")

    def __init__(self, automaton, offset: int, ghash: str = ""):
        self.automaton = automaton
        self.state = 0
        self.offset = offset
        self.ghash = ghash
        self.degraded = False

    @property
    def gstate(self) -> int:
        """GLOBAL table state uploaded to device (0 = universal row)."""
        return 0 if self.degraded else self.offset + self.state


class _Slot:
    def __init__(self, index: int):
        self.index = index
        self.pos = 0  # next cache position for this row
        self.history: list[int] = []  # tokens whose KV is written (prefix reuse)
        self.req: BatchRequest | None = None
        self.pending: list[int] = []  # prompt tokens not yet prefilled
        self.last_token = 0  # feeds the next decode step
        self.last_logits: np.ndarray | None = None
        # token already sampled (on device, tail of a super-step block) but not
        # yet ingested — consumed by _advance_row instead of a host sample
        self.next_token: int | None = None
        # prefix-cache lease pinning the blocks this slot was seeded from
        # (released at _finish; shrunk when history is truncated)
        self.lease = None
        # device-pool block table (paged KV, docs/PAGED_KV.md): pool block
        # ids backing virtual positions [0, len(blocks)*bt); one pool ref
        # held per entry. Retained across requests like `history` — the
        # same-slot rewind's backing store.
        self.blocks: list[int] = []
        self.admit_t = 0.0  # monotonic admission time (dispatch watchdog)
        # last_token is sampled/delivered but its KV not yet written: a
        # dispatch that fails AFTER _advance_row consumed next_token must not
        # re-advance (and spuriously finish) the row on retry — _advance_row
        # is a no-op while armed; the successful ingesting dispatch clears it
        self.armed = False
        # set BEFORE a super-step's delivery loop when the scan will park
        # this row clamped at seq_len-1 (destroying that history row): a
        # mid-loop _finish must harvest the TRUNCATED history, not the
        # poisoned row (consumed by _harvest_into_cache / the post-loop clamp)
        self.clamp_pos: int | None = None
        # speculative drafting state lives in the engine's Proposer
        # (runtime/speculative.py): attached at admission, fed per delivered
        # token, detached at finish/preempt — keyed by this slot's index
        # per-tenant token counter child, resolved ONCE at admission so the
        # per-token hot path (_emit) pays a bound-method call, not a label
        # dict lookup
        self.tok_counter = None
        # grammar constraint handle (constrain/): attached at admission
        # when the request carries an automaton, advanced in _emit,
        # released (table region freed) at finish/preempt/wedge
        self.constraint: _SlotConstraint | None = None


class _InflightStep:
    """An issued-but-undelivered K-step super-step OR draft-verify dispatch.

    Holds the DEVICE arrays the dispatch will produce (`toks` the (K, B)
    token block, plus the (last_tok, pos, rng) carry the next dispatch can
    chain from) and the host-side schedule it was issued against: full
    B-length `starts`/`budget`/`temps` lists plus the (slot, request) pairs
    of its live rows. A chained dispatch's schedule is SPECULATIVE — derived
    assuming its predecessor delivers every budgeted token — and is validated
    against the predecessor's actual delivery before this dispatch is kept.

    kind "verify" (docs/SERVING.md "Speculative decoding"): `toks` is the
    (T, B) per-position target block, `ndraft` the per-row real draft counts
    (-1 = parked), `acc` the device (B,) accepted lengths, and `budget` the
    per-row MAXIMUM emit (ndraft+1) — delivery reads the actual emit, acc+1,
    from the device. The carry is rewound to each row's verified frontier on
    device, so a chained scan consumes it soundly for any accept outcome."""

    __slots__ = ("rows", "k", "starts", "budget", "temps", "toks", "tok",
                 "pos", "rng", "t_issue", "chained", "kind", "ndraft", "acc",
                 "cstate")

    def __init__(self, rows, k, starts, budget, temps, toks, tok, pos, rng,
                 t_issue, chained, kind="scan", ndraft=None, acc=None,
                 cstate=None):
        self.rows = rows  # list[(slot, request)] for budget > 0 rows
        self.k = k
        self.starts = starts  # expected per-row device start positions
        self.budget = budget
        self.temps = temps
        self.toks = toks  # device (K, B) token block
        self.tok = tok  # device (B,) block-tail token (next dispatch's input)
        self.pos = pos  # device (B,) positions after the budgeted ingestions
        self.rng = rng  # device (B, 2) advanced xorshift* state
        self.t_issue = t_issue
        self.chained = chained
        self.kind = kind  # "scan" | "verify"
        self.ndraft = ndraft  # verify: per-row draft counts (-1 = parked)
        self.acc = acc  # verify: device (B,) accepted draft lengths
        # masked dispatch only: device (B,) GLOBAL constraint states after
        # the budgeted emissions — a chained masked scan consumes it
        self.cstate = cstate


class BatchEngine:
    """Engine-compatible construction (same spec/params arguments), `slots` sequences.

    Use submit() for async operation or generate() for the Engine-compatible blocking
    call. The scheduler thread starts lazily on first submit and can be stopped with
    close().
    """

    def __init__(self, spec: ModelSpec, params, tokenizer=None, *, slots: int = 2,
                 superstep: int = 8, pipeline: bool = True, prefix_cache=True,
                 prefix_cache_blocks: int = 0, prefix_block_tokens: int = 16,
                 prefix_cache_q80: bool = False, max_queue: int = 0,
                 queue_ttl: float = 0.0, max_retries: int = 3,
                 retry_backoff: float = 0.05, speculative: int = 0,
                 spec_min_draft: int = 1, spec_chain_expect: float = 2.0,
                 spec_adaptive: bool = True,
                 draft_model=None, draft_k: int = 0,
                 constrain_states: int = 512,
                 tenants: TenantRegistry | None = None,
                 slo_ttft_interactive: float = 0.0,
                 slo_ttft_batch: float = 0.0,
                 slo_tpot_interactive: float = 0.0,
                 paged_kv: bool = True, kv_block_tokens: int = 16,
                 kv_pool_blocks: int = 0,
                 **engine_kw):
        from .engine import Engine

        assert slots >= 1
        assert superstep >= 1
        assert engine_kw.get("sp", 1) in (None, 1), (
            "continuous batching needs per-row cache positions, which the "
            "sequence-sharded (ring) cache does not support")
        self.slots_n = slots
        # Device-resident paged KV (docs/PAGED_KV.md, default ON; the
        # --no-paged-kv escape hatch reverts to the dense per-slot caches):
        # KV lives in a (L, N, hk, bt, hs) device block pool, each slot
        # carries a block table, and cross-request prefix reuse is a
        # refcounted block-table REMAP — zero host→device KV bytes on a
        # radix hit. A shared dense PrefixCache instance forces the dense
        # layout (the caller asked for host-pool sharing semantics); the
        # Engine gate below additionally drops it under sp/dp sharding or
        # host/disc KV spill.
        kv_pool_cfg = None
        from ..cache import PrefixCache as _DensePC

        if paged_kv and not isinstance(prefix_cache, _DensePC):
            bt = max(int(kv_block_tokens), 1)
            while bt > 1 and spec.seq_len % bt:
                bt //= 2  # the parity gather wants bt | seq_len
            w = spec.seq_len // bt
            n_blocks = int(kv_pool_blocks) or (slots * w + slots + 1)
            # floor: one full context + the scratch block + one spare, or
            # no request could ever run to seq_len
            kv_pool_cfg = (max(n_blocks, w + 2), bt)
        self._eng = Engine(spec, params, tokenizer, batch=slots,
                           kv_pool=kv_pool_cfg, **engine_kw)
        self.kv_pool = None  # DeviceKVPool metadata (None = dense layout)
        self._kv_bt = 0
        if self._eng.kv_pool is not None:
            from ..cache.device_pool import DeviceKVPool

            n_blocks, self._kv_bt = self._eng.kv_pool
            self.kv_pool = DeviceKVPool(n_blocks, self._kv_bt)
            self._kv_w = spec.seq_len // self._kv_bt
            self._tables_np = np.zeros((slots, self._kv_w), np.int32)
            self._tables_dev = None  # rebuilt lazily after table edits
        # admission seeding cost readout (bench.py shared-prefix columns):
        # host→device KV bytes moved and wall time spent seeding slots —
        # ~0 bytes on the paged path (remap), the full fetched span dense
        self.seed_bytes = 0
        self.seed_ms = 0.0
        # check the ENGINE's resolution (kwarg or DLT_PROLOGUE env) — warning on
        # the kwarg alone would miss the env route the flag help advertises
        if self._eng.fused_prologue and slots > 1:
            import sys

            print("⚠️  --prologue is inert with batched decode (the prologue "
                  "kernels take one activation row; forward gates them off for "
                  "B > 1) — the A/B lever will not engage", file=sys.stderr,
                  flush=True)
        self.spec = spec
        self.tokenizer = tokenizer
        self.superstep = superstep  # K: decode steps fused per device dispatch
        # pipelined super-steps (docs/SERVING.md "Pipelined decode"): chain
        # dispatch N+1 from N's device-resident carry while N's block is
        # delivered host-side. K=1 has no block to overlap; keep it off there.
        self.pipeline = pipeline and superstep >= 2
        self._inflight: _InflightStep | None = None
        self._last_ready_t: float | None = None  # perf_counter of last results
        self._gap_t: float | None = None  # last dispatch-ready time, gap metric
        self._slots = [_Slot(i) for i in range(slots)]
        self._queue: "queue.Queue[BatchRequest]" = queue.Queue()
        # Multi-tenant policy (docs/SERVING.md "Multi-tenant serving"):
        # `tenants` configures per-tenant quotas + fair-share weights (None
        # = single default tenant: quotas off, weights uniform — the
        # pre-tenancy behavior); the slo_* targets drive SLO-aware shedding
        # at submit (0 = off); `_drain` measures completions/sec so every
        # Retry-After hint tracks real load instead of a constant; the
        # wait queue itself is a two-class weighted-fair queue, not a FIFO.
        self.tenants = tenants
        self.slo_ttft = {"interactive": max(slo_ttft_interactive, 0.0),
                         "batch": max(slo_ttft_batch, 0.0)}
        self.slo_tpot_interactive = max(slo_tpot_interactive, 0.0)
        self._drain = DrainRate()
        self._tpot_ema_ms = 0.0  # measured per-token ms (scheduler-written)
        # overflow requests with no free slot; guarded by _plock (close() may run while
        # the scheduler thread is still finishing a long device step)
        self._pending: WeightedFairQueue = WeightedFairQueue(tenants)
        self._plock = threading.Lock()  # guards: _pending
        # Batched speculative decoding (docs/SERVING.md "Speculative
        # decoding"): spec_k > 0 drafts up to k tokens per row from the
        # slot's NgramIndex and verifies every row's block in ONE (B, 1+k)
        # dispatch — the weights stream once for up to k+1 tokens per row.
        # spec_min_draft gates a verify dispatch on total drafted tokens
        # (below it the K-step scan serves better); spec_chain_expect is the
        # accept-aware chaining threshold: while the engine's accept EMA is
        # at/above it, back-to-back verifies beat diluting them with chained
        # scans, so chaining is declined (reason "spec").
        self.spec_k = max(int(speculative), 0)
        if self.spec_k:
            # a verify block must fit the context with room to decode
            self.spec_k = min(self.spec_k, spec.seq_len - 2)
        self.spec_min_draft = max(int(spec_min_draft), 1)
        self.spec_chain_expect = float(spec_chain_expect)
        # optimistic start: speculation engages immediately and the EMA
        # adapts down on non-repetitive workloads (updated per verify)
        self._spec_ema = float(self.spec_k)
        # Model-based drafting (docs/SERVING.md "Model-based drafting"):
        # draft_model (path, or a (spec, params) pair for tests) loads a
        # second small sharded model CO-RESIDENT on this engine's mesh that
        # drafts up to draft_k (default spec_k) tokens per row in one scan
        # dispatch; n-gram lookup remains the per-row fallback (and the
        # whole proposer when no drafter is configured, or its load fails —
        # a drafter is an accelerator, never a correctness gate). The
        # ADAPTIVE PER-ROW k controller (spec_adaptive, default on) drives
        # each row's draft length from its own accept EMA, bucketed to the
        # verify T buckets so adaptation cannot mint new compiled programs.
        self.adaptive = (AdaptiveK(self.spec_k)
                         if self.spec_k and spec_adaptive else None)
        self.drafter = None
        if draft_model is not None and self.spec_k and self._eng.dp > 1:
            # the drafter's programs are tp-only (draft/loop.py) — gate at
            # construction like the paged-KV dp/sp gate, instead of letting
            # every proposal turn raise its way to the permanent disable
            import sys

            print("💡 --draft-model disabled: the drafter is tp-only and "
                  "this engine shards rows over dp — using n-gram drafting",
                  file=sys.stderr)
            draft_model = None
        if draft_model is not None and self.spec_k:
            try:
                from ..draft.drafter import ModelDrafter

                dk = min(int(draft_k) or self.spec_k, self.spec_k)
                if isinstance(draft_model, (tuple, list)):
                    dspec, dparams = draft_model
                    self.drafter = ModelDrafter(
                        dspec, dparams, mesh=self._eng.mesh, slots=slots,
                        target_spec=spec, tokenizer=tokenizer,
                        dtype=self._eng.dtype,
                        use_pallas=self._eng.use_pallas,
                        compress_collectives=self._eng.compress,
                        moe_sharding=self._eng.moe_sharding, k_cap=dk)
                else:
                    self.drafter = ModelDrafter.load(
                        str(draft_model), mesh=self._eng.mesh, slots=slots,
                        target_spec=spec, tokenizer=tokenizer,
                        dtype=self._eng.dtype,
                        use_pallas=self._eng.use_pallas,
                        compress_collectives=self._eng.compress,
                        moe_sharding=self._eng.moe_sharding, k_cap=dk)
            except Exception as e:
                import sys

                print(f"⚠️  draft model unavailable ({e!r}) — degrading to "
                      "n-gram drafting", file=sys.stderr, flush=True)
        # Grammar-constrained decoding (constrain/, docs/SERVING.md
        # "Constrained decoding"): the stacked device constraint table is
        # created lazily at the first constrained admission (unconstrained
        # engines never pay the (cap, V) host arrays), and the
        # GrammarProposer rides the mux so constrained rows draft their
        # forced-transition chains while co-batched chat rows keep
        # model/ngram drafts.
        from ..constrain import GrammarProposer

        self.constrain_states = max(int(constrain_states), 2)
        self.constrain_table = None  # ConstraintTable, lazy
        self.constrain_degraded = 0
        self.grammar_proposer = GrammarProposer()
        self.proposer = ProposerMux(NgramProposer(), self.drafter,
                                    grammar=self.grammar_proposer)
        self.prefilled_tokens = 0  # observability: total tokens run through prefill
        self.decode_steps = 0  # observability: batched device decode dispatches
        self.super_steps = 0  # observability: K-step fused dispatches (subset)
        self.verify_steps = 0  # observability: draft-verify dispatches (subset)
        self.mixed_steps = 0  # observability: prefill dispatches carrying decode rows
        self._loops: dict[tuple, object] = {}  # (k, mode, window) -> batched loop
        # scheduler wakeup: a Condition, not a sleep-poll — submit() notifies,
        # so enqueue latency is bounded by lock handoff, not a poll interval
        self._cond = threading.Condition()
        self._shutdown = False
        self._draining = False  # drain mode: serve in-flight, refuse new
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()  # guards: _thread
        # scheduler epoch (resilience/supervisor.py): recover_wedged() bumps
        # it to abandon a scheduler thread stuck in a hung device call — the
        # stale thread observes the bump at its next epoch check and unwinds
        # via _StaleEpoch instead of mutating the replacement state. Each
        # scheduler thread records ITS epoch thread-locally so the checks
        # compare against the epoch the thread was born into, not a value
        # re-read after the bump (which would blind the check to a bump
        # landing between loop entry and the dispatch)
        self._epoch = 0
        self._tls = threading.local()
        self.wedge_recoveries = 0  # observability: supervisor escalations
        # Admission control (docs/ROBUSTNESS.md): max_queue bounds the number
        # of requests WAITING for a slot (0 = unbounded, the pre-PR-4
        # behavior); queue_ttl bounds how long a request may wait queued;
        # both are plain attributes so a server can tune them live.
        self.max_queue = max_queue
        self.queue_ttl = queue_ttl
        # transient-dispatch retry policy: capped exponential backoff
        # starting at retry_backoff seconds, max_retries attempts beyond the
        # first before the error escalates to engine scope
        self.max_retries = max_retries
        self.retry_backoff = retry_backoff
        self._last_dispatch_t: float | None = None  # monotonic, watchdog
        _DISPATCH_AGE.set_function(self._dispatch_age)
        # Cross-request prefix cache (cache/): pass False to disable, True for
        # defaults, or a ready PrefixCache instance to share one across
        # engines. Host/disc-spill paged engines are excluded — their ring
        # layout has no plain [0, n) row prefix to seed. In device-pool mode
        # the cache is the radix DIRECTORY over device blocks
        # (cache/device_pool.py): hits remap block tables instead of copying
        # rows, and its cold tier is the same host KVBlockPool the dense
        # cache used (one unified demotion path, docs/PAGED_KV.md).
        self.prefix_cache = None
        if self.kv_pool is not None:
            if prefix_cache:
                from ..cache import default_pool_blocks
                from ..cache.device_pool import PagedPrefixCache

                hk = self._eng.k_cache.shape[2]
                cold = prefix_cache_blocks or default_pool_blocks(
                    (spec.n_layers, slots, hk, spec.seq_len,
                     spec.head_size),
                    self._eng.k_cache.dtype.itemsize, self._kv_bt, slots)
                self.prefix_cache = PagedPrefixCache(
                    self.kv_pool, self._kv_bt, cold_blocks=cold,
                    q80=prefix_cache_q80)
        elif not self._eng.paged:
            from ..cache import make_prefix_cache

            self.prefix_cache = make_prefix_cache(
                self._eng.k_cache.shape, self._eng.k_cache.dtype.itemsize,
                slots=slots, prefix_cache=prefix_cache,
                blocks=prefix_cache_blocks, block_tokens=prefix_block_tokens,
                q80=prefix_cache_q80)
        _SLOTS_TOTAL.set(slots)

    @classmethod
    def load(cls, model_path: str, tokenizer_path: str | None = None, *,
             max_seq_len: int = 0, weights_ftype=None, slots: int = 2,
             superstep: int = 8, **kw) -> "BatchEngine":
        """Engine.load-compatible constructor (same flag surface, same vocab check)."""
        from ..formats.mfile import load_model
        from ..tokenizer.bpe import Tokenizer

        spec, params = load_model(model_path, max_seq_len, weights_ftype)
        tokenizer = Tokenizer.load(tokenizer_path) if tokenizer_path else None
        if tokenizer is not None and tokenizer.vocab_size != spec.vocab_size:
            raise ValueError(
                f"tokenizer vocab {tokenizer.vocab_size} != model vocab {spec.vocab_size}")
        return cls(spec, params, tokenizer, slots=slots, superstep=superstep, **kw)

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------

    def submit(self, prompt: list[int], max_tokens: int, sampler,
               on_token=None, stop_check=None, *, deadline: float | None = None,
               ttl: float | None = None, rid: str | None = None,
               ctx=None, resume_tokens: int = 0, tenant: str = "",
               klass: str = "interactive",
               export_kv: bool = False, constraint=None,
               constraint_hash: str = "") -> BatchRequest:
        """Enqueue a request. `deadline` (seconds) bounds the WHOLE request
        (queue + generation; finish reason "deadline", partial output kept);
        `ttl` bounds queue wait only (overrides the engine's queue_ttl).
        `rid`/`ctx` set the request id and trace context; both default from
        the caller's bound reqctx (api_server's handler thread) or are
        originated here, so every request is traceable even when submitted
        outside the HTTP layer. `resume_tokens` marks the last N entries of
        `prompt` as mid-stream-failover resume tokens (generated and
        delivered by a failed replica; docs/FLEET.md "Resume protocol") —
        the caller must pass the sampler already fast-forwarded past their
        coins; admission then re-prefills prompt ⊕ resume (mostly a radix
        prefix-cache hit) and generation continues byte-identical to the
        uninterrupted run.

        `tenant`/`klass` are the multi-tenant scheduling identity
        (docs/SERVING.md "Multi-tenant serving"): `tenant` defaults from
        the bound trace context (the api layer's X-Tenant mapping) and
        keys quota + fair-share accounting; `klass` is "interactive"
        (strict priority, may preempt batch rows) or "batch" (absorbs
        slack, shed first). Raises EngineDraining/EngineClosed during
        shutdown, QuotaExceeded (429) when the tenant's token bucket is
        exhausted, and EngineSaturated (503) when the wait queue is at
        max_queue or SLO-aware shedding refuses the class — both with
        Retry-After derived from the measured queue drain rate."""
        if self._draining and not self._shutdown:
            raise EngineDraining(
                "BatchEngine is draining (serving in-flight requests only)")
        if self._shutdown:
            raise EngineClosed("BatchEngine is closed")
        faults.fire("batch.submit")
        if klass not in CLASSES:
            raise InvalidRequest(
                f"unknown scheduling class {klass!r} (want one of {CLASSES})")
        c = ctx if ctx is not None else reqctx.current()
        tenant = tenant or (c.tenant if c is not None else "") \
            or DEFAULT_TENANT
        cost = float(len(prompt) + max(max_tokens, 1))
        if self.tenants is not None:
            # quota first: a throttled tenant must get its honest 429 even
            # when the queue is empty (quota is policy, not load)
            try:
                self.tenants.acquire(tenant, cost)
            except Exception as e:
                _QUOTA_THROTTLED.labels(
                    tenant=self.tenants.canonical(tenant)).inc()
                raise e
        try:
            self._admission_control(tenant, klass, cost)
        except Exception:
            # a shed request received zero service: refund the quota debit
            # or overload bursts would double-punish within-quota tenants
            # (drained bucket + 503) and 429 them after capacity recovers
            if self.tenants is not None:
                self.tenants.refund(tenant, cost)
            raise
        req = BatchRequest(list(prompt), max_tokens, sampler, on_token, stop_check)
        req.tenant = tenant
        req.klass = klass
        req.wfq_cost = cost
        req.export_kv = export_kv
        if constraint is not None:
            # structural rejects belong at submit (the api edge maps them
            # to 400): an automaton that can NEVER fit the table is a
            # client error, not the runtime capacity condition alloc
            # degrades on
            if getattr(constraint, "n_states", 0) > self.constrain_states - 1:
                if self.tenants is not None:
                    self.tenants.refund(tenant, cost)
                raise InvalidRequest(
                    f"grammar too large: {constraint.n_states} automaton "
                    f"states exceed the engine's constraint table "
                    f"({self.constrain_states - 1} usable states)")
            req.constraint = constraint
            req.constraint_hash = constraint_hash
        if not req.prompt:
            req.prompt = [self.tokenizer.bos_id if self.tokenizer else 1]
        req.resume_tokens = min(max(int(resume_tokens), 0), len(req.prompt))
        if req.resume_tokens:
            _RESUMED.inc()
            _RESUME_TOKENS.inc(req.resume_tokens)
        # request identity: adopt the caller's trace context (the HTTP
        # handler thread's contextvar) or originate one, and make the
        # context carry the request id so the faults.fire → flight hook can
        # attribute injections fired inside this request's scheduler scope
        rid = rid or (c.request_id if c is not None and c.request_id else "")
        if not rid:
            rid = f"req-{uuid.uuid4().hex[:16]}"
        req.rid = rid
        if c is None:
            req.ctx = reqctx.new_context(rid, tenant)
        elif c.request_id != rid or c.tenant != tenant:
            req.ctx = dataclasses.replace(c, request_id=rid, tenant=tenant)
        else:
            req.ctx = c
        flight.start(rid, req.ctx.trace_id, prompt_tokens=len(req.prompt),
                     max_tokens=max_tokens,
                     **{"tenant": tenant, "class": klass})
        req.submit_t = time.perf_counter()
        if deadline is not None and deadline > 0:
            req.deadline_t = req.submit_t + deadline
        eff_ttl = self.queue_ttl if ttl is None else ttl
        if eff_ttl and eff_ttl > 0:
            req.queue_ttl_t = req.submit_t + eff_ttl
        # put BEFORE ensure: racing a recover_wedged(), a request already in
        # the queue is drained and failed retriable by the recovery, and a
        # put landing after it finds _thread=None so ensure spawns the fresh
        # scheduler — ensure-first could observe the doomed thread as alive
        # and then enqueue into a queue nothing serves
        self._queue.put(req)
        self._ensure_thread()
        with self._cond:
            self._cond.notify()
        return req

    def _admission_control(self, tenant: str, klass: str,
                           cost: float) -> None:
        """Load shedding at submit (docs/SERVING.md "Multi-tenant serving").
        Two displacement rules make the shed order policy-true instead of
        arrival-order-true:

        - class: an INTERACTIVE arrival that would be refused first evicts
          the least-entitled queued batch request (batch sheds before
          interactive);
        - weight: a BATCH arrival hitting the full queue displaces the
          least-entitled queued batch item when its own virtual finish tag
          is SMALLER (more entitled) — so under uniform flooding the queue
          holds weight-proportional work and delivered throughput tracks
          the configured weights rather than arrival luck.

        Every refusal carries Retry-After derived from the measured queue
        drain rate (EMA completions/sec vs depth, resilience/tenancy.py),
        never a hardcoded constant."""
        with self._plock:
            queued = len(self._pending) + self._queue.qsize()
        reason = None
        if self.max_queue and queued >= self.max_queue:
            reason = "queue"
        tgt = self.slo_ttft.get(klass, 0.0)
        if reason is None and tgt and queued > 0:
            # projected wait for the LAST place in line, applied only when
            # a backlog actually exists: an idle engine serves within ~one
            # dispatch whatever the historical drain rate says — without
            # the queued>0 gate, a long-idle engine's decayed EMA (tiny but
            # nonzero) projected an absurd wait and shed at queue depth 0.
            # Cold start (no completion observed yet) projects 0 likewise.
            if self._drain.queue_wait(queued + 1) > tgt:
                reason = "slo_ttft"
        if (reason is None and klass == "batch" and self.slo_tpot_interactive
                and self._tpot_ema_ms > self.slo_tpot_interactive * 1e3):
            # decode is already past the interactive TPOT target: one more
            # batch row widens every shared dispatch further — refuse batch
            reason = "slo_tpot"
        if reason is None:
            return
        if klass == "interactive":
            with self._plock:
                # drain first: evictable batch work may still sit in the
                # cross-thread queue while the scheduler is mid-dispatch —
                # an interactive arrival must never be refused while ANY
                # queued batch request exists
                self._drain_submit_queue()
                victim = self._pending.evict_last("batch")
            if victim is not None:
                # shed batch before interactive: the evicted batch request
                # gets the honest 503 this arrival would otherwise have
                self._shed_queued(victim, reason, queued)
                _SLO_SHED.labels(**{"class": "batch"}).inc()
                return
        elif reason == "queue":
            with self._plock:
                self._drain_submit_queue()  # same visibility rule as above
                worst = self._pending.last_tag("batch")
                victim = None
                if (worst is not None and
                        self._pending.entry_tag(tenant, "batch",
                                                cost) < worst):
                    victim = self._pending.evict_last("batch")
            if victim is not None:
                # weighted shed: this batch arrival is MORE entitled than
                # the queue's worst resident — displace it
                self._shed_queued(victim, reason, queued)
                return
        _SHED.inc()
        if reason != "queue":
            _SLO_SHED.labels(**{"class": klass}).inc()
        raise EngineSaturated(
            f"admission refused ({reason}): class={klass}, queue depth "
            f"{queued}" + (f" at max_queue={self.max_queue}"
                           if reason == "queue" else ""),
            retry_after=self._drain.retry_after(queued + 1))

    def _shed_queued(self, req: BatchRequest, reason: str,
                     queued: int) -> None:
        """Fail a queued request displaced by a higher-priority admission
        (the shed-batch-first path) with the same typed error + honest
        Retry-After an admission-time shed would have surfaced."""
        _SHED.inc()
        req.error = EngineSaturated(
            f"shed from the wait queue ({reason}): an interactive admission "
            "displaced this batch request",
            retry_after=self._drain.retry_after(queued))
        req.finish = "error"
        _REQUESTS.labels(finish="error").inc()
        flight.finish(req.rid, "error", error=repr(req.error))
        req.done.set()

    def generate(self, prompt: list[int], max_tokens: int, sampler,
                 on_token=None, stop_check=None) -> tuple[list[int], GenerationStats]:
        """Blocking Engine.generate-compatible call (rides the batched scheduler)."""
        req = self.submit(prompt, max_tokens, sampler, on_token, stop_check)
        out = req.wait()
        return out, req.stats

    @property
    def draining(self) -> bool:
        return self._draining and not self._shutdown

    def scheduler_alive(self) -> bool:
        """True while the scheduler thread can serve (running, or not yet
        lazily started). False only after the thread died — the /healthz
        liveness signal."""
        # single atomic reference read on a health-probe path: taking _lock
        # here would make /healthz contend with _ensure_thread/recover_wedged
        t = self._thread  # dlint: ignore[lock-guard] -- atomic ref snapshot; staleness only skews one health probe
        return t is None or t.is_alive()

    def load_stats(self) -> dict:
        """Slot/queue load reading for the /healthz replica block a fleet
        router's least-loaded routing consumes (fleet/membership.py):
        `free_slots` = slots with no request bound, `queue_depth` = requests
        waiting for one (admitted-pending + submit queue)."""
        with self._plock:
            occupied = sum(1 for s in self._slots if s.req is not None)
            queued = len(self._pending) + self._queue.qsize()
        return {"slots": self.slots_n,
                "free_slots": self.slots_n - occupied,
                "queue_depth": queued}

    def spec_stats(self) -> dict | None:
        """Speculative-decoding block for /v1/stats (docs/SERVING.md
        "Model-based drafting"): engine-level accept counters plus the
        proposer (which drafter is live, degradation state) and the
        adaptive controller's per-row k breakdown. None when speculation is
        off. Reads are lock-protected where the scheduler adapts
        (AdaptiveK) and plain-counter snapshots elsewhere."""
        if not self.spec_k:
            return None
        snap = metrics.snapshot()
        drafted = snap.get("batch_spec_drafted_tokens_total", 0)
        out = {
            "k": self.spec_k,
            "verify_steps": self.verify_steps,
            "drafted_tokens": drafted,
            "accepted_tokens": snap.get("batch_spec_accepted_tokens_total",
                                        0),
            "accept_rate": (snap.get("batch_spec_accepted_tokens_total", 0)
                            / drafted if drafted else None),
            "proposer": self.proposer.describe(),
        }
        if self.adaptive is not None:
            out["adaptive"] = {
                "k_cap": self.adaptive.k_cap,
                "buckets": list(self.adaptive.buckets),
                "rows": {str(r): v
                         for r, v in self.adaptive.stats().items()},
            }
        return out

    def constrain_stats(self) -> dict:
        """Constrained-decoding block for /v1/stats (docs/SERVING.md
        "Constrained decoding"): rows currently decoding under a grammar,
        table capacity, and degradations. The api layer merges the edge's
        compile-cache stats (constrain.compile_stats) alongside."""
        tbl = self.constrain_table
        return {
            "active_rows": tbl.active_rows if tbl is not None else 0,
            "table_states": self.constrain_states,
            "table_used": (sum(n for _off, n in tbl._regions.values())
                           if tbl is not None else 0),
            "degraded": self.constrain_degraded,
        }

    def _dispatch_age(self) -> float:
        """Watchdog reading: 0 while nothing is in flight (an idle scheduler
        is not a hung one); otherwise seconds since the scheduler last made
        progress — the later of the last completed dispatch and the oldest
        live admission, so a hang in the very FIRST dispatch (or the first
        after an idle period) grows from the moment work arrived instead of
        reading 0 / a stale pre-idle timestamp forever."""
        busy = [s.admit_t for s in self._slots if s.req is not None]
        if not busy:
            return 0.0
        ref = min(busy)
        if self._last_dispatch_t is not None and self._last_dispatch_t > ref:
            ref = self._last_dispatch_t
        return max(time.monotonic() - ref, 0.0)

    def dispatch_age(self) -> float:
        """Public watchdog reading (resilience/supervisor.py): seconds since
        the scheduler last made progress while work is in flight, 0 idle —
        the same number the batch_dispatch_age_seconds gauge exports."""
        return self._dispatch_age()

    def recover_wedged(self, error: Exception | None = None,
                       reinit: bool = True) -> bool:
        """Supervisor escalation (resilience/supervisor.py, docs/ROBUSTNESS.md):
        the scheduler stopped making progress — a device dispatch (or its
        result transfer) is hung, the BENCH_r03/r04 documented backend outage
        shape — so act instead of observing:

        1. ABANDON the wedged scheduler thread: bump the engine epoch. The
           stuck thread cannot be interrupted, but every path it can wake on
           checks the epoch before touching engine state and unwinds via
           _StaleEpoch; its locals reference the OLD slot objects and OLD
           cache arrays, both replaced below.
        2. FAIL every in-flight and queued request with EngineWedged — a
           RETRIABLE error: the HTTP layer surfaces it as a resumable
           failure, so a durable fleet router re-submits each request's
           journal to a surviving replica (docs/FLEET.md "Resume protocol").
        3. RE-INITIALIZE the backend (`reinit=True`): drop every compiled
           loop/step and allocate fresh KV caches, so the next admission
           runs against clean device state instead of buffers a zombie
           dispatch may still write. Returns False when re-init itself
           fails (the replica should stay unhealthy and be ejected).

        The next submit() lazily starts a fresh scheduler thread. Safe to
        call from any thread; concurrent calls serialize on the engine lock.
        """
        err = error if error is not None else EngineWedged(
            f"engine made no dispatch progress for "
            f"{self._dispatch_age():.1f}s; in-flight requests failed "
            "(retriable) and the backend was re-initialized")
        with self._lock:
            self._epoch += 1
            stale = self._thread
            self._thread = None  # next submit spawns a fresh scheduler
        if stale is not None and stale.is_alive():
            # a LIVE (merely slow, or killed-by-a-test) scheduler observes
            # the bump at its next loop/dispatch check and exits within one
            # iteration — wait briefly so the slot/cache swap below runs
            # single-threaded. A genuinely hung thread times this out and
            # is caught by the thread-epoch checks when it eventually wakes.
            stale.join(timeout=1.0)
        self.wedge_recoveries += 1
        old_slots = self._slots
        with self._plock:
            # fresh slot objects FIRST: the abandoned thread's locals hold
            # refs to the old list, so nothing it does can reach new requests
            self._slots = [_Slot(i) for i in range(self.slots_n)]
            # constraint table regions were keyed by the old slots; drop the
            # whole table (re-created lazily at the next constrained
            # admission) rather than freeing per-row under a wedged epoch
            self.constrain_table = None
            _CONSTRAIN_ROWS.set(0)
            for s in old_slots:
                if self.prefix_cache is not None and s.lease is not None:
                    self.prefix_cache.release(s.lease)
                    s.lease = None
                if self.kv_pool is not None and s.blocks:
                    self.kv_pool.decref(s.blocks)
                    s.blocks = []
                req = s.req
                s.req = None
                s.pending = []
                self.proposer.detach(s.index)
                if self.adaptive is not None:
                    self.adaptive.detach(s.index)
                if req is not None and not req.done.is_set():
                    req.error = err
                    req.finish = "error"
                    _WEDGE_FAILED.inc()
                    flight.finish(req.rid, "error", error=repr(err))
                    req.done.set()
            while True:
                try:
                    self._pending.append(self._queue.get_nowait())
                except queue.Empty:
                    break
            for req in self._pending:
                req.error = err
                req.finish = "error"
                _WEDGE_FAILED.inc()
                flight.finish(req.rid, "error", error=repr(err))
                req.done.set()
            self._pending.clear()
            _QUEUE_DEPTH.set(0)
            _SLOTS_OCCUPIED.set(0)
        self._inflight = None
        _PIPELINE_DEPTH.set(0)
        self._last_dispatch_t = None  # age restarts from the next admission
        ok = True
        if reinit:
            try:
                faults.fire("engine.reinit")
                eng = self._eng
                self._loops.clear()
                eng._steps.clear()
                eng._decode_loops.clear()
                eng.k_cache, eng.v_cache = eng._init_cache()
                if self.drafter is not None:
                    # a zombie may still hold (and have donated) the
                    # drafter's buffers — fresh caches, programs, row state
                    self.drafter.reset_backend()
                if self.kv_pool is not None:
                    # fresh pool arrays: every allocation and directory
                    # handle referenced the replaced buffers
                    self.kv_pool.reset()
                    if self.prefix_cache is not None:
                        self.prefix_cache.reset()
                    self._tables_np[:] = 0
                    self._tables_dev = None
            except Exception as e:
                ok = False
                print(f"🔴 backend re-initialization failed: {e!r}")
        _WEDGE_RECOVERIES.labels(outcome="ok" if ok else "reinit_failed").inc()
        return ok

    def close(self, drain: bool = False, timeout: float | None = None) -> None:
        """Stop the engine. `drain=True` (the SIGTERM path): refuse new
        admissions (submit raises EngineDraining) but let every in-flight AND
        already-queued request finish, bounded by `timeout` seconds (None =
        30); then close. `drain=False`: abort everything immediately —
        waiters get EngineClosed."""
        if drain and not self._shutdown:
            self._draining = True
            deadline = time.monotonic() + (30.0 if timeout is None else timeout)
            while time.monotonic() < deadline:
                with self._plock:
                    busy = (any(s.req is not None for s in self._slots)
                            or bool(self._pending))
                if not busy and self._queue.empty():
                    break
                time.sleep(0.01)
        self._shutdown = True
        with self._cond:
            self._cond.notify_all()
        # snapshot the scheduler ref under its lock (a concurrent
        # recover_wedged may swap it mid-close; joining the OLD reference
        # after the swap would wait on an abandoned zombie while the fresh
        # scheduler kept serving a closed engine) — but join OUTSIDE the
        # lock: holding it through a 30 s join would block _ensure_thread
        # and recover_wedged for the whole drain
        with self._lock:
            t = self._thread
        if t is not None:
            t.join(timeout=30)
        # detach the watchdog callback IF it is still ours (a later engine
        # may have claimed the gauge): a bound method left on the
        # module-global gauge would pin this engine's params + KV caches
        # past close() for the process lifetime
        if _DISPATCH_AGE._fn == self._dispatch_age:
            _DISPATCH_AGE.set_function(None)
        # unblock every waiter: in-flight slots and still-queued requests. The
        # scheduler may still be alive after the join timeout (long device step), so
        # snapshot each slot's request and tolerate it finishing concurrently.
        err = EngineClosed("BatchEngine closed")
        with self._plock:
            for s in self._slots:
                if self.prefix_cache is not None and s.lease is not None:
                    self.prefix_cache.release(s.lease)
                    s.lease = None
                req = s.req
                if req is not None and not req.done.is_set():
                    req.error = err
                    s.req = None
                    s.pending = []
                    flight.finish(req.rid, "error", error=repr(err))
                    req.done.set()
            while True:
                try:
                    self._pending.append(self._queue.get_nowait())
                except queue.Empty:
                    break
            for req in self._pending:
                req.error = err
                flight.finish(req.rid, "error", error=repr(err))
                req.done.set()
            self._pending.clear()

    # ------------------------------------------------------------------
    # scheduler
    # ------------------------------------------------------------------

    def _ensure_thread(self) -> None:
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(target=self._loop, daemon=True,
                                                name="batch-engine")
                self._thread.start()

    def _assign(self, req: BatchRequest) -> _Slot | None:
        """Place a request on the free slot with the longest common token prefix
        (the multi-slot generalization of the reference NaiveCache), then try
        to extend the reuse from the cross-request prefix cache: when the radix
        index covers more of the prompt than the slot's own history, the extra
        rows are copied in from the block pool and prefill starts at the seeded
        position (docs/PREFIX_CACHE.md).

        A PREEMPTED request (req.out non-empty: a batch row displaced by an
        interactive admission, docs/SERVING.md "Multi-tenant serving")
        re-admits against prompt ⊕ delivered — the same forced-prefix
        construction as a durable resume (docs/FLEET.md): its sampler
        already sits after exactly the delivered coins, the preempting
        _finish-style release harvested the row into the prefix cache, so
        re-prefill is mostly a radix hit and generation continues
        byte-identical to the uninterrupted run."""
        free = [s for s in self._slots if s.req is None]
        if not free:
            return None
        # effective admission prompt: original prompt plus any tokens
        # already delivered before a preemption (empty for fresh requests)
        full = req.prompt + req.out if req.out else req.prompt

        def common(s: _Slot) -> int:
            n = 0
            for a, b in zip(s.history, full):
                if a != b:
                    break
                n += 1
            return min(n, len(full) - 1)
        best = max(free, key=common)
        rewind = common(best)
        reuse = rewind
        if self.kv_pool is not None:
            # paged admission (docs/PAGED_KV.md): the radix directory hit
            # is a refcounted block-table remap, not a row copy — bind the
            # request's context so the batch.prefix_seed span attributes
            with reqctx.use(req.ctx):
                reuse = self._paged_adopt(best, req, rewind, full)
        elif self.prefix_cache is not None:
            # [0, reuse) is served by the slot's own resident rows; anything
            # the radix seed adds on top is counted as hit_tokens inside.
            # Cross-thread trace re-entry: the seed runs on the scheduler
            # thread but belongs to THIS request — bind its context so the
            # batch.prefix_seed span carries the request's trace id.
            self.prefix_cache.note_resident(reuse)
            with reqctx.use(req.ctx):
                reuse = self._seed_from_cache(best, req, reuse, full)
        best.admit_t = time.monotonic()  # before .req: the watchdog keys on req
        best.req = req
        best.pos = reuse
        best.history = list(full[:reuse])
        best.pending = full[reuse:]
        best.last_logits = None
        best.next_token = None
        best.clamp_pos = None
        best.armed = False
        # drafting corpus/frontier: the FULL prompt (including any reused
        # prefix and preemption- or resume-delivered tokens) — the proposer
        # (n-gram index and/or model-drafter row state) re-attaches whole,
        # so preemption re-admission and durable resume need nothing special
        if self.spec_k:
            self.proposer.attach(best.index, full)
            if self.adaptive is not None:
                self.adaptive.attach(best.index)
        else:
            self.proposer.detach(best.index)
        self._attach_constraint(best, req)
        # per-tenant delivery counter child, resolved once per admission so
        # the per-token _emit path pays no label lookup
        best.tok_counter = _TENANT_TOKENS.labels(
            tenant=self.tenants.canonical(req.tenant)
            if self.tenants is not None else req.tenant)
        req.stats.prompt_tokens = len(full)
        # queue TTL bounds the wait before FIRST service only: a later
        # preemption must not let the original bound expire a request that
        # already has delivered output
        req.queue_ttl_t = 0.0
        # admission reuse reading (rewind + radix seed): the prefill this
        # request SKIPPED — for a resume admission this is the number the
        # "resume cost ≈ one suffix prefill" claim rests on, surfaced per
        # request so api-level resume counters can report it
        req.stats.reused_tokens = reuse
        qw_ms = ((time.perf_counter() - req.submit_t) * 1e3
                 if req.submit_t else 0.0)
        if req.submit_t:
            _QUEUE_WAIT.observe(qw_ms / 1e3)
        flight.event(req.rid, "admitted", slot=best.index,
                     queue_wait_ms=round(qw_ms, 3), rewind_tokens=rewind,
                     seeded_tokens=reuse - rewind,
                     **({"resume_tokens": req.resume_tokens}
                        if req.resume_tokens else {}))
        return best

    def _attach_constraint(self, slot: _Slot, req: BatchRequest) -> None:
        """Bind the request's grammar automaton to the slot: allocate a
        region in the (lazy) device constraint table, replay any
        already-delivered tokens through the automaton so preemption
        re-admission and durable resume continue from the right grammar
        state, and register the live handle with the GrammarProposer. A
        full table degrades this row to unconstrained (counter + flight
        event, never a client failure)."""
        slot.constraint = None
        self.grammar_proposer.detach(slot.index)
        aut = req.constraint
        if aut is None:
            if self.constrain_table is not None:
                _CONSTRAIN_ROWS.set(self.constrain_table.active_rows)
            return
        if self.constrain_table is None:
            from ..constrain import ConstraintTable

            self.constrain_table = ConstraintTable(
                self.spec.vocab_size, self.constrain_states)
        off = self.constrain_table.alloc(slot.index, aut)
        if off is None:
            self.constrain_degraded += 1
            _CONSTRAIN_DEGRADED.labels(reason="capacity").inc()
            flight.event(req.rid, "constrain_degraded", reason="capacity",
                         grammar=req.constraint_hash)
            _CONSTRAIN_ROWS.set(self.constrain_table.active_rows)
            return
        sc = _SlotConstraint(aut, off, req.constraint_hash)
        # tokens the grammar already consumed: a resume prefix (last
        # resume_tokens of the prompt — generated elsewhere) then any
        # preemption-delivered output. A replay token outside the grammar
        # means the constraint cannot be honored from here — degrade
        # honestly rather than emit a mask for the wrong state.
        replay = (req.prompt[len(req.prompt) - req.resume_tokens:]
                  if req.resume_tokens else [])
        for t in list(replay) + list(req.out):
            nxt = aut.advance(sc.state, t)
            if nxt < 0:
                sc.degraded = True
                self.constrain_degraded += 1
                _CONSTRAIN_DEGRADED.labels(reason="divergence").inc()
                flight.event(req.rid, "constrain_degraded",
                             reason="divergence", grammar=sc.ghash)
                break
            sc.state = nxt
        slot.constraint = sc
        self.grammar_proposer.attach_constraint(slot.index, sc)
        flight.event(req.rid, "constrain_attached", grammar=sc.ghash,
                     states=aut.n_states, offset=off)
        _CONSTRAIN_ROWS.set(self.constrain_table.active_rows)

    def _release_constraint(self, slot: _Slot) -> None:
        """Free the slot's constraint-table region (finish/preempt/wedge).
        The proposer-side registration is cleared by ProposerMux.detach at
        the same call sites."""
        slot.constraint = None
        if self.constrain_table is not None:
            self.constrain_table.free(slot.index)
            _CONSTRAIN_ROWS.set(self.constrain_table.active_rows)

    def _degrade_constraint(self, slot: _Slot, reason: str) -> None:
        """Park the row on the universal (unconstrained) table state after
        a masking fault or grammar divergence — decoding continues, the
        constraint is dropped, and the degradation is visible in
        constrain_degraded_total and the flight timeline (the documented
        fallback: degrade > fail, docs/ROBUSTNESS.md)."""
        sc = slot.constraint
        if sc is None or sc.degraded:
            return
        sc.degraded = True
        self.constrain_degraded += 1
        _CONSTRAIN_DEGRADED.labels(reason=reason).inc()
        if slot.req is not None:
            flight.event(slot.req.rid, "constrain_degraded", reason=reason,
                         grammar=sc.ghash)

    def _seed_from_cache(self, slot: _Slot, req: BatchRequest,
                         reuse: int, full: list[int] | None = None) -> int:
        """Consult the radix index for the admission prompt (`full` =
        prompt ⊕ preemption-delivered tokens; defaults to req.prompt); when
        it beats the same-slot rewind, scatter the pool blocks' rows into
        the slot's cache rows [reuse, n) and return the seeded length n
        (the new prefill start). The acquired lease stays on the slot until
        _finish (eviction must respect in-flight slots); seeding failures
        fall back to plain prefill — the cache is an optimization, never a
        correctness gate."""
        if full is None:
            full = req.prompt
        try:
            faults.fire("batch.cache_seed", slot=slot.index)
            lease = self.prefix_cache.lookup(full,
                                             cap=self.spec.seq_len - 1)
            if lease is None:
                return reuse
            if lease.tokens <= reuse:
                self.prefix_cache.mark_unused(lease)
                return reuse
        except Exception as e:
            # a raising radix lookup (or injected seed fault) must cost only
            # the cache win — NOT escape into the scheduler loop, where it
            # would fail every in-flight request and leave this one queued
            from ..cache import warn_degraded

            warn_degraded("lookup", e)
            return reuse
        eng = self._eng
        n = lease.tokens
        t0 = time.perf_counter()
        try:
            with trace.span("batch.prefix_seed",
                            {"slot": slot.index, "tokens": n,
                             "rewind": reuse}):
                # fetch only the span the rewind doesn't already hold, as ONE
                # contiguous (2, L, hk, n-reuse, hs) buffer: a single
                # host->device transfer and one scatter per cache tensor
                # (previously: contiguize + upload + scatter per K/V half)
                rows = jnp.asarray(
                    self.prefix_cache.fetch_packed(lease, skip=reuse),
                    eng.dtype)
                eng.k_cache = eng.k_cache.at[:, slot.index, :, reuse:n, :].set(
                    rows[0])
                eng.v_cache = eng.v_cache.at[:, slot.index, :, reuse:n, :].set(
                    rows[1])
        except Exception as e:
            self.prefix_cache.mark_unused(lease)
            from ..cache import warn_degraded

            warn_degraded("seed", e)  # fall back to full prefill
            return reuse
        # host→device KV bytes this admission moved (the scatter baseline
        # the paged remap path eliminates — bench.py shared-prefix columns)
        self.seed_bytes += int(rows.nbytes)
        self.seed_ms += (time.perf_counter() - t0) * 1e3
        slot.lease = lease
        self.prefix_cache.mark_seeded(lease, n - reuse)
        _PREFIX_SEEDED.inc(n - reuse)
        return n

    # ------------------------------------------------------------------
    # device-resident paged KV (docs/PAGED_KV.md)
    # ------------------------------------------------------------------

    def _tables(self):
        """Current (B, W) device block table; re-uploaded only after a table
        edit (a few hundred BYTES of metadata — never KV rows)."""
        if self._tables_dev is None:
            self._tables_dev = jnp.asarray(self._tables_np)
        return self._tables_dev

    def _table_row(self, slot: _Slot) -> None:  # hot-path
        """Rewrite one slot's table row from slot.blocks (filler entries
        point at the scratch block, whose contents are never read)."""
        row = self._tables_np[slot.index]
        row[:] = 0
        row[:len(slot.blocks)] = slot.blocks
        self._tables_dev = None

    def _paged_release_slot(self, slot: _Slot) -> None:
        """Drop a slot's whole table (and the rewind stock it backs). The
        committed full blocks live on through any directory references."""
        if slot.blocks:
            self.kv_pool.decref(slot.blocks)
        slot.blocks = []
        slot.history = []
        slot.pos = 0
        self._table_row(slot)

    def _paged_alloc(self, n: int, exclude: _Slot | None = None) -> list[int]:
        """Allocate n pool blocks, reclaiming directory/idle-slot stock
        under pressure; raises KVPoolExhausted (request-scope) when the
        pool genuinely cannot serve. `exclude` shields one slot from the
        idle-slot reclaim tier — the ADOPTING slot looks idle (req is
        bound only after _paged_adopt returns), and releasing it mid-adopt
        would double-free the very blocks being rewired."""
        ids = self.kv_pool.alloc(n)
        if ids is None:
            self._paged_reclaim(n, exclude=exclude)
            ids = self.kv_pool.alloc(n)
        if ids is None:
            from ..cache.device_pool import KVPoolExhausted

            raise KVPoolExhausted(
                f"device KV pool exhausted: {n} block(s) needed, "
                f"{self.kv_pool.free_blocks()} free after reclaim "
                "(raise --kv-pool-blocks or admit fewer long contexts)")
        return ids

    def _paged_reclaim(self, need: int, exclude: _Slot | None = None) -> None:
        """Free device blocks: demote/evict LRU unreferenced directory
        nodes first (cold tier keeps the prefix servable), then drop idle
        slots' retained rewind tables — their committed blocks survive via
        the directory where it references them. `exclude` (see
        _paged_alloc) is never released. Only the DEFICIT is reclaimed:
        demoting `need` blocks when all but one are already free would
        churn the directory (and its D2H copies) for nothing."""
        deficit = need - self.kv_pool.free_blocks()
        if deficit <= 0:
            return
        if self.prefix_cache is not None:
            self.prefix_cache.reclaim(deficit, self._read_block)
        if self.kv_pool.free_blocks() >= need:
            return
        for sl in self._slots:
            if sl.req is None and sl.blocks and sl is not exclude:
                self._paged_release_slot(sl)
                if self.kv_pool.free_blocks() >= need:
                    return

    def _read_block(self, bid: int):
        """Device→host copy of one pool block's rows (L, hk, bt, hs) — the
        directory's demotion payload."""
        eng = self._eng
        return np.asarray(eng.k_cache[:, bid]), np.asarray(eng.v_cache[:, bid])

    def _paged_ensure(self, slot: _Slot, upto: int) -> None:
        """Grow the slot's table so every position < upto has a real block
        (writes beyond coverage would land in the scratch block — fine for
        parked garbage, fatal for committed rows)."""
        need = -(-min(upto, self.spec.seq_len) // self._kv_bt) \
            - len(slot.blocks)
        if need <= 0:
            return
        ids = self._paged_alloc(need, exclude=slot)
        start = len(slot.blocks)
        slot.blocks.extend(ids)
        self._tables_np[slot.index, start:start + need] = ids
        self._tables_dev = None

    def _paged_cow(self, slot: _Slot, lo: int, hi: int) -> None:
        """Copy-on-write: make the blocks backing positions [lo, hi)
        exclusively owned before the slot writes there. A shared block
        (directory reference or a sibling slot's remap) gets a private
        device-side copy — a D2D transfer, zero host bytes — so the shared
        copy's committed rows can never be scribbled on."""
        bt = self._kv_bt
        eng = self._eng
        for idx in range(lo // bt, min(-(-hi // bt), len(slot.blocks))):
            bid = slot.blocks[idx]
            if not self.kv_pool.shared(bid):
                continue
            nb = self._paged_alloc(1, exclude=slot)[0]
            eng.k_cache = _pool_block_copy(eng.k_cache, bid, nb)
            eng.v_cache = _pool_block_copy(eng.v_cache, bid, nb)
            self.kv_pool.decref([bid])
            self.kv_pool.note_cow()
            slot.blocks[idx] = nb
            self._tables_np[slot.index, idx] = nb
            self._tables_dev = None

    def _paged_adopt(self, slot: _Slot, req: BatchRequest, rewind: int,
                     full: list[int]) -> int:
        """Paged admission seeding: extend the same-slot rewind with a
        DIRECTORY REMAP — shared full blocks are increfed into the slot's
        table (zero bytes moved), a partially-used boundary block is CoW'd
        so the slot can append, and cold (demoted) blocks pay exactly one
        host→device promotion upload. Returns the reuse length (the prefill
        start). Mirrors _seed_from_cache's degraded-mode contract: any
        failure falls back to what the rewind already covered."""
        from ..cache.device_pool import _REMAPPED, _SEED_BYTES

        bt = self._kv_bt
        pc = self.prefix_cache
        eng = self._eng
        t0 = time.perf_counter()
        lease = None
        if pc is not None:
            pc.note_resident(rewind)
            try:
                faults.fire("batch.cache_seed", slot=slot.index)
                lease = pc.lookup(full, cap=self.spec.seq_len - 1)
                if lease is not None and lease.tokens <= rewind:
                    pc.mark_unused(lease)
                    lease = None
            except Exception as e:
                from ..cache import warn_degraded

                warn_degraded("lookup", e)
                lease = None
        if lease is None:
            # rewind-only: trim the retained table to the rewound prefix
            # and make its boundary block writable (the first append lands
            # at `rewind`, possibly inside a directory-shared block)
            reuse = self._paged_adopt_rewind_only(slot, rewind)
            self.seed_ms += (time.perf_counter() - t0) * 1e3
            return reuse
        n = lease.tokens
        m, part = n // bt, n % bt
        blocks: list[int] = []
        moved = 0
        try:
            with trace.span("batch.prefix_seed",
                            {"slot": slot.index, "tokens": n,
                             "rewind": rewind, "remap": True}):
                for i, node in enumerate(lease.nodes):
                    tier, h = node.handle
                    if tier == "cold":
                        # promote: one host→device upload, then the
                        # directory itself holds the device copy again.
                        # promote() takes the DIRECTORY's own ref — drop
                        # the allocation ref right after, or every
                        # promotion leaks one never-freeable block
                        k, v = pc.fetch_cold(h)
                        nb = self._paged_alloc(1, exclude=slot)[0]
                        eng.k_cache = _pool_block_set(
                            eng.k_cache, nb, jnp.asarray(k, eng.dtype))
                        eng.v_cache = _pool_block_set(
                            eng.v_cache, nb, jnp.asarray(v, eng.dtype))
                        moved += k.nbytes + v.nbytes
                        pc.promote(node, nb)
                        self.kv_pool.decref([nb])
                        tier, h = node.handle
                    if i < m:
                        self.kv_pool.incref([h])
                        blocks.append(h)
                    else:
                        # partial boundary block: private copy (D2D) the
                        # slot can append into without touching the
                        # directory's committed rows
                        nb = self._paged_alloc(1, exclude=slot)[0]
                        eng.k_cache = _pool_block_copy(eng.k_cache, h, nb)
                        eng.v_cache = _pool_block_copy(eng.v_cache, h, nb)
                        self.kv_pool.note_cow()
                        blocks.append(nb)
        except Exception as e:
            if blocks:
                self.kv_pool.decref(blocks)
            pc.mark_unused(lease)
            from ..cache import warn_degraded

            warn_degraded("seed", e)  # fall back to the rewind stock
            self.seed_ms += (time.perf_counter() - t0) * 1e3
            return self._paged_adopt_rewind_only(slot, rewind)
        old = slot.blocks
        slot.blocks = blocks
        if old:
            self.kv_pool.decref(old)
        self._table_row(slot)
        slot.lease = lease
        pc.mark_seeded(lease, n - rewind)
        _PREFIX_SEEDED.inc(n - rewind)
        _REMAPPED.inc(m)
        if moved:
            _SEED_BYTES.inc(moved)
            self.seed_bytes += moved
        self.seed_ms += (time.perf_counter() - t0) * 1e3
        return n

    def _paged_adopt_rewind_only(self, slot: _Slot, rewind: int) -> int:
        """Degraded-seed fallback: keep only the rewound prefix's blocks."""
        bt = self._kv_bt
        keep = min(-(-rewind // bt), len(slot.blocks))
        if keep < len(slot.blocks):
            self.kv_pool.decref(slot.blocks[keep:])
            del slot.blocks[keep:]
            self._table_row(slot)
        if rewind % bt:
            self._paged_cow(slot, rewind, rewind + 1)
        return rewind

    def _dispatched(self, kind: str, call):
        """Run one device dispatch with transient-fault retry: classify()
        'transient' errors (injected TransientDispatchError, or any exception
        carrying fault_scope='transient') are retried up to max_retries times
        with capped exponential backoff; anything else propagates unchanged.
        Retry is sound here because a transient failure by definition raised
        before the dispatch consumed its inputs (the injection points fire
        before the device call; a real mid-execution failure classifies
        'engine' and is never retried against possibly-donated buffers).

        EPOCH GUARD (recover_wedged): when the supervisor abandoned this
        thread while it was stuck inside `call()` (or the injected-latency
        sleep standing in for a hung device), the bump is observed HERE, on
        the first instruction after the stall — before the caller can rebind
        eng.k_cache/v_cache over the re-initialized backend's fresh arrays
        or deliver tokens into slots that now belong to other requests."""
        delay = self.retry_backoff
        attempt = 0
        # the THREAD's epoch, not a fresh read: a bump landing before this
        # call must still be detected at the post-call check
        epoch = getattr(self._tls, "epoch", self._epoch)
        while True:
            try:
                faults.fire("batch.dispatch", kind=kind, attempt=attempt)
                out = call()
                if self._epoch != epoch:
                    raise _StaleEpoch()
                self._last_dispatch_t = time.monotonic()
                return out
            except Exception as e:
                if self._epoch != epoch:
                    raise _StaleEpoch() from None
                if classify(e) != "transient" or attempt >= self.max_retries:
                    raise
                _ENGINE_ERRORS.labels(kind="transient").inc()
                _RETRIES.inc()
                attempt += 1
                # the retry stalls every in-flight request equally: each
                # timeline records it (the co-batched blast radius of a
                # transient, made visible per request)
                for s in self._slots:
                    if s.req is not None:
                        flight.event(s.req.rid, "dispatch_retry",
                                     kind=kind, attempt=attempt)
                time.sleep(min(delay, 1.0))
                delay *= 2

    def _step(self, tokens_rows: list[list[int]], starts: list[int], t: int,
              kind: str = "step"):
        """Run one batched (B, t) step; returns logits (B, t, vocab) np.ndarray."""
        eng = self._eng
        window = eng._window_for(max(s + t for s in starts))
        step = eng._step_for(window)
        toks = jnp.asarray(np.asarray(tokens_rows, dtype=np.int32))
        start_pos = jnp.asarray(np.asarray(starts, dtype=np.int32))
        # snapshot the cache refs NOW and rebind only after _dispatched's
        # epoch check: a thread abandoned by recover_wedged mid-stall must
        # neither donate the re-initialized backend's fresh cache arrays nor
        # rebind its stale outputs over them
        kc_in, vc_in = eng.k_cache, eng.v_cache
        tables = self._tables() if self.kv_pool is not None else None

        def call():
            if tables is not None:
                logits, kc, vc = step(
                    eng.params, eng.rope, toks, kc_in, vc_in, start_pos,
                    tables)
            else:
                logits, kc, vc = step(
                    eng.params, eng.rope, toks, kc_in, vc_in, start_pos)
            return np.asarray(logits), kc, vc

        out, eng.k_cache, eng.v_cache = self._dispatched(kind, call)
        # sync dispatch: results are host-side now — the reference point the
        # device-idle-gap histogram measures the next decode issue against
        self._gap_t = time.perf_counter()
        return out

    def _finish(self, slot: _Slot, finish: str) -> None:
        req = slot.req
        req.finish = finish
        # engine-side completion: the api layer (when there is one) adds
        # TTFT/E2E to the same record after its own _observe_done; `error`
        # only when real — its presence marks the record slow-log-eligible
        flight.finish(req.rid, finish,
                      generated_tokens=req.stats.generated_tokens,
                      **({"error": repr(req.error)}
                         if req.error is not None else {}))
        slot.req = None
        slot.pending = []
        slot.next_token = None
        self.proposer.detach(slot.index)
        if self.adaptive is not None:
            self.adaptive.detach(slot.index)
        slot.tok_counter = None
        self._release_constraint(slot)
        # service-rate bookkeeping (docs/SERVING.md "Multi-tenant serving"):
        # one completion noted to the drain estimator — the denominator of
        # every Retry-After hint — plus per-tenant completion accounting
        self._drain.note()
        _DRAIN_RATE.set(self._drain.rate())
        _TENANT_REQUESTS.labels(
            tenant=(self.tenants.canonical(req.tenant)
                    if self.tenants is not None else req.tenant),
            **{"class": req.klass}).inc()
        if self.prefix_cache is not None and slot.lease is not None:
            # the lease pins blocks for the IN-FLIGHT period only; release
            # before done.set() so a caller observing completion sees no
            # residual reservation (the harvest below re-walks the tree and
            # needs no pin — insert guards its own chain)
            self.prefix_cache.release(slot.lease)
            slot.lease = None
        if req.export_kv:
            # disaggregation export (docs/DISAGG.md): host-snapshot the
            # committed prompt blocks BEFORE done.set() — the /v1/kv
            # handler wakes on done and must find kv_export populated; and
            # this runs on the scheduler thread, the only place device
            # cache reads cannot race a donating dispatch
            try:
                req.kv_export = self._export_slot_blocks(
                    slot, len(req.prompt))
            except Exception as e:
                from ..cache import warn_degraded

                warn_degraded("export", e)
        _REQUESTS.labels(finish=finish).inc()
        req.done.set()
        # harvest AFTER done.set(): the slot's history/rows stay valid (they
        # also back the same-slot rewind), and the copy-out must not extend
        # the finished client's wait
        if self.prefix_cache is not None:
            self._harvest_into_cache(slot)

    def _harvest_into_cache(self, slot: _Slot) -> None:
        """Copy the finished slot's committed prefix into the block pool (the
        cross-request half of prefix reuse). history's rows [0, len(history))
        are committed by construction — every truncation site shrinks history
        before the rows are overwritten."""
        pc = self.prefix_cache
        if slot.clamp_pos is not None:
            # the in-flight super-step parked this row clamped at clamp_pos,
            # destroying that row — drop it from the harvestable history NOW
            # (the post-loop truncation would run too late for this harvest)
            self._truncate_history(slot, slot.clamp_pos)
            slot.clamp_pos = None
        try:
            if self.kv_pool is not None:
                # zero-copy harvest: the directory takes REFS on the slot's
                # committed full blocks — no device→host transfer at all
                n = len(slot.history) // self._kv_bt
                if n:
                    with trace.span("batch.prefix_insert",
                                    {"slot": slot.index,
                                     "tokens": n * self._kv_bt,
                                     "remap": True}):
                        pc.insert_blocks(slot.history, slot.blocks[:n])
            elif len(slot.history) >= pc.block_tokens:
                eng = self._eng

                def harvest(t0: int, t1: int):
                    return (np.asarray(eng.k_cache[:, slot.index, :, t0:t1]),
                            np.asarray(eng.v_cache[:, slot.index, :, t0:t1]))

                with trace.span("batch.prefix_insert",
                                {"slot": slot.index,
                                 "tokens": len(slot.history)}):
                    pc.insert(slot.history, harvest)
        except Exception as e:  # a failed insert must not kill the scheduler
            from ..cache import warn_degraded

            warn_degraded("insert", e)

    def _truncate_history(self, sl: _Slot, p: int) -> None:
        """Truncate a slot's reusable history to p tokens — its rows >= p are
        (about to be) overwritten by clamped scratch writes — and shrink any
        prefix-cache lease past p. Without the shrink a clamped park would
        leave the radix reservation pinning blocks for a prefix the slot no
        longer holds, blocking their eviction until _finish (and lying about
        what the slot can re-insert)."""
        if p < len(sl.history):
            sl.history = sl.history[:p]
        if sl.lease is not None and p < sl.lease.tokens:
            self.prefix_cache.shrink(sl.lease, p)

    def _park_positions(self, t: int) -> list[int]:
        """Per-row start positions for rows not participating in this step: park at the
        row's current pos so garbage lands on masked future positions, clamped so the
        write stays inside the cache. A clamped park (row sitting within t of the end)
        overwrites that row's tail history, so the reusable prefix is truncated to the
        write start."""
        s = self.spec.seq_len
        starts = []
        for sl in self._slots:
            p = min(sl.pos, max(s - t, 0))
            if p < sl.pos:
                if self.kv_pool is not None and sl.req is None:
                    # paged idle slot: a clamped park would scribble into
                    # possibly directory-shared tail blocks — drop the
                    # rewind stock instead of CoW-ing for garbage (the
                    # committed full blocks live on in the directory)
                    self._paged_release_slot(sl)
                    p = 0
                    starts.append(p)
                    continue
                self._truncate_history(sl, p)
                if self.kv_pool is not None:
                    # the clamped scratch writes [p, p+t) must not land in
                    # shared blocks (the directory's committed rows). A
                    # pool that cannot even serve the CoW fails ONLY this
                    # request — the slot then parks empty on the scratch
                    # block like any idle row (callers re-filter for
                    # reaped rows after _park_positions)
                    try:
                        self._paged_cow(sl, p, min(p + t, s))
                    except Exception as e:
                        if classify(e) != "request":
                            raise
                        self._fail_request(sl, e)
                        self._paged_release_slot(sl)
                        p = 0
            starts.append(p)
        return starts

    def _admit(self) -> None:
        """Drain the cross-thread queue into the scheduler-local
        weighted-fair wait queue, reap cancelled/expired queued requests,
        and assign in WFQ order onto free slots — interactive class first,
        tenants by weight (docs/SERVING.md "Multi-tenant serving"). When no
        slot is free and the fair queue's head is INTERACTIVE, a batch-class
        row is preempted at this super-step boundary (its request re-queued,
        to resume byte-identical later) so interactive TTFT is bounded by
        one dispatch, not a batch request's whole generation."""
        now = time.perf_counter()
        # preempted rows' prefix harvests are SNAPSHOTTED under the lock
        # but copied device→host after it: jax arrays are immutable, so
        # the captured cache refs survive the slot's reassignment, and the
        # transfer must not stall submit()/admission callers on _plock
        harvests: list[tuple] = []
        with self._plock:
            self._drain_submit_queue()
            # queue-TTL / deadline expiry applies to EVERY queued request,
            # not just the head — under sustained occupancy the head may
            # never admit, and requests behind it must still time out
            expired = []
            for req in self._pending:
                expired_by = ("queue_ttl" if req.queue_ttl_t
                              and now >= req.queue_ttl_t
                              else "deadline" if req.deadline_t
                              and now >= req.deadline_t else None)
                if expired_by is not None:
                    expired.append((req, expired_by))
            for req, expired_by in expired:
                self._pending.remove(req)
                req.finish = "deadline"
                # a preempted request with delivered output keeps it (the
                # decode-deadline contract); only a never-served request
                # surfaces the typed error
                if not req.out:
                    req.error = DeadlineExceeded(
                        f"request expired in queue ({expired_by})")
                _DEADLINE_EXPIRED.labels(where="queue").inc()
                _REQUESTS.labels(finish="deadline").inc()
                flight.finish(req.rid, "deadline", expired_by=expired_by)
                req.done.set()
            while True:
                req = self._pending.peek_next()
                if req is None:
                    break
                # heads leave via pop_next(), NOT remove(): pop advances
                # the class's virtual time to the served tag, which is
                # what anchors a later-arriving tenant's first tag at
                # "now" instead of zero — without it a tenant returning
                # from idle would be charged its entire lifetime service
                # against newcomers and starve (the SFQ V(t) invariant)
                if req.cancelled:
                    self._pending.pop_next()
                    req.finish = "cancelled"
                    _REQUESTS.labels(finish="cancelled").inc()
                    flight.finish(req.rid, "cancelled")
                    req.done.set()
                    continue
                try:
                    assigned = self._assign(req)
                except Exception as e:
                    # an admission failure is attributable to the request
                    # being admitted: fail IT and dequeue — leaving it at
                    # the head would re-raise every pass (hanging its waiter
                    # forever) while _fail_all killed innocent neighbors
                    self._pending.pop_next()
                    _ENGINE_ERRORS.labels(kind="request").inc()
                    req.error = e
                    req.finish = "error"
                    _REQUESTS.labels(finish="error").inc()
                    req.done.set()
                    continue
                if assigned is None:
                    # no free slot: an interactive head may preempt a
                    # batch-class row (super-step boundary — the scheduler
                    # is between dispatches right here); a batch head waits
                    if req.klass == "interactive" and self._try_preempt(
                            harvests):
                        continue  # a slot is free now; re-try this head
                    break
                self._pending.pop_next()
            _QUEUE_DEPTH.set(len(self._pending) + self._queue.qsize())
        for history, kc, vc, index in harvests:
            self._harvest_rows(history, kc, vc, index)

    def _drain_submit_queue(self) -> None:  # holds: self._plock
        """Move cross-thread submissions into the weighted-fair queue.
        Shared by the scheduler's _admit and the submit-side shed paths —
        eviction must see EVERY queued batch request, including ones still
        in the cross-thread queue because the scheduler is mid-dispatch."""
        while True:
            try:
                self._pending.append(self._queue.get_nowait())
            except queue.Empty:
                break

    def _try_preempt(self, harvests: list) -> bool:  # holds: self._plock
        """Free one slot for a waiting interactive request by preempting the
        batch-class row with the least delivered output (the cheapest
        resume). Interactive rows are never preempted. Returns True when a
        slot was freed; the victim's deferred prefix-harvest payload (if
        any) is appended to `harvests` for the caller to run OUTSIDE the
        lock."""
        victims = [s for s in self._slots
                   if s.req is not None and s.req.klass == "batch"
                   and not s.req.done.is_set() and not s.req.cancelled]
        if not victims:
            return False
        h = self._preempt_slot(min(victims, key=lambda s: len(s.req.out)))
        if h is not None:
            harvests.append(h)
        return True

    def _preempt_slot(self, slot: _Slot):  # holds: self._plock
        """Release a batch row at a super-step boundary and re-queue its
        request (docs/SERVING.md "Multi-tenant serving"). The release
        mirrors _finish WITHOUT completing the request: the prefix-cache
        lease is released and a SNAPSHOT of the committed history + cache
        arrays is returned for a deferred harvest into the radix pool
        (device→host copies must not run under _plock; jax arrays are
        immutable so the snapshot survives the slot's reassignment) — the
        later re-admission (prompt ⊕ delivered, _assign) is then mostly a
        cache hit, the same "resume cost ≈ one suffix prefill" economics
        as a durable failover. An in-flight chained dispatch covering this
        row is discarded at delivery by the existing reaped-row rollback
        (slot.req changed), exactly like a cancel, and the sampler was
        already resynced to the delivered coins — so the resumed
        generation is byte-identical to an uninterrupted run
        (tests/test_tenancy.py pins greedy AND seeded-stochastic)."""
        req = slot.req
        req.preemptions += 1
        _PREEMPTED.inc()
        flight.event(req.rid, "preempted", slot=slot.index,
                     delivered=len(req.out))
        slot.req = None
        slot.pending = []
        slot.next_token = None
        self.proposer.detach(slot.index)
        if self.adaptive is not None:
            self.adaptive.detach(slot.index)
        slot.tok_counter = None
        # the grammar state is NOT kept across preemption: re-admission
        # replays prompt ⊕ delivered through the automaton in
        # _attach_constraint, the same rebuild-from-truth the proposer does
        self._release_constraint(slot)
        harvest = None
        if self.prefix_cache is not None:
            if slot.lease is not None:
                self.prefix_cache.release(slot.lease)
                slot.lease = None
            if slot.clamp_pos is not None:
                # an in-flight scan flagged a clamped park: the poisoned
                # tail must not be harvested (mirrors _harvest_into_cache)
                self._truncate_history(slot, slot.clamp_pos)
                slot.clamp_pos = None
            if self.kv_pool is not None:
                # paged: the harvest is a refcount, not a copy — run it
                # inline (deferring would race the slot's reassignment
                # CoW-ing or freeing the very blocks being inserted)
                try:
                    n = len(slot.history) // self._kv_bt
                    if n:
                        self.prefix_cache.insert_blocks(slot.history,
                                                        slot.blocks[:n])
                except Exception as e:
                    from ..cache import warn_degraded

                    warn_degraded("insert", e)
            else:
                eng = self._eng
                harvest = (list(slot.history), eng.k_cache, eng.v_cache,
                           slot.index)
        # nominal re-queue cost: the original admission already charged the
        # FULL request cost into the tenant's virtual time — charging the
        # remainder again would double-bill every preemption and erode the
        # tenant's configured share
        self._pending.push(req, req.tenant, req.klass, 1.0)
        return harvest

    def _harvest_rows(self, history: list[int], kc, vc, index: int) -> None:
        """Deferred preemption harvest: copy the snapshotted committed rows
        into the prefix-cache pool. Runs OUTSIDE _plock — the snapshot
        arrays are immutable, so the slot may already be serving its next
        request."""
        pc = self.prefix_cache
        if pc is None:
            return
        try:
            if len(history) >= pc.block_tokens:
                def harvest(t0: int, t1: int):
                    return (np.asarray(kc[:, index, :, t0:t1]),
                            np.asarray(vc[:, index, :, t0:t1]))

                with trace.span("batch.prefix_insert",
                                {"slot": index, "tokens": len(history)}):
                    pc.insert(history, harvest)
        except Exception as e:  # degraded cache, never a scheduler error
            from ..cache import warn_degraded

            warn_degraded("insert", e)

    def _export_slot_blocks(self, slot: _Slot, prompt_len: int):
        """Host snapshot of the slot's committed prompt-prefix KV as
        fixed-size blocks — the disaggregation export payload (docs/
        DISAGG.md): (tokens, [(k, v) per block], block_tokens), each side
        an (L, hk, bt, hs) host array. Scheduler thread ONLY: device cache
        reads must not race a donating dispatch. Only FULL blocks of the
        prompt export (a partial tail block has no directory home on the
        importing side); a clamped park truncates the exportable span the
        same way it truncates the harvest."""
        bt = self._kv_bt or (self.prefix_cache.block_tokens
                             if self.prefix_cache is not None else 0)
        if bt <= 0:
            return None
        p = min(prompt_len, len(slot.history))
        if slot.clamp_pos is not None:
            p = min(p, slot.clamp_pos)
        n = p // bt
        if n == 0:
            return None
        tokens = list(slot.history[:n * bt])
        eng = self._eng
        if self.kv_pool is not None:
            blocks = [self._read_block(bid) for bid in slot.blocks[:n]]
        else:
            k = np.asarray(eng.k_cache[:, slot.index, :, :n * bt])
            v = np.asarray(eng.v_cache[:, slot.index, :, :n * bt])
            blocks = [(k[:, :, i * bt:(i + 1) * bt],
                       v[:, :, i * bt:(i + 1) * bt]) for i in range(n)]
        return tokens, blocks, bt

    def import_kv_blocks(self, tokens: list[int], blocks: list) -> int:
        """Adopt externally-shipped HOST KV blocks (the decode half of a
        disaggregated admission, docs/DISAGG.md) into the prefix cache:
        `blocks[i]` is the (k, v) pair covering token block i of `tokens`.
        Pure host bookkeeping — a paged directory stores them as COLD
        nodes (the existing admission path pays the one host→device
        promotion upload, on the scheduler thread), a dense cache inserts
        them into its host pool (the existing seed scatter applies them) —
        so this is safe to call from any HTTP handler thread. Returns the
        token span the cache now covers (0 = nothing imported; the caller
        admits with a plain local prefill)."""
        pc = self.prefix_cache
        if pc is None:
            return 0
        bt = pc.block_tokens
        n = min(len(tokens) // bt, len(blocks))
        if n <= 0:
            return 0
        span = list(tokens[:n * bt])
        if self.kv_pool is not None:
            return pc.insert_cold(span, blocks[:n]) * bt
        k = np.concatenate([np.asarray(b[0]) for b in blocks[:n]], axis=2)
        v = np.concatenate([np.asarray(b[1]) for b in blocks[:n]], axis=2)
        pc.insert(span, lambda t0, t1: (k[:, :, t0:t1], v[:, :, t0:t1]))
        # report what the cache actually HOLDS, not what it was handed: a
        # lease-pinned-full pool can refuse every block, and claiming the
        # span anyway would count an "imported" success for KV the
        # admission must then re-prefill
        return pc.covered_blocks(span) * bt

    def _reap_slots(self) -> None:
        """Free slots whose request was cancelled or whose wall-clock
        deadline expired (finish "deadline": partial output is kept; the
        waiter gets DeadlineExceeded only when nothing was generated)."""
        now = time.perf_counter()
        for sl in self._slots:
            req = sl.req
            if req is None:
                continue
            if req.cancelled:  # frees the slot immediately, even mid-prefill
                self._finish(sl, "cancelled")
            elif req.deadline_t and now >= req.deadline_t:
                if not req.out:
                    req.error = DeadlineExceeded(
                        "generation deadline expired before the first token")
                _DEADLINE_EXPIRED.labels(where="decode").inc()
                self._finish(sl, "deadline")

    def _fail_request(self, slot: _Slot, e: Exception) -> None:
        """Blast-radius 'request': fail ONLY this slot's request; the other
        co-batched slots keep decoding."""
        _ENGINE_ERRORS.labels(kind="request").inc()
        slot.req.error = e
        self._finish(slot, "error")

    def _fail_all(self, e: Exception) -> None:
        """Blast-radius 'engine': the shared dispatch failed unattributably
        (caches possibly indeterminate) — fail every in-flight request. The
        scheduler thread itself SURVIVES and keeps serving new admissions."""
        _ENGINE_ERRORS.labels(kind="engine").inc()
        if self._inflight is not None:
            # a chained dispatch issued against the now-failed schedule is
            # garbage: drop its device refs; the next dispatch re-uploads
            # host state (which _finish below makes authoritative)
            _PIPELINE_FLUSHES.labels(reason="error").inc()
            self._inflight = None
            _PIPELINE_DEPTH.set(0)
        for s in self._slots:
            if s.req is not None:
                s.req.error = e
                self._finish(s, "error")

    def _loop(self) -> None:
        epoch = self._epoch
        self._tls.epoch = epoch  # the epoch this thread was born into
        _SCHED_ALIVE.set(1)
        try:
            while not self._shutdown and self._epoch == epoch:
                try:
                    self._loop_once()
                except _StaleEpoch:
                    return  # abandoned by recover_wedged: unwind silently
                except Exception as e:
                    # _loop_once guards the dispatch phase itself; this outer
                    # net covers the admission/reap phase too (prefix-cache
                    # lookup at _assign, lease release at a deadline _finish)
                    # so NO exception can kill the scheduler thread — the
                    # invariant perf/fault_matrix.py asserts
                    if self._epoch != epoch:
                        return  # stale thread: the state is not ours to fail
                    try:
                        self._fail_all(e)
                    except Exception:
                        pass  # even a failing abort must not stop the loop
                    with self._cond:
                        if not self._shutdown:
                            self._cond.wait(timeout=0.05)
        finally:
            # a stale thread's exit must not clobber the replacement epoch's
            # liveness gauge or pipeline state
            if self._epoch == epoch:
                if self._inflight is not None:  # close() mid-pipeline
                    _PIPELINE_FLUSHES.labels(reason="close").inc()
                    self._inflight = None
                _PIPELINE_DEPTH.set(0)
                _SCHED_ALIVE.set(0)

    def _loop_once(self) -> None:
        self._admit()
        self._reap_slots()
        prefill = [s for s in self._slots if s.req and s.pending]
        active = [s for s in self._slots if s.req and not s.pending]
        _SLOTS_OCCUPIED.set(sum(1 for s in self._slots if s.req is not None))
        try:
            if self._inflight is not None:
                # a chained super-step is running on device: deliver it (and
                # maybe chain its successor) before any new dispatch shape —
                # every later device op already depends on its cache writes
                fl = self._inflight
                self._inflight = None
                self._pipeline_advance(fl)
            elif prefill:
                # class-aware prefill order (docs/SERVING.md "Multi-tenant
                # serving"): an interactive row's prefill goes first — with
                # slot-order FIFO an interactive admission could wait
                # behind several batch rows' long prompts, unbounding the
                # TTFT the preemption path just bounded
                victim = min(prefill,
                             key=lambda s: (s.req.klass != "interactive",
                                            s.index))
                try:
                    # mixed step: active decode rows ride the prefill dispatch
                    # at T=1 instead of stalling behind it
                    self._prefill_step(victim, riders=active)
                except Exception as e:
                    # a request-scope fault during a prefill dispatch is
                    # attributable to the prefilling request (it fired before
                    # shared state changed): kill ONLY it. The riders remain
                    # consistent — their armed token re-dispatches next pass.
                    if classify(e) == "request" and victim.req is not None:
                        self._fail_request(victim, e)
                    else:
                        raise
            elif active:
                self._decode_step(active)
            else:
                # idle: sleep on the condition until submit()/close()
                # notifies. The timeout is only a safety net (e.g. a
                # queued request cancelled while idle has no notifier);
                # enqueue latency is set by the notify, not this number.
                # 0.1 s also bounds queue-TTL/deadline detection while idle.
                self._gap_t = None  # an idle device is not a starved one
                with self._cond:
                    if self._queue.empty() and not self._shutdown:
                        self._cond.wait(timeout=0.1)
        except Exception as e:  # unattributable: fail all, survive, back off
            self._fail_all(e)
            # brief condition-based backoff so a persistently failing step
            # cannot spin the scheduler hot (a notify still wakes it early)
            with self._cond:
                if not self._shutdown:
                    self._cond.wait(timeout=0.05)

    def _emit(self, slot: _Slot, token: int) -> bool:  # hot-path
        """Deliver one sampled token to the request (output list, stats,
        on_token stream) and run the host-side finish checks. Returns False
        when the request finished (slot released). slot.pos must already count
        the ingestion of this token's input. Runs under the request's trace
        context: a fault injected at batch.emit (or a broken callback) lands
        on the right flight-recorder timeline."""
        req = slot.req
        with reqctx.use(req.ctx):
            # per-request delivery fault point: fires inside the same try
            # blocks that guard a broken sampler/on_token callback, so an
            # injected error here kills exactly one co-batched request
            # (tests/test_resilience.py)
            faults.fire("batch.emit", slot=slot.index, n_out=len(req.out))
            req.out.append(token)
            # proposer corpus/frontier sync: every DELIVERED token, in
            # order (no-op for rows with no drafting state attached)
            self.proposer.push(slot.index, token)
            sc = slot.constraint
            if sc is not None and not sc.degraded:
                # host mirror of the device constraint carry: exact integer
                # bookkeeping per delivered token, so after a full delivery
                # no device readback or resync is ever needed. A token the
                # grammar disallows can only arrive off a degraded/unmasked
                # path — park the row unconstrained rather than mask from a
                # wrong state.
                nxt = sc.automaton.advance(sc.state, token)
                if nxt < 0:
                    self._degrade_constraint(slot, "divergence")
                else:
                    sc.state = nxt
            req.stats.generated_tokens += 1
            _DECODE_TOKENS.inc()
            if slot.tok_counter is not None:  # per-tenant delivery share
                slot.tok_counter.inc()
            if req.on_token is not None:
                req.on_token(token)
            if req.stop_check is not None and req.stop_check(token):
                self._finish(slot, "stop")
                return False
            if len(req.out) >= req.max_tokens or slot.pos >= self.spec.seq_len:
                self._finish(slot, "length")
                return False
            return True

    def _advance_row(self, slot: _Slot) -> bool:  # hot-path
        """Ensure slot.last_token holds the row's next un-ingested token —
        either the device-sampled tail of the previous super-step block, or a
        fresh host-side sample from last_logits (with delivery + finish
        checks). Returns False when the request finished instead."""
        req = slot.req
        if req.cancelled:
            self._finish(slot, "cancelled")
            return False
        if slot.armed:  # last_token already holds the next un-ingested token
            return True  # (the previous dispatch failed before writing it)
        if slot.next_token is not None:  # sampled on device, already delivered
            slot.last_token = slot.next_token
            slot.next_token = None
            slot.armed = True
            return True
        if slot.last_logits is None:  # context end hit during prefill
            self._finish(slot, "length")
            return False
        if req.max_tokens <= 0:  # parity with Engine.generate: zero-token request
            self._finish(slot, "length")
            return False
        logits = slot.last_logits
        sc = slot.constraint
        if sc is not None and not sc.degraded:
            # host-side grammar enforcement (the T=1 / post-prefill sampling
            # site): the SAME finite mask value the masked device programs
            # use, so host- and device-sampled tokens agree bit-for-bit
            # under an identical rng stream. A masking fault degrades this
            # row to unconstrained — never fails the request.
            try:
                faults.fire("constrain.mask", slot=slot.index)
                from .device_loop import MASK_NEG

                allowed = sc.automaton.mask_bool(sc.state)
                arr = np.array(logits, dtype=np.float32).reshape(-1)  # dlint: ignore[hot-sync] -- logits arrive host-side for the sampler anyway; masking rides the same transfer
                n = min(arr.shape[0], allowed.shape[0])
                arr[:n][~allowed[:n]] = np.float32(MASK_NEG)
                arr[n:] = np.float32(MASK_NEG)  # vocab padding: never legal
                logits = arr
            except Exception:
                self._degrade_constraint(slot, "mask")
                logits = slot.last_logits
        try:
            token = req.sampler.sample(logits)
            alive = self._emit(slot, token)
        except Exception as e:
            # a broken callback (e.g. client disconnect mid-stream) fails ONLY
            # this request; the other slots keep decoding
            _ENGINE_ERRORS.labels(kind="request").inc()
            req.error = e
            self._finish(slot, "error")
            return False
        if not alive:
            return False
        slot.last_token = token
        slot.last_logits = None
        slot.armed = True
        return True

    def _prefill_step(self, slot: _Slot, riders: list[_Slot] = ()) -> None:
        # request-scope injection point: fires BEFORE the rider advance and
        # the device dispatch, so an injected error is attributable to the
        # prefilling request alone (_loop_once fails only it); bound to the
        # request's trace context for timeline attribution
        with reqctx.use(slot.req.ctx):
            faults.fire("batch.prefill", slot=slot.index,
                        pending=len(slot.pending))
        t0 = time.perf_counter()
        s = self.spec.seq_len
        room = s - slot.pos
        if room <= 0:
            slot.last_logits = None
            slot.pending = []
            return
        # mixed prefill+decode: each active decode row rides this dispatch with
        # its next token at index 0 (rows advance one token per prefill chunk
        # instead of stalling behind it)
        riders = [r for r in riders if self._advance_row(r)]
        chunk = next((c for c in PREFILL_CHUNKS if len(slot.pending) >= c), 1)
        chunk = min(chunk, room)
        # keep parked rows' scratch writes inside the cache without touching history:
        # a parked row writes [pos, pos+chunk) which must fit under seq_len; shrink the
        # chunk when any OTHER row sits too close to the end (its history would be
        # corrupted by a clamped write below its pos)
        for other in self._slots:
            if other is not slot and other.req is not None:
                chunk = min(chunk, max(s - other.pos, 1))
        piece = slot.pending[:chunk]
        t = len(piece)
        starts = self._park_positions(t)
        if slot.req is None:  # reaped by a clamp-park CoW exhaustion
            return
        riders = [r for r in riders if r.req is not None]
        starts[slot.index] = slot.pos
        rows = [[0] * t for _ in self._slots]
        rows[slot.index] = piece
        for r in riders:
            # real token at index 0, scratch beyond: the rider's positions
            # pos+1..pos+t-1 are masked future slots its own later decodes
            # overwrite (in-bounds by the chunk shrink above)
            starts[r.index] = r.pos
            rows[r.index] = [r.last_token] + [0] * (t - 1)
        if self.kv_pool is not None:
            # block coverage for every committed write this dispatch makes
            # (the prefill chunk, each rider's one real token); scratch
            # beyond coverage lands in the scratch block by design. A
            # RIDER's exhaustion fails the rider, not the innocent prefill
            # (the victim's own failure propagates and is attributed to it
            # by _loop_once's request-scope handler)
            self._paged_ensure(slot, slot.pos + t)
            for r in riders[:]:
                try:
                    self._paged_ensure(r, r.pos + 1)
                except Exception as e:
                    if classify(e) != "request":
                        raise
                    self._fail_request(r, e)
                    riders.remove(r)
        # the dispatch belongs to the prefilling request: bind its context
        # so the span (and any dispatch fault) carries its trace id
        with reqctx.use(slot.req.ctx), \
                trace.span("batch.mixed_step" if riders else "batch.prefill",
                           {"chunk": t, "riders": len(riders)}):
            logits = self._step(rows, starts, t,
                                kind="mixed" if riders else "prefill")
        if riders:
            self.mixed_steps += 1
        dt_ms = (time.perf_counter() - t0) * 1000.0
        flight.event(slot.req.rid, "prefill_chunk", chunk=t,
                     riders=len(riders), ms=round(dt_ms, 3))
        (_DISP_MIXED if riders else _DISP_PREFILL).observe(dt_ms / 1000.0)
        _PREFILL_TOKENS.inc(t)
        # rows neither prefilling nor riding spent this dispatch parked
        _PARKED_ROW_STEPS.inc(self.slots_n - 1 - len(riders))
        self.prefilled_tokens += t
        slot.pos += t
        slot.history.extend(piece)
        slot.pending = slot.pending[t:]
        if not slot.pending:
            slot.last_logits = logits[slot.index, -1]
            slot.last_token = slot.history[-1]
        slot.req.stats.prefill_ms += dt_ms
        slot.req.stats.dispatch_ms.append(dt_ms)
        for r in riders:  # each rider decoded one token in this dispatch
            r.last_logits = logits[r.index, 0]
            r.history.append(r.last_token)
            r.pos += 1
            r.armed = False  # the dispatch ingested last_token's KV
            r.req.stats.token_ms.append(dt_ms)
            r.req.stats.infer_ms.append(dt_ms)
            r.req.stats.dispatch_ms.append(dt_ms)

    def _decode_step(self, active: list[_Slot]) -> None:
        # bring every row to its next un-ingested token (host-samples rows at a
        # prefill/single-step boundary; consumes the device-sampled tail after
        # a super-step)
        for slot in active[:]:
            if not self._advance_row(slot):
                active.remove(slot)
        if self.kv_pool is not None:
            # every row's next write needs a real block behind it; a pool
            # that cannot serve even after reclaim fails ONLY that request
            for slot in active[:]:
                try:
                    self._paged_ensure(slot, slot.pos + 1)
                except Exception as e:
                    if classify(e) != "request":
                        raise
                    self._fail_request(slot, e)
                    active.remove(slot)
        if not active:
            return
        if self.spec_k:
            # speculative path: draft per-row n-gram proposals; when any row
            # has a draft worth verifying, spend this dispatch on a (B, T)
            # verify block instead of the scan — one weight stream for up to
            # T tokens per row. Empty drafts fall through to the scan.
            plan = self._plan_verify(active)
            if plan is not None:
                self._verify_step(*plan)
                return
        k = self.superstep
        if k > 1:
            with self._plock:
                waiting = bool(self._pending) or not self._queue.empty()
            if not waiting:
                # per-row step budget: stop advancing at max_tokens / context
                # end (the row parks for the rest of the scan)
                budgets = {
                    slot.index: min(k, slot.req.max_tokens - len(slot.req.out),
                                    self.spec.seq_len - slot.pos)
                    for slot in active}
                if max(budgets.values()) >= 2:
                    self._super_step(active, k, budgets)
                    return
        # single batched T=1 step: the admission-latency (and tail) path
        t0 = time.perf_counter()
        starts = self._park_positions(1)
        # a clamp-park CoW under pool exhaustion may have reaped a row
        active = [s for s in active if s.req is not None]
        if not active:
            return
        rows = [[0]] * self.slots_n
        for slot in active:
            starts[slot.index] = slot.pos
            rows[slot.index] = [slot.last_token]
        with trace.span("batch.single_step", {"rows": len(active)}):
            logits = self._step(rows, starts, 1, kind="single_step")
        self.decode_steps += 1
        dt_ms = (time.perf_counter() - t0) * 1000.0
        _DISP_SINGLE.observe(dt_ms / 1000.0)
        _PARKED_ROW_STEPS.inc(self.slots_n - len(active))
        for slot in active:
            slot.last_logits = logits[slot.index, -1]
            slot.history.append(slot.last_token)
            slot.pos += 1
            slot.armed = False  # the dispatch ingested last_token's KV
            slot.req.stats.token_ms.append(dt_ms)
            slot.req.stats.infer_ms.append(dt_ms)
            slot.req.stats.dispatch_ms.append(dt_ms)

    def _batched_loop(self, k: int, mode: str, window: int | None,
                      masked: bool = False):
        """Compiled K-step batched device loop for this engine's config
        (one program per (k, mode, window-bucket), memoized). `masked`
        selects the grammar-constrained variant (constraint-table mask
        applied before sampling, automaton state in the carry) — a
        SEPARATE program keyed with a masked flag, so unconstrained
        service keeps today's exact pinned programs (perf/dlint.py
        compile manifest)."""
        key = (k, mode, window) if not masked else (k, mode, window, "mask")
        if key not in self._loops:
            from .device_loop import make_batched_decode_loop

            eng = self._eng
            self._loops[key] = make_batched_decode_loop(
                self.spec, eng.mesh, eng.params, k, mode=mode, dtype=eng.dtype,
                use_pallas=eng.use_pallas,
                compress_collectives=eng.compress, donate_cache=True,
                attn_window=window, cache_write=eng.cache_write,
                moe_sharding=eng.moe_sharding,
                fused_prologue=eng.fused_prologue,
                kv_block_tokens=self._kv_bt,
                paged_kernel=eng.paged_kernel,
                masked=masked)
        return self._loops[key]

    def _verify_loop(self, t: int, mode: str, window: int | None,
                     masked: bool = False):
        """Compiled (B, T=t) draft-verify program for this engine's config
        (one per (t, mode, window-bucket), memoized alongside the scans).
        `masked` selects the grammar-constrained variant — target rows are
        masked position-by-position along the proposal's state chain, so a
        draft token the grammar forbids can never be accepted."""
        key = (("verify", t, mode, window) if not masked
               else ("verify", t, mode, window, "mask"))
        if key not in self._loops:
            from .device_loop import make_batched_verify_loop

            eng = self._eng
            self._loops[key] = make_batched_verify_loop(
                self.spec, eng.mesh, eng.params, t, mode=mode, dtype=eng.dtype,
                use_pallas=eng.use_pallas,
                compress_collectives=eng.compress, donate_cache=True,
                attn_window=window, cache_write=eng.cache_write,
                moe_sharding=eng.moe_sharding,
                fused_prologue=eng.fused_prologue,
                kv_block_tokens=self._kv_bt,
                paged_kernel=eng.paged_kernel,
                masked=masked)
        return self._loops[key]

    def _constrained(self, rows) -> bool:
        """True when any live row in this dispatch decodes under a
        non-degraded grammar — the masked program variants engage only
        then, so purely-unconstrained batches never pay the mask gather."""
        return any(s.constraint is not None and not s.constraint.degraded
                   for s, _req in rows)

    def _cstate_vec(self) -> np.ndarray:
        """(B,) GLOBAL constraint-table states from the host mirrors —
        uploaded when a masked dispatch is NOT chained (the chained case
        consumes the predecessor's device carry). Rows without a grammar
        ride the universal state 0. The constrain.mask fault point fires
        here per constrained row: an injected error degrades that row
        (documented fallback), latency models a slow mask fetch."""
        cs = np.zeros(self.slots_n, np.int32)
        for s in self._slots:
            sc = s.constraint
            if sc is None:
                continue
            if not sc.degraded:
                try:
                    faults.fire("constrain.mask", slot=s.index)
                except Exception:
                    self._degrade_constraint(s, "mask")
            cs[s.index] = sc.gstate
        return cs

    def _verify_block_for(self, t: int) -> int:
        """Block-length bucket (2, 3, 5, 9, 17, ... capped at 1+spec_k):
        verify programs compile per length, so raw per-dispatch lengths
        would compile O(spec_k) programs; buckets bound it to O(log k).
        Padding positions are scratch writes beyond the frontier — the same
        masked-slot discipline every over-decode already relies on."""
        return verify_block_bucket(t, 1 + self.spec_k)

    def _plan_verify(self, active: list[_Slot]):
        """Draft per-row proposals for one verify dispatch. Returns
        (active, T, drafts) or None when no row drafted spec_min_draft
        tokens (a draftless verify emits 1 token per row for a full-width
        dispatch — the K-step scan serves that regime better). Caps mirror
        the sequential loop (runtime/speculative.py): a row drafts at most
        min(k, max_tokens-room, context-room) so emitting the full accepted
        block never overruns max_tokens or the cache, and T shrinks so
        every live row's T block writes stay inside seq_len.

        Per-row draft lengths additionally follow the ADAPTIVE controller
        (docs/SERVING.md "Model-based drafting"): each row's cap is its own
        accept-EMA bucket — a chat row that accepts 2-long drafts stops
        paying for 8-wide ones, a row whose EMA collapses disengages
        entirely (k=0, re-probing on the slow-reprobe horizon) — while
        proposals come from the engine's Proposer (model drafter when
        configured and able, n-gram lookup otherwise), all rows served in
        one propose_batch call so a model drafter drafts every row in ONE
        scan dispatch."""
        s = self.spec.seq_len
        want: dict[int, int] = {}
        for slot in active:
            req = slot.req
            cap = min(self.spec_k, req.max_tokens - len(req.out) - 1,
                      s - slot.pos - 2)
            if self.adaptive is not None:
                cap = min(cap, self.adaptive.k_for(slot.index))
            want[slot.index] = cap
        drafts = self.proposer.propose_batch(
            {i: c for i, c in want.items() if c > 0})
        total = 0
        max_pos = 0
        for slot in active:
            d = drafts.setdefault(slot.index, [])
            del d[max(want[slot.index], 0):]  # never outdraft the caps
            total += len(d)
            max_pos = max(max_pos, slot.pos)
        if total < self.spec_min_draft:
            return None
        t = self._verify_block_for(1 + max(len(d) for d in drafts.values()))
        room = s - max_pos
        if t > room:
            # context-end shrink rounds DOWN to a bucket: per-length tail
            # programs (t = room, room-1, ...) would mint O(k) fresh
            # compiles exactly at the latency-critical end of long requests
            b = 2
            while b < t and 2 * (b - 1) + 1 <= room:
                b = 2 * (b - 1) + 1
            t = b if b <= room else 0
        if t < 2:
            return None
        for d in drafts.values():
            del d[t - 1:]  # context-end shrink may cut long drafts
        return active, t, drafts

    def _verify_step(self, active: list[_Slot], t: int,
                     drafts: dict[int, list[int]]) -> None:
        """One draft-verify super-step (docs/SERVING.md "Speculative
        decoding"): every active row rides a (B, T) block — its pending
        token plus its n-gram draft, padded — the device verifies all rows
        in one forward (weights stream ONCE for up to T tokens per row) and
        delivery emits each row's accepted prefix plus the correction/bonus
        token. Rejected tails sit beyond the verified frontier on masked
        slots (the free-rollback discipline); the device carry is rewound to
        the frontier so a chained scan composes for any accept outcome."""
        faults.fire("batch.verify", rows=len(active), block=t)
        if self.kv_pool is not None:
            for slot in active[:]:
                try:
                    self._paged_ensure(slot, slot.pos + t)
                except Exception as e:
                    if classify(e) != "request":
                        raise
                    self._fail_request(slot, e)
                    active.remove(slot)
                    drafts.pop(slot.index, None)
            if not active:
                return
        starts = self._park_positions(t)
        # a clamp-park CoW under pool exhaustion may have reaped a row
        active = [s for s in active if s.req is not None]
        if not active:
            return
        ndraft = [-1] * self.slots_n  # -1 parks the row inside the block
        props = [[0] * t for _ in range(self.slots_n)]
        budget = [0] * self.slots_n  # per-row MAX emit (accept + correction)
        rows: list[tuple[_Slot, BatchRequest]] = []
        for slot in active:
            i = slot.index
            d = drafts.get(i, [])
            starts[i] = slot.pos
            props[i] = [slot.last_token] + d + [0] * (t - 1 - len(d))
            ndraft[i] = len(d)
            budget[i] = len(d) + 1
            rows.append((slot, slot.req))
        fl = self._issue_verify_step(rows, t, ndraft, props, budget, starts)
        self._pipeline_advance(fl)

    # hot-path
    def _issue_verify_step(self, rows: list, t: int, ndraft: list[int],
                           props: list[list[int]], budget: list[int],
                           starts: list[int]) -> _InflightStep:
        """Dispatch one (B, T) verify block asynchronously. Always uploads
        host state (a verify is never chained FROM: its proposals are
        host-drafted from delivered history), but its returned carry is
        frontier-rewound on device, so successors may chain from IT."""
        eng = self._eng
        temps = [0.0] * self.slots_n
        topps = [0.9] * self.slots_n
        rng = np.zeros((self.slots_n, 2), np.uint32)
        greedy = True
        for slot, req in rows:
            i = slot.index
            smp = req.sampler
            temps[i] = float(getattr(smp, "temperature", 0.0))
            topps[i] = float(getattr(smp, "topp", 0.9))
            greedy = greedy and temps[i] == 0.0
            state = int(getattr(smp, "state", 0)) & ((1 << 64) - 1)
            rng[i] = state >> 32, state & 0xFFFFFFFF
        mode = "greedy" if greedy else "sample"
        window = eng._window_for(min(max(starts) + t, self.spec.seq_len))
        masked = self._constrained(rows)
        loop = self._verify_loop(t, mode, window, masked)
        if self._gap_t is not None:
            _DISPATCH_GAP.observe(max(time.perf_counter() - self._gap_t, 0.0))
        t_issue = time.perf_counter()
        kc_in, vc_in = eng.k_cache, eng.v_cache  # same stale-epoch discipline
        tables = self._tables() if self.kv_pool is not None else None
        constrain = None
        if masked:
            # a verify is never chained FROM, so its constraint states come
            # from the fully-delivered host mirrors — same as the rng
            # a mask fault inside _cstate_vec degrades that row to the
            # universal state 0 — the masked program then passes its logits
            # through untouched, so the dispatch itself stays valid
            cmask, cdelta = self.constrain_table.device()
            constrain = (jnp.asarray(self._cstate_vec()), cmask, cdelta)
            _CONSTRAIN_DISPATCHES.inc()
        with trace.span("batch.verify_issue",
                        {"block": t, "rows": len(rows),
                         "drafted": sum(max(n, 0) for n in ndraft)}):
            if masked:
                def call():
                    toks, acc, tok, pos, rng_out, kc, vc, cst = loop(
                        eng.params, eng.rope, props, kc_in, vc_in,
                        starts, rng, temps, topps, ndraft, tables,
                        constrain=constrain)
                    return toks, acc, tok, pos, rng_out, kc, vc, cst

                (toks, acc, tok, pos, rng_out, eng.k_cache,
                 eng.v_cache, cst) = self._dispatched("verify", call)
            else:
                def call():
                    toks, acc, tok, pos, rng_out, kc, vc = loop(
                        eng.params, eng.rope, props, kc_in, vc_in,
                        starts, rng, temps, topps, ndraft, tables)
                    return toks, acc, tok, pos, rng_out, kc, vc

                (toks, acc, tok, pos, rng_out, eng.k_cache,
                 eng.v_cache) = self._dispatched("verify", call)
                cst = None
        _PIPELINE_DEPTH.set(1)
        for a in (toks, acc, rng_out):
            try:
                a.copy_to_host_async()
            except Exception:
                pass
        return _InflightStep(rows, t, starts, budget, temps, toks, tok, pos,
                             rng_out, t_issue, False, kind="verify",
                             ndraft=ndraft, acc=acc, cstate=cst)

    def _drafts_ready(self, rows: list) -> bool:
        """Cheap probe: would a verify dispatch have material to work with?
        Consulted by the accept-aware chain policy BEFORE the in-flight
        block delivers, so it sees the pre-block corpus — advisory only
        (a model drafter counts as ready whenever its row can run: it
        always drafts k tokens, that is the point of it)."""
        for slot, _req in rows:
            k = (self.adaptive.k_for(slot.index)
                 if self.adaptive is not None else self.spec_k)
            if k > 0 and self.proposer.ready(slot.index, k,
                                             self.spec_min_draft):
                return True
        return False

    def _super_step(self, active: list[_Slot], k: int,
                    budgets: dict[int, int]) -> None:
        """One K-step fused dispatch from host state: every active row decodes
        up to its budget on device (sampling included), then the returned
        (K, B) block is delivered host-side with EOS/stop/max checks per
        token. A row that stops mid-block keeps its position at the verified
        frontier — the over-decoded rows beyond it sit on masked slots and
        are overwritten by the slot's next real writes (free rollback). With
        pipelining, the NEXT super-step is chained from this one's device
        carry before delivery starts (_pipeline_advance)."""
        if self.kv_pool is not None:
            for slot in active[:]:
                try:
                    self._paged_ensure(slot, slot.pos + budgets[slot.index])
                except Exception as e:
                    if classify(e) != "request":
                        raise
                    self._fail_request(slot, e)
                    active.remove(slot)
            if not active:
                return
        starts = self._park_positions(1)
        # a clamp-park CoW under pool exhaustion may have reaped a row
        active = [s for s in active if s.req is not None]
        if not active:
            return
        budget = [0] * self.slots_n
        rows: list[tuple[_Slot, BatchRequest]] = []
        for slot in active:
            starts[slot.index] = slot.pos
            budget[slot.index] = budgets[slot.index]
            rows.append((slot, slot.req))
        fl = self._issue_super_step(rows, k, budget, starts)
        self._pipeline_advance(fl)

    def _pipeline_advance(self, fl: _InflightStep) -> None:
        """Drive one pipeline turn: optionally issue the super-step AFTER
        `fl` chained from its device-resident carry (so the device never
        idles through the host delivery loop below), then deliver `fl` and
        validate the speculation — a chained dispatch survives only when
        every row it decodes delivered its full budget and stayed live."""
        nxt = None
        plan = None
        if self.pipeline and not self._shutdown and not self._draining:
            plan = self._plan_chain(fl)
        if plan is not None:
            with self._plock:
                waiting = bool(self._pending) or not self._queue.empty()
            if waiting or any(s.req and s.pending for s in self._slots):
                # a request needs the next dispatch for admission/prefill:
                # break the chain instead of extending it — the pipelined
                # analog of the K -> 1 admission-latency drop
                _PIPELINE_FLUSHES.labels(reason="admission").inc()
                plan = None
        if plan is not None and self.kv_pool is not None:
            # the chained dispatch's speculative writes need block coverage
            # (and clamped parks need exclusive blocks) BEFORE issue; a pool
            # that cannot serve declines the chain instead of failing rows
            rows, starts, budget, clamp = plan
            try:
                for slot, _req in rows:
                    self._paged_ensure(slot, starts[slot.index]
                                       + budget[slot.index])
                for slot in clamp:
                    self._paged_cow(slot, self.spec.seq_len - 1,
                                    self.spec.seq_len)
            except Exception:
                _PIPELINE_FLUSHES.labels(reason="pool").inc()
                plan = None
        if plan is not None:
            rows, starts, budget, clamp = plan
            for slot in clamp:
                # the chained scan parks this row clamped at seq_len-1,
                # destroying that history row — flag it before fl's delivery
                # so a mid-delivery _finish harvests the truncated prefix
                slot.clamp_pos = self.spec.seq_len - 1
            nxt = self._issue_super_step(rows, self.superstep, budget, starts,
                                         chain=fl)
        try:
            status = self._deliver_super_step(fl)
        except BaseException:
            if nxt is not None:
                # delivery failed with the chained dispatch still a local:
                # account for it here — _fail_all only sees self._inflight
                _PIPELINE_FLUSHES.labels(reason="error").inc()
            _PIPELINE_DEPTH.set(0)
            raise
        if nxt is not None:
            reason = self._chain_divergence(nxt, status)
            if reason is not None:
                self._flush_inflight(nxt, reason)
            else:
                self._inflight = nxt
        _PIPELINE_DEPTH.set(1 if self._inflight is not None else 0)

    def _plan_chain(self, fl: _InflightStep):  # hot-path
        """Speculative schedule for the scan super-step after `fl`, assuming
        `fl` delivers every budgeted token: same rows, re-derived budgets
        from the expected positions/output lengths. Returns (rows, starts,
        budget, clamp_slots), or None when no row would decode >= 2 steps
        (the single-step / admission path takes over), a reap is imminent,
        or the ACCEPT-AWARE policy declines (docs/SERVING.md "Speculative
        decoding"): while the engine's accept EMA is at/above
        spec_chain_expect, the next dispatch should be a host-drafted verify
        block (which cannot chain — its proposals need delivered tokens),
        not a K-step scan that would dilute it to ~1 token per step-cost.

        A verify predecessor is planned against FULL acceptance — the
        maximal positions/output lengths — so the derived budgets are sound
        for ANY actual accept: the chained scan consumes the device carry,
        which the verify loop rewound to the true frontier, and a row that
        accepted less simply decodes with a conservative budget. Only a row
        that FINISHED mid-verify (stop/length/cancel) flushes the chain,
        exactly like the scan-after-scan divergence rule."""
        k = self.superstep
        s = self.spec.seq_len
        now = time.perf_counter()
        if fl.kind == "verify":
            if self._spec_ema >= self.spec_chain_expect:
                _PIPELINE_FLUSHES.labels(reason="spec").inc()
                return None
            gain = [nd + 1 if nd >= 0 else 0 for nd in fl.ndraft]
        elif (self.spec_k and self._spec_ema >= self.spec_chain_expect
              and self._drafts_ready(fl.rows)):
            # extending the scan chain would outrun the verify those
            # drafts are ready for — break it (flush reason "spec")
            _PIPELINE_FLUSHES.labels(reason="spec").inc()
            return None
        else:
            gain = fl.budget
        starts = [st + g for st, g in zip(fl.starts, gain)]
        budget = [0] * self.slots_n
        rows: list[tuple[_Slot, BatchRequest]] = []
        clamp: list[_Slot] = []
        for slot, req in fl.rows:
            i = slot.index
            if req.cancelled or (req.deadline_t and now >= req.deadline_t):
                return None  # _reap_slots fires next pass: don't outrun it
            exp_out = len(req.out) + gain[i]
            b = min(k, req.max_tokens - exp_out, s - starts[i])
            if b > 0:
                budget[i] = b
                rows.append((slot, req))
            elif starts[i] >= s:
                clamp.append(slot)
        if not rows or max(budget) < 2:
            return None
        return rows, starts, budget, clamp

    # hot-path
    def _issue_super_step(self, rows: list, k: int, budget: list[int],
                          starts: list[int],
                          chain: _InflightStep | None = None) -> _InflightStep:
        """Dispatch one K-step batched decode WITHOUT waiting for results
        (async device dispatch: the call returns future arrays). chain=None
        uploads host state — slot last_token/pos plus each sampler's
        xorshift* state — exactly like the unpipelined super-step did;
        chain=<predecessor> feeds that dispatch's device-resident (last_tok,
        pos, rng) carry straight back in, no host round trip, with
        `starts`/`budget` the caller's speculative schedule."""
        eng = self._eng
        temps = [0.0] * self.slots_n
        topps = [0.9] * self.slots_n
        tokens = [0] * self.slots_n
        rng = np.zeros((self.slots_n, 2), np.uint32)
        greedy = True
        for slot, req in rows:
            i = slot.index
            smp = req.sampler
            temps[i] = float(getattr(smp, "temperature", 0.0))
            topps[i] = float(getattr(smp, "topp", 0.9))
            greedy = greedy and temps[i] == 0.0
            if chain is None:
                tokens[i] = slot.last_token
                state = int(getattr(smp, "state", 0)) & ((1 << 64) - 1)
                rng[i] = state >> 32, state & 0xFFFFFFFF
        mode = "greedy" if greedy else "sample"
        window = eng._window_for(min(max(st + max(b, 1)
                                         for st, b in zip(starts, budget)),
                                     self.spec.seq_len))
        masked = self._constrained(rows)
        loop = self._batched_loop(k, mode, window, masked)
        if chain is None:
            tok_in, pos_in, rng_in = tokens, starts, rng
            if self._gap_t is not None:
                # device-idle gap: results of the previous dispatch landed at
                # _gap_t and nothing ran on device until this issue
                _DISPATCH_GAP.observe(max(time.perf_counter() - self._gap_t,
                                          0.0))
        else:
            tok_in, pos_in, rng_in = chain.tok, chain.pos, chain.rng
            _DISPATCH_GAP.observe(0.0)  # chained: the device never went idle
        t_issue = time.perf_counter()
        kc_in, vc_in = eng.k_cache, eng.v_cache  # same stale-epoch discipline
        tables = self._tables() if self.kv_pool is not None else None
        constrain = None
        if masked:
            # constraint carry: a chained dispatch consumes the
            # predecessor's device-resident states (same rule as tok/rng);
            # an unchained one uploads the host mirrors. A predecessor
            # issued masked always carries cstate — _constrained() is
            # deterministic in the (identical) row set, so the chain never
            # crosses the masked/unmasked program boundary.
            cmask, cdelta = self.constrain_table.device()
            cin = (chain.cstate if chain is not None and chain.cstate
                   is not None else jnp.asarray(self._cstate_vec()))
            constrain = (cin, cmask, cdelta)
            _CONSTRAIN_DISPATCHES.inc()
        with trace.span("batch.super_step_issue",
                        {"k": k, "rows": len(rows),
                         "chained": chain is not None}):
            if masked:
                def call():
                    toks, tok, pos, rng_out, kc, vc, cst = loop(
                        eng.params, eng.rope, tok_in, kc_in, vc_in,
                        pos_in, rng_in, temps, topps, budget, tables,
                        constrain=constrain)
                    return toks, tok, pos, rng_out, kc, vc, cst

                (toks, tok, pos, rng_out, eng.k_cache,
                 eng.v_cache, cst) = self._dispatched("super_step", call)
            else:
                def call():
                    toks, tok, pos, rng_out, kc, vc = loop(
                        eng.params, eng.rope, tok_in, kc_in, vc_in,
                        pos_in, rng_in, temps, topps, budget, tables)
                    return toks, tok, pos, rng_out, kc, vc

                (toks, tok, pos, rng_out, eng.k_cache,
                 eng.v_cache) = self._dispatched("super_step", call)
                cst = None
        _PIPELINE_DEPTH.set(2 if chain is not None else 1)
        for a in (toks, rng_out):
            try:  # start the non-blocking host copy now; delivery's
                a.copy_to_host_async()  # np.asarray picks the buffer up
            except Exception:  # an optimization hint only — e.g. dp-sharded
                pass  # outputs may refuse the whole-array async copy
        return _InflightStep(rows, k, starts, budget, temps, toks, tok, pos,
                             rng_out, t_issue, chain is not None, cstate=cst)

    # hot-path
    def _deliver_super_step(self, fl: _InflightStep) -> dict[int, str]:
        """Host-side delivery of an issued super-step: block on the (K, B)
        token transfer, then per row run EOS/stop/max checks, emit tokens,
        and resync the sampler RNG (full delivery adopts the device state;
        partial delivery replays exactly the delivered coins — bit-exact
        either way). Returns per-slot-index outcomes — "alive" (full budget
        delivered, request still decoding) or the finish reason — the
        validity oracle for a dispatch chained from this one's carry."""
        k = fl.k
        s = self.spec.seq_len
        epoch = getattr(self._tls, "epoch", self._epoch)
        with trace.span("batch.super_step", {"k": k, "rows": len(fl.rows),
                                             "tokens": sum(fl.budget),
                                             "kind": fl.kind,
                                             "chained": fl.chained}):
            toks = np.asarray(fl.toks)  # dlint: ignore[hot-sync] -- THE delivery fence: one (K,B) block transfer per super-step is the design (1 sync per K tokens)
            rng_out = np.asarray(fl.rng)  # dlint: ignore[hot-sync] -- rides the same fence; copy_to_host_async at issue makes this a pickup, not a stall
            acc = np.asarray(fl.acc) if fl.kind == "verify" else None  # dlint: ignore[hot-sync] -- same fence (verify accept lengths)
        if self._epoch != epoch:
            # a hung transfer is the other place a wedged thread blocks; an
            # abandoned thread waking here must not deliver into slots that
            # now belong to the replacement epoch's requests
            raise _StaleEpoch()
        t_ready = time.perf_counter()
        self._last_dispatch_t = time.monotonic()
        # device-span estimate: the device could not start this dispatch
        # before it was issued, nor before the previous dispatch's results
        # were ready. Under overlap the issue->ready wall includes the time
        # spent queued behind the predecessor — which the host used for the
        # predecessor's delivery loop; that hidden slice is overlap_ms.
        base = fl.t_issue
        if self._last_ready_t is not None and self._last_ready_t > base:
            base = self._last_ready_t
        dev_ms = max((t_ready - base) * 1000.0, 1e-6)
        overlap_ms = (base - fl.t_issue) * 1000.0
        self._last_ready_t = t_ready
        self._gap_t = t_ready
        self.decode_steps += 1
        if fl.kind == "verify":
            self.verify_steps += 1
            _SPEC_VERIFY_STEPS.inc()
            _DISP_VERIFY.observe(dev_ms / 1000.0)
        else:
            self.super_steps += 1
            _DISP_SUPER.observe(dev_ms / 1000.0)
        _SUPERSTEP_TOKENS.observe(sum(fl.budget))
        # rows that ride the scan without a live request park for all k steps;
        # rows with a short budget park for the steps past it
        _PARKED_ROW_STEPS.inc(self.slots_n * k - sum(fl.budget))
        status: dict[int, str] = {}
        accs: list[int] = []  # per-row accepted lengths (verify EMA input)
        for slot, req in fl.rows:
            i = slot.index
            b = fl.budget[i]
            if fl.kind == "verify":
                # actual emit: accepted drafts + the correction/bonus token
                # (fl.budget holds the maximum, ndraft+1)
                b = int(acc[i]) + 1
            if slot.req is not req or req.done.is_set():
                # reaped (cancel/deadline/close) between issue and delivery:
                # the block was decoded past a frontier that no longer exists
                _ROLLBACK_TOKENS.inc(b)
                flight.event(req.rid, "rollback", tokens=b, where="reaped")
                status[i] = "cancelled"
                continue
            if not self._advance_row(slot):
                # chained dispatch: consume the PREVIOUS block's tail token
                # (the device already fed it; this mirrors _decode_step's
                # pre-issue advance). A cancel observed here lands the row in
                # _finish and discards its block.
                _ROLLBACK_TOKENS.inc(b)
                status[i] = req.finish
                continue
            if fl.kind == "scan" and b < k and fl.starts[i] + b >= s:
                # the scan parked this row mid-block clamped at s-1, whose
                # scratch writes destroyed that history row — record it BEFORE
                # delivery: reaching pos == s finishes the request inside the
                # loop below, and that _finish's harvest must not commit the
                # poisoned row (_harvest_into_cache consumes clamp_pos)
                # (verify blocks never clamp a live row: _plan_verify shrinks
                # T so every live row's block fits under seq_len)
                slot.clamp_pos = s - 1
                flight.event(req.rid, "park_clamped", pos=s - 1)
            if fl.kind == "verify":
                # per-request speculation accounting, recorded BEFORE the
                # emit loop so spec_turns keys on the pre-block output length
                # (the accept-length oracle in tests/test_batched_spec.py)
                nd = fl.ndraft[i]
                a = b - 1
                accs.append(a)
                # per-row adaptation + per-proposer attribution: a drafting
                # row's EMA follows its accept; a row that rode draftless
                # ticks toward re-probe (docs/SERVING.md "Model-based
                # drafting")
                if self.adaptive is not None:
                    self.adaptive.observe(i, nd, a)
                self.proposer.observe(i, a)
                req.stats.spec_steps += 1
                req.stats.spec_drafted += nd
                req.stats.spec_accepted += a
                req.stats.spec_turns.append((len(req.out), nd, a))
                req.stats.spec_step_ms.append(dev_ms)
                _SPEC_DRAFTED.inc(nd)
                _SPEC_ACCEPTED.inc(a)
                flight.event(req.rid, "verify_step", block=k, drafted=nd,
                             accepted=a)
            block = toks[:b, i].tolist()
            smp = req.sampler
            state0 = int(getattr(smp, "state", 0))
            per_tok = dev_ms / b
            # measured decode TPOT (ms/token, decayed) — the signal the
            # slo_tpot_interactive admission gate reads: when delivered
            # pace is already past the interactive target, new batch-class
            # admissions are refused before they widen the dispatches
            self._tpot_ema_ms += 0.2 * (per_tok - self._tpot_ema_ms)
            req.stats.dispatch_ms.append(dev_ms)
            req.stats.overlap_ms.append(overlap_ms)
            x = slot.last_token  # ingested input of the block's first step
            slot.armed = False  # the scan ingested last_token's KV
            alive = True
            delivered = 0  # block tokens actually handed to the request
            try:
                for tok in block:
                    if req.cancelled:
                        self._finish(slot, "cancelled")
                        alive = False
                        break
                    slot.history.append(x)
                    slot.pos += 1  # pos counts ingestions through this token's input
                    req.stats.token_ms.append(per_tok)
                    req.stats.infer_ms.append(per_tok)
                    delivered += 1
                    if not self._emit(slot, tok):
                        alive = False
                        break
                    x = tok
            except Exception as e:
                # broken sampler/on_token/stop_check (or an injected
                # batch.emit fault): this request alone dies; the other rows'
                # blocks deliver normally (blast-radius isolation)
                _ENGINE_ERRORS.labels(kind="request").inc()
                req.error = e
                self._finish(slot, "error")
                alive = False
            if delivered < b:
                # frontier rewind: the device decoded b tokens for this row but
                # the host delivered fewer (stop/cancel/error mid-block) — the
                # tail sits on masked slots and is discarded
                _ROLLBACK_TOKENS.inc(b - delivered)
                flight.event(req.rid, "rollback", tokens=b - delivered,
                             where="mid_block")
            if fl.temps[i] != 0.0 and hasattr(smp, "state"):
                # resync the host sampler to the coins actually DELIVERED, not
                # the full budget the device drew: a stop/cancel mid-block
                # discards the tail, and the sequential stream never draws for
                # discarded tokens (a caller-owned sampler reused across
                # requests must see one unbroken sequence). For a fully
                # delivered block this equals the device's returned state —
                # which a chained successor is already carrying forward.
                if alive and delivered == b:
                    smp.state = np.uint64((int(rng_out[i, 0]) << 32)
                                          | int(rng_out[i, 1]))
                else:
                    from .sampler import _random_u32

                    s64 = np.uint64(state0)
                    for _ in range(delivered):
                        s64, _ = _random_u32(s64)
                    smp.state = s64
            if alive:
                # block fully delivered; its tail is sampled but not ingested
                slot.next_token = block[-1]
                slot.last_logits = None
            if slot.clamp_pos is not None:
                # row did not finish mid-loop (the harvest consumes clamp_pos
                # when it did): apply the clamp truncation here — mirror of
                # the _park_positions clamp, incl. the lease shrink
                self._truncate_history(slot, slot.clamp_pos)
                slot.clamp_pos = None
            # per-row timeline + trace attribution: one super_step entry per
            # request it advanced, and (tracing on) a per-row instant bound
            # to the request's context so the event carries ITS trace id —
            # the cross-thread re-entry that makes one shared dispatch
            # attributable per request in the merged fleet trace
            flight.event(req.rid, "super_step", k=k, budget=b,
                         delivered=delivered, chained=fl.chained)
            if trace.current() is not None:
                with reqctx.use(req.ctx):
                    trace.instant("batch.row_delivered",
                                  {"slot": i, "delivered": delivered,
                                   "k": k})
            status[i] = "alive" if alive else req.finish
        if fl.kind == "verify":
            if accs:
                # accept EMA drives the chain policy: high expected accept →
                # back-to-back verifies; low → chained scans keep overlap
                self._spec_ema = (0.7 * self._spec_ema
                                  + 0.3 * (sum(accs) / len(accs)))
            if _SPEC_DRAFTED.value > 0:
                _SPEC_ACCEPT_RATE.set(_SPEC_ACCEPTED.value
                                      / _SPEC_DRAFTED.value)
        elif self.spec_k:
            # slow regression toward optimism while scans run: a decayed EMA
            # must not disengage speculation FOREVER (verifies are the only
            # signal that raises it) — after ~a dozen scans the policy
            # re-probes with one verify and re-learns the true accept rate,
            # bounding the waste on hopeless workloads to one wide dispatch
            # per dozen scans while phase changes (output turning repetitive
            # mid-stream) are picked up within the same horizon
            self._spec_ema += 0.05 * (self.spec_k - self._spec_ema)
            if self.adaptive is not None:
                # the same slow-reprobe policy PER ROW: a scan turn passed
                # without these rows drafting
                for slot, _req in fl.rows:
                    self.adaptive.tick(slot.index)
        return status

    def _chain_divergence(self, nxt: _InflightStep,
                          status: dict[int, str]) -> str | None:
        """None when every row the chained dispatch decodes matched the
        speculated schedule (predecessor delivered its full budget and the
        request is still live); otherwise the flush reason."""
        for slot, _req in nxt.rows:
            st = status.get(slot.index, "cancelled")
            if st != "alive":
                return {"stop": "stop", "cancelled": "cancel",
                        "error": "error"}.get(st, "finish")
        return None

    def _flush_inflight(self, fl: _InflightStep, reason: str) -> None:
        """Discard a chained dispatch whose speculated schedule diverged from
        what its predecessor actually delivered. The rollback is free: every
        write the flushed scan makes lands at or beyond its row's committed
        frontier (masked scratch, overwritten by the slot's next real
        writes), context-end parks were flagged via clamp_pos at issue, and
        the next dispatch re-uploads tokens/positions/RNG from host state —
        which delivery kept bit-exact (the xorshift* stream never advances
        for discarded tokens)."""
        _PIPELINE_FLUSHES.labels(reason=reason).inc()
        _ROLLBACK_TOKENS.inc(sum(fl.budget))
        for slot, req in fl.rows:
            flight.event(req.rid, "pipeline_flush", reason=reason,
                         tokens=fl.budget[slot.index])
