"""Prompt-lookup speculative decoding (greedy, model-free).

No reference counterpart — a beyond-parity decode accelerator that exploits the
TPU decode regime: a T = 1+k verify step streams the weights ONCE for k+1
tokens, so on a bandwidth-bound chip it costs roughly one decode step. Drafts
come from the context itself (n-gram suffix lookup, the "prompt lookup
decoding" technique): find the most recent earlier occurrence of the current
tail n-gram and propose the tokens that followed it. Repetitive workloads
(code, chat templates, retrieval contexts) accept long drafts; adversarial
text degrades gracefully to ~1 token/step plus one wasted row of compute.

Exactness: greedy acceptance emits EXACTLY the tokens the sequential host loop
would (each accepted token equals the argmax the step itself produced; the
first mismatch is replaced by the step's own argmax — the standard greedy
speculative identity). Sampling (temperature > 0) is NOT supported — the
caller falls back to the sequential loop.

Rollback is free under the repo's cache disciplines: rows committed for
rejected positions sit BEYOND the rewound start_pos, and every read path masks
slots >= start_pos (deferred window masks, ring attention live_end, paged ring
slot formula), so the next step simply overwrites them. Engine.seek() handles
the paged hot ring's wrapped slots.
"""

from __future__ import annotations

from ..obs import metrics, trace

_DRAFTED = metrics.counter(
    "spec_drafted_tokens_total", "Draft tokens proposed by prompt lookup")
_ACCEPTED = metrics.counter(
    "spec_accepted_tokens_total", "Draft tokens the verify step accepted")
_VERIFY_STEPS = metrics.counter(
    "spec_verify_steps_total", "Speculative verify dispatches")
_ACCEPT_RATE = metrics.gauge(
    "spec_accept_rate", "Cumulative accepted/drafted ratio (process lifetime)")
# the shared decode-token counter (get-or-create returns engine.py's instance)
_ENGINE_DECODE_TOKENS = metrics.counter(
    "engine_decode_tokens_total", "Tokens decoded by the sequential engine")


def propose_ngram(tokens: list[int], k: int, *, max_ngram: int = 4,
                  min_ngram: int = 1) -> list[int]:
    """Draft up to k tokens by matching the longest tail n-gram earlier in
    `tokens` (most recent occurrence wins) and copying its continuation.

    Brute-force reference: O(len * ngram) list-slice comparisons per call —
    at 16k context with no match that approaches the cost of the decode step
    it is meant to amortize. The generation loop uses NgramIndex (same
    answers, O(max_ngram) dict lookups per proposal); this form remains the
    oracle the index is tested against."""
    n = len(tokens)
    if n < min_ngram + 1 or k <= 0:
        return []
    for size in range(min(max_ngram, n - 1), min_ngram - 1, -1):
        tail = tokens[n - size:]
        # most recent earlier occurrence of the tail n-gram; start <= n-size-1
        # guarantees the continuation slice holds at least one token
        for start in range(n - size - 1, -1, -1):
            if tokens[start:start + size] == tail:
                return list(tokens[start + size:start + size + k])
    return []


class NgramIndex:
    """Incremental tail-n-gram -> most-recent-occurrence index over a growing
    token list: propose() is O(max_ngram) dict lookups instead of
    propose_ngram's full-history rescan, with identical answers.

    Registration lags the tail by one append: the brute force only accepts
    occurrences whose continuation holds at least one token (start <=
    n-size-1, i.e. the n-gram ends at most at n-1), so on each append to
    length m we register the grams ENDING at m-1 — exactly the newly-eligible
    occurrences. The dict keeps the largest start per gram, which is the
    brute force's most-recent-wins scan order.

    Memory bound: the dicts gain one entry per UNIQUE n-gram for the life of
    the index, which on a long-lived batched serving slot (one NgramIndex per
    conversation, runtime/batch_engine.py) grows without bound. `max_entries`
    caps the total: when registration crosses it the dicts are rebuilt from a
    bounded tail window (sized so the rebuilt index holds at most
    ~max_entries/2 entries), after which proposals only match occurrences
    inside that window — recency is exactly what prompt-lookup prefers
    anyway, so distant-history matches are the cheapest thing to shed. The
    token list itself stays whole (ints, and propose() stores absolute start
    indices into it)."""

    def __init__(self, tokens: list[int], *, max_ngram: int = 4,
                 min_ngram: int = 1, max_entries: int = 65536):
        self.tokens: list[int] = []
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram
        self.sizes = range(min_ngram, max_ngram + 1)
        self.max_entries = max_entries
        self.window = max(max_entries // (2 * len(self.sizes)), 4 * max_ngram)
        self._entries = 0
        self._last: dict[int, dict[tuple, int]] = {s: {} for s in self.sizes}
        self.extend(tokens)

    def _register(self, end: int) -> None:
        """Register the grams ENDING at token index `end` (their continuation
        starts at `end`, so they just became legal occurrences)."""
        for size in self.sizes:
            if end >= size:
                d = self._last[size]
                gram = tuple(self.tokens[end - size:end])
                if gram not in d:
                    self._entries += 1
                d[gram] = end - size

    def append(self, tok: int) -> None:
        self.tokens.append(tok)
        self._register(len(self.tokens) - 1)
        if self._entries > self.max_entries:
            self._rebuild()

    def _rebuild(self) -> None:
        """Re-register only the grams ending inside the tail window; amortized
        O(1) per append (each rebuild is O(window), triggered at most every
        ~max_entries/2 appends)."""
        n = len(self.tokens)
        self._last = {s: {} for s in self.sizes}
        self._entries = 0
        for end in range(max(n - self.window, self.min_ngram), n):
            self._register(end)

    @property
    def entries(self) -> int:
        """Total registered n-gram entries across sizes (memory gauge)."""
        return self._entries

    def extend(self, tokens: list[int]) -> None:
        for t in tokens:
            self.append(t)

    def propose(self, k: int) -> list[int]:
        """propose_ngram(self.tokens, k) via the index."""
        tokens = self.tokens
        n = len(tokens)
        if n < self.min_ngram + 1 or k <= 0:
            return []
        for size in range(min(self.max_ngram, n - 1), self.min_ngram - 1, -1):
            start = self._last[size].get(tuple(tokens[n - size:]))
            if start is not None:
                return list(tokens[start + size:start + size + k])
        return []

    def propose_extended(self, k: int) -> list[int]:
        """propose(), re-proposed from the virtually extended sequence until
        k tokens are drafted or the lookup goes dry.

        Most-recent-wins truncates exactly where prompt-lookup shines: on a
        cyclic tail (code/JSON repetition, greedy attractor loops) the most
        recent occurrence of the tail n-gram overlaps the tail itself, so
        its continuation is clipped to 1-2 tokens by the end of the list.
        Treating the draft as accepted and looking up again (the tail n-gram
        of tokens+draft, continuations still read from the real token list)
        unrolls the cycle to the full k — the draft a verify block can
        actually amortize. Each round adds >= 1 token, so at most k
        lookups."""
        out = self.propose(k)
        while 0 < len(out) < k:
            merged = self.tokens[-self.max_ngram:] + out
            more: list[int] = []
            for size in range(min(self.max_ngram, len(merged)),
                              self.min_ngram - 1, -1):
                start = self._last[size].get(tuple(merged[-size:]))
                if start is not None:
                    more = list(self.tokens[start + size:
                                            start + size + k - len(out)])
                    break
            if not more:
                break
            out += more
        return out[:k]


# ----------------------------------------------------------------------
# Proposer protocol (docs/SERVING.md "Model-based drafting")
# ----------------------------------------------------------------------
# A proposer supplies per-row draft tokens to the BatchEngine's verify path.
# Implementations: NgramProposer (prompt-lookup, below), draft/drafter.py
# ModelDrafter (a co-resident small sharded model), and ProposerMux (per-row
# routing between them). All methods run on the scheduler thread unless a
# class documents otherwise; `row` is the engine slot index.
#
#   name: str                      # "ngram" | "model" | "mux" (stats/metrics)
#   attach(row, tokens)            # bind a row; tokens = prompt ⊕ delivered
#   detach(row)                    # release the row (finish/preempt/wedge)
#   push(row, tok)                 # one delivered token (corpus/frontier sync)
#   propose(row, k) -> list[int]   # up to k draft tokens for the row
#   observe(row, accepted)         # verify outcome for the row's last drafts
#
# propose_batch(want: {row: k}) -> {row: drafts} is the batched form the
# engine actually calls (a model drafter serves every row in ONE scan
# dispatch); the default below routes it through per-row propose().


def verify_block_bucket(t: int, cap: int) -> int:
    """Block-length bucket (2, 3, 5, 9, 17, ... capped at `cap`): verify and
    draft-scan programs compile per length, so raw per-dispatch lengths would
    compile O(k) programs; buckets bound it to O(log k). Padding positions
    are scratch writes beyond the frontier — the same masked-slot discipline
    every over-decode already relies on."""
    b = 2
    while b < t:
        b = 2 * (b - 1) + 1
    return min(b, cap)


def draft_buckets(k_cap: int) -> list[int]:
    """Per-row draft-count buckets derived from the verify T buckets
    (T = 1 + k: k ∈ 1, 2, 4, 8, ...), capped at k_cap — the adaptive-k
    controller only ever requests these lengths, so per-row adaptation can
    never mint a verify (or drafter-scan) program the fixed-k path would
    not also compile."""
    out = []
    b = 1
    while b < k_cap:
        out.append(b)
        b *= 2
    out.append(k_cap)
    return out


class NgramProposer:
    """Per-row NgramIndex behind the Proposer protocol — the PR-8 prompt-
    lookup drafter re-expressed as one implementation among several."""

    name = "ngram"

    def __init__(self, *, max_ngram: int = 4, max_entries: int = 65536):
        self.max_ngram = max_ngram
        self.max_entries = max_entries
        self._idx: dict[int, NgramIndex] = {}

    def attach(self, row: int, tokens: list[int]) -> None:
        self._idx[row] = NgramIndex(list(tokens), max_ngram=self.max_ngram,
                                    max_entries=self.max_entries)

    def detach(self, row: int) -> None:
        self._idx.pop(row, None)

    def push(self, row: int, tok: int) -> None:
        idx = self._idx.get(row)
        if idx is not None:
            idx.append(tok)

    def propose(self, row: int, k: int) -> list[int]:
        idx = self._idx.get(row)
        if idx is None or k <= 0:
            return []
        return idx.propose_extended(k)

    def propose_batch(self, want: dict[int, int]) -> dict[int, list[int]]:
        return {row: d for row, k in want.items()
                if (d := self.propose(row, k))}

    def observe(self, row: int, accepted: int) -> None:
        pass  # the corpus already advanced via push()

    def ready(self, row: int, k: int, min_draft: int) -> bool:
        """Cheap advisory probe: would propose() return >= min_draft?"""
        return len(self.propose(row, k)) >= min_draft


class AdaptiveK:
    """Per-row adaptive draft length (docs/SERVING.md "Model-based
    drafting"): each row's k follows its own accept EMA so chat, code, json
    and open-ended rows co-batched in one engine each find their own
    operating point. k values are drawn from draft_buckets() (the verify
    T buckets minus 1) so adaptation cannot cause recompile creep.

    Policy per verify turn (observe): full accept counts as accepted+1 —
    the row would likely have accepted more, so the EMA can climb past the
    current bucket and k grows; a partial accept pulls the EMA toward the
    measured accept length and k shrinks to the smallest bucket covering
    it. Below `engage` the row DISENGAGES (k_for -> 0: no drafts, no wasted
    verify width); while disengaged — and on any turn the row passes
    without drafting (tick) — the EMA regresses slowly UP toward
    `reprobe_to` (just past the engage floor, never dragging an
    already-confident row down): the PR-8 slow-reprobe policy per row, so
    after ~a dozen idle turns the row re-probes with the SMALLEST bucket
    (one cheap draft) and only ramps back up if the probe accepts —
    a hopeless row (e.g. a high-temperature stochastic stream sampling far
    from the drafter's argmax) costs one 1-token draft per horizon instead
    of riding every verify at full width."""

    def __init__(self, k_cap: int, *, alpha: float = 0.3,
                 engage: float = 0.35, reprobe: float = 0.05):
        self.k_cap = max(int(k_cap), 1)
        self.buckets = draft_buckets(self.k_cap)
        self.alpha = alpha
        self.engage = engage
        self.reprobe = reprobe
        self.reprobe_to = 2.0 * engage  # re-probe lands on the k=1 bucket
        import threading

        # stats() is read from API threads while the scheduler adapts
        self._lock = threading.Lock()  # guards: _ema
        self._ema: dict[int, float] = {}

    def attach(self, row: int) -> None:
        with self._lock:
            # optimistic start (the PR-8 engine-EMA convention): speculation
            # engages at full width and adapts down on hopeless rows
            self._ema[row] = float(self.k_cap) + 1.0

    def detach(self, row: int) -> None:
        with self._lock:
            self._ema.pop(row, None)

    def _k_from_ema(self, ema: float) -> int:
        """The one place the engage threshold + bucket choice live (k_for
        and stats() must report the same policy)."""
        if ema < self.engage:
            return 0
        for b in self.buckets:
            if b >= ema:
                return b
        return self.k_cap

    def k_for(self, row: int) -> int:
        with self._lock:
            ema = self._ema.get(row)
        if ema is None:
            return self.k_cap  # unattached rows get the fixed-k behavior
        return self._k_from_ema(ema)

    def observe(self, row: int, drafted: int, accepted: int) -> None:
        if drafted <= 0:
            return self.tick(row)
        val = accepted + 1.0 if accepted >= drafted else float(accepted)
        with self._lock:
            if row in self._ema:
                self._ema[row] += self.alpha * (val - self._ema[row])

    def tick(self, row: int) -> None:
        """A turn passed without this row drafting (scan, or rode a verify
        draftless): regress slowly up toward the re-probe point so
        disengagement is never forever — and never drag a confident row's
        EMA down (a row paused only because its proposer went dry must not
        forget its accept history)."""
        with self._lock:
            ema = self._ema.get(row)
            if ema is not None and ema < self.reprobe_to:
                self._ema[row] = ema + self.reprobe * (self.reprobe_to - ema)

    def stats(self) -> dict[int, dict]:
        with self._lock:
            snap = dict(self._ema)
        return {row: {"ema": round(ema, 3), "k": self._k_from_ema(ema)}
                for row, ema in snap.items()}


class ProposerMux:
    """Per-row routing between a model drafter and the n-gram fallback
    (docs/SERVING.md "Model-based drafting"). The drafter serves every row
    it can (attached, within its own context window, healthy) in one scan
    dispatch; remaining rows fall back to prompt lookup. A raising drafter
    degrades: the failing dispatch's rows fall back to n-gram proposals
    (the request never sees the failure), and `max_failures` CONSECUTIVE
    propose failures disable the drafter for the engine's lifetime —
    n-gram-only from then on, exactly the pre-drafter behavior.

    `grammar` (constrain.GrammarProposer) is consulted FIRST for rows it
    serves: a grammar-constrained row whose automaton sits on a
    forced-transition chain drafts that chain — the target's only legal
    continuation, guaranteed accept, zero drafting compute — while
    co-batched unconstrained rows in the SAME want dict fall through to
    the model/ngram routing unchanged.

    Scheduler-thread-only except stats()/describe() (reads of counters and
    the drafter's own locked stats — torn reads only skew a stats scrape)."""

    name = "mux"

    def __init__(self, ngram: NgramProposer, drafter=None, *,
                 grammar=None, max_failures: int = 8):
        self.ngram = ngram
        self.drafter = drafter
        self.grammar = grammar
        self.max_failures = max_failures
        self.failures = 0  # consecutive; reset on success
        self.errors = 0  # lifetime (stats)
        self.disabled = False
        # which proposer drafted each row's LAST proposal (per-proposer
        # accept attribution; scheduler-thread-only)
        self.last_src: dict[int, str] = {}

    def _model_ok(self) -> bool:
        return self.drafter is not None and not self.disabled

    def attach(self, row: int, tokens: list[int]) -> None:
        self.ngram.attach(row, tokens)
        if self.drafter is not None:
            self.drafter.attach(row, tokens)

    def detach(self, row: int) -> None:
        self.ngram.detach(row)
        if self.drafter is not None:
            self.drafter.detach(row)
        if self.grammar is not None:
            self.grammar.detach(row)
        self.last_src.pop(row, None)

    def push(self, row: int, tok: int) -> None:
        self.ngram.push(row, tok)
        if self.drafter is not None:
            self.drafter.push(row, tok)

    def propose(self, row: int, k: int) -> list[int]:
        return self.propose_batch({row: k}).get(row, [])

    def propose_batch(self, want: dict[int, int]) -> dict[int, list[int]]:
        out: dict[int, list[int]] = {}
        # grammar first: forced-chain drafts are certain accepts, so they
        # always beat a learned draft for the rows they cover; remaining
        # (unconstrained / off-chain) rows keep the model/ngram routing
        if self.grammar is not None:
            for row, d in self.grammar.propose_batch(want).items():
                out[row] = d
                self.last_src[row] = "grammar"
                _PROPOSED.labels(proposer="grammar").inc(len(d))
            want = {row: k for row, k in want.items() if row not in out}
            if not want:
                return out
        mout: dict[int, list[int]] = {}
        if self._model_ok():
            try:
                mout = self.drafter.propose_batch(want)
                self.failures = 0
            except Exception as e:
                # a failing drafter costs only its drafts — every row falls
                # back to prompt lookup below, the request never notices
                self.failures += 1
                self.errors += 1
                _DRAFT_ERRORS.inc()
                if self.failures >= self.max_failures and not self.disabled:
                    self.disabled = True
                    _DRAFT_DISABLED.set(1)
                    import sys

                    print(f"🔴 model drafter disabled after "
                          f"{self.failures} consecutive failures: {e!r} — "
                          "degrading to n-gram drafting", file=sys.stderr)
                mout = {}
        for row, d in mout.items():
            out[row] = d
            self.last_src[row] = "model"
            _PROPOSED.labels(proposer="model").inc(len(d))
        for row, k in want.items():
            if row in out:
                continue
            d = self.ngram.propose(row, k)
            if d:
                out[row] = d
                self.last_src[row] = "ngram"
                _PROPOSED.labels(proposer="ngram").inc(len(d))
        return out

    def observe(self, row: int, accepted: int) -> None:
        src = self.last_src.get(row)
        if src is not None and accepted > 0:
            _PROP_ACCEPTED.labels(proposer=src).inc(accepted)
        if self.drafter is not None:
            self.drafter.observe(row, accepted)

    def ready(self, row: int, k: int, min_draft: int) -> bool:
        if k <= 0:
            return False
        if self.grammar is not None and self.grammar.ready(row, k,
                                                           min_draft):
            return True  # a forced chain long enough is a certain accept
        if self._model_ok() and self.drafter.can_serve(row, k):
            return True  # a model drafts k tokens whenever it can run
        return self.ngram.ready(row, k, min_draft)

    def describe(self) -> dict:
        d = self.drafter
        out = {"model": d is not None, "disabled": self.disabled,
               "errors": self.errors}
        if d is not None:
            out["drafter"] = d.stats()
        if self.grammar is not None:
            out["grammar"] = self.grammar.stats()
        return out


_PROPOSED = metrics.counter(
    "batch_spec_proposer_drafted_total",
    "Draft tokens fed to batched verify dispatches, by proposer",
    labelnames=("proposer",))
_PROP_ACCEPTED = metrics.counter(
    "batch_spec_proposer_accepted_total",
    "Accepted draft tokens, by the proposer that drafted them",
    labelnames=("proposer",))
_DRAFT_ERRORS = metrics.counter(
    "batch_draft_errors_total",
    "Model-drafter propose failures degraded to n-gram drafting")
_DRAFT_DISABLED = metrics.gauge(
    "batch_draft_disabled",
    "1 while the model drafter is disabled after consecutive failures")


def generate_speculative(engine, prompt_tokens: list[int], max_tokens: int,
                         sampler, *, k: int = 8, on_token=None,
                         stop_check=None,
                         history_tokens: list[int] | None = None):
    """Greedy generation with prompt-lookup drafts; returns (tokens, stats)
    exactly equal to engine.generate()'s output for temperature 0.

    Each iteration runs ONE step over [last_token] + draft (T <= 1+k),
    accepts the matching prefix, emits the step's own argmax as the
    correction, and rewinds the cache to the verified frontier via
    engine.seek(). Extra stats fields: spec_steps (verify dispatches),
    spec_drafted, spec_accepted (draft tokens that matched)."""
    from .engine import GenerationStats
    import time

    assert getattr(sampler, "temperature", 0.0) == 0.0, (
        "speculative decoding is greedy-only; use the sequential loop for "
        "temperature > 0")
    stats = GenerationStats()
    # modeled traffic only: the T=1+k verify program's collectives differ from
    # the traced T=1 step's (the logits all-gather scales with T) — presenting
    # another program's trace as "measured" is the round-1 defect
    # _fill_traffic's provenance flag exists to prevent
    engine._fill_traffic(stats)
    # spec_steps/spec_drafted/spec_accepted/spec_step_ms start at their
    # GenerationStats dataclass defaults

    # the proposer's corpus: the FULL conversation when the caller prefix-
    # reused most of it (api_server passes history_tokens=whole prompt while
    # prompt_tokens is just the delta) — prompt-lookup draws its drafts from
    # exactly that repetitive history
    assert history_tokens is None or (
        history_tokens[-len(prompt_tokens):] == list(prompt_tokens)), (
        "history_tokens must end with prompt_tokens")
    history = NgramIndex(list(history_tokens) if history_tokens
                         else list(prompt_tokens))
    if len(prompt_tokens) > 1:
        # prefill everything but the last prompt token; each verify block
        # starts with the pending token, so its logits re-derive in-block
        engine.prefill(prompt_tokens[:-1], stats)
    stats.prompt_tokens = len(prompt_tokens)
    out: list[int] = []
    last = prompt_tokens[-1]
    done = False
    while not done and len(out) < max_tokens:
        t0 = time.perf_counter()
        room = engine.spec.seq_len - engine.pos - 1
        if room <= 0:
            break
        # draft cap room-1, not room: emitting full[i] is sequential-legal only
        # while the ingest position after it stays BELOW seq_len (the
        # sequential loop breaks at pos >= seq_len before sampling again), so
        # the block may fill at most up to position seq_len-1
        draft = history.propose_extended(
            min(k, room - 1, max_tokens - len(out) - 1))
        block = [last] + draft
        pos_before = engine.pos
        with trace.span("spec.verify", {"draft": len(draft),
                                        "pos": pos_before}):
            full = engine.infer_chunk_logits(block)  # (T, vocab)
        stats.spec_steps += 1
        stats.spec_drafted += len(draft)
        accepted = 0
        emitted: list[int] = []
        for i in range(len(block)):
            target = sampler.sample(full[i])  # argmax w/ sampler's tie-breaks
            emitted.append(target)
            if i < len(draft) and target == draft[i]:
                accepted += 1
            else:
                break
        stats.spec_accepted += accepted
        stats.spec_turns.append((len(out), len(draft), accepted))
        _VERIFY_STEPS.inc()
        _DRAFTED.inc(len(draft))
        _ACCEPTED.inc(accepted)
        if _DRAFTED.value > 0:
            _ACCEPT_RATE.set(_ACCEPTED.value / _DRAFTED.value)
        # real per-dispatch verify time; token_ms/infer_ms get the per-token
        # AVERAGE of it (see GenerationStats: percentiles are synthetic when
        # spec_steps > 0, aggregate tokens/s stays correct)
        dt_full = (time.perf_counter() - t0) * 1000.0
        stats.spec_step_ms.append(dt_full)
        stats.dispatch_ms.append(dt_full)
        dt_ms = dt_full / len(emitted)
        stop_j = None
        for j, tok in enumerate(emitted):
            out.append(tok)
            history.append(tok)
            stats.generated_tokens += 1
            _ENGINE_DECODE_TOKENS.inc()
            stats.token_ms.append(dt_ms)
            stats.infer_ms.append(dt_ms)
            if on_token is not None:
                on_token(tok)
            if stop_check is not None and stop_check(tok):
                done = True
                stop_j = j
                break
            if len(out) >= max_tokens:
                break
        # rewind to the verified frontier: rows beyond it were computed from
        # rejected inputs (masked reads make the stale rows invisible). On a
        # stop at emitted index j the frontier excludes the stop token's
        # ingestion — the sequential loop breaks before inferring it.
        frontier = pos_before + 1 + (stop_j if stop_j is not None else accepted)
        engine.seek(frontier)
        last = out[-1]
        if engine.pos >= engine.spec.seq_len:
            break
    return out, stats
