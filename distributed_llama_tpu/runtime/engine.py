"""Inference engine: model loading, SPMD step compilation, generation loop, stats.

This is the TPU-native replacement for the reference's App::run wiring + Inference/Worker
drivers (src/app.cpp:123-155, src/tasks.cpp:158-230):

    SocketPool::connect + worker processes  ->  jax.sharding.Mesh over local TPU devices
    Transformer::loadRootFromFile + weight streaming -> formats.load_model + shard_params
    Inference::infer (per-token task loop)  ->  one jitted SPMD step, KV caches donated
    tryWaitForPos / sendPos                 ->  gone (start_pos is a step argument)
    Inference::getStats I/T split           ->  GenerationStats (device step wall time +
                                                analytic collective-bytes model, since
                                                ICI transfer overlaps compute under XLA)

Prefill runs in chunks of [64, 8, 1] tokens (3 compiled shapes) — the reference prefills
strictly token-by-token (dllama.cpp:163-167), so chunked prefill is a capability win.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ..models.params import Params, decode_stream_bytes, prepare_for_pallas
from ..models.spec import ModelSpec
from ..obs import flight, metrics, trace
from ..resilience import faults
from ..ops.rope import RopeTables
from ..parallel.mesh import AXIS_TP, make_mesh
from ..parallel.tp import make_sharded_forward, shard_params
from ..quants import FloatType
from ..tokenizer.bpe import Tokenizer

PREFILL_CHUNKS = (64, 8, 1)

# Real per-dispatch wall times (device step + logits host transfer), the
# measured complement of GenerationStats' synthetic per-token averages.
# Children resolved once — the hot path pays one observe(), no label lookup.
_DISPATCH_SECONDS = metrics.histogram(
    "engine_dispatch_seconds",
    "Wall time of one device dispatch (incl. the logits host transfer)",
    labelnames=("kind",))
_DISP_PREFILL = _DISPATCH_SECONDS.labels(kind="prefill")
_DISP_DECODE = _DISPATCH_SECONDS.labels(kind="decode")
_DISP_LOOP = _DISPATCH_SECONDS.labels(kind="device_loop")
_PREFILL_TOKENS = metrics.counter(
    "engine_prefill_tokens_total", "Prompt tokens run through prefill")
_DECODE_TOKENS = metrics.counter(
    "engine_decode_tokens_total", "Tokens decoded by the sequential engine")


@dataclass
class GenerationStats:
    """Per-token timing + traffic, the analog of the reference's G/I/T + S/R printout
    (dllama.cpp:76-93, socket.cpp:280-285)."""

    prompt_tokens: int = 0
    generated_tokens: int = 0
    # prompt tokens whose prefill was skipped at admission (same-slot rewind
    # + radix prefix-cache seed) — for a resumed request this is the share of
    # prompt ⊕ delivered-tokens the new replica did NOT have to re-run
    reused_tokens: int = 0
    prefill_ms: float = 0.0
    # Per-token wall/device times. NOTE: when a dispatch covers several tokens
    # (speculative verify blocks, device-loop chunks, BatchEngine super-steps)
    # each entry is the dispatch time divided by its token count — an average,
    # not a measured per-token latency; aggregate tokens/s stays correct, but
    # per-token percentiles are synthetic whenever spec_steps > 0 or a
    # multi-token loop ran. spec_step_ms keeps the real per-dispatch times.
    token_ms: list[float] = field(default_factory=list)
    infer_ms: list[float] = field(default_factory=list)
    # speculative decoding (runtime/speculative.py + the batched verify path
    # in runtime/batch_engine.py): verify dispatches, draft tokens
    # proposed/accepted, and each verify dispatch's wall time
    spec_steps: int = 0
    spec_drafted: int = 0
    spec_accepted: int = 0
    spec_step_ms: list[float] = field(default_factory=list)
    # one (tokens_out_before, drafted, accepted) triple per verify turn —
    # keyed by output length so the batched verify path can be oracle-checked
    # against the sequential loop turn-for-turn (tests/test_batched_spec.py)
    spec_turns: list = field(default_factory=list)
    # REAL per-dispatch times (one entry per device dispatch, however many
    # tokens it covered) — the honest latency series next to the synthetic
    # token_ms averages above. The same numbers feed the
    # engine_dispatch_seconds / batch_dispatch_seconds histograms. Under
    # PIPELINED super-steps (runtime/batch_engine.py) a dispatch's wall time
    # no longer equals its cost — the host delivers the previous block while
    # it runs — so each entry is the DEVICE-SIDE span estimate (issue or
    # predecessor-completion, whichever is later, to results-ready) and
    # overlap_ms below records the hidden host slice per dispatch.
    dispatch_ms: list[float] = field(default_factory=list)
    # per-SUPER-STEP milliseconds of wall clock that ran concurrently with
    # the predecessor still executing on device (0.0 when not pipelined; one
    # entry per super-step dispatch only — docs/OBSERVABILITY.md)
    overlap_ms: list[float] = field(default_factory=list)
    sent_kbytes_per_token: float = 0.0
    recv_kbytes_per_token: float = 0.0
    # provenance of the S/R numbers: "modeled" = the analytic formula below;
    # "measured" = exact per-step accounting of the compiled program's collectives
    # (Engine.collective_stats). The reference measured socket bytes at runtime
    # (socket.cpp:280-285); printing a model as if measured was a round-1 defect.
    traffic_source: str = "modeled"

    @property
    def avg_token_ms(self) -> float:
        return float(np.mean(self.token_ms)) if self.token_ms else 0.0

    @property
    def avg_infer_ms(self) -> float:
        return float(np.mean(self.infer_ms)) if self.infer_ms else 0.0

    @property
    def tokens_per_second(self) -> float:
        return 1000.0 / self.avg_token_ms if self.token_ms else 0.0


def collective_kbytes_per_token(spec: ModelSpec, tp: int, compress: bool) -> float:
    """Bytes each device exchanges per decoded token. Mirrors the reference's
    S/R socket counters (root broadcast+gather per layer, tasks.cpp:44-94)
    with ring-collective wire costs:

    - per layer, two activation all-reduces (attention-out + ffn-out), each
      2x(tp-1)/tp of its payload. Compressed, the payload is the Q80 wire
      format (int8 vals + f16 scale per 32-block = 34/32 bytes/elem) moved by
      the two-phase quantized reduce in parallel/collectives.py — all_to_all
      then all_gather, each (tp-1)/tp of the compressed payload, so the SAME
      2x(tp-1)/tp factor holds and this estimate is true of the real program
      (the old single-phase all_gather form shipped tp/2 x more than claimed;
      estimate-vs-measured is pinned in tests/test_engine.py);
    - one logits all-gather: each device contributes its vocab/tp slice and
      receives the rest, (tp-1)/tp of the full f32 logits row."""
    if tp <= 1:
        return 0.0
    elem = 34 / 32 if compress else 4  # Q80 wire bytes/elem vs f32
    per_layer = 2 * spec.dim * elem  # attention-out psum + ffn-out psum payloads
    layers = 2 * (tp - 1) / tp * spec.n_layers * per_layer
    logits = (tp - 1) / tp * spec.vocab_size * 4
    return (layers + logits) / 1024.0


class Engine:
    def __init__(self, spec: ModelSpec, params: Params, tokenizer: Tokenizer | None = None,
                 *, tp: int | None = None, sp: int = 1, dp: int = 1, dtype=None,
                 use_pallas: bool | None = None,
                 compress_collectives: bool = False, batch: int = 1,
                 pod: bool = False, cache_write: str | None = None,
                 moe_sharding: str = "slice", fused_prologue: bool | None = None,
                 prefill_kernel: bool | None = None,
                 fused_matmul: bool | None = None,
                 kv_cache_storage: str | None = None,
                 kv_cache_resident: int = 1024,
                 kv_cache_dir: str | None = None,
                 kv_pool: tuple[int, int] | None = None,
                 paged_kernel: bool | None = None):
        self.spec = spec
        self.tokenizer = tokenizer
        on_tpu = jax.default_backend() == "tpu"
        # decode is HBM-bound on TPU: bf16 activations/caches halve cache traffic, and
        # matvec numerics are int8 (Q80) in the kernel either way. f32 on CPU keeps the
        # golden/parity tests exact.
        self.dtype = dtype if dtype is not None else (jnp.bfloat16 if on_tpu
                                                      else jnp.float32)
        self.compress = compress_collectives
        if use_pallas is None:
            use_pallas = on_tpu
        # one rounded resident value drives every paged-mode decision (the
        # fits-check, the tp default, and the ring allocation) — three
        # different thresholds here previously let `--kv-cache-resident 1000`
        # page against a ring rounded up to the full seq_len (empty cold,
        # pure callback overhead forever)
        self.kv_resident = max(64, (kv_cache_resident + 63) // 64 * 64)
        assert kv_cache_storage in (None, "ram", "host", "disc"), kv_cache_storage
        self.paged = (kv_cache_storage in ("host", "disc")
                      and spec.seq_len > self.kv_resident)
        if self.paged and tp is None:
            tp = 1  # paged mode is single-chip; don't let the mesh grab every device
        # Device-resident paged KV (docs/PAGED_KV.md): kv_pool=(n_blocks,
        # block_tokens) replaces the contiguous per-slot caches with a
        # (L, N, hk, bt, hs) block pool + per-row block tables (BatchEngine
        # owns the tables/refcounts; this engine allocates the arrays and
        # builds table-aware step programs). Excluded combinations fall
        # back to the dense layout here — ONE gate for every caller.
        if kv_pool is not None and (self.paged or sp > 1 or dp > 1):
            import sys

            print("💡 device-resident paged KV disabled: incompatible with "
                  + ("host/disc KV paging" if self.paged else "sp/dp sharding")
                  + " — using the dense contiguous cache layout",
                  file=sys.stderr)
            kv_pool = None
        self.kv_pool = kv_pool
        if paged_kernel is None:
            import os

            # tri-state: explicit env wins, unset defers to the use_pallas
            # resolution below (TPU + quantized weights → kernel on)
            env = os.environ.get("DLT_PAGED_KERNEL", "").lower()
            if env in ("1", "true", "yes", "interp"):
                paged_kernel = True
            elif env in ("0", "false", "no"):
                paged_kernel = False
        self._paged_kernel_req = paged_kernel  # resolved after use_pallas
        if pod:
            # multi-host job: mesh over EVERY chip in the job (the SPMD replacement
            # for the reference's worker fleet, dllama.cpp:205-221). Caller must have
            # run init_multihost() first so jax.devices() is global.
            from ..parallel.mesh import make_pod_mesh

            self.mesh = make_pod_mesh(tp=tp, sp=sp,
                                      dp=dp if dp > 1 else None)
            from ..parallel.mesh import AXIS_DP

            dp = self.mesh.shape[AXIS_DP]
        else:
            self.mesh = make_mesh(tp=tp, sp=sp, dp=dp)
        assert batch % dp == 0, (
            f"batch={batch} must divide over dp={dp} (each dp shard holds "
            "batch/dp cache rows)")
        self.tp = self.mesh.shape[AXIS_TP]
        self.sp = sp
        self.dp = dp
        # KV cache discipline (models/forward.py): "deferred" keeps the caches
        # loop-invariant in the layer scan — avoids the whole-cache carry copies
        # XLA TPU inserts for dynamically-indexed carry updates (round-4 trace:
        # ~11.6 ms/token at 7B). Supported on every path, including sp (the ring
        # attends committed rows + the chunk as a register block, and the commit
        # is a masked window write — commit_kv_rows_sharded). None = auto
        # (deferred).
        self.cache_write = cache_write or "deferred"
        # fused rmsnorm+quantize prologue kernels (ops/pallas_prologue.py):
        # opt-in (flag or DLT_PROLOGUE=1) until the hardware A/B lands — the
        # round-4 lesson is not to default to never-executed kernels
        if fused_prologue is None:
            import os

            # parse, don't bool(): DLT_PROLOGUE=0 must mean OFF (A/B control arm)
            fused_prologue = os.environ.get("DLT_PROLOGUE", "").lower() in (
                "1", "true", "yes")
        self.fused_prologue = fused_prologue
        # MoE expert placement: "slice" TP-slices every expert's hidden axis (the
        # reference's scheme); "expert" shards WHOLE experts over tp — the capacity
        # axis for Grok-1-314B-class expert weights (parallel/sharding.py)
        self.moe_sharding = moe_sharding if spec.is_moe else "slice" 
        has_quant = any(
            getattr(t, "ftype", None) in (FloatType.Q40, FloatType.Q80)
            for t in params["blocks"].values())
        self.use_pallas = use_pallas and has_quant
        # fused dequant-matmul for prefill / batched decode
        # (ops/pallas_q4_mm.py): opt-in (flag or DLT_PREFILL_KERNEL=1) until
        # the hardware A/B lands — same policy as the prologue kernels
        if prefill_kernel is None:
            import os

            prefill_kernel = os.environ.get("DLT_PREFILL_KERNEL", "").lower() in (
                "1", "true", "yes")
        self.prefill_kernel = prefill_kernel and self.use_pallas
        if self.prefill_kernel:
            self.use_pallas = "all"  # qmatmul's M>1 kernel opt-in
        # fused batched serving path (--fused-matmul / DLT_FUSED_MATMUL):
        # everything "all" lowers PLUS the fused epilogues — residual add in
        # the wo/w2 accumulator init and the silu·mul FFN gate-pair kernel
        # (w1/w3 stay un-merged so the pair kernel can take them). Subsumes
        # prefill_kernel; opt-in until the hardware A/B lands.
        if fused_matmul is None:
            import os

            fused_matmul = os.environ.get("DLT_FUSED_MATMUL", "").lower() in (
                "1", "true", "yes")
        self.fused_matmul = bool(fused_matmul) and bool(self.use_pallas)
        if self.fused_matmul:
            self.use_pallas = "fused"
        # paged-attention kernel gate (ops/pallas_paged_attention.py):
        # explicit request (kwarg / DLT_PAGED_KERNEL) wins; default follows
        # use_pallas (TPU + quantized weights). CPU tests force it on via
        # the env knob — the kernel then runs in interpret mode.
        self.paged_kernel = bool(
            self._paged_kernel_req if self._paged_kernel_req is not None
            else self.use_pallas) and self.kv_pool is not None
        if self.use_pallas:
            params = prepare_for_pallas(params, self.tp,
                                        moe_sharding=self.moe_sharding,
                                        spec=spec,
                                        keep_gate_pair=self.fused_matmul)
        self.params = shard_params(params, self.mesh, spec,
                                   moe_sharding=self.moe_sharding)
        # global (all-shard) weight bytes one decode step streams — per-chip traffic
        # divides by tp; used for the achieved-GB/s printout (perf/PROFILE.md)
        self.decode_weight_bytes = decode_stream_bytes(self.params, spec)
        self.rope = RopeTables.create(spec)
        self.batch = batch
        # Paged (out-of-core) KV cache — the reference's --kv-cache-storage
        # disc rebuilt TPU-native (runtime/paged_cache.py): device hot ring +
        # authoritative host/disk store + per-layer cold-attention callbacks.
        # A capacity valve for contexts whose cache exceeds HBM; single-chip,
        # single-sequence (use --sp to go FAST at long context instead).
        self.store = None
        if kv_cache_storage in ("host", "disc") and not self.paged:
            import sys

            print(f"💡 kv-cache-storage={kv_cache_storage} ignored: the full "
                  f"seq_len {spec.seq_len} cache fits the {self.kv_resident}-"
                  "slot resident budget (nothing to page)", file=sys.stderr)
        if self.paged:
            assert self.tp == 1 and sp == 1 and dp == 1 and batch == 1, (
                "paged KV cache is single-chip, single-sequence (tp=sp=dp="
                "batch=1); shard the cache over chips with --sp instead")
            from .paged_cache import HostKVStore

            host_dtype = (np.float32 if self.dtype == jnp.float32
                          else np.dtype(jnp.bfloat16))
            self.store = HostKVStore(spec, self.kv_resident, batch=1,
                                     storage=kv_cache_storage,
                                     directory=kv_cache_dir, dtype=host_dtype)
        self._steps: dict[int | None, object] = {}  # attn_window bucket -> jitted step
        self.k_cache, self.v_cache = self._init_cache()
        self.pos = 0
        self._decode_loops: dict[tuple, object] = {}  # (chunk, mode, window) -> loop
        self._loop_traffics: dict[tuple, object] = {}  # (chunk, mode) -> CollectiveTraffic
        self._measured_traffic = None  # lazy CollectiveTraffic of the T=1 decode step

    # attention reads only the first `window` cache positions — a static bucket so
    # decode cache traffic tracks the live context, not the allocated seq_len (the
    # reference's 0..pos attention loop gets this for free, llama2-tasks.cpp:62-93).
    # Buckets are powers of two from 256 up; each compiles once.
    _WINDOW_MIN = 256

    def _window_for(self, pos_end: int) -> int | None:
        """Smallest window bucket covering cache positions [0, pos_end)."""
        s = self.spec.seq_len
        if self.paged:
            return None  # the hot ring IS the window; cold attends on host
        if self.sp > 1 and self.cache_write != "deferred":
            return None  # contiguous (inscan) ring walks the full sharded cache
        if s <= self._WINDOW_MIN:
            return None  # tiny contexts: no bucketing
        w = self._WINDOW_MIN
        while w < pos_end:
            w *= 2
        return None if w >= s else w

    def _step_for(self, window: int | None):
        if window == "paged_warm":
            # warm phase of the paged engine: while pos + T <= resident the
            # ring layout coincides with a plain cache prefix (slot ==
            # position) and the cold segment is provably empty — run the
            # ordinary deferred step over the ring-sized caches and skip the
            # n_layers host callback round-trips per step entirely
            window = None
        elif self.paged:
            if "paged" not in self._steps:
                from .paged_cache import make_paged_step

                self._steps["paged"] = make_paged_step(
                    self.spec, self.store, dtype=self.dtype,
                    use_pallas=self.use_pallas,
                    fused_prologue=self.fused_prologue)
            return self._steps["paged"]
        if self.kv_pool is not None:
            # table-aware step (docs/PAGED_KV.md): same window buckets, one
            # extra (B, W) block-table argument — keyed apart from the dense
            # programs so the compile manifest tracks them separately
            key = ("pagedkv", window)
            if key not in self._steps:
                self._steps[key] = make_sharded_forward(
                    self.spec, self.mesh, self.params, dtype=self.dtype,
                    use_pallas=self.use_pallas,
                    compress_collectives=self.compress,
                    donate_cache=True, attn_window=window,
                    cache_write="deferred", moe_sharding=self.moe_sharding,
                    fused_prologue=self.fused_prologue,
                    kv_block_tokens=self.kv_pool[1],
                    paged_kernel=self.paged_kernel)
            return self._steps[key]
        if window not in self._steps:
            self._steps[window] = make_sharded_forward(
                self.spec, self.mesh, self.params, dtype=self.dtype,
                use_pallas=self.use_pallas, compress_collectives=self.compress,
                donate_cache=True, attn_window=window,
                cache_write=self.cache_write, moe_sharding=self.moe_sharding,
                fused_prologue=self.fused_prologue)
        return self._steps[window]

    @property
    def _step(self):
        """The full-window step (collective tracing / tests)."""
        return self._step_for(None)

    @classmethod
    def load(cls, model_path: str, tokenizer_path: str | None = None, *,
             max_seq_len: int = 0, weights_ftype: FloatType | None = None,
             **kw) -> "Engine":
        from ..formats.mfile import load_model

        spec, params = load_model(model_path, max_seq_len, weights_ftype)
        tokenizer = Tokenizer.load(tokenizer_path) if tokenizer_path else None
        if tokenizer is not None and tokenizer.vocab_size != spec.vocab_size:
            raise ValueError(
                f"tokenizer vocab {tokenizer.vocab_size} != model vocab {spec.vocab_size}")
        return cls(spec, params, tokenizer, **kw)

    def _init_cache(self):
        if self.paged:
            from .paged_cache import init_ring_cache

            return init_ring_cache(self.spec, self.kv_resident, batch=1,
                                   dtype=self.dtype)
        if self.kv_pool is not None:
            # device block pool (docs/PAGED_KV.md): (L, N, hk, bt, hs), kv
            # heads sharded over tp like the dense cache's head axis
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            from ..parallel.sharding import effective_kv_heads
            from ..parallel.mesh import AXIS_TP as _TP

            n_blocks, bt = self.kv_pool
            hk = effective_kv_heads(self.spec, self.tp)
            shape = (self.spec.n_layers, n_blocks, hk, bt,
                     self.spec.head_size)
            sh = NamedSharding(self.mesh, P(None, None, _TP))
            return (jax.device_put(jnp.zeros(shape, self.dtype), sh),
                    jax.device_put(jnp.zeros(shape, self.dtype), sh))
        from ..parallel.tp import init_sharded_kv_cache

        return init_sharded_kv_cache(self.spec, self.mesh, batch=self.batch,
                                     dtype=self.dtype)

    def reset(self) -> None:
        self.pos = 0

    def seek(self, pos: int) -> None:
        """Set the decode position (prefix reuse rewind, api_server NaiveCache).

        Plain mode: the full cache keeps every position, so moving pos is
        enough. Paged mode: after a wrap, ring slots hold rows from the
        ABANDONED continuation's later positions, which the slot-position
        formula (models/forward.py paged branch) would mislabel as earlier
        committed rows — restore the ring from the authoritative host store
        (zeros for never-written slots are masked arithmetically)."""
        assert 0 <= pos <= self.pos, f"seek({pos}) past live context {self.pos}"
        if self.paged and pos < self.pos:
            L, B, hk, R, hs = self.k_cache.shape
            n_stale = self.pos - pos
            if n_stale < R:
                # targeted patch (the speculative-decoding rollback path runs
                # this EVERY step with a rejected draft — a full ring rebuild
                # + HBM re-upload here would dwarf the step being saved):
                # each rolled-back position's slot must revert to its previous
                # occupant (position q-R, from the host store); slots whose
                # previous occupant is negative never held a valid row below
                # the new frontier and stay masked by the slot-position
                # formula regardless of content.
                stale = np.arange(pos, self.pos)
                prev = stale - R
                valid = prev >= 0
                if valid.any():
                    slots = jnp.asarray(stale[valid] % R)
                    krows = jnp.asarray(
                        np.asarray(self.store.k[:, :, :, prev[valid]],
                                   np.float32), self.dtype)
                    vrows = jnp.asarray(
                        np.asarray(self.store.v[:, :, :, prev[valid]],
                                   np.float32), self.dtype)
                    self.k_cache = self.k_cache.at[:, :, :, slots, :].set(krows)
                    self.v_cache = self.v_cache.at[:, :, :, slots, :].set(vrows)
            else:
                # rolled back a full wrap or more: rebuild the ring outright
                lo = max(0, pos - R)
                kr = np.zeros((L, B, hk, R, hs), np.float32)
                vr = np.zeros_like(kr)
                if pos > lo:
                    idx = np.arange(lo, pos) % R
                    kr[:, :, :, idx] = np.asarray(self.store.k[:, :, :, lo:pos],
                                                  np.float32)
                    vr[:, :, :, idx] = np.asarray(self.store.v[:, :, :, lo:pos],
                                                  np.float32)
                self.k_cache = jnp.asarray(kr, self.dtype)
                self.v_cache = jnp.asarray(vr, self.dtype)
        self.pos = pos

    def _trace_pos_args(self):
        """Trailing step args for collective-traffic tracing: start_pos
        (plus a zero block table in device-pool mode, where the step is
        table-aware and start_pos is per-row)."""
        if self.kv_pool is not None:
            w = -(-self.spec.seq_len // self.kv_pool[1])
            return (jnp.zeros((self.batch,), jnp.int32),
                    jnp.zeros((self.batch, w), jnp.int32))
        return (self._pos_arg(0),)

    def _pos_arg(self, pos):
        """start_pos step argument: scalar normally, per-row (B,) under dp sharding
        (the dp in_spec shards the row axis, so a scalar can't be passed)."""
        if self.dp > 1:
            return jnp.full((self.batch,), pos, jnp.int32)
        return jnp.int32(pos)

    def collective_stats(self):
        """Exact per-decode-step collective traffic of the compiled step program.

        Traces the T=1 decode step and accounts every collective it executes
        (scan-body psums x n_layers, logits all-gather, ...) with ring-algorithm
        wire costs — the measured replacement for collective_kbytes_per_token's
        analytic model (reference counted socket bytes, socket.cpp:280-285)."""
        if self._measured_traffic is None:
            from ..parallel.hlo_stats import jaxpr_collective_traffic

            tokens = jnp.zeros((self.batch, 1), jnp.int32)
            closed = jax.make_jaxpr(self._step)(
                self.params, self.rope, tokens, self.k_cache, self.v_cache,
                *self._trace_pos_args())
            self._measured_traffic = jaxpr_collective_traffic(
                closed, dict(self.mesh.shape))
            from ..parallel.hlo_stats import publish_traffic

            # surface the measured numbers as gauges — EQuARX-style accounting
            # as a permanent /metrics fact, not a one-off bench artifact
            publish_traffic(self._measured_traffic, program="decode_t1")
        return self._measured_traffic

    def compiled_collective_stats(self):
        """Collective traffic read from the XLA-OPTIMIZED module of the T=1 step —
        the cross-check for collective_stats(): the jaxpr accounting predicts what
        was traced; this sees what XLA actually lowered (all-reduce rewrites,
        combining, async pairs). Semantics differ on loops: the jaxpr walker
        multiplies scan-body collectives by the trip count, while this counts HLO
        instructions once (the layer scan compiles to a while loop), so per-layer
        collectives appear once here — compare per-instruction kinds/payloads, not
        totals. Costs a full compile on first call (memoized after)."""
        if getattr(self, "_compiled_traffic", None) is not None:
            return self._compiled_traffic
        from ..parallel.hlo_stats import collective_traffic

        tokens = jnp.zeros((self.batch, 1), jnp.int32)
        lowered = jax.jit(self._step).lower(
            self.params, self.rope, tokens, self.k_cache, self.v_cache,
            *self._trace_pos_args())
        hlo = lowered.compile().as_text()
        self._compiled_traffic = collective_traffic(hlo, self.tp * self.sp)
        return self._compiled_traffic

    def _fill_traffic(self, stats: GenerationStats, measured=None,
                      per_tokens: int = 1) -> None:
        """Per-token S/R from `measured` (a CollectiveTraffic for a program covering
        `per_tokens` tokens) or, when None, the analytic model — provenance recorded
        either way. Each program (host step vs device loop) must be measured by its
        own trace; a different program's numbers are never presented as measured."""
        if measured is not None:
            kb = measured.sent_bytes_per_device / per_tokens / 1024.0
            stats.sent_kbytes_per_token = stats.recv_kbytes_per_token = kb
            stats.traffic_source = "measured"
        else:
            stats.sent_kbytes_per_token = stats.recv_kbytes_per_token = (
                collective_kbytes_per_token(self.spec, self.tp, self.compress))
            stats.traffic_source = "modeled"

    # ------------------------------------------------------------------
    # core stepping
    # ------------------------------------------------------------------

    def infer_chunk(self, tokens: list[int] | np.ndarray) -> np.ndarray:
        """Run a chunk of tokens at the current position; returns last-token logits
        (vocab,) and advances pos. Bounds-checked against seq_len (the reference hard-stops
        at context end, dllama.cpp:190-192)."""
        return self._infer(tokens)[-1]

    def _infer(self, tokens: list[int] | np.ndarray) -> np.ndarray:
        """One step over T tokens; returns all T positions' logits (T, vocab)
        and advances pos (shared body of infer_chunk / infer_chunk_logits)."""
        tokens = np.asarray(tokens, dtype=np.int32)
        t = len(tokens)
        if self.pos + t > self.spec.seq_len:
            raise ValueError(f"context overflow: pos {self.pos} + {t} > {self.spec.seq_len}")
        with trace.span("engine.dispatch", {"t": t, "pos": self.pos}):
            return self._infer_traced(tokens, t)

    def _infer_traced(self, tokens: np.ndarray, t: int) -> np.ndarray:
        faults.fire("engine.dispatch", t=t, pos=self.pos)
        t0 = time.perf_counter()
        if self.paged:
            # warm phase (pos + T within the ring) takes the callback-free
            # plain step; the paged step only builds once real cold history
            # is about to exist
            step = self._step_for("paged_warm" if self.pos + t <= self.kv_resident
                                  else None)
        else:
            step = self._step_for(self._window_for(self.pos + t))
        # the host loop drives ONE sequence; with batch>1 slots (BatchEngine backing
        # store) or dp sharding, tile the row across the batch so token/cache/pos
        # shapes stay congruent (rows 1.. do redundant work; BatchEngine drives the
        # step directly with real per-row data instead)
        if self.batch > 1 and not getattr(self, "_warned_tiled_batch", False):
            self._warned_tiled_batch = True
            import sys

            print(f"⚠️  Engine(batch={self.batch}) host loop tiles one sequence "
                  f"across all {self.batch} rows — {self.batch}x redundant compute. "
                  "Use BatchEngine (api_server --batch) to drive real per-row "
                  "requests.", file=sys.stderr)
        toks = jnp.tile(jnp.asarray(tokens)[None, :], (self.batch, 1))
        if self.paged and self.pos + t <= self.kv_resident:
            # warm phase: slot == position, cold empty — plain deferred step
            # (see _step_for), with the new rows sliced from the committed ring
            # for the host-store append (the authoritative history the paged
            # step's cold callbacks will read once the ring wraps)
            logits, self.k_cache, self.v_cache = step(
                self.params, self.rope, toks, self.k_cache,
                self.v_cache, self._pos_arg(self.pos))
            self.store.append(
                np.asarray(self.k_cache[:, :, :, self.pos:self.pos + t]),
                np.asarray(self.v_cache[:, :, :, self.pos:self.pos + t]),
                self.pos)
        elif self.paged:
            logits, self.k_cache, self.v_cache, (k_rows, v_rows) = step(
                self.params, self.rope, toks, self.k_cache,
                self.v_cache, self._pos_arg(self.pos))
            # the host store is the authoritative history the next step's
            # cold callbacks read — append before advancing pos
            self.store.append(np.asarray(k_rows), np.asarray(v_rows), self.pos)
        else:
            logits, self.k_cache, self.v_cache = step(
                self.params, self.rope, toks, self.k_cache,
                self.v_cache, self._pos_arg(self.pos))
        self.pos += t
        out = np.asarray(logits)[0]  # host transfer: the honest dispatch fence
        dt = time.perf_counter() - t0
        # a 1-token dispatch is decode-shaped regardless of which loop issued
        # it (prefill's tail chunks of 1 land here too — same program, same
        # cost); decode TOKENS are counted at the generation loops, which know
        # whether a token was decoded or merely prompt-ingested
        (_DISP_PREFILL if t > 1 else _DISP_DECODE).observe(dt)
        return out

    def infer_chunk_logits(self, tokens: list[int] | np.ndarray) -> np.ndarray:
        """infer_chunk, but returns ALL T positions' logits (T, vocab) — the
        verify step of speculative decoding (runtime/speculative.py) needs
        every position's argmax. Advances pos by T like infer_chunk;
        speculative callers seek() back to the verified frontier."""
        return self._infer(tokens)

    def generate_speculative(self, prompt_tokens: list[int], max_tokens: int,
                             sampler, *, k: int = 8, on_token=None,
                             stop_check=None,
                             history_tokens: list[int] | None = None):
        """Greedy prompt-lookup speculative decoding (runtime/speculative.py):
        emits exactly generate()'s tokens, usually in fewer dispatches."""
        from .speculative import generate_speculative

        return generate_speculative(self, prompt_tokens, max_tokens, sampler,
                                    k=k, on_token=on_token,
                                    stop_check=stop_check,
                                    history_tokens=history_tokens)

    def prefill(self, tokens: list[int], stats: GenerationStats | None = None) -> np.ndarray:
        """Chunked prompt ingestion; returns logits after the last prompt token."""
        t0 = time.perf_counter()
        tokens = list(tokens)
        logits = None
        i = 0
        with trace.span("engine.prefill", {"tokens": len(tokens)}):
            while i < len(tokens):
                for chunk in PREFILL_CHUNKS:
                    if len(tokens) - i >= chunk:
                        logits = self.infer_chunk(tokens[i:i + chunk])
                        i += chunk
                        break
        _PREFILL_TOKENS.inc(len(tokens))
        dt_ms = (time.perf_counter() - t0) * 1000.0
        # flight-recorder timeline entry for the sequential serving path
        # (--batch 1): rid resolves from the caller's bound trace context
        # (api_server handler thread), no-op outside a recorded request
        flight.event(None, "prefill", tokens=len(tokens),
                     ms=round(dt_ms, 3))
        if stats is not None:
            stats.prefill_ms = dt_ms
            stats.prompt_tokens = len(tokens)
        return logits

    def generate(self, prompt_tokens: list[int], max_tokens: int, sampler,
                 on_token=None, stop_check=None) -> tuple[list[int], GenerationStats]:
        """Host generation loop: prefill + sample/step until max_tokens, context end, or
        stop_check truth. on_token(token_id) streams tokens out."""
        stats = GenerationStats()
        self._fill_traffic(stats, self._measured_traffic)
        logits = self.prefill(prompt_tokens, stats)
        out: list[int] = []
        for _ in range(max_tokens):
            if self.pos >= self.spec.seq_len:
                break
            t0 = time.perf_counter()
            token = sampler.sample(logits)
            out.append(token)
            stats.generated_tokens += 1
            if on_token is not None:
                on_token(token)
            if stop_check is not None and stop_check(token):
                break
            if self.pos >= self.spec.seq_len:
                break
            t1 = time.perf_counter()
            logits = self.infer_chunk([token])
            t2 = time.perf_counter()
            _DECODE_TOKENS.inc()
            stats.infer_ms.append((t2 - t1) * 1000.0)
            stats.token_ms.append((t2 - t0) * 1000.0)
            stats.dispatch_ms.append((t2 - t1) * 1000.0)
        return out, stats

    def generate_with(self, prompt_tokens: list[int], max_tokens: int, sampler,
                      *, device_loop_chunk: int = 0, speculative_k: int = 0,
                      history_tokens: list[int] | None = None,
                      **kw) -> tuple[list[int], GenerationStats]:
        """generate / generate_chunked / generate_speculative dispatch — the
        single switch point for every app surface's --device-loop and
        --speculative flags. Speculation is greedy-only (temperature 0) and
        wins over the device loop when both are requested. history_tokens
        (optional, speculative only): full already-cached context for the
        n-gram proposer when prompt_tokens is a prefix-reuse delta."""
        if speculative_k > 0:
            if getattr(sampler, "temperature", 0.0) == 0.0:
                return self.generate_speculative(prompt_tokens, max_tokens,
                                                 sampler, k=speculative_k,
                                                 history_tokens=history_tokens,
                                                 **kw)
            if not getattr(self, "_warned_spec_fallback", False):
                # once per engine, not per request — a serving default of
                # temperature 0.7 would otherwise print this on every call
                self._warned_spec_fallback = True
                import sys

                print("⚠️  --speculative is greedy-only (temperature 0); "
                      "falling back to the "
                      + ("on-device loop" if device_loop_chunk > 0
                         and not self.paged else "sequential host loop")
                      + " for sampled requests.", file=sys.stderr)
        if device_loop_chunk > 0:
            if self.paged:
                import sys

                print("⚠️  --device-loop is incompatible with the paged KV "
                      "cache (host-store appends happen between dispatches); "
                      "using the host loop.", file=sys.stderr)
            else:
                return self.generate_chunked(prompt_tokens, max_tokens, sampler,
                                             chunk=device_loop_chunk, **kw)
        return self.generate(prompt_tokens, max_tokens, sampler, **kw)

    # ------------------------------------------------------------------
    # device-loop generation (one dispatch per chunk of tokens)
    # ------------------------------------------------------------------

    def _decode_loop(self, chunk: int, mode: str, window: int | None = None):
        if (chunk, mode, window) not in self._decode_loops:
            from .device_loop import make_decode_loop

            self._decode_loops[chunk, mode, window] = make_decode_loop(
                self.spec, self.mesh, self.params, chunk, mode=mode, dtype=self.dtype,
                use_pallas=self.use_pallas,
                compress_collectives=self.compress, donate_cache=True,
                attn_window=window, cache_write=self.cache_write,
                moe_sharding=self.moe_sharding,
                fused_prologue=self.fused_prologue)
        return self._decode_loops[chunk, mode, window]

    def _loop_traffic(self, chunk: int, mode: str, loop):
        """Measured collective traffic of the device-loop program itself (it is a
        different compiled program than the host step — its own trace, not the
        T=1 step's, covers `chunk` tokens). Computed only when the user opted into
        measurement via collective_stats() — tracing a large model costs seconds."""
        key = (chunk, mode)
        if key not in self._loop_traffics:
            from ..parallel.hlo_stats import jaxpr_collective_traffic

            closed = jax.make_jaxpr(loop)(
                self.params, self.rope, jnp.int32(1), self.k_cache, self.v_cache,
                jnp.int32(0), jax.random.PRNGKey(0), jnp.float32(0.0),
                jnp.float32(0.9))
            self._loop_traffics[key] = jaxpr_collective_traffic(
                closed, dict(self.mesh.shape))
        return self._loop_traffics[key]

    def generate_chunked(self, prompt_tokens: list[int], max_tokens: int, sampler,
                         on_token=None, stop_check=None, chunk: int = 16,
                         ) -> tuple[list[int], GenerationStats]:
        """Generate with the on-device scan loop: forward + sample stay on device and
        each dispatch returns `chunk` tokens (vs the reference's strictly per-token host
        loop, dllama.cpp:17-94). Greedy (temperature 0) emits exactly the host loop's
        tokens; stochastic sampling uses the device PRNG (not xorshift-bit-compatible).

        KV-cache positions beyond an early stop are overwritten by later writes at those
        positions, so mid-chunk stops need no rollback.
        """
        stats = GenerationStats()
        self._fill_traffic(stats)
        if len(prompt_tokens) > 1:
            self.prefill(prompt_tokens[:-1], stats)
        stats.prompt_tokens = len(prompt_tokens)
        # sampler.state is a full-range uint64 (xorshift*); PRNGKey takes an int64
        key = jax.random.PRNGKey(int(getattr(sampler, "state", 0)) & (2**63 - 1))
        temperature = getattr(sampler, "temperature", 0.0)
        topp = getattr(sampler, "topp", 0.9)
        out: list[int] = []
        token = prompt_tokens[-1]
        mode = "greedy" if temperature == 0.0 else "sample"
        done = False
        while not done and len(out) < max_tokens:
            want = max_tokens - len(out)
            seq_left = self.spec.seq_len - self.pos
            if seq_left <= 0:
                break
            if seq_left < chunk:
                # near the context end a full chunk would overrun the cache; finish
                # with the per-token host loop instead of compiling a tail-sized scan
                tail, tail_stats = self.generate(
                    [token], min(want, seq_left), sampler, on_token=on_token,
                    stop_check=stop_check)
                out.extend(tail)
                stats.generated_tokens += len(tail)
                stats.token_ms.extend(tail_stats.token_ms)
                stats.infer_ms.extend(tail_stats.infer_ms)
                break
            # always run the compiled full-chunk program; a short tail (want < chunk)
            # just truncates the emitted tokens — cache entries past pos are dead and
            # overwritten by later writes at those positions
            loop = self._decode_loop(chunk, mode, self._window_for(self.pos + chunk))
            if self._measured_traffic is not None and stats.traffic_source != "measured":
                self._fill_traffic(stats, self._loop_traffic(chunk, mode, loop),
                                   per_tokens=chunk)
            t0 = time.perf_counter()
            with trace.span("engine.device_loop", {"chunk": chunk,
                                                   "pos": self.pos}):
                key, sub = jax.random.split(key)
                tokens, _, self.k_cache, self.v_cache = loop(
                    self.params, self.rope, token, self.k_cache, self.v_cache,
                    self.pos, sub, temperature, topp)
                tokens = np.asarray(tokens)[:want]
            dt_full = (time.perf_counter() - t0) * 1000.0
            _DISP_LOOP.observe(dt_full / 1000.0)
            _DECODE_TOKENS.inc(len(tokens))
            flight.event(None, "device_loop", chunk=chunk,
                         emitted=len(tokens), ms=round(dt_full, 3))
            stats.dispatch_ms.append(dt_full)
            # the dispatch always computes a full `chunk` of tokens even when the
            # emitted tail is shorter — divide by the compiled chunk size so
            # per-token stats reflect actual device cost
            dt_ms = dt_full / chunk
            for i, t in enumerate(tokens.tolist()):
                out.append(t)
                stats.generated_tokens += 1
                stats.token_ms.append(dt_ms)
                stats.infer_ms.append(dt_ms)
                if on_token is not None:
                    on_token(t)
                if stop_check is not None and stop_check(t):
                    done = True
                    self.pos += i + 1
                    break
            else:
                self.pos += len(tokens)
                token = int(tokens[-1])
        return out, stats
