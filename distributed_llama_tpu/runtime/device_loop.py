"""On-device multi-token decode loop: scan(forward + sample) in one compiled program.

The reference drives generation strictly token-by-token from the host (generate,
dllama.cpp:17-94): each token costs a host round trip to sample and re-dispatch. That is
a CPU-runtime artifact; the TPU-native shape of the loop is a `lax.scan` over decode
steps *inside* the jitted SPMD program — the sampled token feeds the next embedding
lookup on device, and the host gets a chunk of tokens back per dispatch instead of one.

Sampling runs on device with the reference Sampler's semantics (temperature softmax,
top-p nucleus with the (1-topp)/(n-1) pre-filter cutoff — src/tokenizer.cpp:307-415).
Temperature 0 (greedy argmax) matches the host sampler token-for-token; stochastic
sampling uses JAX's counter-based PRNG instead of the reference's xorshift*, so seeds
are not bit-compatible with the host Sampler (runtime/sampler.py keeps the exact
xorshift* port for host-side parity).

Under tensor parallelism the post-all-gather logits are replicated, so every device
computes the same sample — no extra collective is needed for the token broadcast (the
reference ships `pos` over TCP instead: sendPos, src/tasks.cpp:137-152).

Performance note (round 3): the round-2 measurement that found the device loop slower
was taken when forward() restacked the full KV caches through scan xs/ys every token —
the loop-carried copies it blamed were ~4 GB/token at 7B. forward() now carries the
caches with layer-indexed in-place updates and windowed attention reads
(models/forward.py), which removes that traffic for the host loop and the device loop
alike; what remains for the device loop to win is amortizing the ~1.5-3.5 ms
per-dispatch tunnel overhead across `n_steps` tokens per dispatch. Re-measure with
`python bench.py --device-loop N` (the axon tunnel was down for the remainder of round
3, so the post-redesign comparison is pending hardware).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..models.forward import forward
from ..models.spec import ModelSpec
from ..ops.rope import RopeTables
from ..parallel.mesh import AXIS_SP, AXIS_TP
from ..parallel.sharding import kv_cache_pspec_for_mesh, param_pspecs
from ..parallel.tp import _expand_pspec_tree


def device_sample(logits: jax.Array, key: jax.Array, temperature: jax.Array,
                  topp: jax.Array) -> jax.Array:
    """Sample one token id from a (vocab,) f32 logits row, reference semantics."""
    n = logits.shape[0]

    def greedy(_):
        return jnp.argmax(logits).astype(jnp.int32)

    def stochastic(u):
        probs = jax.nn.softmax(logits / temperature)

        def mult(u):
            csum = jnp.cumsum(probs)
            idx = jnp.searchsorted(csum, u * csum[-1], side="right")
            return jnp.minimum(idx, n - 1).astype(jnp.int32)

        def nucleus(u):
            # pre-filter cutoff (tokenizer.cpp:338-345), then nucleus over the sorted
            # survivors. Degenerate all-filtered case decays to argmax (the reference
            # reads probindex[-1], which is UB).
            cutoff = (1.0 - topp) / (n - 1)
            masked = jnp.where(probs >= cutoff, probs, 0.0)
            order = jnp.argsort(-masked)
            p = masked[order]
            csum = jnp.cumsum(p)
            over = csum > topp
            last = jnp.where(jnp.any(over), jnp.argmax(over), n - 1)
            r = u * csum[last]
            pick = jnp.searchsorted(csum, r, side="right")
            return order[jnp.minimum(pick, last)].astype(jnp.int32)

        return jax.lax.cond((topp > 0.0) & (topp < 1.0), nucleus, mult, u)

    u = jax.random.uniform(key)
    return jax.lax.cond(temperature == 0.0, greedy, stochastic, u)


def make_decode_loop(spec: ModelSpec, mesh, params, n_steps: int, *, mode: str = "greedy",
                     dtype=None, use_pallas: bool = False,
                     compress_collectives: bool = False, donate_cache: bool = True,
                     attn_window: int | None = None, cache_write: str = "inscan",
                     moe_sharding: str = "slice", fused_prologue: bool = False):
    """Build fn(params, rope, token, kc, vc, start_pos, key, temperature, topp) ->
    (tokens (n_steps,), last_logits (vocab,), kc, vc).

    `token` is the last prompt token (B=1); the loop decodes n_steps tokens, feeding
    each sample back as the next input. KV caches advance n_steps positions.

    `mode` is static: "greedy" compiles a pure argmax step (no sort anywhere — XLA may
    execute both sides of a runtime cond, and the nucleus path's full-vocab sort is
    expensive on TPU); "sample" compiles device_sample with runtime temperature/topp.
    """
    assert mode in ("greedy", "sample"), mode
    dtype = dtype or jnp.float32
    sp = mesh.shape.get(AXIS_SP, 1)
    if sp > 1 and cache_write != "deferred":
        # the in-scan (contiguous) ring walks the full sharded cache; the
        # deferred ring is STRIPED and honors the window (models/forward.py)
        attn_window = None
    param_specs = _expand_pspec_tree(params, param_pspecs(params, moe_sharding))
    kv_spec = kv_cache_pspec_for_mesh(mesh)
    rope_type = spec.rope_type

    fwd = functools.partial(forward, spec=spec, dtype=dtype, axis_name=AXIS_TP,
                            sp_axis_name=AXIS_SP if sp > 1 else None, sp_size=sp,
                            use_pallas=use_pallas,
                            compress_collectives=compress_collectives,
                            attn_window=attn_window, cache_write=cache_write,
                            fused_prologue=fused_prologue)

    def loop(p, rope_cos, rope_sin, token, kc, vc, start_pos, key, temperature, topp):
        rope = RopeTables(rope_cos, rope_sin, rope_type)

        def step(carry, i):
            token, row0, kc, vc = carry
            logits, kc, vc = fwd(p, rope=rope, tokens=token[None, None],
                                 k_cache=kc, v_cache=vc, start_pos=start_pos + i)
            row = logits[0, -1].astype(jnp.float32)
            if mode == "greedy":
                nxt = jnp.argmax(row).astype(jnp.int32)
            else:
                nxt = device_sample(row, jax.random.fold_in(key, i), temperature, topp)
            return (nxt, row, kc, vc), nxt

        row0 = jnp.zeros((spec.vocab_size,), jnp.float32)
        (tok, row, kc, vc), tokens = jax.lax.scan(
            step, (token, row0, kc, vc), jnp.arange(n_steps, dtype=jnp.int32))
        return tokens, row, kc, vc

    sharded = jax.shard_map(
        loop, mesh=mesh,
        in_specs=(param_specs, P(), P(), P(), kv_spec, kv_spec, P(), P(), P(), P()),
        out_specs=(P(), P(), kv_spec, kv_spec),
        check_vma=False,
    )
    donate = (4, 5) if donate_cache else ()
    jitted = jax.jit(sharded, donate_argnums=donate)

    def run(p, rope: RopeTables, token, kc, vc, start_pos, key, temperature=0.0,
            topp=0.9):
        return jitted(p, rope.cos, rope.sin, jnp.asarray(token, jnp.int32), kc, vc,
                      jnp.int32(start_pos), key, jnp.float32(temperature),
                      jnp.float32(topp))

    return run
