"""On-device multi-token decode loop: scan(forward + sample) in one compiled program.

The reference drives generation strictly token-by-token from the host (generate,
dllama.cpp:17-94): each token costs a host round trip to sample and re-dispatch. That is
a CPU-runtime artifact; the TPU-native shape of the loop is a `lax.scan` over decode
steps *inside* the jitted SPMD program — the sampled token feeds the next embedding
lookup on device, and the host gets a chunk of tokens back per dispatch instead of one.

Two loops live here: make_decode_loop (B=1, the --device-loop CLI path) and
make_batched_decode_loop (per-row positions/budgets/RNG — the BatchEngine's
K-step super-step; docs/SERVING.md). The batched loop samples with the host
Sampler's own xorshift* generator (implemented below on split uint32 halves,
bit-exact with runtime/sampler._random_u32) so a request's sample stream stays
one sequence across host- and device-sampled tokens.

Sampling runs on device with the reference Sampler's semantics (temperature softmax,
top-p nucleus with the (1-topp)/(n-1) pre-filter cutoff — src/tokenizer.cpp:307-415).
Temperature 0 (greedy argmax) matches the host sampler token-for-token; stochastic
sampling uses JAX's counter-based PRNG instead of the reference's xorshift*, so seeds
are not bit-compatible with the host Sampler (runtime/sampler.py keeps the exact
xorshift* port for host-side parity).

Under tensor parallelism the post-all-gather logits are replicated, so every device
computes the same sample — no extra collective is needed for the token broadcast (the
reference ships `pos` over TCP instead: sendPos, src/tasks.cpp:137-152).

Performance note (round 3): the round-2 measurement that found the device loop slower
was taken when forward() restacked the full KV caches through scan xs/ys every token —
the loop-carried copies it blamed were ~4 GB/token at 7B. forward() now carries the
caches with layer-indexed in-place updates and windowed attention reads
(models/forward.py), which removes that traffic for the host loop and the device loop
alike; what remains for the device loop to win is amortizing the ~1.5-3.5 ms
per-dispatch tunnel overhead across `n_steps` tokens per dispatch. Re-measure with
`python bench.py --device-loop N` (the axon tunnel was down for the remainder of round
3, so the post-redesign comparison is pending hardware).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..models.forward import forward
from ..models.spec import ModelSpec
from ..ops.rope import RopeTables
from ..resilience import faults
from ..parallel.mesh import AXIS_SP, AXIS_TP
from ..parallel.sharding import kv_cache_pspec_for_mesh, param_pspecs
from ..parallel.tp import _expand_pspec_tree


def _tp_axis(mesh, compress_collectives: bool) -> str | None:
    """AXIS_TP, or None when the tp axis has one member: a 1-member axis has
    nothing to reduce, so dropping the name elides every psum/all_gather AND
    lets the "fused" matmul policy fold residual adds into the kernels
    (illegal before a real TP merge). Compressed collectives keep the axis —
    their Q80 wire quantization is part of the numerics even over one
    member."""
    return AXIS_TP if (mesh.shape[AXIS_TP] > 1 or compress_collectives) else None


def device_sample_coin(logits: jax.Array, u: jax.Array, temperature: jax.Array,
                       topp: jax.Array) -> jax.Array:
    """Sample one token id from a (vocab,) f32 logits row, reference semantics.

    `u` is the uniform coin in [0, 1) — supplied by the caller so the batched
    loop can feed the on-device xorshift* stream that mirrors the host Sampler
    (the host draws exactly one coin per stochastic sample, so carrying the
    xorshift* state through the scan keeps host and device state in sync)."""
    n = logits.shape[0]

    def greedy(_):
        return jnp.argmax(logits).astype(jnp.int32)

    def stochastic(u):
        probs = jax.nn.softmax(logits / temperature)

        def mult(u):
            csum = jnp.cumsum(probs)
            idx = jnp.searchsorted(csum, u * csum[-1], side="right")
            return jnp.minimum(idx, n - 1).astype(jnp.int32)

        def nucleus(u):
            # pre-filter cutoff (tokenizer.cpp:338-345), then nucleus over the sorted
            # survivors. Degenerate all-filtered case decays to argmax (the reference
            # reads probindex[-1], which is UB).
            cutoff = (1.0 - topp) / (n - 1)
            masked = jnp.where(probs >= cutoff, probs, 0.0)
            order = jnp.argsort(-masked)
            p = masked[order]
            csum = jnp.cumsum(p)
            over = csum > topp
            last = jnp.where(jnp.any(over), jnp.argmax(over), n - 1)
            r = u * csum[last]
            pick = jnp.searchsorted(csum, r, side="right")
            return order[jnp.minimum(pick, last)].astype(jnp.int32)

        return jax.lax.cond((topp > 0.0) & (topp < 1.0), nucleus, mult, u)

    return jax.lax.cond(temperature == 0.0, greedy, stochastic, u)


def device_sample(logits: jax.Array, key: jax.Array, temperature: jax.Array,
                  topp: jax.Array) -> jax.Array:
    """device_sample_coin with the coin drawn from JAX's counter-based PRNG
    (B=1 loop; seeds are not bit-compatible with the host xorshift* Sampler)."""
    return device_sample_coin(logits, jax.random.uniform(key), temperature, topp)


# ------------------------------------------------------------------
# on-device xorshift* (the host Sampler's RNG, utils.cpp:79-90)
# ------------------------------------------------------------------
# The uint64 state is carried as two uint32 halves: jnp.uint64 silently
# downcasts without jax_enable_x64, and flipping that flag globally would
# change every f32 promotion in the model. All ops below are bit-exact with
# runtime/sampler._random_u32, so the BatchEngine can hand a host Sampler's
# state to the device loop and write the advanced state back afterwards.

_XSM_HI = 0x2545F491  # 0x2545F4914F6CDD1D, the xorshift* multiplier
_XSM_LO = 0x4F6CDD1D


def _mul32_wide(a: jax.Array, b) -> tuple[jax.Array, jax.Array]:
    """Full 32x32 -> 64-bit product as (hi32, lo32), in uint32 arithmetic."""
    a0, a1 = a & 0xFFFF, a >> 16
    b0, b1 = b & 0xFFFF, b >> 16
    p00, p01, p10, p11 = a0 * b0, a0 * b1, a1 * b0, a1 * b1
    mid = (p00 >> 16) + (p01 & 0xFFFF) + (p10 & 0xFFFF)  # < 2^18, no overflow
    lo = (p00 & 0xFFFF) | ((mid & 0xFFFF) << 16)
    hi = p11 + (p01 >> 16) + (p10 >> 16) + (mid >> 16)
    return hi, lo


def _xor_shr(hi, lo, n: int):
    """s ^ (s >> n) on a split uint64, 0 < n < 32."""
    return hi ^ (hi >> n), lo ^ ((lo >> n) | (hi << (32 - n)))


def _xor_shl(hi, lo, n: int):
    """s ^ (s << n) on a split uint64, 0 < n < 32."""
    return hi ^ ((hi << n) | (lo >> (32 - n))), lo ^ (lo << n)


def xorshift_star_step(hi: jax.Array, lo: jax.Array):
    """One xorshift* round; returns (hi', lo', out_u32). Vectorizes over any
    leading shape. Bit-exact with sampler._random_u32 (same state evolution,
    same high-32 output of the 64-bit multiply)."""
    hi, lo = _xor_shr(hi, lo, 12)
    hi, lo = _xor_shl(hi, lo, 25)
    hi, lo = _xor_shr(hi, lo, 27)
    # out = ((s * M) mod 2^64) >> 32 = hi32(lo*M_lo) + lo*M_hi + hi*M_lo (mod 2^32)
    ph, _ = _mul32_wide(lo, jnp.uint32(_XSM_LO))
    out = ph + lo * jnp.uint32(_XSM_HI) + hi * jnp.uint32(_XSM_LO)
    return hi, lo, out


def xorshift_coin(hi: jax.Array, lo: jax.Array):
    """Advance the state and return (hi', lo', coin in [0,1) f32) — the exact
    randomF32 mapping the host Sampler uses (utils.cpp:88-90)."""
    hi, lo, out = xorshift_star_step(hi, lo)
    return hi, lo, (out >> 8).astype(jnp.float32) * jnp.float32(1.0 / 16777216.0)


def make_decode_loop(spec: ModelSpec, mesh, params, n_steps: int, *, mode: str = "greedy",
                     dtype=None, use_pallas: bool = False,
                     compress_collectives: bool = False, donate_cache: bool = True,
                     attn_window: int | None = None, cache_write: str = "inscan",
                     moe_sharding: str = "slice", fused_prologue: bool = False):
    """Build fn(params, rope, token, kc, vc, start_pos, key, temperature, topp) ->
    (tokens (n_steps,), last_logits (vocab,), kc, vc).

    `token` is the last prompt token (B=1); the loop decodes n_steps tokens, feeding
    each sample back as the next input. KV caches advance n_steps positions.

    `mode` is static: "greedy" compiles a pure argmax step (no sort anywhere — XLA may
    execute both sides of a runtime cond, and the nucleus path's full-vocab sort is
    expensive on TPU); "sample" compiles device_sample with runtime temperature/topp.
    """
    assert mode in ("greedy", "sample"), mode
    dtype = dtype or jnp.float32
    sp = mesh.shape.get(AXIS_SP, 1)
    if sp > 1 and cache_write != "deferred":
        # the in-scan (contiguous) ring walks the full sharded cache; the
        # deferred ring is STRIPED and honors the window (models/forward.py)
        attn_window = None
    param_specs = _expand_pspec_tree(params, param_pspecs(params, moe_sharding))
    kv_spec = kv_cache_pspec_for_mesh(mesh)
    rope_type = spec.rope_type

    fwd = functools.partial(forward, spec=spec, dtype=dtype,
                            axis_name=_tp_axis(mesh, compress_collectives),
                            sp_axis_name=AXIS_SP if sp > 1 else None, sp_size=sp,
                            use_pallas=use_pallas,
                            compress_collectives=compress_collectives,
                            attn_window=attn_window, cache_write=cache_write,
                            fused_prologue=fused_prologue)

    # hot-path: traced
    def loop(p, rope_cos, rope_sin, token, kc, vc, start_pos, key, temperature, topp):
        rope = RopeTables(rope_cos, rope_sin, rope_type)

        def step(carry, i):
            token, row0, kc, vc = carry
            logits, kc, vc = fwd(p, rope=rope, tokens=token[None, None],
                                 k_cache=kc, v_cache=vc, start_pos=start_pos + i)
            row = logits[0, -1].astype(jnp.float32)
            if mode == "greedy":
                nxt = jnp.argmax(row).astype(jnp.int32)
            else:
                nxt = device_sample(row, jax.random.fold_in(key, i), temperature, topp)
            return (nxt, row, kc, vc), nxt

        row0 = jnp.zeros((spec.vocab_size,), jnp.float32)
        (tok, row, kc, vc), tokens = jax.lax.scan(
            step, (token, row0, kc, vc), jnp.arange(n_steps, dtype=jnp.int32))
        return tokens, row, kc, vc

    from ..compat import shard_map

    sharded = shard_map(
        loop, mesh=mesh,
        in_specs=(param_specs, P(), P(), P(), kv_spec, kv_spec, P(), P(), P(), P()),
        out_specs=(P(), P(), kv_spec, kv_spec),
        check_vma=False,
    )
    donate = (4, 5) if donate_cache else ()
    jitted = jax.jit(sharded, donate_argnums=donate)

    # hot-path
    def run(p, rope: RopeTables, token, kc, vc, start_pos, key, temperature=0.0,
            topp=0.9):
        faults.fire("device_loop.dispatch", n_steps=n_steps)
        return jitted(p, rope.cos, rope.sin, jnp.asarray(token, jnp.int32), kc, vc,
                      jnp.int32(start_pos), key, jnp.float32(temperature),
                      jnp.float32(topp))

    return run


# disallowed-logit fill for grammar masking (constrain/): finite so the
# softmax shift never meets inf-inf, large enough that exp underflows to 0
# exactly — the host mask path (batch_engine._advance_row) uses the SAME
# constant so host and device masked samples stay bit-compatible
MASK_NEG = -1e30


# hot-path: traced
def _apply_token_mask(rows, mrow):
    """Lower disallowed logits: `mrow` is the packed uint32 allowed bitmask
    gathered per row (..., W) from the constrain table; bit v&31 of word
    v>>5 covers token v. Universal rows (all-ones) make this the identity,
    so unconstrained co-batched rows are bit-identical to the unmasked
    program."""
    v = rows.shape[-1]
    vi = jnp.arange(v, dtype=jnp.int32)
    words = jnp.take(mrow, vi >> 5, axis=-1)  # (..., V)
    allowed = (words >> (vi & 31).astype(jnp.uint32)) & jnp.uint32(1)
    return jnp.where(allowed.astype(bool), rows, jnp.float32(MASK_NEG))


def make_batched_decode_loop(spec: ModelSpec, mesh, params, n_steps: int, *,
                             mode: str = "greedy", dtype=None,
                             use_pallas: bool = False,
                             compress_collectives: bool = False,
                             donate_cache: bool = True,
                             attn_window: int | None = None,
                             cache_write: str = "inscan",
                             moe_sharding: str = "slice",
                             fused_prologue: bool = False,
                             kv_block_tokens: int = 0,
                             paged_kernel: bool = False,
                             masked: bool = False):
    """Batched K-step super-step: `lax.scan` over n_steps decode steps for ALL
    cache rows at once, sampling on device — the serving-path generalization of
    make_decode_loop (B=1) that converts the BatchEngine's hot loop from one
    host sync per token to one per n_steps tokens.

    Builds fn(params, rope, tokens (B,), kc, vc, start_pos (B,), rng (B, 2)
    uint32 [hi, lo], temperature (B,), topp (B,), budget (B,)) ->
    (tokens (n_steps, B), last_tok (B,), pos (B,), rng (B, 2), kc, vc).

    The (last_tok, pos, rng) trailer is the loop's final carry, returned as
    DEVICE arrays: last_tok is each row's block-tail sample (its KV not yet
    ingested — exactly the next dispatch's input token), pos the row's
    position after its budgeted ingestions, rng the advanced xorshift*
    state. A pipelined scheduler (runtime/batch_engine.py) feeds them
    straight back as the next dispatch's (tokens, start_pos, rng) without
    waiting for the (n_steps, B) block's host transfer, so super-step N+1
    chains from N's device state while N is still being delivered host-side.

    Per-row carry: each row decodes at its own `start_pos` (continuous
    batching) and stops advancing after `budget[r]` steps — a parked row keeps
    riding the scan with its position pinned at min(pos, seq_len-1), so its
    garbage writes land on masked slots that the row's next real token
    overwrites (the same discipline the host scheduler's _park_positions
    uses). The scheduler sets budget below n_steps for rows near their
    max_tokens / context end, and 0 for empty slots.

    Sampling: `mode` is static like make_decode_loop's. "sample" carries each
    row's xorshift* state (split uint32 halves) and consumes exactly one coin
    per live stochastic sample — bit-compatible state evolution with the host
    Sampler, so the scheduler uploads sampler.state before the dispatch and
    writes the returned state back after. Greedy rows (temperature 0) draw no
    coins, matching the host.

    Under dp the row axis shards over the dp mesh axis (tokens/start_pos/rng/
    sampler params ride P(dp), like make_sharded_forward's batched step).

    kv_block_tokens > 0 selects the device-resident paged KV layout
    (docs/PAGED_KV.md): kc/vc are the (L, N, hk, bt, hs) block pool and the
    built fn takes a trailing (B, W) block-table argument mapping each
    row's virtual positions to pool blocks (loop-invariant across the scan;
    the scheduler ensures coverage for every budgeted write pre-dispatch).

    masked=True builds the grammar-constrained variant (constrain/,
    docs/SERVING.md "Constrained decoding"): the per-row automaton state
    rides the scan carry, each step gathers the state's packed bitmask row
    from the device-resident constrain table, lowers disallowed logits to
    MASK_NEG BEFORE the greedy argmax / split-uint32 sampler, and advances
    the state through the emitted token. run() then takes
    constrain=(cstate (B,) int32 GLOBAL states, mask (S, W) uint32,
    delta (S, V) int32) and appends the final automaton state to its
    outputs. Rows at state 0 (the universal row) sample identically to the
    unmasked program; the unmasked build is byte-for-byte today's program
    so its pinned dispatch signature is untouched.
    """
    from ..parallel.mesh import AXIS_DP

    assert mode in ("greedy", "sample"), mode
    dtype = dtype or jnp.float32
    sp = mesh.shape.get(AXIS_SP, 1)
    dp = mesh.shape.get(AXIS_DP, 1)
    assert sp == 1, "batched decode needs per-row cache positions (no sp ring)"
    paged = kv_block_tokens > 0
    assert not (paged and dp > 1), "paged KV is tp-only (no dp sharding)"
    param_specs = _expand_pspec_tree(params, param_pspecs(params, moe_sharding))
    kv_spec = (P(None, None, AXIS_TP) if paged
               else kv_cache_pspec_for_mesh(mesh))
    rope_type = spec.rope_type
    seq_len = spec.seq_len

    fwd = functools.partial(forward, spec=spec, dtype=dtype,
                            axis_name=_tp_axis(mesh, compress_collectives),
                            sp_axis_name=None, sp_size=1, use_pallas=use_pallas,
                            compress_collectives=compress_collectives,
                            attn_window=attn_window, cache_write=cache_write,
                            fused_prologue=fused_prologue,
                            block_tokens=kv_block_tokens,
                            paged_kernel=paged_kernel)

    # hot-path: traced
    def loop(p, rope_cos, rope_sin, tokens, kc, vc, start_pos, rng_hi, rng_lo,
             temperature, topp, budget, tables, cstate, cmask, cdelta):
        rope = RopeTables(rope_cos, rope_sin, rope_type)

        def step(carry, i):
            tok, pos, sh, sl, cst, kc, vc = carry
            live = i < budget  # (B,)
            # parked rows write scratch at their current position (clamped to
            # stay in-cache); reads mask slots >= start_pos so it is invisible,
            # and the row's next real decode overwrites it
            step_pos = jnp.where(live, pos, jnp.minimum(pos, seq_len - 1))
            logits, kc, vc = fwd(p, rope=rope, tokens=tok[:, None],
                                 k_cache=kc, v_cache=vc, start_pos=step_pos,
                                 block_tables=tables if paged else None)
            rows = logits[:, -1].astype(jnp.float32)  # (B, vocab)
            if masked:
                rows = _apply_token_mask(rows, cmask[cst])
            if mode == "greedy":
                nxt = jnp.argmax(rows, axis=-1).astype(jnp.int32)
            else:
                nsh, nsl, coin = xorshift_coin(sh, sl)
                nxt = jax.vmap(device_sample_coin)(rows, coin, temperature,
                                                   topp)
                drew = live & (temperature != 0.0)
                sh = jnp.where(drew, nsh, sh)
                sl = jnp.where(drew, nsl, sl)
            if masked:
                # advance the automaton through the emitted token (a masked
                # sample is always an allowed transition)
                cst = jnp.where(live, cdelta[cst, nxt], cst)
            tok = jnp.where(live, nxt, tok)
            pos = jnp.where(live, pos + 1, pos)
            return (tok, pos, sh, sl, cst, kc, vc), nxt

        (tok, pos, sh, sl, cst, kc, vc), toks = jax.lax.scan(
            step, (tokens, start_pos, rng_hi, rng_lo, cstate, kc, vc),
            jnp.arange(n_steps, dtype=jnp.int32))
        return toks, tok, pos, sh, sl, cst, kc, vc

    from ..compat import shard_map

    row = P(AXIS_DP) if dp > 1 else P()
    toks_out = P(None, AXIS_DP) if dp > 1 else P()

    if masked:
        in_specs = (param_specs, P(), P(), row, kv_spec, kv_spec, row, row,
                    row, row, row, row, P(), row, P(), P())
        out_specs = (toks_out, row, row, row, row, row, kv_spec, kv_spec)
        sharded = shard_map(loop, mesh=mesh, in_specs=in_specs,
                            out_specs=out_specs, check_vma=False)
    else:
        # the unmasked build keeps today's exact program arity so its
        # pinned compile-manifest signature is untouched (boolean policy)
        def plain(p, rope_cos, rope_sin, tokens, kc, vc, start_pos, rng_hi,
                  rng_lo, temperature, topp, budget, tables):
            cz = jnp.zeros(tokens.shape, jnp.int32)
            toks, tok, pos, sh, sl, _, kc, vc = loop(
                p, rope_cos, rope_sin, tokens, kc, vc, start_pos, rng_hi,
                rng_lo, temperature, topp, budget, tables, cz, None, None)
            return toks, tok, pos, sh, sl, kc, vc

        sharded = shard_map(
            plain, mesh=mesh,
            in_specs=(param_specs, P(), P(), row, kv_spec, kv_spec, row, row,
                      row, row, row, row, P()),
            out_specs=(toks_out, row, row, row, row, kv_spec, kv_spec),
            check_vma=False,
        )
    donate = (4, 5) if donate_cache else ()
    jitted = jax.jit(sharded, donate_argnums=donate)

    # hot-path
    def run(p, rope: RopeTables, tokens, kc, vc, start_pos, rng, temperature,
            topp, budget, tables=None, constrain=None):
        faults.fire("device_loop.batched_dispatch", n_steps=n_steps)
        rng = jnp.asarray(rng, jnp.uint32).reshape(-1, 2)
        if tables is None:
            tables = jnp.zeros((rng.shape[0], 1), jnp.int32)  # dense: unused
        args = (p, rope.cos, rope.sin, jnp.asarray(tokens, jnp.int32), kc, vc,
                jnp.asarray(start_pos, jnp.int32), rng[:, 0], rng[:, 1],
                jnp.asarray(temperature, jnp.float32),
                jnp.asarray(topp, jnp.float32), jnp.asarray(budget, jnp.int32),
                jnp.asarray(tables, jnp.int32))
        if masked:
            cstate, cmask, cdelta = constrain
            toks, tok, pos, sh, sl, cst, kc, vc = jitted(
                *args, jnp.asarray(cstate, jnp.int32), cmask, cdelta)
            return (toks, tok, pos, jnp.stack([sh, sl], axis=1), kc, vc,
                    cst)
        toks, tok, pos, sh, sl, kc, vc = jitted(*args)
        return toks, tok, pos, jnp.stack([sh, sl], axis=1), kc, vc

    return run


def make_batched_verify_loop(spec: ModelSpec, mesh, params, block: int, *,
                             mode: str = "greedy", dtype=None,
                             use_pallas: bool = False,
                             compress_collectives: bool = False,
                             donate_cache: bool = True,
                             attn_window: int | None = None,
                             cache_write: str = "inscan",
                             moe_sharding: str = "slice",
                             fused_prologue: bool = False,
                             kv_block_tokens: int = 0,
                             paged_kernel: bool = False,
                             masked: bool = False):
    """Batched draft-verify super-step: ONE (B, T=block) forward ingests each
    row's proposal block and on-device acceptance turns it into up to T
    tokens per row — the speculative-decoding counterpart of
    make_batched_decode_loop (docs/SERVING.md "Speculative decoding").

    Decode is HBM-bandwidth-bound: a T-token dispatch streams the quantized
    weight blocks ONCE for all T positions, so verifying a k-token draft
    costs roughly one decode step while delivering accept+1 tokens. Drafts
    are host-side per-slot n-gram proposals (runtime/speculative.py); this
    program verifies every row's block in one dispatch.

    Builds fn(params, rope, proposals (B, T), kc, vc, start_pos (B,),
    rng (B, 2) uint32 [hi, lo], temperature (B,), topp (B,), ndraft (B,)) ->
    (targets (T, B), acc (B,), last_tok (B,), pos (B,), rng (B, 2), kc, vc).

    Per row r: proposals[r] = [pending_token, draft_0..draft_{nd-1}, pad...]
    with nd = ndraft[r] (-1 parks the row: its start_pos must already be
    host-clamped a la _park_positions so all T scratch writes stay
    in-cache). The forward writes the whole block's KV at start_pos..+T-1;
    a target token is sampled at every position with the host Sampler's
    semantics, and acc[r] counts the leading drafts whose target matched —
    the standard speculative identity: emitted tokens are targets[0..acc],
    where targets[acc] is the correction (first mismatch's own sample) or
    the bonus token (full accept). Rejected positions hold KV computed from
    rejected inputs, but they sit beyond the verified frontier pos+acc+1
    where every read path masks them (the free-rollback discipline).

    The (last_tok, pos, rng) trailer is rewound to the verified frontier ON
    DEVICE: last_tok = targets[acc] (sampled, not yet ingested), pos =
    start_pos + acc + 1, and rng the xorshift* state after exactly acc+1
    coins for live stochastic rows (greedy rows draw none) — coin i of the
    stream samples target i, so accepted-or-corrected tokens consume coins
    in exactly the host Sampler's order and a chained scan dispatch
    (runtime/batch_engine.py) can consume the carry for ANY accept outcome.

    masked=True is the grammar-constrained variant (constrain/): the
    automaton state chain is advanced along each row's PROPOSAL tokens, so
    position i's target is sampled under the mask of the state reached
    after drafts 0..i-1 — masked verify validates an accepted block
    position-by-position, and a draft token the grammar disallows can
    never be accepted (its position's masked target cannot equal it). The
    returned frontier state is the automaton advanced through exactly the
    acc+1 EMITTED tokens (proposal-path states equal emitted-path states
    for every accepted position). run() takes constrain=(cstate, mask,
    delta) like the masked decode loop and appends the frontier state to
    its outputs; the unmasked build keeps today's program untouched.
    """
    from ..parallel.mesh import AXIS_DP

    assert mode in ("greedy", "sample"), mode
    assert block >= 2, "a verify block needs at least one draft position"
    dtype = dtype or jnp.float32
    sp = mesh.shape.get(AXIS_SP, 1)
    dp = mesh.shape.get(AXIS_DP, 1)
    assert sp == 1, "batched verify needs per-row cache positions (no sp ring)"
    paged = kv_block_tokens > 0
    assert not (paged and dp > 1), "paged KV is tp-only (no dp sharding)"
    param_specs = _expand_pspec_tree(params, param_pspecs(params, moe_sharding))
    kv_spec = (P(None, None, AXIS_TP) if paged
               else kv_cache_pspec_for_mesh(mesh))
    rope_type = spec.rope_type

    fwd = functools.partial(forward, spec=spec, dtype=dtype,
                            axis_name=_tp_axis(mesh, compress_collectives),
                            sp_axis_name=None, sp_size=1, use_pallas=use_pallas,
                            compress_collectives=compress_collectives,
                            attn_window=attn_window, cache_write=cache_write,
                            fused_prologue=fused_prologue,
                            block_tokens=kv_block_tokens,
                            paged_kernel=paged_kernel)

    # hot-path: traced
    def loop(p, rope_cos, rope_sin, proposals, kc, vc, start_pos, rng_hi,
             rng_lo, temperature, topp, ndraft, tables, cstate, cmask,
             cdelta):
        rope = RopeTables(rope_cos, rope_sin, rope_type)
        b = proposals.shape[0]
        live = ndraft >= 0  # (B,)
        logits, kc, vc = fwd(p, rope=rope, tokens=proposals, k_cache=kc,
                             v_cache=vc, start_pos=start_pos,
                             block_tables=tables if paged else None)
        rows = logits.astype(jnp.float32)  # (B, T, vocab)
        if masked:
            # automaton states along the PROPOSAL path: position i's target
            # is masked by the state after drafts 0..i-1 (st_chain[i]); the
            # chain equals the emitted-token path for every position up to
            # and including the first mismatch, which is all the scheduler
            # ever delivers
            sts = [cstate]
            for i in range(1, block):
                sts.append(cdelta[sts[-1], proposals[:, i]])
            st_chain = jnp.stack(sts)  # (T, B)
            rows = _apply_token_mask(rows, cmask[st_chain.T])  # (B, T, V)
        if mode == "greedy":
            targets = jnp.argmax(rows, axis=-1).astype(jnp.int32)  # (B, T)
        else:
            # T coins per row in host-stream order: coin i (and the state
            # after i+1 draws) samples the block's i-th emitted token
            def draw(carry, _):
                sh, sl = carry
                nsh, nsl, coin = xorshift_coin(sh, sl)
                return (nsh, nsl), (coin, nsh, nsl)

            _, (coins, shs, sls) = jax.lax.scan(
                draw, (rng_hi, rng_lo), None, length=block)
            sample_row = jax.vmap(device_sample_coin,
                                  in_axes=(0, 0, None, None))  # over T
            targets = jax.vmap(sample_row, in_axes=(0, 1, 0, 0))(
                rows, coins, temperature, topp)  # (B, T)
        # accepted length: leading draft positions whose target matched
        # (cumprod-of-matches sum), capped by the row's real draft count
        di = jnp.arange(block - 1, dtype=jnp.int32)
        match = ((targets[:, :-1] == proposals[:, 1:])
                 & (di[None, :] < ndraft[:, None]))
        acc = jnp.cumprod(match.astype(jnp.int32), axis=1).sum(axis=1)
        acc = jnp.where(live, acc, 0)
        ridx = jnp.arange(b)
        last = jnp.where(live, targets[ridx, acc], proposals[:, 0])
        pos = jnp.where(live, start_pos + acc + 1, start_pos)
        if mode == "sample":
            # rewind the rng carry to the verified frontier: the block
            # consumed exactly acc+1 coins (one per emitted token); greedy
            # rows drew none, matching the host Sampler
            drew = live & (temperature != 0.0)
            rng_hi = jnp.where(drew, shs[acc, ridx], rng_hi)
            rng_lo = jnp.where(drew, sls[acc, ridx], rng_lo)
        if masked:
            # frontier automaton state: the chain state at the accept
            # boundary advanced through the emitted correction/bonus token
            # (`last` was sampled under st_chain[acc]'s mask, so the
            # transition is always an allowed one)
            cst = jnp.where(live, cdelta[st_chain[acc, ridx], last], cstate)
            return targets.T, acc, last, pos, rng_hi, rng_lo, cst, kc, vc
        return targets.T, acc, last, pos, rng_hi, rng_lo, kc, vc

    from ..compat import shard_map

    row = P(AXIS_DP) if dp > 1 else P()
    mat = P(AXIS_DP, None) if dp > 1 else P()
    toks_out = P(None, AXIS_DP) if dp > 1 else P()
    if masked:
        sharded = shard_map(
            loop, mesh=mesh,
            in_specs=(param_specs, P(), P(), mat, kv_spec, kv_spec, row, row,
                      row, row, row, row, P(), row, P(), P()),
            out_specs=(toks_out, row, row, row, row, row, row, kv_spec,
                       kv_spec),
            check_vma=False,
        )
    else:
        # unmasked arity unchanged: the pinned verify[...] signatures in
        # perf/compile_manifest.json stay exactly as before (boolean policy)
        def plain(p, rope_cos, rope_sin, proposals, kc, vc, start_pos,
                  rng_hi, rng_lo, temperature, topp, ndraft, tables):
            cz = jnp.zeros(proposals.shape[:1], jnp.int32)
            return loop(p, rope_cos, rope_sin, proposals, kc, vc, start_pos,
                        rng_hi, rng_lo, temperature, topp, ndraft, tables,
                        cz, None, None)

        sharded = shard_map(
            plain, mesh=mesh,
            in_specs=(param_specs, P(), P(), mat, kv_spec, kv_spec, row, row,
                      row, row, row, row, P()),
            out_specs=(toks_out, row, row, row, row, row, kv_spec, kv_spec),
            check_vma=False,
        )
    donate = (4, 5) if donate_cache else ()
    jitted = jax.jit(sharded, donate_argnums=donate)

    # hot-path
    def run(p, rope: RopeTables, proposals, kc, vc, start_pos, rng,
            temperature, topp, ndraft, tables=None, constrain=None):
        faults.fire("device_loop.verify_dispatch", block=block)
        rng = jnp.asarray(rng, jnp.uint32).reshape(-1, 2)
        if tables is None:
            tables = jnp.zeros((rng.shape[0], 1), jnp.int32)  # dense: unused
        args = (p, rope.cos, rope.sin, jnp.asarray(proposals, jnp.int32), kc,
                vc, jnp.asarray(start_pos, jnp.int32), rng[:, 0], rng[:, 1],
                jnp.asarray(temperature, jnp.float32),
                jnp.asarray(topp, jnp.float32), jnp.asarray(ndraft, jnp.int32),
                jnp.asarray(tables, jnp.int32))
        if masked:
            cstate, cmask, cdelta = constrain
            toks, acc, tok, pos, sh, sl, cst, kc, vc = jitted(
                *args, jnp.asarray(cstate, jnp.int32), cmask, cdelta)
            return (toks, acc, tok, pos, jnp.stack([sh, sl], axis=1), kc, vc,
                    cst)
        toks, acc, tok, pos, sh, sl, kc, vc = jitted(*args)
        return toks, acc, tok, pos, jnp.stack([sh, sl], axis=1), kc, vc

    return run
