"""Paged (out-of-core) KV cache: host/disk-resident history + device hot ring.

TPU-native equivalent of the reference's `--kv-cache-storage disc`
(src/transformer.cpp:312-318, src/utils.cpp:50-67 — the KV cache mmap'd to disk
files so contexts larger than RAM still run, at page-fault speed). On TPU the
chip can only attend HBM-resident keys, so the same capacity valve is built the
flash-attention way instead of the mmap way:

- The device cache keeps a RING of the R most recent positions (slot = position
  mod R) — decode's hot window stays HBM-fast.
- Every committed row is also appended to an authoritative HOST store (RAM for
  "host", an np.memmap file pair for "disc" — the direct descendant of the
  reference's createMmap'd kvCache files).
- Attention over the cold history [0, pos-R) is computed ON HOST per layer
  (one jax.pure_callback per layer inside the layer scan) and merged with the
  device's hot segment by the flash-attention segment identity
  (ops/attention.py merge_attention_partials) — mathematically exact, not an
  approximation (no history truncation).

Cost model (honest): each decoded token reads the entire cold cache from host
memory — bytes = L * 2 * hk * (pos - R) * hs * itemsize — plus L small
host<->device callback round-trips. At 7B/16k ctx that is ~2-8 GB/token from
host DRAM/disk page cache: a capacity valve, not a fast path (the reference's
disc mode pays the same shape of cost through page faults). For speed at long
context, shard the cache over chips with --sp (ring attention) instead; use
paged mode when the context simply does not fit the chips you have.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np

from ..models.forward import forward, init_kv_cache
from ..models.spec import ModelSpec
from ..obs import metrics, trace
from ..ops.rope import RopeTables
from ..resilience import faults

_RESIDENT = metrics.gauge(
    "paged_resident_positions", "HBM hot-ring slots (--kv-cache-resident)")
_STORE_BYTES = metrics.gauge(
    "paged_store_bytes", "Authoritative host/disc KV store allocation")
_APPENDED = metrics.counter(
    "paged_appended_rows_total", "Positions committed to the host store")
_SPILL_BYTES = metrics.counter(
    "paged_spill_bytes_total", "Bytes written to the disc-backed store (mmap)")
_COLD_CALLS = metrics.counter(
    "paged_cold_attend_calls_total", "Host cold-attention callbacks served")
_COLD_BYTES = metrics.counter(
    "paged_cold_bytes_total", "Cold K/V bytes read from the host store")


class HostKVStore:
    """Authoritative full-context KV store on host RAM ("host") or an
    np.memmap'd file pair ("disc"). Layout (L, B, hk, S, hs), same axis order
    as the device caches.

    Storage (allocation, memmap files, owned-temp-dir weakref cleanup) is
    delegated to cache/block_pool.HostKVArena — the ONE host-spill backend
    (ISSUE 12 satellite: this module previously carried its own duplicate
    of that logic); this class keeps only the paged-attention semantics
    (append discipline + the per-layer cold-attention callback)."""

    def __init__(self, spec: ModelSpec, resident: int, *, batch: int = 1,
                 storage: str = "host", directory: str | None = None,
                 dtype=np.float32):
        from ..cache.block_pool import HostKVArena

        self.spec = spec
        self.resident = resident
        self.storage = storage
        shape = (spec.n_layers, batch, spec.n_kv_heads, spec.seq_len,
                 spec.head_size)
        self._arena = HostKVArena(shape, dtype, storage=storage,
                                  directory=directory)
        _RESIDENT.set(resident)
        _STORE_BYTES.set(self.nbytes())

    # storage facade: existing callers (engine.py seek/append paths, tests)
    # read .k/.v/.paths directly — keep them as live views of the arena
    @property
    def k(self):
        return self._arena.k

    @property
    def v(self):
        return self._arena.v

    @property
    def paths(self):
        return self._arena.paths

    @property
    def _owned_dir(self):
        return self._arena._owned_dir

    def cleanup(self) -> None:
        """Delete the cache file pair and its directory IF the arena created
        the directory itself (mkdtemp default). Idempotent."""
        self._arena.cleanup()

    def nbytes(self) -> int:
        return self._arena.nbytes()

    def append(self, k_rows: np.ndarray, v_rows: np.ndarray, pos: int) -> None:
        """Write the step's new rows (L, B, hk, T, hs) at positions
        [pos, pos+T)."""
        faults.fire("paged.append", pos=pos)
        t = k_rows.shape[3]
        self.k[:, :, :, pos:pos + t] = k_rows
        self.v[:, :, :, pos:pos + t] = v_rows
        _APPENDED.inc(t)
        if self.storage == "disc":
            _SPILL_BYTES.inc(k_rows.nbytes + v_rows.nbytes)

    def cold_attend(self, layer: int, q: np.ndarray, start_pos: int
                    ) -> tuple[np.ndarray, np.ndarray]:
        """Host-side attention partial over the cold history [0, start_pos-R).

        q: (B, T, hq, hs) f32. Returns (normalized out (B, T, hq, hs) f32,
        lse (B, T, hq) f32); an empty cold segment returns lse -inf (zero
        weight under the merge). All cold positions precede every query
        position, so no causal mask is needed."""
        faults.fire("paged.cold_attend", layer=layer)
        b, t, hq, hs = q.shape
        cold = max(0, int(start_pos) - self.resident)
        if cold <= 0:
            return (np.zeros((b, t, hq, hs), np.float32),
                    np.full((b, t, hq), -np.inf, np.float32))
        with trace.span("paged.cold_attend", {"layer": layer, "cold": cold}):
            return self._cold_attend_traced(layer, q, cold)

    def _cold_attend_traced(self, layer: int, q: np.ndarray, cold: int
                            ) -> tuple[np.ndarray, np.ndarray]:
        b, t, hq, hs = q.shape
        hk = self.k.shape[2]
        g = hq // hk
        kc = np.asarray(self.k[layer, :, :, :cold], np.float32)  # (B,hk,C,hs)
        vc = np.asarray(self.v[layer, :, :, :cold], np.float32)
        _COLD_CALLS.inc()
        _COLD_BYTES.inc(kc.nbytes + vc.nbytes)
        qg = q.reshape(b, t, hk, g, hs) * np.float32(1.0 / math.sqrt(hs))
        scores = np.einsum("btkgd,bkcd->btkgc", qg, kc)  # (B,T,hk,g,C)
        m = scores.max(axis=-1)
        e = np.exp(scores - m[..., None])
        l = e.sum(axis=-1)
        out = np.einsum("btkgc,bkcd->btkgd", e, vc) / l[..., None]
        lse = m + np.log(l)
        return (out.reshape(b, t, hq, hs).astype(np.float32),
                lse.reshape(b, t, hq).astype(np.float32))


def init_ring_cache(spec: ModelSpec, resident: int, *, batch: int = 1,
                    dtype=jnp.float32):
    """Device hot-ring caches: (L, B, hk, R, hs) — seq axis sized to the
    resident window instead of seq_len."""
    return init_kv_cache(spec, batch=batch, dtype=dtype, seq_len=resident)


def make_paged_step(spec: ModelSpec, store: HostKVStore, *, dtype=jnp.float32,
                    use_pallas: bool = False, fused_prologue: bool = False):
    """Jitted single-device paged forward step.

    Returns fn(params, rope, tokens, kc, vc, start_pos) ->
    (logits, kc, vc, (k_rows, v_rows)). The caller must append the returned
    rows to `store` (Engine.infer_chunk does) — the host store is the
    authoritative history the per-layer cold callback reads."""

    def cold_host(layer_idx, q, start_pos):
        return store.cold_attend(int(layer_idx), np.asarray(q, np.float32),
                                 int(start_pos))

    def paged_cold(layer_idx, q, start_pos):
        shapes = (jax.ShapeDtypeStruct(q.shape, jnp.float32),
                  jax.ShapeDtypeStruct(q.shape[:-1], jnp.float32))
        return jax.pure_callback(cold_host, shapes, layer_idx, q, start_pos)

    fwd = functools.partial(forward, spec=spec, dtype=dtype, axis_name=None,
                            use_pallas=use_pallas, cache_write="deferred",
                            attn_window=None, paged_cold=paged_cold,
                            fused_prologue=fused_prologue)
    rope_type = spec.rope_type

    def step(p, rope_cos, rope_sin, tokens, kc, vc, start_pos):
        rope = RopeTables(rope_cos, rope_sin, rope_type)
        return fwd(p, rope=rope, tokens=tokens, k_cache=kc, v_cache=vc,
                   start_pos=start_pos)

    jitted = jax.jit(step, donate_argnums=(4, 5))

    def run(p, rope: RopeTables, tokens, kc, vc, start_pos):
        return jitted(p, rope.cos, rope.sin, tokens, kc, vc, start_pos)

    return run
