from .sampler import Sampler  # noqa: F401
from .engine import Engine, GenerationStats  # noqa: F401
