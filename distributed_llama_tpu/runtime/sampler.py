"""Token sampler: greedy argmax / temperature softmax / top-p nucleus.

Behavior-parity port of the reference Sampler (src/tokenizer.cpp:307-415) including its
xorshift* RNG (src/utils.cpp:79-90) so seeded runs reproduce the reference's sampling
sequence exactly. Runs host-side on the logits vector (the reference samples on the root
CPU; here logits are one small device->host transfer per token). A fused on-device
sampler is a future optimization — EOS detection needs the decoded text host-side anyway
(SURVEY.md §7 hard parts).
"""

from __future__ import annotations

import numpy as np


def _random_u32(state: np.uint64) -> tuple[np.uint64, int]:
    """xorshift* (utils.cpp:79-86)."""
    s = int(state)
    s ^= (s >> 12) & 0xFFFFFFFFFFFFFFFF
    s = (s ^ (s << 25)) & 0xFFFFFFFFFFFFFFFF
    s ^= s >> 27
    out = ((s * 0x2545F4914F6CDD1D) & 0xFFFFFFFFFFFFFFFF) >> 32
    return np.uint64(s), int(out)


def _softmax(x: np.ndarray) -> np.ndarray:
    m = np.max(x)
    e = np.exp(x - m)
    return e / e.sum()


class Sampler:
    def __init__(self, vocab_size: int, temperature: float = 0.0, topp: float = 0.9,
                 seed: int = 0):
        self.vocab_size = vocab_size
        self.temperature = float(temperature)
        self.topp = float(topp)
        self.state = np.uint64(seed)

    def set_temp(self, temperature: float) -> None:
        self.temperature = float(temperature)

    def set_seed(self, seed: int) -> None:
        self.state = np.uint64(seed)

    # hot-path
    def _coin(self) -> float:
        self.state, u = _random_u32(self.state)
        return (u >> 8) / 16777216.0  # randomF32, utils.cpp:88-90

    def fast_forward(self, n_tokens: int) -> None:
        """Advance the xorshift* stream past the coins `n_tokens` already
        sampled tokens consumed — the RNG half of a durable-request resume
        (docs/FLEET.md "Resume protocol"): a replica re-admitting a request
        whose first k generated tokens were delivered elsewhere prefills
        prompt ⊕ those tokens and fast-forwards the sampler by k, so its
        continuation is byte-identical to the uninterrupted run. Every
        stochastic sample() draws EXACTLY one coin (mult and top-p alike);
        greedy (temperature 0) draws none, so this is a no-op there."""
        if self.temperature == 0.0:
            return
        for _ in range(n_tokens):
            self.state, _ = _random_u32(self.state)

    def sample(self, logits: np.ndarray) -> int:  # hot-path
        logits = np.asarray(logits, dtype=np.float32).reshape(-1)[: self.vocab_size]  # dlint: ignore[hot-sync] -- logits arrive host-side (the dispatch fence already paid the transfer); this is a dtype/shape normalize
        if self.temperature == 0.0:
            return int(np.argmax(logits))
        probs = _softmax(logits / self.temperature)
        coin = self._coin()
        if self.topp <= 0 or self.topp >= 1:
            return self._sample_mult(probs, coin)
        return self._sample_topp(probs, coin)

    # hot-path
    def _sample_mult(self, probs: np.ndarray, coin: float) -> int:
        cdf = np.cumsum(probs)
        idx = int(np.searchsorted(cdf, coin, side="right"))
        return min(idx, self.vocab_size - 1)

    # first argpartition selection width: the topp=0.9 nucleus of a peaked
    # softmax is almost always a handful of tokens, so one O(n) partition
    # beats the old full-survivor O(n log n) sort per token — host sampling
    # now sits directly on the delivery loop the pipelined batched scheduler
    # overlaps with device decode (docs/SERVING.md "Pipelined decode")
    _TOPP_SELECT = 64

    # hot-path
    def _sample_topp(self, probs: np.ndarray, coin: float) -> int:
        """Nucleus sampling with the reference's cutoff pre-filter
        (tokenizer.cpp:328-369), the sort taken over an np.argpartition
        top-M selection instead of every pre-filter survivor. M doubles
        until the selected mass covers topp (worst case: the full-sort
        fallback, the exact old path). Bit-identical with _sample_topp_full:
        the selection keeps EVERY survivor >= the partition pivot, so
        boundary ties are all present and the stable (prob desc, index asc)
        sort of the selection is exactly the full sort's prefix — same
        cumsum partials, same crossing index, same pick."""
        n = len(probs)
        cutoff = (1.0 - self.topp) / (n - 1)
        idx = np.nonzero(probs >= cutoff)[0]
        if len(idx) == 0:
            # degenerate params (huge temperature + tiny topp): nothing passes the
            # pre-filter; the reference indexes probindex[-1] (UB) — fall back to mult
            return self._sample_mult(probs, coin)
        p_all = probs[idx]
        m = self._TOPP_SELECT
        while True:
            if m < len(idx):
                part = np.argpartition(-p_all, m - 1)[:m]
                pivot = p_all[part].min()  # the m-th largest survivor prob
                cand = np.nonzero(p_all >= pivot)[0]
                order = idx[cand[np.argsort(-p_all[cand], kind="stable")]]
            else:
                # descending sort by prob over every survivor (stable, like
                # the reference qsort by prob only) — the pre-selection path
                order = idx[np.argsort(-p_all, kind="stable")]
            p = probs[order]
            csum = np.cumsum(p)
            cut = np.nonzero(csum > self.topp)[0]
            if len(cut) == 0 and m < len(idx):
                m *= 2  # selection mass short of topp: widen and retry
                continue
            last = cut[0] if len(cut) else len(p) - 1
            r = coin * csum[last]
            pick = int(np.searchsorted(csum[: last + 1], r, side="right"))
            pick = min(pick, last)
            return int(order[pick])  # dlint: ignore[hot-sync] -- order is host numpy (argsort of a host probs row); no device array reaches this function

    def _sample_topp_full(self, probs: np.ndarray, coin: float) -> int:
        """The pre-selection full-survivor-sort nucleus path, kept verbatim
        as the bit-identity oracle for _sample_topp (tests/test_pipeline.py
        asserts new == old over adversarial tie-heavy distributions)."""
        n = len(probs)
        cutoff = (1.0 - self.topp) / (n - 1)
        idx = np.nonzero(probs >= cutoff)[0]
        if len(idx) == 0:
            return self._sample_mult(probs, coin)
        order = idx[np.argsort(-probs[idx], kind="stable")]
        p = probs[order]
        csum = np.cumsum(p)
        cut = np.nonzero(csum > self.topp)[0]
        last = cut[0] if len(cut) else len(p) - 1
        r = coin * csum[last]
        pick = int(np.searchsorted(csum[: last + 1], r, side="right"))
        pick = min(pick, last)
        return int(order[pick])
