"""Tokenizer converters -> `.t` files.

Three sources, mirroring the reference's converter trio:
- llama2: sentencepiece `tokenizer.model` (convert-tokenizer-llama2.py). The
  sentencepiece package is not available in this image, so the ModelProto is parsed with
  a minimal protobuf wire-format reader (field 1 = repeated SentencePiece{1: piece,
  2: score, 3: type}) — same pieces/scores, no dependency.
- llama3: tiktoken-format `tokenizer.model` (base64 token + rank per line) with the 256
  reserved special tokens and the llama3 chat template (convert-tokenizer-llama3.py:13-76).
- hf: `tokenizer.json` BPE vocab + added_tokens (convert-tokenizer-hf.py:20-64), scores
  descending by rank.

Usage:
    python -m distributed_llama_tpu.converter.convert_tokenizer llama2 <dir> [out.t]
    python -m distributed_llama_tpu.converter.convert_tokenizer llama3 <dir> [out.t]
    python -m distributed_llama_tpu.converter.convert_tokenizer hf <dir> [out.t]
"""

from __future__ import annotations

import base64
import json
import os
import struct
import sys

from ..formats.tfile import TokenizerData, write_tokenizer

LLAMA2_CHAT_TEMPLATE = (
    "{% if messages[0]['role'] == 'system' %}...{% endif %}{% for message in messages %}"
    "{% if message['role'] == 'user' %}{{ bos_token + '[INST] ' + message['content'] + "
    "' [/INST]' }}{% elif message['role'] == 'assistant' %}{{ message['content'] + "
    "eos_token }}{% endif %}{% endfor %}")

LLAMA3_CHAT_TEMPLATE = (
    "{% set loop_messages = messages %}{% for message in loop_messages %}"
    "{% set content = '<|start_header_id|>' + message['role'] + '<|end_header_id|>\n\n'"
    "+ message['content'] | trim + '<|eot_id|>' %}{% if loop.index0 == 0 %}"
    "{% set content = bos_token + content %}{% endif %}{{ content }}{% endfor %}"
    "{% if add_generation_prompt %}"
    "{{ '<|start_header_id|>assistant<|end_header_id|>\n\n' }}{% endif %}")


# ---------------------------------------------------------------------------
# minimal protobuf wire parser for sentencepiece ModelProto
# ---------------------------------------------------------------------------


def _read_varint(buf: bytes, i: int) -> tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[i]
        i += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, i
        shift += 7


def _iter_fields(buf: bytes):
    i = 0
    while i < len(buf):
        tag, i = _read_varint(buf, i)
        field, wire = tag >> 3, tag & 7
        if wire == 0:  # varint
            val, i = _read_varint(buf, i)
        elif wire == 1:  # 64-bit
            val, i = buf[i:i + 8], i + 8
        elif wire == 2:  # length-delimited
            ln, i = _read_varint(buf, i)
            val, i = buf[i:i + ln], i + ln
        elif wire == 5:  # 32-bit
            val, i = buf[i:i + 4], i + 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val


def parse_sentencepiece_model(path: str) -> tuple[list[bytes], list[float]]:
    """Extract (pieces, scores) from a sentencepiece ModelProto file."""
    with open(path, "rb") as f:
        data = f.read()
    pieces: list[bytes] = []
    scores: list[float] = []
    for field, wire, val in _iter_fields(data):
        if field == 1 and wire == 2:  # repeated SentencePiece
            piece, score = b"", 0.0
            for f2, w2, v2 in _iter_fields(val):
                if f2 == 1 and w2 == 2:
                    piece = v2
                elif f2 == 2 and w2 == 5:
                    score = struct.unpack("<f", v2)[0]
            pieces.append(piece)
            scores.append(score)
    return pieces, scores


def convert_llama2(dir_path: str, out: str) -> None:
    pieces, scores = parse_sentencepiece_model(os.path.join(dir_path, "tokenizer.model"))
    # sentencepiece marks whitespace with U+2581 (convert-tokenizer-llama2.py:31)
    vocab = [p.decode("utf-8", "replace").replace("▁", " ").encode() for p in pieces]
    td = TokenizerData(vocab=vocab, scores=scores, bos_id=1, eos_id=2, chat_eos_id=2,
                       max_token_length=max(len(v) for v in vocab),
                       chat_template=LLAMA2_CHAT_TEMPLATE)
    write_tokenizer(out, td)
    print(f"✅ {out} ({len(vocab)} tokens)")


def convert_llama3(dir_path: str, out: str) -> None:
    """tiktoken-format model: 'base64token rank' lines + 256 reserved specials."""
    path = os.path.join(dir_path, "tokenizer.model")
    vocab: list[bytes] = []
    scores: list[float] = []
    with open(path, "rb") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            tok_b64, rank = line.split()
            vocab.append(base64.b64decode(tok_b64))
            scores.append(-float(int(rank)))
    n_base = len(vocab)
    specials = ["<|begin_of_text|>", "<|end_of_text|>",
                "<|reserved_special_token_0|>", "<|reserved_special_token_1|>",
                "<|finetune_right_pad_id|>", "<|step_id|>", "<|start_header_id|>",
                "<|end_header_id|>", "<|eom_id|>", "<|eot_id|>", "<|python_tag|>"]
    specials += [f"<|reserved_special_token_{i}|>" for i in range(2, 247)]
    for s in specials:
        vocab.append(s.encode())
        scores.append(-float(len(vocab)))
    bos = n_base + specials.index("<|begin_of_text|>")
    eos = n_base + specials.index("<|end_of_text|>")
    eot = n_base + specials.index("<|eot_id|>")
    td = TokenizerData(vocab=vocab, scores=scores, bos_id=bos, eos_id=eos,
                       chat_eos_id=eot, max_token_length=max(len(v) for v in vocab),
                       chat_template=LLAMA3_CHAT_TEMPLATE)
    write_tokenizer(out, td)
    print(f"✅ {out} ({len(vocab)} tokens)")


def convert_hf_tokenizer(dir_path: str, out: str) -> None:
    with open(os.path.join(dir_path, "tokenizer_config.json"), encoding="utf-8") as f:
        cfg = json.load(f)
    with open(os.path.join(dir_path, "tokenizer.json"), encoding="utf-8") as f:
        tj = json.load(f)
    assert tj["model"]["type"] == "BPE", tj["model"]["type"]
    vocab: list[bytes] = []
    scores: list[float] = []
    for token, idx in tj["model"]["vocab"].items():
        assert idx == len(vocab), "non-contiguous vocab"
        vocab.append(token.encode())
        scores.append(-float(idx))
    bos_id = eos_id = -1
    for at in tj.get("added_tokens", []):
        if at["id"] == len(vocab):
            vocab.append(at["content"].encode())
            scores.append(-float(at["id"]))
        if at["content"] == cfg.get("bos_token"):
            bos_id = at["id"]
        if at["content"] == cfg.get("eos_token"):
            eos_id = at["id"]
    td = TokenizerData(vocab=vocab, scores=scores, bos_id=bos_id, eos_id=eos_id,
                       chat_eos_id=eos_id, max_token_length=max(len(v) for v in vocab),
                       chat_template=cfg.get("chat_template"))
    write_tokenizer(out, td)
    print(f"✅ {out} ({len(vocab)} tokens)")


def main(argv=None):
    argv = argv or sys.argv[1:]
    if len(argv) < 2:
        print(__doc__)
        sys.exit(1)
    kind, dir_path = argv[0], argv[1]
    out = argv[2] if len(argv) > 2 else f"dllama_tokenizer_{kind}.t"
    {"llama2": convert_llama2, "llama3": convert_llama3,
     "hf": convert_hf_tokenizer}[kind](dir_path, out)


if __name__ == "__main__":
    main()
