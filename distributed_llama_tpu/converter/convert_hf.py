"""HF safetensors checkpoint -> `.m` converter (llama / mistral / mixtral / grok-class).

Counterpart of the reference converter/convert-hf.py with the same tensor plan and Q/K
rotary re-permutation (HF stores q/k in GPT-NeoX half-split order; the `.m` runtime uses
Meta interleaved order — convert-hf.py:12-15), but:
- streams tensor-by-tensor with numpy (no torch residency),
- includes the Mixtral router tensor `block_sparse_moe.gate.weight`, which the reference
  fork's plan omits (convert-hf.py:67-75) even though its own loader requires it
  (transformer.cpp:505) — an upstream bug, fixed here,
- supports tied embeddings (missing lm_head -> reuse embed_tokens).

Usage: python -m distributed_llama_tpu.converter.convert_hf <model_dir> <q40|q80|f16|f32> [out.m]
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

from ..formats.mfile import write_header, write_tensor
from ..models.spec import ArchType, HiddenAct, ModelSpec, RopeType
from ..quants import FloatType

FT = {"f32": FloatType.F32, "f16": FloatType.F16, "q40": FloatType.Q40,
      "q80": FloatType.Q80}


def permute_rotary(w: np.ndarray, n_heads: int) -> np.ndarray:
    """NeoX half-split -> interleaved rotary layout (reference permute, convert-hf.py:12-15)."""
    out_dim = w.shape[0]
    return (w.reshape(n_heads, 2, out_dim // n_heads // 2, *w.shape[1:])
            .swapaxes(1, 2).reshape(w.shape))


def spec_from_config(config: dict, max_seq_len: int = 0) -> ModelSpec:
    arch_map = {"llama": ArchType.LLAMA, "mistral": ArchType.LLAMA,
                "mixtral": ArchType.MIXTRAL}
    arch = arch_map.get(config["model_type"])
    if arch is None:
        raise ValueError(f"unsupported model_type {config['model_type']!r}")
    act = {"gelu": HiddenAct.GELU, "silu": HiddenAct.SILU}[config.get("hidden_act", "silu")]
    rs = config.get("rope_scaling") or {}
    rope_type = RopeType.UNKNOWN
    if rs:
        rope_type = {"llama3": RopeType.LLAMA3_1}.get(rs.get("rope_type"))
        if rope_type is None:
            raise ValueError(f"unsupported rope scaling {rs.get('rope_type')!r}")
    return ModelSpec(
        arch_type=arch,
        dim=config["hidden_size"],
        hidden_dim=config["intermediate_size"],
        n_layers=config["num_hidden_layers"],
        n_heads=config["num_attention_heads"],
        n_kv_heads=config.get("num_key_value_heads", config["num_attention_heads"]),
        vocab_size=config["vocab_size"],
        seq_len=max_seq_len or config["max_position_embeddings"],
        n_experts=config.get("num_local_experts", 0),
        n_active_experts=config.get("num_experts_per_tok", 0),
        hidden_act=act,
        rope_theta=float(config.get("rope_theta", 10000.0)),
        rope_type=rope_type,
        rope_scaling_factor=float(rs.get("factor", 0)),
        rope_scaling_low_freq_factor=float(rs.get("low_freq_factor", 0)),
        rope_scaling_high_freq_factor=float(rs.get("high_freq_factor", 0)),
        rope_scaling_orig_max_seq_len=int(rs.get("original_max_position_embeddings", 0)),
    )


class HfCheckpoint:
    """Lazy multi-file safetensors reader returning numpy arrays."""

    def __init__(self, model_dir: str):
        from safetensors import safe_open

        self.files = sorted(
            os.path.join(model_dir, f) for f in os.listdir(model_dir)
            if f.endswith(".safetensors"))
        if not self.files:
            raise FileNotFoundError(f"no .safetensors files in {model_dir}")
        self._open = safe_open
        self._handles: dict[str, object] = {}
        self._index: dict[str, str] = {}
        for path in self.files:
            # framework="pt": HF checkpoints are commonly bf16, which numpy lacks
            with safe_open(path, framework="pt") as f:
                for key in f.keys():
                    self._index[key] = path

    def __contains__(self, key: str) -> bool:
        return key in self._index

    def get(self, key: str) -> np.ndarray:
        path = self._index[key]
        if path not in self._handles:
            self._handles.clear()  # keep one file resident
            self._handles[path] = self._open(path, framework="pt")
        t = self._handles[path].get_tensor(key)
        return t.float().numpy()


def tensor_plan(spec: ModelSpec, ckpt: HfCheckpoint):
    """Yield (name-in-.m-order, array) from HF names (plan: convert-hf.py:52-90)."""

    def get(key, transform=None):
        t = ckpt.get(key)
        if t.dtype != np.float32:
            t = t.astype(np.float32)
        return transform(t) if transform else t

    yield "embedding", get("model.embed_tokens.weight")
    for l in range(spec.n_layers):
        pre = f"model.layers.{l}"
        yield "wq", get(f"{pre}.self_attn.q_proj.weight",
                        lambda w: permute_rotary(w, spec.n_heads))
        yield "wk", get(f"{pre}.self_attn.k_proj.weight",
                        lambda w: permute_rotary(w, spec.n_kv_heads))
        yield "wv", get(f"{pre}.self_attn.v_proj.weight")
        yield "wo", get(f"{pre}.self_attn.o_proj.weight")
        if spec.is_moe:
            yield "router", get(f"{pre}.block_sparse_moe.gate.weight")
            for e in range(spec.n_experts):
                ep = f"{pre}.block_sparse_moe.experts.{e}"
                yield "moe_up", get(f"{ep}.w3.weight")
                yield "moe_gate", get(f"{ep}.w1.weight")
                yield "moe_down", get(f"{ep}.w2.weight")
        else:
            yield "w1", get(f"{pre}.mlp.gate_proj.weight")
            yield "w2", get(f"{pre}.mlp.down_proj.weight")
            yield "w3", get(f"{pre}.mlp.up_proj.weight")
        yield "rms_att", get(f"{pre}.input_layernorm.weight")
        yield "rms_ffn", get(f"{pre}.post_attention_layernorm.weight")
    yield "rms_final", get("model.norm.weight")
    if "lm_head.weight" in ckpt:
        yield "wcls", get("lm_head.weight")
    else:  # tied embeddings
        yield "wcls", get("model.embed_tokens.weight")


def convert(model_dir: str, ftype: FloatType, out_path: str,
            max_seq_len: int = 0) -> ModelSpec:
    with open(os.path.join(model_dir, "config.json")) as f:
        config = json.load(f)
    spec = spec_from_config(config, max_seq_len)
    ckpt = HfCheckpoint(model_dir)
    norm_names = {"embedding", "rms_att", "rms_ffn", "rms_moe", "rms_ffn2", "rms_final"}
    with open(out_path, "wb") as f:
        write_header(f, spec, ftype)
        for name, tensor in tensor_plan(spec, ckpt):
            ft = FloatType.F32 if name in norm_names else ftype
            write_tensor(f, tensor, ft)
            print(f"🔶 wrote {name} {tensor.shape} as "
                  f"{'f32' if name in norm_names else ftype.name.lower()}")
    print(f"✅ {out_path}")
    return spec


def main(argv=None):
    argv = argv or sys.argv[1:]
    if len(argv) < 2:
        print(__doc__)
        sys.exit(1)
    model_dir, ft = argv[0], FT[argv[1]]
    out = argv[2] if len(argv) > 2 else f"dllama_{os.path.basename(model_dir)}_{argv[1]}.m"
    convert(model_dir, ft, out)


if __name__ == "__main__":
    main()
