"""Meta llama checkpoint (consolidated.*.pth shards) -> `.m` converter.

Counterpart of reference converter/convert-llama.py: concatenates tensor shards across
the consolidated files along the correct parallel axis (column-parallel weights cat on
axis 0; row-parallel wo/w2 and the embedding cat on axis 1 — convert-llama.py:74-91),
norms and embedding forced F32, streamed one tensor at a time.

Usage: python -m distributed_llama_tpu.converter.convert_llama <modelDir> <q40|q80|f16|f32> [out.m]
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

from ..formats.mfile import write_header, write_tensor
from ..models.spec import ArchType, ModelSpec
from ..quants import FloatType
from .convert_hf import FT

# row-parallel (input-dim sharded -> cat axis 1); everything else is axis 0.
# suffix-matched: per-layer keys arrive as "layers.N.attention.wo.weight"
_AXIS1_SUFFIXES = (".attention.wo.weight", ".feed_forward.w2.weight",
                   "tok_embeddings.weight")


def _load_shards(model_dir: str):
    import torch

    paths = sorted(p for p in os.listdir(model_dir) if p.startswith("consolidated."))
    if not paths:
        raise FileNotFoundError(f"no consolidated.*.pth in {model_dir}")
    shards = []
    for p in paths:
        print(f"💿 loading {p}...")
        shards.append(torch.load(os.path.join(model_dir, p), map_location="cpu",
                                 weights_only=True, mmap=True))
    return shards


def _get(shards, key: str) -> np.ndarray:
    parts = [s[key] for s in shards]
    if len(parts) == 1 or parts[0].ndim == 1:
        t = parts[0]
    else:
        import torch

        axis = 1 if key.endswith(_AXIS1_SUFFIXES) else 0
        t = torch.cat(parts, dim=axis)
    return t.float().numpy()


def spec_from_params(params: dict, vocab_size: int, max_seq_len: int) -> ModelSpec:
    n_heads = params["n_heads"]
    dim = params["dim"]
    # meta params.json stores the ffn multiplier recipe; hidden_dim is derivable but the
    # tensors carry it directly, so callers pass it in via probe (see convert()).
    return ModelSpec(
        arch_type=ArchType.LLAMA,
        dim=dim,
        hidden_dim=params["__hidden_dim__"],
        n_layers=params["n_layers"],
        n_heads=n_heads,
        n_kv_heads=params.get("n_kv_heads", n_heads),
        vocab_size=vocab_size,
        seq_len=max_seq_len,
        rope_theta=float(params.get("rope_theta", 10000.0)),
    )


def convert(model_dir: str, ftype: FloatType, out_path: str,
            max_seq_len: int = 2048) -> ModelSpec:
    with open(os.path.join(model_dir, "params.json")) as f:
        params = json.load(f)
    shards = _load_shards(model_dir)
    emb = _get(shards, "tok_embeddings.weight")
    vocab_size, _ = emb.shape
    params["__hidden_dim__"] = sum(s["layers.0.feed_forward.w1.weight"].shape[0]
                                   for s in shards)
    spec = spec_from_params(params, vocab_size, max_seq_len)

    def plan():
        yield "embedding", emb
        for l in range(spec.n_layers):
            pre = f"layers.{l}"
            yield "wq", _get(shards, f"{pre}.attention.wq.weight")
            yield "wk", _get(shards, f"{pre}.attention.wk.weight")
            yield "wv", _get(shards, f"{pre}.attention.wv.weight")
            yield "wo", _get(shards, f"{pre}.attention.wo.weight")
            yield "w1", _get(shards, f"{pre}.feed_forward.w1.weight")
            yield "w2", _get(shards, f"{pre}.feed_forward.w2.weight")
            yield "w3", _get(shards, f"{pre}.feed_forward.w3.weight")
            yield "rms_att", _get(shards, f"{pre}.attention_norm.weight")
            yield "rms_ffn", _get(shards, f"{pre}.ffn_norm.weight")
        yield "rms_final", _get(shards, "norm.weight")
        yield "wcls", _get(shards, "output.weight")

    norm_names = {"embedding", "rms_att", "rms_ffn", "rms_final"}
    with open(out_path, "wb") as f:
        write_header(f, spec, ftype)
        for name, tensor in plan():
            ft = FloatType.F32 if name in norm_names else ftype
            write_tensor(f, tensor, ft)
            print(f"🔶 wrote {name} {tensor.shape}")
    print(f"✅ {out_path}")
    return spec


def main(argv=None):
    argv = argv or sys.argv[1:]
    if len(argv) < 2:
        print(__doc__)
        sys.exit(1)
    out = argv[2] if len(argv) > 2 else "dllama_model.m"
    convert(argv[0], FT[argv[1]], out)


if __name__ == "__main__":
    main()
