"""Counters / gauges / histograms with Prometheus text exposition.

Dependency-free replacement for prometheus_client, scoped to what the
serving stack needs:

- `Counter` — monotonically increasing float (tokens, requests, rollbacks).
- `Gauge` — set/inc/dec value (slot occupancy, queue depth, resident pages).
- `Histogram` — fixed log-scale buckets with cumulative counts + sum + count
  (latencies: TTFT, TPOT, queue wait, per-dispatch times). Buckets are fixed
  at construction — observation is a bisect + one add under the metric's
  lock, cheap enough for per-dispatch hot paths.
- `Registry.render()` — Prometheus text exposition format 0.0.4, served by
  api_server's `GET /metrics`.
- `Registry.snapshot()` — the same data as plain JSON-able dicts, served by
  `GET /v1/stats`.

Metric constructors are get-or-create on (name) so module wiring can declare
metrics at call sites without import-order coupling; re-declaring a name with
a different type or label set raises (silent merging would corrupt scrapes).

Labels follow the prometheus_client child model: a metric declared with
`labelnames` is a family; `.labels(k=v)` returns the child holding the
values. Unlabeled metrics hold their value directly.

All values are process-local and reset on restart, exactly like
prometheus_client's default registry; rates are the scraper's job.
"""

from __future__ import annotations

import math
import threading
from bisect import bisect_left

__all__ = ["Counter", "Gauge", "Histogram", "Registry", "REGISTRY",
           "counter", "gauge", "histogram", "log_buckets", "render",
           "snapshot"]


def log_buckets(lo: float, hi: float, per_decade: int = 4) -> tuple[float, ...]:
    """Log-scale bucket upper bounds from `lo` to >= `hi`, `per_decade` per
    decade, rounded to 4 significant digits so the exposition's `le` labels
    are stable across platforms (no 0.30000000000000004)."""
    assert 0 < lo < hi and per_decade >= 1
    out = []
    i = math.floor(per_decade * math.log10(lo) + 0.5)
    while True:
        b = 10.0 ** (i / per_decade)
        b = float(f"{b:.4g}")
        out.append(b)
        if b >= hi:
            return tuple(out)
        i += 1


# latency buckets in SECONDS (Prometheus convention): 100 µs .. 100 s
DEFAULT_TIME_BUCKETS = log_buckets(1e-4, 100.0, per_decade=4)
# size buckets (tokens, rows, bytes-ish counts): 1 .. 100k
DEFAULT_SIZE_BUCKETS = log_buckets(1.0, 1e5, per_decade=4)


def _fmt(v: float) -> str:
    """Prometheus sample value formatting: integers without the trailing .0."""
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    return repr(int(f)) if f.is_integer() and abs(f) < 1e15 else repr(f)


def _label_str(labelnames: tuple[str, ...], labelvalues: tuple[str, ...]) -> str:
    if not labelnames:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"'
                     for k, v in zip(labelnames, labelvalues))
    return "{" + inner + "}"


def _escape_label(v: str) -> str:
    return str(v).replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")


class _Metric:
    """Family: owns children keyed by label values; unlabeled metrics are
    their own single child."""

    typ = "untyped"

    def __init__(self, name: str, help: str, labelnames: tuple[str, ...] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._children: dict[tuple[str, ...], _Metric] = {}
        self._lock = threading.Lock()  # guards: _children
        self._init_value()

    def _init_value(self) -> None:
        self.value = 0.0

    def labels(self, **kv) -> "_Metric":
        assert set(kv) == set(self.labelnames), (
            f"{self.name}: labels {sorted(kv)} != declared {self.labelnames}")
        key = tuple(str(kv[k]) for k in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = type(self)(self.name, self.help)
                self._children[key] = child
        return child

    def _samples(self) -> list[tuple[str, str, float]]:
        """[(suffix, label_str, value)] for exposition."""
        raise NotImplementedError

    def _iter_children(self):
        if self.labelnames:
            with self._lock:
                items = sorted(self._children.items())
            for key, child in items:
                yield _label_str(self.labelnames, key), child
        else:
            yield "", self

    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.typ}"]
        for lbl, child in self._iter_children():
            for suffix, extra_lbl, v in child._samples():
                # histogram bucket samples carry their own {le=...}; merge
                lab = lbl
                if extra_lbl:
                    lab = (lbl[:-1] + "," + extra_lbl[1:]) if lbl else extra_lbl
                lines.append(f"{self.name}{suffix}{lab} {_fmt(v)}")
        return "\n".join(lines)

    def snapshot(self):
        if self.labelnames:
            return {lbl or "{}": child.snapshot()
                    for lbl, child in self._iter_children()}
        return self._snapshot_self()

    def _snapshot_self(self):
        return self.value


class Counter(_Metric):
    typ = "counter"

    def inc(self, v: float = 1.0) -> None:
        assert v >= 0, f"counter {self.name} decremented by {v}"
        with self._lock:
            self.value += v

    def _samples(self):
        return [("", "", self.value)]


class Gauge(_Metric):
    typ = "gauge"

    def _init_value(self) -> None:
        self.value = 0.0
        self._fn = None

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)

    def inc(self, v: float = 1.0) -> None:
        with self._lock:
            self.value += v

    def dec(self, v: float = 1.0) -> None:
        with self._lock:
            self.value -= v

    def set_function(self, fn) -> None:
        """Evaluate `fn()` at scrape time instead of a stored value — the
        prometheus_client callback-gauge idiom, for values that are a
        *reading* of live state (e.g. seconds since the last scheduler
        dispatch) rather than an event stream. A raising callback degrades
        to the last stored value: a scrape must never 500 because the
        subject died (that being exactly when the scrape matters)."""
        self._fn = fn

    def _read(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:
                pass
        return self.value

    def _samples(self):
        return [("", "", self._read())]

    def _snapshot_self(self):
        return self._read()


class Histogram(_Metric):
    typ = "histogram"

    def __init__(self, name: str, help: str, labelnames: tuple[str, ...] = (),
                 buckets: tuple[float, ...] = DEFAULT_TIME_BUCKETS):
        self.buckets = tuple(sorted(buckets))
        assert self.buckets, "histogram needs at least one finite bucket"
        super().__init__(name, help, labelnames)

    def _init_value(self) -> None:
        self.counts = [0] * (len(self.buckets) + 1)  # +1: the +Inf bucket
        self.sum = 0.0
        self.count = 0

    def labels(self, **kv):
        # children must share the family's bucket layout
        child = super().labels(**kv)
        child.buckets = self.buckets
        if len(child.counts) != len(self.buckets) + 1:
            child._init_value()
        return child

    def observe(self, v: float) -> None:
        # bisect_left: a value exactly on a bound belongs IN that bucket
        # (Prometheus le="x" means observations <= x)
        i = bisect_left(self.buckets, v)
        with self._lock:
            self.counts[i] += 1
            self.sum += v
            self.count += 1

    def _samples(self):
        out = []
        cum = 0
        with self._lock:
            counts = list(self.counts)
            total, s = self.count, self.sum
        for b, c in zip(self.buckets, counts):
            cum += c
            out.append(("_bucket", '{le="' + _fmt(b) + '"}', cum))
        out.append(("_bucket", '{le="+Inf"}', total))
        out.append(("_sum", "", s))
        out.append(("_count", "", total))
        return out

    def _snapshot_self(self):
        with self._lock:
            counts = list(self.counts)
            total, s = self.count, self.sum
        return {"count": total, "sum": s,
                "buckets": {_fmt(b): c for b, c in zip(self.buckets, counts)},
                "overflow": counts[-1]}


class Registry:
    def __init__(self):
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()  # guards: _metrics

    def _get_or_create(self, cls, name: str, help: str, labelnames=(), **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                assert type(m) is cls and m.labelnames == tuple(labelnames), (
                    f"metric {name} re-declared as {cls.__name__}"
                    f"({labelnames}) but exists as {type(m).__name__}"
                    f"({m.labelnames})")
                if cls is Histogram:
                    # a silent bucket-layout merge would put a second call
                    # site's observations in wrong-scale buckets
                    want = tuple(sorted(kw.get("buckets",
                                               DEFAULT_TIME_BUCKETS)))
                    assert m.buckets == want, (
                        f"histogram {name} re-declared with buckets {want} "
                        f"but exists with {m.buckets}")
                return m
            m = cls(name, help, tuple(labelnames), **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str, labelnames=()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str, labelnames=()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str, labelnames=(),
                  buckets: tuple[float, ...] = DEFAULT_TIME_BUCKETS
                  ) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def render(self) -> str:
        """Prometheus text exposition (format 0.0.4), trailing newline
        included per spec."""
        with self._lock:
            metrics = [self._metrics[k] for k in sorted(self._metrics)]
        return "\n".join(m.render() for m in metrics) + "\n"

    def snapshot(self) -> dict:
        """JSON-able {name: value|histogram-dict} of every metric."""
        with self._lock:
            metrics = dict(self._metrics)
        return {name: m.snapshot() for name, m in sorted(metrics.items())}

    def clear(self) -> None:
        """Drop every metric (tests only — live handles go stale)."""
        with self._lock:
            self._metrics.clear()


REGISTRY = Registry()

# module-level conveniences bound to the default registry — the repo's wiring
# calls these at use sites (get-or-create keeps that cheap and order-free)
counter = REGISTRY.counter
gauge = REGISTRY.gauge
histogram = REGISTRY.histogram
render = REGISTRY.render
snapshot = REGISTRY.snapshot
