"""Process self-telemetry: the serving process's own health on /metrics.

A replica exposed rich request metrics but nothing about ITSELF — no uptime
(restart loops invisible), no RSS (a leaking prefix-cache pool looked like
healthy traffic), no thread count (handler-thread leaks invisible), and the
tracer's `dropped_events` truncation counter lived only inside `--trace`
dumps. `install_process_metrics()` registers callback gauges for all of
these plus a Prometheus info-style `dllama_build_info{python,jax}` gauge
(constant 1; the labels are the data) so a fleet scrape can tell which
interpreter/jax build each replica runs — version skew during a rolling
upgrade is exactly when per-replica attribution matters.

Dependency discipline: versions come from importlib.metadata, NOT from
importing jax — the fleet router calls this too and must stay a ~stdlib
process (the PR 6 lazy-import work keeps ~350 MB of jax out of it).
Idempotent: callers re-invoke freely (api_server serve(), router serve,
tests); gauges are get-or-create and the callbacks are stateless reads.
"""

from __future__ import annotations

import os
import sys
import threading
import time

from . import metrics, trace

__all__ = ["install_process_metrics"]

_START_T = time.monotonic()  # import time ~ process start for our entrypoints


def _rss_bytes() -> float:
    """Resident set size via resource.getrusage. Linux reports ru_maxrss in
    KiB (macOS in bytes) — and it is the PEAK, which for a long-lived server
    is the honest capacity-planning number anyway."""
    import resource

    rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    return float(rss if sys.platform == "darwin" else rss * 1024)


_VERSIONS: dict[str, str] = {}


def _dist_version(name: str) -> str:
    # memoized: importlib.metadata scans dist-info on every call, and
    # install_process_metrics runs once per serve() (tests spin many)
    if name not in _VERSIONS:
        try:
            from importlib.metadata import version

            _VERSIONS[name] = version(name)
        except Exception:
            _VERSIONS[name] = "unavailable"
    return _VERSIONS[name]


def install_process_metrics() -> None:
    metrics.gauge(
        "dllama_uptime_seconds",
        "Seconds since this serving process started",
    ).set_function(lambda: time.monotonic() - _START_T)
    metrics.gauge(
        "dllama_process_rss_bytes",
        "Peak resident set size (resource.getrusage ru_maxrss)",
    ).set_function(_rss_bytes)
    metrics.gauge(
        "dllama_threads",
        "Live Python threads (threading.active_count)",
    ).set_function(threading.active_count)
    metrics.gauge(
        "dllama_tracer_dropped_events",
        "Span events the bounded trace ring has dropped (0 when tracing "
        "is disabled) — a truncated --trace//v1/trace export is visible "
        "on /metrics before anyone opens the file",
    ).set_function(
        lambda: (trace.current().dropped_events
                 if trace.current() is not None else 0))
    info = metrics.gauge(
        "dllama_build_info",
        "Build/runtime identity (constant 1; the labels are the data)",
        labelnames=("python", "jax"))
    info.labels(
        python="%d.%d.%d" % sys.version_info[:3],
        jax=_dist_version("jax"),
    ).set(1)
    # set once at install, not a callback: the value is the identity of THIS
    # process (it matches the pid stamped into trace exports); a supervisor
    # restart replaces the whole series along with the process
    metrics.gauge(
        "dllama_process_pid",
        "OS pid of this serving process (matches a single-process --trace "
        "export's pid and the os_pid field of otherData.processes in a "
        "fleet-merged trace, whose events carry remapped index pids)",
    ).set(os.getpid())
