"""Observability: span tracing, metrics, trace context, flight recorder.

Sibling modules, all dependency-free and safe to import from any layer:

- `obs.trace`  — thread-safe span tracer with Chrome trace-event JSON export
  (Perfetto-loadable); process-wide no-op until `trace.install()` runs
  (`dllama --trace out.json`, `bench.py --trace`); `merge_chrome_traces`
  folds a fleet's per-process traces into one aligned file.
- `obs.metrics` — counters / gauges / histograms with Prometheus text
  exposition, served by `api_server` at `GET /metrics` (and as a JSON
  snapshot at `GET /v1/stats`).
- `obs.reqctx` — W3C trace-context (traceparent) propagation: one 128-bit
  trace id follows a request from the fleet router through the replica's
  HTTP handler into the BatchEngine scheduler's per-row work.
- `obs.flight` — per-request flight recorder: a bounded ring of the last N
  completed request timelines, served at `GET /v1/requests`, with a
  `--slow-log` JSONL exemplar stream.
- `obs.process` — process self-telemetry gauges (uptime, RSS, threads,
  tracer drops, build info) for /metrics.

The runtime (engine, batch_engine, speculative, paged_cache, hlo_stats) is
instrumented unconditionally: metrics cost one lock + add per event and the
disabled tracer/recorder cost one global check per call site
(perf/obs_overhead.py pins the overhead at <1% of a decode dispatch).
docs/OBSERVABILITY.md has the full span/metric inventory.
"""

from . import flight, metrics, process, reqctx, trace

__all__ = ["flight", "metrics", "process", "reqctx", "trace"]
