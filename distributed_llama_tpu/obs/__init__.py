"""Observability: structured span tracing + Prometheus-style metrics.

Two sibling modules, both dependency-free and safe to import from any layer:

- `obs.trace`  — thread-safe span tracer with Chrome trace-event JSON export
  (Perfetto-loadable); process-wide no-op until `trace.install()` runs
  (`dllama --trace out.json`, `bench.py --trace`).
- `obs.metrics` — counters / gauges / histograms with Prometheus text
  exposition, served by `api_server` at `GET /metrics` (and as a JSON
  snapshot at `GET /v1/stats`).

The runtime (engine, batch_engine, speculative, paged_cache, hlo_stats) is
instrumented unconditionally: metrics cost one lock + add per event and the
disabled tracer costs one global check per span (perf/obs_overhead.py pins
the overhead at <1% of a decode dispatch). docs/OBSERVABILITY.md has the
full span/metric inventory.
"""

from . import metrics, trace

__all__ = ["metrics", "trace"]
