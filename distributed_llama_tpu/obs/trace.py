"""Structured span tracing with Chrome trace-event export.

The reference engine's only timeline is the per-token G/I/T printout
(dllama.cpp:76-93); one number per token, averaged, gone when the process
exits. This tracer records *spans* — named wall-clock intervals with nesting
(prefill chunks inside a prefill, super-steps inside a request) — into a
bounded in-memory ring buffer and exports them as Chrome trace-event JSON,
loadable in Perfetto (ui.perfetto.dev) or chrome://tracing.

Design constraints, in priority order:

1. **Zero-cost when disabled.** Every hot path in the repo calls
   `obs.trace.span(...)` unconditionally; when no tracer is installed the
   call returns a shared no-op context manager (one global lookup + one
   function call, no allocation). perf/obs_overhead.py pins this at <1% of a
   decode dispatch.
2. **Thread-safe.** The BatchEngine scheduler thread, HTTP handler threads,
   and the main thread all emit spans concurrently; the buffer is a
   lock-guarded deque and span timing state lives on the span object itself
   (never in shared state).
3. **Bounded.** The ring buffer drops the OLDEST events past `capacity` —
   a long-running server never grows without bound; `dropped_events` counts
   what was lost so an exported trace is honest about truncation.
4. **Monotonic clocks.** Timestamps come from time.perf_counter_ns()
   relative to tracer start; wall-clock (time.time) appears once in the
   export metadata, so NTP steps can never fold spans over each other.

Optional `jax.profiler` pass-through: with `jax_annotations=True` each span
also enters a jax.profiler.TraceAnnotation, so the spans show up inside an
XLA device trace (perf/PROFILE.md workflow) under the same names.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque

__all__ = ["Tracer", "span", "instant", "install", "uninstall", "current"]


class _NullSpan:
    """Shared no-op context manager returned while tracing is disabled."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def add(self, **args) -> None:  # parity with _Span.add
        pass


_NULL_SPAN = _NullSpan()


class _Span:
    """One live span: created by Tracer.span(), recorded at __exit__."""

    __slots__ = ("_tracer", "name", "args", "_t0", "_annot")

    def __init__(self, tracer: "Tracer", name: str, args: dict | None):
        self._tracer = tracer
        self.name = name
        self.args = args
        self._annot = None

    def add(self, **args) -> None:
        """Attach result metadata discovered mid-span (token counts, sizes)."""
        if self.args is None:
            self.args = args
        else:
            self.args.update(args)

    def __enter__(self):
        if self._tracer._annotate:
            try:
                import jax.profiler

                self._annot = jax.profiler.TraceAnnotation(self.name)
                self._annot.__enter__()
            except Exception:
                self._annot = None  # device trace unavailable: spans still record
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter_ns()
        if self._annot is not None:
            self._annot.__exit__(*exc)
        self._tracer._record(self.name, self._t0, t1, self.args)
        return False


class Tracer:
    """Thread-safe span recorder with a bounded ring buffer.

    Spans are recorded AT EXIT as Chrome "X" (complete) events — start
    timestamp + duration — so nesting in the viewer is purely geometric:
    a child span's [ts, ts+dur] interval lies inside its parent's, because
    the child entered after and exited before on the same thread.
    """

    def __init__(self, capacity: int = 65536, *, jax_annotations: bool = False):
        assert capacity > 0
        self.capacity = capacity
        self._events: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._annotate = jax_annotations
        self._epoch_ns = time.perf_counter_ns()
        self._wall_start = time.time()
        self.dropped_events = 0
        self._thread_names: dict[int, str] = {}

    # -- recording ------------------------------------------------------

    def span(self, name: str, args: dict | None = None) -> _Span:
        return _Span(self, name, args)

    def instant(self, name: str, args: dict | None = None) -> None:
        """Point-in-time marker (Chrome "i" event)."""
        ts = (time.perf_counter_ns() - self._epoch_ns) / 1e3
        self._append({"name": name, "ph": "i", "ts": ts, "s": "t",
                      "pid": 1, "tid": threading.get_ident(),
                      **({"args": args} if args else {})})

    def _record(self, name: str, t0_ns: int, t1_ns: int,
                args: dict | None) -> None:
        ev = {"name": name, "ph": "X",
              "ts": (t0_ns - self._epoch_ns) / 1e3,  # Chrome wants microseconds
              "dur": (t1_ns - t0_ns) / 1e3,
              "pid": 1, "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        self._append(ev)

    def _append(self, ev: dict) -> None:
        tid = threading.get_ident()
        with self._lock:
            if tid not in self._thread_names:
                self._thread_names[tid] = threading.current_thread().name
            if len(self._events) == self.capacity:
                self.dropped_events += 1
            self._events.append(ev)

    # -- export ---------------------------------------------------------

    def events(self) -> list[dict]:
        """Snapshot of buffered events (oldest first), plus thread metadata."""
        with self._lock:
            evs = list(self._events)
            names = dict(self._thread_names)
        meta = [{"name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
                 "args": {"name": tname}} for tid, tname in sorted(names.items())]
        return meta + evs

    def to_chrome_trace(self) -> dict:
        """The Chrome trace-event JSON object (load in Perfetto as-is)."""
        return {
            "traceEvents": self.events(),
            "displayTimeUnit": "ms",
            "otherData": {
                "wall_start_unix": self._wall_start,
                "dropped_events": self.dropped_events,
            },
        }

    def dump(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped_events = 0


# ----------------------------------------------------------------------
# module-level switch: the instrumented hot paths call these directly
# ----------------------------------------------------------------------

_tracer: Tracer | None = None


def install(capacity: int = 65536, *, jax_annotations: bool = False) -> Tracer:
    """Enable tracing process-wide; returns the tracer (idempotent: a second
    install replaces the first — one tracer owns the buffer at a time)."""
    global _tracer
    _tracer = Tracer(capacity, jax_annotations=jax_annotations)
    return _tracer


def uninstall() -> None:
    global _tracer
    _tracer = None


def current() -> Tracer | None:
    return _tracer


def span(name: str, args: dict | None = None):
    """`with span("engine.decode", {"t": 1}):` — no-op unless install()ed.

    Args are passed as an optional dict (not **kwargs) so the disabled path
    does not even build a dict per call site when the caller pre-builds
    nothing; callers that want rich args construct the dict inline, paying
    for it only at sites they chose to annotate."""
    t = _tracer
    if t is None:
        return _NULL_SPAN
    return t.span(name, args)


def instant(name: str, args: dict | None = None) -> None:
    t = _tracer
    if t is not None:
        t.instant(name, args)
