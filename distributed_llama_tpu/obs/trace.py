"""Structured span tracing with Chrome trace-event export.

The reference engine's only timeline is the per-token G/I/T printout
(dllama.cpp:76-93); one number per token, averaged, gone when the process
exits. This tracer records *spans* — named wall-clock intervals with nesting
(prefill chunks inside a prefill, super-steps inside a request) — into a
bounded in-memory ring buffer and exports them as Chrome trace-event JSON,
loadable in Perfetto (ui.perfetto.dev) or chrome://tracing.

Design constraints, in priority order:

1. **Zero-cost when disabled.** Every hot path in the repo calls
   `obs.trace.span(...)` unconditionally; when no tracer is installed the
   call returns a shared no-op context manager (one global lookup + one
   function call, no allocation). perf/obs_overhead.py pins this at <1% of a
   decode dispatch.
2. **Thread-safe.** The BatchEngine scheduler thread, HTTP handler threads,
   and the main thread all emit spans concurrently; the buffer is a
   lock-guarded deque and span timing state lives on the span object itself
   (never in shared state).
3. **Bounded.** The ring buffer drops the OLDEST events past `capacity` —
   a long-running server never grows without bound; `dropped_events` counts
   what was lost so an exported trace is honest about truncation.
4. **Monotonic clocks.** Timestamps come from time.perf_counter_ns()
   relative to tracer start; wall-clock (time.time) appears once in the
   export metadata, so NTP steps can never fold spans over each other.

Optional `jax.profiler` pass-through: with `jax_annotations=True` each span
also enters a jax.profiler.TraceAnnotation, so the spans show up inside an
XLA device trace (perf/PROFILE.md workflow) under the same names.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from . import reqctx

__all__ = ["Tracer", "span", "instant", "install", "uninstall", "current",
           "set_process_name", "merge_chrome_traces"]


class _NullSpan:
    """Shared no-op context manager returned while tracing is disabled."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def add(self, **args) -> None:  # parity with _Span.add
        pass


_NULL_SPAN = _NullSpan()


class _Span:
    """One live span: created by Tracer.span(), recorded at __exit__.

    `tracer=None` makes the span MODULE-RESOLVED: it records through
    whichever tracer is installed at exit time. Module-level span() uses
    this so a tracer replaced mid-span (install() while spans are in
    flight) receives the event instead of the orphaned predecessor's buffer
    silently swallowing it. A span that ENTERED before the new tracer's
    epoch records a negative ts — correct, not a bug: epochs and span
    clocks read the same monotonic counter, so wall_start_unix + ts still
    names the true absolute time (and merge_chrome_traces aligns on exactly
    that anchor). Spans created via a Tracer instance directly stay bound
    to that instance (tests own their tracer)."""

    __slots__ = ("_tracer", "name", "args", "_t0", "_annot")

    def __init__(self, tracer: "Tracer | None", name: str, args: dict | None):
        self._tracer = tracer
        self.name = name
        self.args = args
        self._annot = None

    def add(self, **args) -> None:
        """Attach result metadata discovered mid-span (token counts, sizes)."""
        if self.args is None:
            self.args = args
        else:
            self.args.update(args)

    def __enter__(self):
        t = self._tracer if self._tracer is not None else _tracer
        if t is not None and t._annotate:
            try:
                import jax.profiler

                self._annot = jax.profiler.TraceAnnotation(self.name)
                self._annot.__enter__()
            except Exception:
                self._annot = None  # device trace unavailable: spans still record
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter_ns()
        if self._annot is not None:
            self._annot.__exit__(*exc)
        t = self._tracer if self._tracer is not None else _tracer
        if t is not None:  # uninstalled mid-span: nowhere to record
            t._record(self.name, self._t0, t1, self.args)
        return False


class Tracer:
    """Thread-safe span recorder with a bounded ring buffer.

    Spans are recorded AT EXIT as Chrome "X" (complete) events — start
    timestamp + duration — so nesting in the viewer is purely geometric:
    a child span's [ts, ts+dur] interval lies inside its parent's, because
    the child entered after and exited before on the same thread.
    """

    def __init__(self, capacity: int = 65536, *, jax_annotations: bool = False,
                 pid: int | None = None, process_name: str | None = None):
        assert capacity > 0
        self.capacity = capacity
        self._events: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()  # guards: _events, _thread_names, dropped_events
        self._annotate = jax_annotations
        self._epoch_ns = time.perf_counter_ns()
        self._wall_start = time.time()
        self.dropped_events = 0
        self._thread_names: dict[int, str] = {}
        # real process identity: every event used to hardcode pid 1, which
        # made multi-process merge (fleet router + N replicas into one
        # Perfetto file) impossible — identical pids folded every process
        # onto one track. process_name labels the pid track in the viewer;
        # servers set it once their bound address is known.
        self.pid = os.getpid() if pid is None else pid
        self.process_name = process_name

    # -- recording ------------------------------------------------------

    def span(self, name: str, args: dict | None = None) -> _Span:
        return _Span(self, name, args)

    @staticmethod
    def _stamp_trace(args: dict | None) -> dict | None:
        """Stamp the active request context's trace id onto event args —
        the engine-side half of distributed tracing: any span/instant
        recorded while reqctx is bound carries the owning request's trace
        id (searchable in Perfetto, joinable with the router's spans).
        Runs only when a tracer IS installed, so the disabled path never
        touches the contextvar."""
        ctx = reqctx.current()
        if ctx is None:
            return args
        args = dict(args) if args else {}
        args.setdefault("trace_id", ctx.trace_id)
        return args

    def instant(self, name: str, args: dict | None = None) -> None:
        """Point-in-time marker (Chrome "i" event)."""
        ts = (time.perf_counter_ns() - self._epoch_ns) / 1e3
        args = self._stamp_trace(args)
        self._append({"name": name, "ph": "i", "ts": ts, "s": "t",
                      "pid": self.pid, "tid": threading.get_ident(),
                      **({"args": args} if args else {})})

    def _record(self, name: str, t0_ns: int, t1_ns: int,
                args: dict | None) -> None:
        args = self._stamp_trace(args)
        ev = {"name": name, "ph": "X",
              "ts": (t0_ns - self._epoch_ns) / 1e3,  # Chrome wants microseconds
              "dur": (t1_ns - t0_ns) / 1e3,
              "pid": self.pid, "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        self._append(ev)

    def _append(self, ev: dict) -> None:
        tid = threading.get_ident()
        with self._lock:
            if tid not in self._thread_names:
                self._thread_names[tid] = threading.current_thread().name
            if len(self._events) == self.capacity:
                self.dropped_events += 1
            self._events.append(ev)

    # -- export ---------------------------------------------------------

    def _snapshot(self) -> tuple[list[dict], dict, int]:
        """(events, thread names, dropped count) taken in ONE critical
        section, so an export can never pair a pre-drop event list with a
        post-drop counter (the torn-pair class the lock-guard pass flags)."""
        with self._lock:
            return (list(self._events), dict(self._thread_names),
                    self.dropped_events)

    def _meta_events(self, names: dict) -> list[dict]:
        meta = []
        if self.process_name:
            meta.append({"name": "process_name", "ph": "M", "pid": self.pid,
                         "args": {"name": self.process_name}})
        meta.extend({"name": "thread_name", "ph": "M", "pid": self.pid,
                     "tid": tid, "args": {"name": tname}}
                    for tid, tname in sorted(names.items()))
        return meta

    def events(self) -> list[dict]:
        """Snapshot of buffered events (oldest first), plus process/thread
        metadata."""
        evs, names, _dropped = self._snapshot()
        return self._meta_events(names) + evs

    def to_chrome_trace(self) -> dict:
        """The Chrome trace-event JSON object (load in Perfetto as-is).
        `wall_start_unix` is the wall clock at the tracer's monotonic epoch —
        the alignment anchor merge_chrome_traces() shifts each process's
        timestamps by, so a fleet's traces share one timeline."""
        evs, names, dropped = self._snapshot()  # ONE critical section
        return {
            "traceEvents": self._meta_events(names) + evs,
            "displayTimeUnit": "ms",
            "otherData": {
                "wall_start_unix": self._wall_start,
                "dropped_events": dropped,
                "pid": self.pid,
                "process_name": self.process_name,
            },
        }

    def dump(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped_events = 0


# ----------------------------------------------------------------------
# module-level switch: the instrumented hot paths call these directly
# ----------------------------------------------------------------------

_tracer: Tracer | None = None


def install(capacity: int = 65536, *, jax_annotations: bool = False,
            process_name: str | None = None) -> Tracer:
    """Enable tracing process-wide; returns the tracer. A second install
    replaces the first; module-level spans already in flight record through
    the NEW tracer at exit (they resolve the installed tracer at record
    time), so a replace can no longer strand events in an orphaned buffer."""
    global _tracer
    _tracer = Tracer(capacity, jax_annotations=jax_annotations,
                     process_name=process_name)
    return _tracer


def uninstall() -> None:
    global _tracer
    _tracer = None


def current() -> Tracer | None:
    return _tracer


def set_process_name(name: str) -> None:
    """Label the installed tracer's process track (servers call this once
    the bound host:port is known); no-op while tracing is disabled."""
    t = _tracer
    if t is not None:
        t.process_name = name


def span(name: str, args: dict | None = None):
    """`with span("engine.decode", {"t": 1}):` — no-op unless install()ed.

    Args are passed as an optional dict (not **kwargs) so the disabled path
    does not even build a dict per call site when the caller pre-builds
    nothing; callers that want rich args construct the dict inline, paying
    for it only at sites they chose to annotate."""
    if _tracer is None:
        return _NULL_SPAN
    # tracer=None: module-resolved — records through whichever tracer is
    # installed when the span exits (see _Span docstring)
    return _Span(None, name, args)


def instant(name: str, args: dict | None = None) -> None:
    t = _tracer
    if t is not None:
        t.instant(name, args)


# ----------------------------------------------------------------------
# fleet merge
# ----------------------------------------------------------------------

def merge_chrome_traces(sources: list[tuple[str, dict]]) -> dict:
    """Merge per-process Chrome traces into ONE Perfetto-loadable document.

    `sources` is [(process label, to_chrome_trace() dict)] — e.g. the fleet
    router's own trace plus every replica's `GET /v1/trace` body. Each
    source gets a distinct pid (its index, so traces from different HOSTS
    with colliding OS pids still separate) labeled with a process_name
    metadata event, and its timestamps are shifted by the difference of the
    sources' `wall_start_unix` anchors onto the EARLIEST process's timeline
    — per-process clocks are monotonic, so after the one wall-clock
    alignment a request's router span and its replica spans sit in true
    temporal order (NTP skew between hosts bounds the residual error).
    `dropped_events` is summed; per-source drop counts are preserved in
    `otherData.processes`."""
    docs = [(label, doc) for label, doc in sources if doc]
    walls = [float((doc.get("otherData") or {}).get("wall_start_unix") or 0.0)
             for _label, doc in docs]
    base = min((w for w in walls if w), default=0.0)
    events: list[dict] = []
    processes = []
    dropped = 0
    for idx, ((label, doc), wall) in enumerate(zip(docs, walls), start=1):
        off_us = ((wall - base) * 1e6) if wall and base else 0.0
        events.append({"name": "process_name", "ph": "M", "pid": idx,
                       "args": {"name": label}})
        for ev in doc.get("traceEvents", []):
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                continue  # replaced by the merge's own label above
            ev = dict(ev)
            ev["pid"] = idx
            if "ts" in ev:
                ev["ts"] = ev["ts"] + off_us
            events.append(ev)
        src_dropped = int((doc.get("otherData") or {}).get("dropped_events")
                          or 0)
        dropped += src_dropped
        processes.append({"pid": idx, "name": label,
                          # the source process's real OS pid (the one its
                          # /metrics dllama_process_pid reports) — merged
                          # events carry the index pid, this is the join key
                          "os_pid": (doc.get("otherData") or {}).get("pid"),
                          "wall_start_unix": wall or None,
                          "dropped_events": src_dropped})
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "wall_start_unix": base or None,
            "dropped_events": dropped,
            "processes": processes,
        },
    }
