"""Request-scoped trace context: W3C trace-context propagation for the fleet.

Before this module existed, no request identity existed anywhere in the
serving stack: the fleet router proxied anonymous bodies, api_server handled
anonymous completions, and the BatchEngine scheduler batched anonymous rows —
a slow or failed request could not be followed from router proxy → replica
HTTP handler → BatchEngine queue → super-step. This module is that identity:

- **TraceContext** — a 128-bit trace id + 64-bit span id (+ sampled flags and
  a serving-local request id), serialized on the wire as the W3C
  `traceparent` header (`00-<32 hex trace>-<16 hex span>-<2 hex flags>`).
  The fleet router ORIGINATES a context per request (or adopts an inbound
  header from an upstream caller), stamps a fresh child span id on every
  proxied hop, and the replica's api_server adopts the header again — so one
  trace id spans the whole fleet path.
- **contextvars carrier** — `use(ctx)` binds the context to the current
  thread's execution context; `current()` reads it. Within one thread
  (api_server handler running the sequential engine) propagation is free.
  The BatchEngine scheduler is a DIFFERENT thread serving many requests per
  super-step, so there is no ambient context to inherit: the scheduler
  re-enters each request's captured context explicitly (`use(req.ctx)`)
  around per-request work — admission, prefill, per-row block delivery — and
  the tracer (obs/trace.py) stamps `trace_id` onto any span/instant recorded
  while a context is active. That is how engine-side events carry the owning
  request's trace id even though one dispatch serves many requests.

Cost discipline matches the rest of obs/: a dataclass + one contextvar
set/reset per scoped region; reading `current()` happens only behind the
"tracer installed" / "flight recorder installed" checks, so the disabled
hot path stays inside the perf/obs_overhead.py <1% gate.
"""

from __future__ import annotations

import contextvars
import os
import re
from dataclasses import dataclass

__all__ = ["TraceContext", "new_context", "parse_traceparent", "adopt",
           "current", "use"]

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})(-.*)?$")


def _rand_hex(nbytes: int) -> str:
    """Non-zero random hex id (the W3C spec reserves the all-zero id as
    invalid; os.urandom returning all zeros is astronomically unlikely but
    the retry costs nothing)."""
    while True:
        h = os.urandom(nbytes).hex()
        if any(c != "0" for c in h):
            return h


@dataclass(frozen=True)
class TraceContext:
    """One request's position in a distributed trace. `trace_id` is shared
    by every hop; `span_id` identifies THIS hop's work; `request_id` is the
    serving-local id (`chatcmpl-...`) the flight recorder keys on — it never
    goes on the wire (traceparent carries only trace/span/flags).
    `tenant` is the serving-local tenant id the HTTP layer mapped from
    `X-Tenant` (docs/SERVING.md "Multi-tenant serving") — like request_id it
    rides the context, not the wire (the router relays the header itself),
    so engine-side flight events and slow-log exemplars attribute work to
    the owning tenant."""

    trace_id: str        # 32 lowercase hex chars (128-bit)
    span_id: str         # 16 lowercase hex chars (64-bit)
    flags: int = 1       # W3C trace-flags; 01 = sampled
    request_id: str = ""
    tenant: str = ""

    def to_traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-{self.flags:02x}"

    def child(self, request_id: str | None = None) -> "TraceContext":
        """Same trace, fresh span id — one per proxied hop / work unit."""
        return TraceContext(self.trace_id, _rand_hex(8), self.flags,
                            self.request_id if request_id is None
                            else request_id, self.tenant)


def new_context(request_id: str = "", tenant: str = "") -> TraceContext:
    """Originate a trace (the fleet router's job for header-less clients)."""
    return TraceContext(_rand_hex(16), _rand_hex(8), 1, request_id, tenant)


def parse_traceparent(header: str | None) -> TraceContext | None:
    """W3C parse; None on anything malformed (an unparseable header must
    start a fresh trace, never crash the request). Per spec: version 0xff
    and all-zero trace/span ids are invalid; version 00 defines EXACTLY
    four fields; a future version (> 00) parses by its first four fields
    with any trailing `-...` ignored — forward compatibility, so a trace
    from a newer upstream proxy still joins instead of silently forking."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if m is None:
        return None
    version, trace_id, span_id, flags, rest = m.groups()
    if version == "ff" or (version == "00" and rest):
        return None
    if set(trace_id) == {"0"} or set(span_id) == {"0"}:
        return None
    return TraceContext(trace_id, span_id, int(flags, 16))


def adopt(header: str | None, request_id: str = "",
          tenant: str = "") -> TraceContext:
    """Continue an inbound trace (fresh child span id) or originate one:
    the single call a server entry point needs. `tenant` stamps the
    serving-local tenant id either way (the wire header carries only
    trace/span/flags)."""
    parent = parse_traceparent(header)
    if parent is None:
        return new_context(request_id, tenant)
    ctx = parent.child(request_id=request_id)
    if tenant:
        import dataclasses

        ctx = dataclasses.replace(ctx, tenant=tenant)
    return ctx


_var: contextvars.ContextVar[TraceContext | None] = contextvars.ContextVar(
    "dllama_reqctx", default=None)


def current() -> TraceContext | None:
    return _var.get()


class use:
    """`with use(ctx):` — bind `ctx` for the block (None explicitly clears:
    a scheduler loop between per-request regions must not leak the previous
    request's identity onto engine-scope events). A slotted class, not
    @contextmanager: this sits on per-token scheduler paths and the plain
    set/reset pair is ~3x cheaper than a generator frame
    (perf/obs_overhead.py includes it in the gated bundle)."""

    __slots__ = ("_ctx", "_token")

    def __init__(self, ctx: TraceContext | None):
        self._ctx = ctx

    def __enter__(self) -> TraceContext | None:
        self._token = _var.set(self._ctx)
        return self._ctx

    def __exit__(self, *exc) -> bool:
        _var.reset(self._token)
        return False
