"""Per-request flight recorder: the last N completed request timelines.

Aggregate histograms answer "how slow are requests"; they cannot answer "why
was THIS request slow". The flight recorder keeps one bounded timeline per
request — admission and queue wait, prefix-cache seed tokens, prefill
chunks, super-steps joined, parks/rollbacks/pipeline flushes, injected
faults (via the resilience/faults.py fire → `note_fault` hook), finish
reason, TTFT/TPOT/E2E — in a ring of the most recent completions, served by
api_server as JSON:

    GET /v1/requests            → recent completed + live summaries
    GET /v1/requests?slowest=K  → the K worst completed requests by E2E
    GET /v1/requests/<id>       → one request's full timeline
                                  (id = the chatcmpl-... request id, or its
                                  32-hex trace id from the merged trace)

plus a structured **slow log** (`--slow-log out.jsonl`): every completion
over `--slow-threshold` seconds appends its full record as one JSON line —
durable exemplars for offline analysis after the ring has rotated.

Discipline (same as obs/trace.py):

- **Zero-cost when disabled**: hot paths call module-level `event()`
  unconditionally; with no recorder installed that is one global None check.
- **Bounded everywhere**: completed records live in a ring of `capacity`
  (oldest evicted, counted); live records are capped (a leak of unfinished
  ids must not grow without bound — evicted-live is its own counter); each
  record holds at most `max_events` timeline entries (overflow counted on
  the record itself, so a truncated timeline is honest about it).
- **Thread-safe**: HTTP handler threads and the scheduler thread write
  concurrently; one lock guards both tables.
"""

from __future__ import annotations

import json
import threading
import time
from collections import OrderedDict

from . import reqctx

__all__ = ["FlightRecorder", "install", "uninstall", "current",
           "event", "start", "annotate", "finish", "note_fault"]


class FlightRecorder:
    def __init__(self, capacity: int = 256, *, live_capacity: int = 1024,
                 max_events: int = 512, slow_log: str | None = None,
                 slow_threshold: float = 1.0):
        assert capacity > 0 and live_capacity > 0 and max_events > 0
        self.capacity = capacity
        self.live_capacity = live_capacity
        self.max_events = max_events
        self.slow_log = slow_log
        self.slow_threshold_ms = slow_threshold * 1000.0
        self._live: "OrderedDict[str, dict]" = OrderedDict()
        self._done: "OrderedDict[str, dict]" = OrderedDict()
        self._lock = threading.Lock()  # guards: _live, _done, evicted_done, evicted_live
        # separate lock for the slow-log file: writes happen OUTSIDE the
        # table lock (file I/O must not stall the scheduler's event path)
        # but concurrent finishes must not interleave lines or double-open
        self._log_lock = threading.Lock()  # guards: _slow_fh
        self._slow_fh = None
        self.evicted_done = 0   # completed records rotated out of the ring
        self.evicted_live = 0   # live records dropped at live_capacity

    # -- recording ------------------------------------------------------

    def _new(self, rid: str, trace_id: str = "") -> dict:
        return {"id": rid, "trace_id": trace_id,
                "start_unix": time.time(), "_t0": time.perf_counter(),
                "events": [], "events_dropped": 0, "finish": None}

    def start(self, rid: str, trace_id: str = "", **meta) -> None:
        """Open (or enrich) a live record. Idempotent: the api layer and the
        engine both call it with whatever identity/meta they know."""
        if not rid:
            return
        with self._lock:
            rec = self._live.get(rid)
            if rec is None:
                rec = self._new(rid, trace_id)
                self._live[rid] = rec
                while len(self._live) > self.live_capacity:
                    self._live.popitem(last=False)
                    self.evicted_live += 1
            elif trace_id and not rec["trace_id"]:
                rec["trace_id"] = trace_id
            rec.update(meta)

    def event(self, rid: str, name: str, **attrs) -> None:
        """Append one timeline entry: {t_ms since record start, name, attrs}.
        Auto-opens the record so engine-side events never depend on the api
        layer having called start() first."""
        if not rid:
            return
        with self._lock:
            rec = self._live.get(rid)
            if rec is None:
                if rid in self._done:  # late event (post-done harvest etc.)
                    rec = self._done[rid]
                else:
                    rec = self._new(rid)
                    self._live[rid] = rec
                    while len(self._live) > self.live_capacity:
                        self._live.popitem(last=False)
                        self.evicted_live += 1
            if len(rec["events"]) >= self.max_events:
                rec["events_dropped"] += 1
                return
            ev = {"t_ms": round((time.perf_counter() - rec["_t0"]) * 1e3, 3),
                  "event": name}
            if attrs:
                ev.update(attrs)
            rec["events"].append(ev)

    def annotate(self, rid: str, **meta) -> None:
        if not rid:
            return
        with self._lock:
            rec = self._live.get(rid) or self._done.get(rid)
            if rec is not None:
                rec.update(meta)

    def drop(self, rid: str) -> None:
        """Discard a live record WITHOUT completing it — for requests shed
        before any engine work (admission-control 503s). A saturation burst
        produces rejects at shed rate; finishing each one would flood the
        slow log and churn every real completion out of the ring exactly
        during the incident the recorder exists to debug."""
        if rid:
            with self._lock:
                self._live.pop(rid, None)

    def finish(self, rid: str, finish: str | None = None, **meta) -> None:
        """Complete a record: move live → ring (or update an already-completed
        one — the engine finishes first, the api layer adds TTFT/E2E after),
        rotate the ring, and append the slow-log exemplar when over
        threshold. The exemplar is written AT MOST once per record, only by
        a finish carrying request-level numbers (`e2e_ms` from the api
        layer, or an `error`) — the engine-side completion alone would log
        a line missing exactly the latency fields the slow log exists for —
        and an ERRORED request is an exemplar regardless of latency (a
        200 ms fault-killed request is the primary debugging target)."""
        if not rid:
            return
        line = None
        with self._lock:
            rec = self._live.pop(rid, None)
            if rec is None:
                rec = self._done.get(rid)
                if rec is None:
                    return
                self._done.move_to_end(rid)
            else:
                rec["e2e_ms"] = round(
                    (time.perf_counter() - rec["_t0"]) * 1e3, 3)
                self._done[rid] = rec
            if finish is not None:
                rec["finish"] = finish
            rec.update(meta)
            while len(self._done) > self.capacity:
                self._done.popitem(last=False)
                self.evicted_done += 1
            if (self.slow_log and not rec.get("_slow_logged")
                    and ("e2e_ms" in meta or meta.get("error") is not None)
                    and (rec.get("e2e_ms", 0.0) >= self.slow_threshold_ms
                         or rec.get("error") is not None)):
                rec["_slow_logged"] = True
                # snapshot only: serialization of a 512-event record takes
                # ~ms and must not happen under the table lock the
                # scheduler's event() path contends on
                line = dict(rec)
                line["events"] = list(rec["events"])
        if line is not None:
            self._write_slow(json.dumps(self._export(line)))

    def _write_slow(self, line: str) -> None:
        try:
            with self._log_lock:
                if self._slow_fh is None:
                    self._slow_fh = open(self.slow_log, "a")  # dlint: ignore[lock-blocking] -- the log lock EXISTS to serialize this fd; only finish() paths contend, never the event() hot path
                self._slow_fh.write(line + "\n")
                self._slow_fh.flush()
        except OSError:
            pass  # an unwritable slow log must never fail a request

    # -- export ---------------------------------------------------------

    @staticmethod
    def _export(rec: dict) -> dict:
        return {k: v for k, v in rec.items() if not k.startswith("_")}

    def get(self, key: str) -> dict | None:
        """Lookup by request id, falling back to trace id (the merged fleet
        trace shows trace ids; the operator pastes one here)."""
        if not key:
            return None  # "" would trace-id-match any auto-started record
        with self._lock:
            rec = self._live.get(key) or self._done.get(key)
            if rec is None:
                for table in (self._done, self._live):
                    for r in reversed(table.values()):
                        if r["trace_id"] == key:
                            rec = r
                            break
                    if rec is not None:
                        break
            return self._export(rec) if rec is not None else None

    def _summary(self, rec: dict, live: bool) -> dict:
        return {"id": rec["id"], "trace_id": rec["trace_id"],
                "start_unix": rec["start_unix"], "live": live,
                "finish": rec["finish"], "e2e_ms": rec.get("e2e_ms"),
                "ttft_ms": rec.get("ttft_ms"), "events": len(rec["events"]),
                "tenant": rec.get("tenant"), "class": rec.get("class")}

    def requests(self, slowest: int = 0, tenant: str | None = None) -> dict:
        """Summary listing; `slowest=K` returns the K worst completed
        requests by E2E instead of recency order; `tenant=` keeps only the
        named tenant's records (the per-tenant debugging entry point —
        "show me THIS tenant's recent requests" during a fairness
        incident)."""
        with self._lock:
            done = [self._summary(r, False) for r in self._done.values()
                    if tenant is None or r.get("tenant") == tenant]
            live = [self._summary(r, True) for r in self._live.values()
                    if tenant is None or r.get("tenant") == tenant]
            # eviction counters snapshotted in the SAME critical section as
            # the tables: reading them after releasing the lock could pair
            # a pre-eviction listing with a post-eviction count (or a torn
            # counter) whenever a finish races the listing — found by the
            # lock-guard pass (docs/ANALYSIS.md)
            evicted, evicted_live = self.evicted_done, self.evicted_live
        if slowest > 0:
            done = sorted(done, key=lambda r: r.get("e2e_ms") or 0.0,
                          reverse=True)[:slowest]
            live = []
        else:
            done.reverse()  # newest first
        return {"completed": done, "live": live,
                "capacity": self.capacity, "evicted": evicted,
                "evicted_live": evicted_live}

    def close(self) -> None:
        with self._log_lock:
            if self._slow_fh is not None:
                try:
                    self._slow_fh.close()
                except OSError:
                    pass
                self._slow_fh = None


# ----------------------------------------------------------------------
# module-level switch (the instrumented hot paths call these directly)
# ----------------------------------------------------------------------

_recorder: FlightRecorder | None = None


def install(capacity: int = 256, **kw) -> FlightRecorder:
    """Enable flight recording process-wide (api_server does this at serve()
    time); a second install replaces the first — closing the predecessor's
    slow-log handle so a reinstall never leaks the fd or an unflushed
    tail line."""
    global _recorder
    if _recorder is not None:
        _recorder.close()
    _recorder = FlightRecorder(capacity, **kw)
    return _recorder


def uninstall() -> None:
    global _recorder
    if _recorder is not None:
        _recorder.close()
    _recorder = None


def current() -> FlightRecorder | None:
    return _recorder


def _resolve_rid(rid: str | None) -> str:
    if rid is not None:
        return rid
    ctx = reqctx.current()
    return ctx.request_id if ctx is not None else ""


def start(rid: str | None, trace_id: str = "", **meta) -> None:
    r = _recorder
    if r is not None:
        r.start(_resolve_rid(rid), trace_id, **meta)


def event(rid: str | None, name: str, **attrs) -> None:
    """Hot-path hook: one global None check when disabled. `rid=None` means
    "the current trace context's request" — call sites that have no request
    handle (sequential engine internals) resolve through reqctx."""
    r = _recorder
    if r is not None:
        r.event(_resolve_rid(rid), name, **attrs)


def annotate(rid: str | None, **meta) -> None:
    r = _recorder
    if r is not None:
        r.annotate(_resolve_rid(rid), **meta)


def finish(rid: str | None, finish: str | None = None, **meta) -> None:
    r = _recorder
    if r is not None:
        r.finish(_resolve_rid(rid), finish, **meta)


def drop(rid: str | None) -> None:
    r = _recorder
    if r is not None:
        r.drop(_resolve_rid(rid))


def note_fault(point: str, kind: str) -> None:
    """resilience/faults.py fire() → timeline hook: attribute an injected
    fault to the request whose context is active at the injection point
    (points that fire outside any request scope record nothing)."""
    r = _recorder
    if r is not None:
        ctx = reqctx.current()
        if ctx is not None and ctx.request_id:
            r.event(ctx.request_id, "fault_injected", point=point, kind=kind)
