from .spec import ArchType, HiddenAct, ModelSpec, RopeType  # noqa: F401
