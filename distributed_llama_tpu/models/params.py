"""Parameter pytrees for all supported architectures.

Layout convention: every per-layer tensor is STACKED along a leading n_layers axis so the
forward pass can `lax.scan` over layers (one compiled block program instead of the
reference's hand-unrolled 25-tasks-per-layer lists, llama2-tasks.cpp:246-276).

Weight matrices keep the reference's (out, in) row-major orientation with quantization
blocks along `in`. Tensor inventory mirrors the `.m` file exactly
(transformer.cpp:494-529):

    embedding (vocab, dim) f32           wcls (vocab, dim) [weights ftype]
    per layer: wq (dim, dim), wk (kv_dim, dim), wv (kv_dim, dim), wo (dim, dim),
       dense: w1/gate (hidden, dim), w2/down (dim, hidden), w3/up (hidden, dim)
       moe:   router (n_experts, dim), moe_up/moe_gate (E, hidden, dim),
              moe_down (E, dim, hidden)
       norms: rms_att (dim,), rms_ffn (dim,) [+ grok1: rms_moe, rms_ffn2]
    rms_final (dim,) f32
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from ..quants import FloatType, QTensor
from .spec import ArchType, ModelSpec

Params = dict[str, Any]


def block_tensor_shapes(spec: ModelSpec) -> dict[str, tuple[tuple[int, ...], bool]]:
    """Per-layer tensor name -> (shape-without-layer-axis, is_quantized_matmul).

    Order matters: it is the `.m` file tensor order within a layer
    (transformer.cpp:498-523).
    """
    d, h, kv, e = spec.dim, spec.hidden_dim, spec.kv_dim, spec.n_experts
    shapes: dict[str, tuple[tuple[int, ...], bool]] = {
        "wq": ((d, d), True),
        "wk": ((kv, d), True),
        "wv": ((kv, d), True),
        "wo": ((d, d), True),
    }
    if spec.is_moe:
        shapes["router"] = ((e, d), True)
        shapes["moe_up"] = ((e, h, d), True)
        shapes["moe_gate"] = ((e, h, d), True)
        shapes["moe_down"] = ((e, d, h), True)
    else:
        shapes["w1"] = ((h, d), True)
        shapes["w2"] = ((d, h), True)
        shapes["w3"] = ((h, d), True)
    shapes["rms_att"] = ((d,), False)
    shapes["rms_ffn"] = ((d,), False)
    if spec.arch_type == ArchType.GROK1:
        shapes["rms_moe"] = ((d,), False)
        shapes["rms_ffn2"] = ((d,), False)
    return shapes


def init_random_params(spec: ModelSpec, weights_ftype: FloatType = FloatType.F32,
                       seed: int = 0, scale: float = 0.02) -> Params:
    """Random-weight model for tests/benchmarks (the reference's golden-test pattern:
    seeded random weights, llama2-tasks-test.cpp:527-608)."""
    rng = np.random.RandomState(seed)

    def randn(*shape):
        return (rng.randn(*shape) * scale).astype(np.float32)

    L = spec.n_layers
    blocks: Params = {}
    for name, (shape, quantized) in block_tensor_shapes(spec).items():
        full = randn(L, *shape)
        if quantized:
            blocks[name] = QTensor.from_float(full, weights_ftype)
        else:
            blocks[name] = full + 1.0  # norm weights around 1
    return {
        "embedding": randn(spec.vocab_size, spec.dim),
        "blocks": blocks,
        "rms_final": randn(spec.dim) + 1.0,
        "wcls": QTensor.from_float(randn(spec.vocab_size, spec.dim), weights_ftype),
    }


_I8_CONVERTIBLE = (FloatType.Q40, FloatType.Q80)

# per-layer tensors whose scan-sliced (and, for MoE stacks, expert-sliced) form is the
# 2-D matvec the decode kernels consume. The router stays planar (use_pallas=False in
# forward — it is tiny). Tensors in _COL_SHARDED get their in-axis TP-sliced
# (ColMatmulSlice), so the i4p split-plane pack must be applied per column group
# (QTensor.to_i4p_layout).
_DENSE_MATMULS = {"wq", "wk", "wv", "wo", "w1", "w2", "w3",
                  "moe_up", "moe_gate", "moe_down"}
_COL_SHARDED = {"wo", "w2", "moe_down"}


def _kernel_convertible(t: QTensor, stacked: bool) -> bool:
    from ..ops.pallas_q8 import q8_shape_supported

    if not (isinstance(t, QTensor) and t.ftype in _I8_CONVERTIBLE):
        return False
    shape = t.shape[1:] if stacked else t.shape
    if len(shape) == 3:  # MoE expert stack (E, out, in): kernel sees one expert slice
        shape = shape[1:]
    return len(shape) == 2 and q8_shape_supported(*shape)


def _decode_layout(t: QTensor, tp: int, col_sharded: bool) -> QTensor:
    """Pick the decode-kernel layout for one weight: Q40 -> i4p split-plane nibbles
    (0.5625 B/weight, the file's own density — pallas_q4 kernel); Q80 -> int8 planes
    (pallas_q8 kernel). Falls back to i8 when the i4p alignment constraints don't hold."""
    if t.ftype == FloatType.Q40:
        k = t.shape[-1]
        groups = tp if col_sharded else 1
        if k % groups == 0 and (k // groups) % 64 == 0:
            return t.to_i4p_layout(col_groups=groups)
    return t.to_i8_layout()


def _concat_rows_grouped(tensors: list[QTensor], tp: int, row_axis: int = 1
                         ) -> QTensor:
    """Concatenate planar QTensors along the row (out) axis, interleaved per TP
    group: the result's rows are [t0_g0, t1_g0, ..., t0_g1, t1_g1, ...] where g_i
    is shard i's row slice of each input, so a P('tp')-on-rows placement lands each
    shard exactly its own inputs' slices, contiguous. Quant blocks run along the
    *in* axis, so row concatenation never touches block structure (numerics are
    bit-identical to the separate tensors).

    row_axis: index of the out axis in the leaves — 1 for stacked dense weights
    (L, out, ...), 2 for stacked MoE expert stacks (L, E, out, ...)."""
    ft = tensors[0].ftype
    assert all(t.layout == "planar" and t.ftype == ft for t in tensors)

    def cat(leaves):
        # planar leaf shapes: data (..., out, nb, 16|32), scales (..., out, nb)
        parts = []
        for a in leaves:
            rows = a.shape[row_axis]
            assert rows % tp == 0, (a.shape, tp)
            parts.append(a.reshape(*a.shape[:row_axis], tp, rows // tp,
                                   *a.shape[row_axis + 1:]))
        out = np.concatenate(parts, axis=row_axis + 1)
        return out.reshape(*out.shape[:row_axis], -1,
                           *out.shape[row_axis + 2:])

    return QTensor(ft, cat([np.asarray(t.data) for t in tensors]),
                   cat([np.asarray(t.scales) for t in tensors]), row_groups=tp)


# merged matvec groups: members share the same activation vector, so one kernel
# launch with the row blocks concatenated replaces 3 (QKV) / 2 (gate+up) launches
# — fewer grid setups and quantize/Xexp prologues per layer. moe_gu merges each
# expert's up+gate the same way (halving per-active-expert launches on the MoE
# decode path). The reference has no counterpart (its task lists issue one
# matmul task per tensor, llama2-tasks.cpp:246-276); this is TPU launch-overhead
# engineering.
_FUSE_GROUPS = {"wqkv": ("wq", "wk", "wv"), "w13": ("w1", "w3"),
                "moe_gu": ("moe_up", "moe_gate")}
# out-axis index within each group's stacked planar leaves
_FUSE_ROW_AXIS = {"wqkv": 1, "w13": 1, "moe_gu": 2}


def fuse_matvec_groups(blocks: Params, spec: ModelSpec | None, tp: int,
                       moe_sharding: str = "slice",
                       skip: tuple[str, ...] = ()) -> Params:
    """Replace wq/wk/wv -> wqkv, w1/w3 -> w13, moe_up/moe_gate -> moe_gu with
    row-concatenated (TP-group interleaved) planar tensors where safe. Skipped
    per group when a member is not kernel-convertible or (QKV) when KV-head
    replication is active (tp > n_kv_heads expands wk/wv rows at shard time,
    after this runs). Under expert sharding the MoE stacks shard by whole
    experts, not rows, so moe_gu concatenates with NO group interleave."""
    from ..parallel.sharding import effective_kv_heads

    out = dict(blocks)
    for fused, members in _FUSE_GROUPS.items():
        if fused in skip:
            continue
        ts = [blocks.get(m) for m in members]
        if not all(isinstance(t, QTensor) and t.layout == "planar"
                   and _kernel_convertible(t, stacked=True) for t in ts):
            continue
        if len({t.ftype for t in ts}) != 1:
            continue
        row_axis = _FUSE_ROW_AXIS[fused]
        groups = tp
        if fused == "moe_gu" and moe_sharding == "expert":
            groups = 1  # whole experts shard over tp; rows stay unsharded
        if any(t.shape[row_axis] % groups for t in ts):
            continue
        if fused == "wqkv":
            if spec is None and tp > 1:
                continue  # can't rule out KV replication without the spec
            if spec is not None and effective_kv_heads(spec, tp) != spec.n_kv_heads:
                continue  # replication rewrites wk/wv rows later; keep separate
        out[fused] = _concat_rows_grouped(ts, groups, row_axis=row_axis)
        for m in members:
            del out[m]
    return out


def prepare_for_pallas(params: Params, tp: int = 1,
                       moe_sharding: str = "slice",
                       spec: ModelSpec | None = None,
                       fuse: bool = True,
                       keep_gate_pair: bool = False) -> Params:
    """Repack the dense matmul weights into the Pallas decode-kernel layouts
    (i4p packed nibbles for Q40, int8 planes for Q80). Row/col TP slices stay
    32-block-aligned; col-sharded tensors are packed per TP column group so each
    shard's slice is self-contained. Under expert sharding the MoE stacks shard by
    whole experts, so their in-axes are NOT column-sliced and pack with groups=1.

    fuse=True additionally merges the QKV and gate/up matvec groups into single
    row-concatenated tensors (fuse_matvec_groups) so decode launches one kernel
    per group instead of one per tensor. keep_gate_pair=True exempts w1/w3
    from that merge: the batched gate-pair kernel (ops/pallas_q4_mm.py
    q4_gated_matmul, Engine fused_matmul) fuses the silu·mul epilogue across
    the SEPARATE pair, which beats the merged-launch win for M>1."""
    import os

    out: Params = {"embedding": params["embedding"], "blocks": {},
                   "rms_final": params["rms_final"]}
    fuse = fuse and not os.environ.get("DLT_NO_FUSE")  # field kill-switch
    blocks = (fuse_matvec_groups(params["blocks"], spec, tp,
                                 moe_sharding=moe_sharding,
                                 skip=("w13",) if keep_gate_pair else ())
              if fuse else params["blocks"])
    for name, t in blocks.items():
        if ((name in _DENSE_MATMULS or name in _FUSE_GROUPS)
                and _kernel_convertible(t, stacked=True)):
            col = name in _COL_SHARDED and not (
                moe_sharding == "expert" and name.startswith("moe_"))
            out["blocks"][name] = _decode_layout(t, tp, col)
        else:
            out["blocks"][name] = t
    wcls = params["wcls"]
    if _kernel_convertible(wcls, stacked=False):
        wcls = _decode_layout(wcls, tp, col_sharded=False)
    out["wcls"] = wcls
    return out


def decode_stream_bytes(params: Params, spec: ModelSpec) -> int:
    """Weight + scale bytes one decode step streams from HBM (embedding row reads
    excluded; MoE expert stacks count only the n_active_experts slices actually
    moved per token). The numerator of the achieved-GB/s observability metric."""
    total = 0
    for name, t in list(params["blocks"].items()) + [("wcls", params["wcls"])]:
        n = t.nbytes() if isinstance(t, QTensor) else t.nbytes
        if name.startswith("moe_") and spec.n_experts:
            n = n * spec.n_active_experts // spec.n_experts
        total += n
    return total


def map_params(params: Params, fn: Callable[[Any], Any]) -> Params:
    """Apply fn to every QTensor/array leaf group (QTensor treated atomically)."""
    out: Params = {}
    for k, v in params.items():
        if isinstance(v, dict):
            out[k] = map_params(v, fn)
        else:
            out[k] = fn(v)
    return out
