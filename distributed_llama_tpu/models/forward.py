"""Unified transformer forward pass for Llama / Mixtral / Grok-1.

TPU-native replacement for the reference's hand-unrolled task graphs
(src/llama2-tasks.cpp:241-298, src/grok1-tasks.cpp:275-354, src/mixtral-tasks.cpp:5-78).
The 25-tasks-per-layer lockstep lists collapse into one `lax.scan` over stacked layer
params; the sync tasks (syncUnitBuffer broadcast / syncSliceOfSlicedBuffer gather+merge,
src/tasks.cpp:44-94) collapse into `psum`/`all_gather` at exactly the points where the
reference gathers partial sums.

The SAME function is the single-device program and the per-shard program: pass
`axis_name="tp"` when tracing under shard_map and every shard-local partial result is
reduced with `psum` where the reference's root merged slices (llamaMergeAtt,
llama2-tasks.cpp:125-131). This makes sliced==unsliced a *structural* property, which the
TP equivalence tests check on an 8-device mesh.

Arch-specific structure:
- LLAMA (dense): pre-norm attention + SwiGLU FFN (w1=gate, w3=up, w2=down).
- MIXTRAL: attention as llama; FFN -> top-2-of-8 MoE (router softmax over all experts,
  top-k renormalized, hb_e = up_e(x) * act(gate_e(x)), out = sum w_ae * down_e(hb_e)).
- GROK1: embedding x78.38367176906169 (grok1-tasks.cpp:11-14); attention output is
  rmsnorm'd (rms_ffn) BEFORE the residual join (grokRmfFfn*, grok1-tasks.cpp:16-41);
  MoE input norm uses rms_moe; MoE output is rmsnorm'd with rms_ffn2 before its residual
  join; logits x0.5773502691896257 (grokFinalize2).
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp

from ..ops.attention import gqa_attention, update_kv_cache
from ..ops.kernels import gelu_tanh, rmsnorm, silu
from ..ops.matmul import qmatmul, qmatmul_gated, qmatmul_q80
from ..ops.ring_attention import (commit_kv_rows_sharded, ring_attention,
                                  update_kv_cache_sharded)
from ..ops.rope import RopeTables, apply_rope
from .spec import ArchType, HiddenAct, ModelSpec

GROK_EMBEDDING_SCALE = 78.38367176906169  # grok1-tasks.cpp:13
GROK_LOGITS_SCALE = 0.5773502691896257  # grok1-tasks.cpp:272


def _localize_qtensors(params):
    """Reset i4p col-group metadata for shard-local execution.

    Col-sharded i4p tensors are packed per TP column group precisely so that each
    shard's slice is ONE self-contained split-plane pack; inside shard_map the local
    QTensor therefore has groups=1 physically, but the aux metadata (static through
    device_put/tree ops) still says groups=tp. Fix it up so dequantize/kernels see the
    local truth."""
    from ..quants import QTensor

    def fix(t):
        if isinstance(t, QTensor) and t.layout == "i4p" and t.groups != 1:
            return QTensor(t.ftype, t.data, t.scales, layout="i4p", groups=1,
                           row_groups=t.row_groups)
        return t

    return jax.tree_util.tree_map(fix, params,
                                  is_leaf=lambda x: isinstance(x, QTensor))


def _act(spec: ModelSpec):
    return silu if spec.hidden_act == HiddenAct.SILU else gelu_tanh


def _act_name(spec: ModelSpec) -> str:
    """Static activation name for the fused gate-pair kernel's epilogue
    (ops/pallas_q4_mm.py matches these formulas in f32)."""
    return "silu" if spec.hidden_act == HiddenAct.SILU else "gelu_tanh"


def _maybe_psum(x: jax.Array, axis_name: str | None, compress: bool = False) -> jax.Array:
    """TP merge point: the reference's gather-partials-and-sum-at-root
    (syncSliceOfSlicedBuffer + merge) becomes an all-reduce over the tp axis.
    `compress` swaps in the int8 Q80-payload all-reduce (the wire-compression
    equivalent of tasks.cpp:96-135)."""
    if axis_name is None:
        return x
    from ..parallel.collectives import psum

    return psum(x, axis_name, compress=compress)


def _attention(x, bp, layer_idx, spec: ModelSpec, rope: RopeTables, kc, vc, start_pos,
               positions, axis_name, sp_axis_name, sp_size, use_pallas, compress,
               window, deferred_write=False, prologue=False, paged_cold=None,
               block_tables=None, block_tokens=0, paged_kernel=False,
               residual=None):
    """Sharded attention sub-block against the FULL stacked caches (L, B, hk, S, hs).

    residual: optional (B, T, dim) block input; when given the returned
    attn_out is ALREADY residual-joined (residual + wo-projection). Under
    use_pallas == "fused" with a single-chip wo (axis_name None) the add runs
    inside the dequant-matmul kernel's accumulator; otherwise it is the same
    `residual + y` the caller used to compute — callers must not re-add.

    Head counts in bp may be TP-local slices; the cache sequence axis may be sp-sharded
    (ring attention). The cache WRITE discipline depends on the caller: in-scan mode
    updates (layer_idx, :, :, pos) in place and returns the caches; deferred mode
    returns only the new (k_t, v_t) rows for forward() to commit after the scan.
    Either way decode's READ is only the first `window` positions (a static bucket
    >= pos+T chosen by the caller), so cache HBM traffic scales with the live
    context, not the allocated seq_len. The reference gets the same effect for free
    because its attention loop runs 0..pos (llama2-tasks.cpp:62-93); with XLA's
    static shapes the window bucket is the equivalent lever.
    """
    b, t, _ = x.shape
    hs = spec.head_size
    _, _, hk, s, _ = kc.shape
    if prologue:
        # fused rmsnorm+quantize prologue kernel (ops/pallas_prologue.py): the
        # norm and the Q80 activation quantization every decode matvec needs
        # collapse into one VPU pass, and the quantized row feeds the inline-Xexp
        # matvec directly (qmatmul_q80)
        from ..ops.pallas_prologue import rmsnorm_quantize_q80

        xq, sx = rmsnorm_quantize_q80(x, bp["rms_att"], spec.norm_eps)

        def project(wname):
            return qmatmul_q80(xq, sx, bp[wname], use_pallas=use_pallas,
                               out_dtype=x.dtype)
    else:
        xb = rmsnorm(x, bp["rms_att"], spec.norm_eps)

        def project(wname):
            return qmatmul(xb, bp[wname], use_pallas=use_pallas)
    if "wqkv" in bp:
        # merged QKV (models/params.py fuse_matvec_groups): ONE kernel launch for
        # all three projections. Local row counts split proportionally to the
        # global dim : kv : kv ratio (exact — every term divides by tp).
        qkv = project("wqkv")
        total = qkv.shape[-1]
        lq = total * spec.dim // (spec.dim + 2 * spec.kv_dim)
        lkv = (total - lq) // 2
        q = qkv[..., :lq]
        k = qkv[..., lq:lq + lkv]
        v = qkv[..., lq + lkv:]
    else:
        q = project("wq")
        k = project("wk")
        v = project("wv")

    def project_out(att):
        """wo projection + TP merge; under the prologue the attention output is
        quantized by the fused kernel instead of inside the matvec. The TP-local
        row width (hq_local*hs) is re-checked — the forward()-level gate only
        validated spec.dim."""
        from ..ops.pallas_prologue import prologue_supported, quantize_q80_row

        if prologue and prologue_supported(att.shape[-1]):
            aq, asx = quantize_q80_row(att)
            y = qmatmul_q80(aq, asx, bp["wo"], use_pallas=use_pallas,
                            out_dtype=x.dtype)
        else:
            if (residual is not None and axis_name is None
                    and use_pallas == "fused"):
                # single-chip wo: fold the residual into the kernel's f32
                # accumulator init (TP partials must psum BEFORE the join,
                # so the fusion is gated to axis_name is None)
                return qmatmul(att, bp["wo"], use_pallas=use_pallas,
                               residual=residual)
            y = qmatmul(att, bp["wo"], use_pallas=use_pallas)
        y = _maybe_psum(y, axis_name, compress)
        return y if residual is None else residual + y
    hq_local = q.shape[-1] // hs
    hk_local = k.shape[-1] // hs
    q = apply_rope(q.reshape(b, t, hq_local, hs), rope, positions)
    k = apply_rope(k.reshape(b, t, hk_local, hs), rope, positions)
    v = v.reshape(b, t, hk_local, hs)
    if sp_axis_name is not None and sp_size > 1:
        # sequence parallelism: each sp member keeps its slice of the cache and the
        # KV blocks rotate around the ring (ops/ring_attention.py).
        if deferred_write:
            # deferred discipline on the sp path: the sharded caches stay
            # loop-invariant (read-only — no full-local-slice carry copies); the
            # ring attends COMMITTED rows only (live_end) plus the current
            # chunk's K/V as a register block, and the new rows ride out as scan
            # ys for forward() to commit with ONE masked window write per cache
            # (ops/ring_attention.py commit_kv_rows_sharded).
            #
            # The deferred sp cache is STRIPED (member m's slot j = position
            # j*sp + m): the live context occupies the same slot prefix on every
            # member, so a static window bucket bounds each rotation to
            # ceil(window/sp) columns — ICI and HBM per step track the LIVE
            # context, not the allocated seq_len (the sp analog of attn_window;
            # impossible under contiguous sharding, where the live prefix
            # concentrates on low-index members).
            k_t = jnp.swapaxes(k, 1, 2).astype(kc.dtype)  # (B, hk, T, hs)
            v_t = jnp.swapaxes(v, 1, 2).astype(vc.dtype)
            kl = jax.lax.dynamic_slice(kc, (layer_idx, 0, 0, 0, 0),
                                       (1, b, hk, s, hs))[0]
            vl = jax.lax.dynamic_slice(vc, (layer_idx, 0, 0, 0, 0),
                                       (1, b, hk, s, hs))[0]
            wl = (None if window is None
                  else min((window + sp_size - 1) // sp_size, s))
            att = ring_attention(q, kl, vl, positions, axis_name=sp_axis_name,
                                 axis_size=sp_size, live_end=start_pos,
                                 chunk=(k_t, v_t, start_pos), striped=True,
                                 window_slots=wl)
            attn_out = project_out(att)
            return attn_out, (k_t, v_t)  # new rows only; caller commits post-scan
        # in-scan form: layer slice out, sharded update, full-layer write-back
        # (the ring path reads the whole local slice anyway)
        kl = jax.lax.dynamic_slice(kc, (layer_idx, 0, 0, 0, 0), (1, b, hk, s, hs))[0]
        vl = jax.lax.dynamic_slice(vc, (layer_idx, 0, 0, 0, 0), (1, b, hk, s, hs))[0]
        kl, vl = update_kv_cache_sharded(kl, vl, k, v, start_pos,
                                         axis_name=sp_axis_name)
        att = ring_attention(q, kl, vl, positions, axis_name=sp_axis_name,
                             axis_size=sp_size)
        kc = jax.lax.dynamic_update_slice(kc, kl[None], (layer_idx, 0, 0, 0, 0))
        vc = jax.lax.dynamic_update_slice(vc, vl[None], (layer_idx, 0, 0, 0, 0))
    elif deferred_write and paged_cold is not None:
        # Paged (out-of-core) cache: the device cache's S axis is a RING of the
        # R most recent positions (slot = position mod R); everything older lives
        # in the host store, and its attention contribution arrives as a
        # (normalized output, lse) partial from the per-layer host callback —
        # merged with the hot segment by the flash-attention segment identity
        # (ops/attention.py merge_attention_partials). TPU-native equivalent of
        # the reference's mmap'd disk KV cache (transformer.cpp:312-318): same
        # capacity valve, but the resident window stays HBM-fast and only the
        # cold history pays host bandwidth.
        k_t = jnp.swapaxes(k, 1, 2).astype(kc.dtype)  # (B, hk, T, hs)
        v_t = jnp.swapaxes(v, 1, 2).astype(vc.dtype)
        kl = jax.lax.dynamic_slice(kc, (layer_idx, 0, 0, 0, 0), (1, b, hk, s, hs))[0]
        vl = jax.lax.dynamic_slice(vc, (layer_idx, 0, 0, 0, 0), (1, b, hk, s, hs))[0]
        # slot j's most recent committed position: p_j = j + R*floor((pos-1-j)/R)
        # (< start_pos by construction; negative = never written = masked). The
        # committed ring covers exactly [max(0, start_pos-R), start_pos) — the
        # host cold segment covers [0, max(0, start_pos-R)) with no overlap.
        slot = jnp.arange(s)
        p_j = slot + s * jnp.floor_divide(start_pos - 1 - slot, s)
        slot_pos = jnp.where(p_j >= 0, p_j, jnp.int32(1 << 30))
        key_pos = jnp.concatenate([slot_pos, start_pos + jnp.arange(t)])
        from ..ops.attention import gqa_attention_lse, merge_attention_partials

        out_h, lse_h = gqa_attention_lse(
            q, jnp.concatenate([kl, k_t], axis=2),
            jnp.concatenate([vl, v_t], axis=2), positions, key_positions=key_pos)
        out_c, lse_c = paged_cold(layer_idx, q.astype(jnp.float32), start_pos)
        att = merge_attention_partials(out_h, lse_h, out_c, lse_c)
        att = att.reshape(b, t, hq_local * hs).astype(x.dtype)
        attn_out = project_out(att)
        return attn_out, (k_t, v_t)  # caller commits into ring slots (mod R)
    elif deferred_write and block_tables is not None:
        # Device-resident paged KV (docs/PAGED_KV.md): the caches are a
        # BLOCK POOL (L, N, hk, bt, hs) and each row's block table maps
        # virtual positions to pool blocks. Two readers, same semantics:
        # the Pallas kernel DMAs exactly the table's blocks pool→VMEM
        # (scalar-prefetch index_map, ops/pallas_paged_attention.py); the
        # XLA fallback gathers the table into the dense window layout and
        # runs the SAME gqa_attention as the dense deferred branch — so on
        # the CPU mesh paged logits are bit-identical to dense logits
        # (the paged-vs-dense token-identity bar, tests/test_paged_kv.py).
        # Writes commit post-scan through the same table (forward() below).
        k_t = jnp.swapaxes(k, 1, 2).astype(kc.dtype)  # (B, hk, T, hs)
        v_t = jnp.swapaxes(v, 1, 2).astype(vc.dtype)
        w_total = block_tables.shape[1]
        win = window or (w_total * block_tokens)
        nb = min(-(-win // block_tokens), w_total)
        if paged_kernel:
            from ..ops.pallas_paged_attention import paged_attention

            out = paged_attention(q.astype(jnp.float32), kc, vc, k_t, v_t,
                                  block_tables, start_pos, layer_idx,
                                  n_read=nb)
            att = out.reshape(b, t, hq_local * hs).astype(x.dtype)
        else:
            from ..ops.pallas_paged_attention import paged_gather_kv

            kw, vw = paged_gather_kv(kc, vc, layer_idx, block_tables, nb)
            vwin = nb * block_tokens
            slot = jnp.arange(vwin)
            # same committed-rows masking (and sentinel arithmetic) as the
            # dense per-row deferred branch below — a table entry past the
            # row's committed length is scratch/garbage and masks out
            slot_pos = jnp.where(slot[None, :] < start_pos[:, None],
                                 slot[None, :], spec.seq_len + 1)  # (B, vwin)
            key_pos = jnp.concatenate(
                [slot_pos, start_pos[:, None] + jnp.arange(t)[None, :]],
                axis=1)
            att = gqa_attention(q, jnp.concatenate([kw, k_t], axis=2),
                                jnp.concatenate([vw, v_t], axis=2),
                                positions, key_positions=key_pos)
        attn_out = project_out(att)
        return attn_out, (k_t, v_t)  # new rows only; caller commits post-scan
    elif deferred_write:
        # deferred-write path: the caches are loop-INVARIANT inside the layer scan —
        # attention reads the window of COMMITTED rows (positions < start_pos) and
        # attends to the current chunk's k/v directly from registers; the new rows
        # ride out of the scan as stacked ys and forward() commits all layers with
        # ONE top-level dynamic_update_slice per cache. Motivation: a scan carry
        # that is dynamic-update-sliced at a loop-varying layer index defeats XLA
        # TPU's in-place while-loop buffer optimization — the round-4 trace shows
        # the full (L,B,hk,S,hs) caches being copied at the step boundary
        # (~11.6 ms/token at 7B, a third of the step). A read-only operand has no
        # copy-on-write hazard.
        k_t = jnp.swapaxes(k, 1, 2).astype(kc.dtype)  # (B, hk, T, hs)
        v_t = jnp.swapaxes(v, 1, 2).astype(vc.dtype)
        win = window or s
        # windows past the single-block VMEM budget take the kernel's window-
        # tiled form (flash-attention carry in scratch, ops/pallas_attention.py)
        # — long contexts never fall back to XLA slicing mid-generation
        if use_pallas and t == 1 and b == 1 and start_pos.ndim == 0:
            # fused decode kernel: the cache window is DMA'd straight out of the
            # stacked buffers inside the kernel (ops/pallas_attention.py) — no
            # per-layer dynamic-slice materialization in XLA at all
            from ..ops.pallas_attention import fused_decode_attention

            g = hq_local // hk
            out = fused_decode_attention(
                q.reshape(hk, g, hs).astype(jnp.float32), kc, vc,
                k_t[0], v_t[0], layer_idx, start_pos, window=win)
            att = out.reshape(1, 1, hq_local * hs).astype(x.dtype)
            attn_out = project_out(att)
            return attn_out, (k_t, v_t)
        kw = jax.lax.dynamic_slice(kc, (layer_idx, 0, 0, 0, 0), (1, b, hk, win, hs))[0]
        vw = jax.lax.dynamic_slice(vc, (layer_idx, 0, 0, 0, 0), (1, b, hk, win, hs))[0]
        # window slot j holds a committed row iff j < start_pos; stale slots get a
        # past-seq_len position so the causal compare masks them. Current-chunk keys
        # carry their true absolute positions.
        slot = jnp.arange(win)
        if start_pos.ndim == 0:
            slot_pos = jnp.where(slot < start_pos, slot, s + 1)  # (win,)
            key_pos = jnp.concatenate([slot_pos, start_pos + jnp.arange(t)])
        else:  # per-row offsets (continuous batching)
            slot_pos = jnp.where(slot[None, :] < start_pos[:, None], slot[None, :],
                                 s + 1)  # (B, win)
            key_pos = jnp.concatenate(
                [slot_pos, start_pos[:, None] + jnp.arange(t)[None, :]], axis=1)
        kfull = jnp.concatenate([kw, k_t], axis=2)  # (B, hk, win+T, hs)
        vfull = jnp.concatenate([vw, v_t], axis=2)
        att = gqa_attention(q, kfull, vfull, positions, key_positions=key_pos)
        attn_out = project_out(att)
        return attn_out, (k_t, v_t)  # new rows only; caller commits post-scan
    elif start_pos.ndim == 1:
        # per-row offsets (continuous batching): vmap'd per-row write on the layer
        # slice, then full-layer write-back
        kl = jax.lax.dynamic_slice(kc, (layer_idx, 0, 0, 0, 0), (1, b, hk, s, hs))[0]
        vl = jax.lax.dynamic_slice(vc, (layer_idx, 0, 0, 0, 0), (1, b, hk, s, hs))[0]
        kl, vl = update_kv_cache(kl, vl, k, v, start_pos)
        win = window or s
        att = gqa_attention(q, kl[:, :, :win], vl[:, :, :win], positions)
        kc = jax.lax.dynamic_update_slice(kc, kl[None], (layer_idx, 0, 0, 0, 0))
        vc = jax.lax.dynamic_update_slice(vc, vl[None], (layer_idx, 0, 0, 0, 0))
    else:
        # in-scan path: tiny in-place write at (layer, :, :, pos), windowed read
        k_t = jnp.swapaxes(k, 1, 2).astype(kc.dtype)[None]  # (1, B, hk, T, hs)
        v_t = jnp.swapaxes(v, 1, 2).astype(vc.dtype)[None]
        kc = jax.lax.dynamic_update_slice(kc, k_t, (layer_idx, 0, 0, start_pos, 0))
        vc = jax.lax.dynamic_update_slice(vc, v_t, (layer_idx, 0, 0, start_pos, 0))
        win = window or s
        kw = jax.lax.dynamic_slice(kc, (layer_idx, 0, 0, 0, 0), (1, b, hk, win, hs))[0]
        vw = jax.lax.dynamic_slice(vc, (layer_idx, 0, 0, 0, 0), (1, b, hk, win, hs))[0]
        att = gqa_attention(q, kw, vw, positions)
    # col-parallel wo: local heads x local input slice -> partial (B, T, dim); psum merges
    attn_out = project_out(att)
    return attn_out, (kc, vc)


def _dense_ffn(x, bp, spec: ModelSpec, axis_name, use_pallas, compress,
               prologue=False, residual=None):
    """Dense FFN on the PRE-norm block input x (the rms_ffn norm is applied
    here so the prologue can fuse it with the activation quantize). One body
    for both modes — only the projection primitive differs: under the prologue
    each activation row is quantized by a fused kernel (ops/pallas_prologue.py)
    and qmatmul_q80 consumes the pre-quantized row; otherwise the matvecs
    quantize internally. TP-local widths are re-checked before each prologue
    kernel — the forward()-level gate only validated spec.dim.

    residual: optional (B, T, dim); when given the return value is ALREADY
    residual + ffn(x) — under use_pallas == "fused" with a single-chip w2
    the add fuses into the down-projection kernel's accumulator init, and the
    gate/up pair (when kept separate — Engine fused_matmul skips the w13
    merge) lowers to ONE silu·mul-epilogue kernel whose (B·T, hidden)
    intermediates never touch HBM. Callers must not re-add."""
    act = _act(spec)
    if prologue:
        from ..ops.pallas_prologue import (prologue_supported, quantize_q80_row,
                                           rmsnorm_quantize_q80)

        xq, sx = rmsnorm_quantize_q80(x, bp["rms_ffn"], spec.norm_eps)

        def project(wname):
            return qmatmul_q80(xq, sx, bp[wname], use_pallas=use_pallas,
                               out_dtype=jnp.float32)

        if "w13" in bp:
            h = _gated_split(project("w13"), act, gate_first=True)
        else:
            h = act(project("w1")) * project("w3")
    else:
        xb = rmsnorm(x, bp["rms_ffn"], spec.norm_eps)
        if "w13" in bp:
            # merged gate+up (fuse_matvec_groups): one launch per TP group;
            # the packed stream is already one pass, only the act·mul epilogue
            # stays un-fused on this layout
            h = _gated_split(qmatmul(xb, bp["w13"], use_pallas=use_pallas),
                             act, gate_first=True)
        else:
            h = qmatmul_gated(xb, bp["w1"], bp["w3"], act=act,
                              act_name=_act_name(spec),
                              use_pallas=use_pallas)
    if prologue and prologue_supported(h.shape[-1]):
        hq, hsx = quantize_q80_row(h)
        out = qmatmul_q80(hq, hsx, bp["w2"], use_pallas=use_pallas,
                          out_dtype=x.dtype)
    else:
        if (residual is not None and axis_name is None
                and use_pallas == "fused"):
            # single-chip w2: residual folds into the kernel accumulator
            # (TP partials must psum before the join — see _attention)
            return qmatmul(h.astype(x.dtype), bp["w2"], use_pallas=use_pallas,
                           residual=residual)
        out = qmatmul(h.astype(x.dtype), bp["w2"], use_pallas=use_pallas)
    out = _maybe_psum(out, axis_name, compress)
    return out if residual is None else residual + out


def _gated_split(y, act, gate_first: bool):
    """Gated-FFN combine from a merged projection output split in halves per TP
    group: w13 is [gate|up] (act(first)*second), moe_gu is [up|gate]
    (first*act(second)) — member order set by _FUSE_GROUPS."""
    hl = y.shape[-1] // 2
    a, b = y[..., :hl], y[..., hl:]
    return act(a) * b if gate_first else a * act(b)


def _make_expert_step(xb, act, use_pallas, merged):
    """Scan body for the expert-major MoE prefill path; the merged form consumes
    the fused [up|gate] stack. Shared by _moe_ffn and _moe_ffn_expert_sharded
    (only the combine weights differ, and they ride in the xs)."""
    if merged:
        def step(acc, ew):
            gu_e, down_e, comb = ew  # QTensors (2h0,d)/(d,h0), comb (B,T)
            hb = _gated_split(qmatmul(xb, gu_e, use_pallas=use_pallas), act,
                              gate_first=False)
            out_e = qmatmul(hb, down_e, use_pallas=use_pallas)
            return acc + out_e * comb[..., None], None
    else:
        def step(acc, ew):
            up_e, gate_e, down_e, comb = ew  # QTensors (h0,d)/(d,h0), comb (B,T)
            hb = qmatmul(xb, up_e, use_pallas=use_pallas) * act(
                qmatmul(xb, gate_e, use_pallas=use_pallas))
            out_e = qmatmul(hb, down_e, use_pallas=use_pallas)
            return acc + out_e * comb[..., None], None
    return step


def _expert_scan_xs(bp, merged, combine):
    if merged:
        return (bp["moe_gu"], bp["moe_down"], combine)
    return (bp["moe_up"], bp["moe_gate"], bp["moe_down"], combine)


def _gather_expert(w, idx):
    """Select expert slices of a stacked QTensor (E, out, in) -> (B, T, K, out, in)."""
    return jax.tree_util.tree_map(lambda a: a[idx], w)


def _moe_ffn(xb, bp, spec: ModelSpec, axis_name, use_pallas, compress):
    """Top-k MoE FFN (grokMoeRouter..grokMoeBlock2, grok1-tasks.cpp:56-228).

    Router runs replicated (the reference runs it root-only and broadcasts indexes).
    Two expert shardings (parallel/sharding.py):
    - slice (default): every expert's hidden axis is TP-sliced like the dense FFN;
      the down-matmul partial sums psum across tp.
    - expert: whole experts shard over tp (detected here by the LOCAL stack's
      expert count being smaller than spec.n_experts under shard_map) — each shard
      computes only the active experts it owns (lax.cond keeps non-owners from
      streaming weights) and the same psum merges the contributions. The capacity
      axis for Grok-1-314B-class expert weights; no reference counterpart.
    """
    b, t, d = xb.shape
    k = spec.n_active_experts
    act = _act(spec)

    router_logits = qmatmul(xb, bp["router"], use_pallas=False).astype(jnp.float32)
    probs = jax.nn.softmax(router_logits, axis=-1)  # softmax over ALL experts
    top_p, top_i = jax.lax.top_k(probs, k)  # (B, T, K)
    weights = top_p / jnp.sum(top_p, axis=-1, keepdims=True)  # renormalize (grokMoeNormWeights)

    merged = "moe_gu" in bp  # fused up+gate stack (fuse_matvec_groups)
    gu_stack = bp["moe_gu"] if merged else bp["moe_up"]
    el = gu_stack.shape[0]  # shard-local expert count
    if axis_name is not None and el != spec.n_experts:
        return _moe_ffn_expert_sharded(xb, bp, spec, axis_name, use_pallas, compress,
                                       top_i, weights, el)

    if use_pallas and b * t == 1 and gu_stack.layout in ("i4p", "i8"):
        # Decode through the fused matvec kernels: dynamic_slice each active expert's
        # packed planes out of the stacked (E, ...) QTensor (moving exactly that
        # expert's bytes through HBM — the reference's per-active-expert matmuls,
        # grok1-tasks.cpp:128-144) and run the same q4/q8 kernel as the dense path.
        def expert_q(wstack, e):
            return jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_slice_in_dim(a, e, 1, 0)[0], wstack)

        out = jnp.zeros_like(xb)
        for j in range(k):
            e = top_i.reshape(k)[j]
            if merged:
                hb = _gated_split(qmatmul(xb, expert_q(bp["moe_gu"], e),
                                          use_pallas=True), act, gate_first=False)
            else:
                hb = qmatmul(xb, expert_q(bp["moe_up"], e), use_pallas=True) * act(
                    qmatmul(xb, expert_q(bp["moe_gate"], e), use_pallas=True))
            out_e = qmatmul(hb, expert_q(bp["moe_down"], e), use_pallas=True)
            out = out + out_e * weights.reshape(k)[j].astype(xb.dtype)
    elif b * t * k <= spec.n_experts:
        # Decode: gather the K active experts' (sliced) weight matrices per token,
        # dequantize, matmul. Moves exactly the active experts' bytes out of HBM — the
        # same bandwidth shape as the reference's per-expert forward calls.
        down_w = _gather_expert(bp["moe_down"], top_i).dequantize(dtype=xb.dtype)
        if merged:
            gu_w = _gather_expert(bp["moe_gu"], top_i).dequantize(dtype=xb.dtype)
            hb = _gated_split(jnp.einsum("btd,btkhd->btkh", xb, gu_w), act,
                              gate_first=False)
        else:
            up_w = _gather_expert(bp["moe_up"], top_i).dequantize(dtype=xb.dtype)
            gate_w = _gather_expert(bp["moe_gate"], top_i).dequantize(dtype=xb.dtype)
            hb = jnp.einsum("btd,btkhd->btkh", xb, up_w) * act(
                jnp.einsum("btd,btkhd->btkh", xb, gate_w))
        out = jnp.einsum("btkh,btkdh->btkd", hb, down_w)
        out = jnp.einsum("btkd,btk->btd", out, weights.astype(xb.dtype))
    else:
        # Prefill: per-token weight gathers would materialize (B,T,K,h,d); instead scan
        # expert-major — each step dequantizes ONE expert's matrices and masks its
        # contribution by the routing weights (zero for tokens that didn't pick it).
        one_hot = jax.nn.one_hot(top_i, spec.n_experts, dtype=xb.dtype)  # (B,T,K,E)
        combine = jnp.einsum("btke,btk->ebt", one_hot, weights.astype(xb.dtype))

        out, _ = jax.lax.scan(_make_expert_step(xb, act, use_pallas, merged),
                              jnp.zeros_like(xb),
                              _expert_scan_xs(bp, merged, combine))
    return _maybe_psum(out, axis_name, compress)


def _moe_ffn_expert_sharded(xb, bp, spec: ModelSpec, axis_name, use_pallas, compress,
                            top_i, weights, el):
    """Expert-parallel MoE FFN body: this shard owns experts
    [shard*el, (shard+1)*el). Decode runs one lax.cond per active expert (owners
    stream and compute, everyone else contributes zeros for free); prefill scans
    the local expert stack with the global routing weights sliced to the local
    window. The trailing psum is the merge point either way."""
    b, t, _ = xb.shape
    k = spec.n_active_experts
    act = _act(spec)
    shard = jax.lax.axis_index(axis_name)
    offset = shard * el
    merged = "moe_gu" in bp

    def expert_q(wstack, e):
        return jax.tree_util.tree_map(
            lambda a: jax.lax.dynamic_slice_in_dim(a, e, 1, 0)[0], wstack)

    def expert_hb(row_x, e_loc):
        """hb for one local expert — merged [up|gate] stack or separate."""
        if merged:
            return _gated_split(qmatmul(row_x, expert_q(bp["moe_gu"], e_loc),
                                        use_pallas=use_pallas), act,
                                gate_first=False)
        return qmatmul(row_x, expert_q(bp["moe_up"], e_loc),
                       use_pallas=use_pallas) * act(
            qmatmul(row_x, expert_q(bp["moe_gate"], e_loc),
                    use_pallas=use_pallas))

    if t == 1 and b * k <= 2 * spec.n_experts:
        # decode (incl. batched slots): one cond per (row, active expert) — owner
        # shards stream and compute exactly the routed experts, everyone else's
        # branch is a free zero. Unrolls b*k conds, so bounded to small batches;
        # bigger batches amortize fine through the local-stack scan below.
        rows = []
        for r in range(b):
            row_x = xb[r:r + 1]
            row_out = jnp.zeros_like(row_x)
            for j in range(k):
                e_rel = top_i[r, 0, j] - offset
                in_range = (e_rel >= 0) & (e_rel < el)
                e_loc = jnp.clip(e_rel, 0, el - 1)
                w_j = weights[r, 0, j].astype(xb.dtype)

                def compute(row_x=row_x, e_loc=e_loc):
                    return qmatmul(expert_hb(row_x, e_loc),
                                   expert_q(bp["moe_down"], e_loc),
                                   use_pallas=use_pallas)

                out_e = jax.lax.cond(in_range, compute,
                                     lambda row_x=row_x: jnp.zeros_like(row_x))
                row_out = row_out + out_e * w_j
            rows.append(row_out)
        out = jnp.concatenate(rows, axis=0) if b > 1 else rows[0]
    else:
        one_hot = jax.nn.one_hot(top_i, spec.n_experts, dtype=xb.dtype)  # (B,T,K,E)
        combine = jnp.einsum("btke,btk->ebt", one_hot, weights.astype(xb.dtype))
        combine_local = jax.lax.dynamic_slice_in_dim(combine, offset, el, 0)

        out, _ = jax.lax.scan(_make_expert_step(xb, act, use_pallas, merged),
                              jnp.zeros_like(xb),
                              _expert_scan_xs(bp, merged, combine_local))
    return _maybe_psum(out, axis_name, compress)


def _block(carry, layer, spec: ModelSpec, rope: RopeTables, start_pos, positions,
           axis_name, sp_axis_name, sp_size, use_pallas, compress, window,
           kc_ro=None, vc_ro=None, prologue=False, paged_cold=None,
           block_tables=None, block_tokens=0, paged_kernel=False):
    """One transformer block as a scan step. Two cache disciplines:

    - in-scan (kc_ro is None): caches travel in the carry and are updated in place
      per layer — carry (x, kc, vc), ys None.
    - deferred (kc_ro/vc_ro set): caches are read-only closures (loop invariants);
      carry is just x and the layer's new K/V rows leave as ys for forward() to
      commit in one top-level write.
    """
    deferred = kc_ro is not None
    if deferred:
        x, kc, vc = carry, kc_ro, vc_ro
    else:
        x, kc, vc = carry
    bp, layer_idx = layer
    # grok residual-joins the NORMALIZED attention output, so the projection
    # kernel cannot fold the raw residual there; every other arch hands the
    # block input down as the fusable residual (contract: attn_out returns
    # already joined when residual is given)
    res_attn = None if spec.arch_type == ArchType.GROK1 else x
    attn_out, kvout = _attention(x, bp, layer_idx, spec, rope, kc, vc, start_pos,
                                 positions, axis_name, sp_axis_name, sp_size,
                                 use_pallas, compress, window,
                                 deferred_write=deferred, prologue=prologue,
                                 paged_cold=paged_cold,
                                 block_tables=block_tables,
                                 block_tokens=block_tokens,
                                 paged_kernel=paged_kernel,
                                 residual=res_attn)
    if not deferred:
        kc, vc = kvout
    if spec.arch_type == ArchType.GROK1:
        # grok: residual-join the *normalized* attention output (grokRmfFfn/Norm/Join)
        x = x + rmsnorm(attn_out, bp["rms_ffn"], spec.norm_eps)
        xb = rmsnorm(x, bp["rms_moe"], spec.norm_eps)
        moe_out = _moe_ffn(xb, bp, spec, axis_name, use_pallas, compress)
        x = x + rmsnorm(moe_out, bp["rms_ffn2"], spec.norm_eps)
    else:
        x = attn_out  # residual-joined inside _attention
        if spec.is_moe:
            xb = rmsnorm(x, bp["rms_ffn"], spec.norm_eps)
            x = x + _moe_ffn(xb, bp, spec, axis_name, use_pallas, compress)
        else:
            x = _dense_ffn(x, bp, spec, axis_name, use_pallas, compress,
                           prologue=prologue, residual=x)
    if deferred:
        return x, kvout  # ys: this layer's (k_t, v_t) new rows
    return (x, kc, vc), None


def forward(params: dict[str, Any], spec: ModelSpec, rope: RopeTables,
            tokens: jax.Array, k_cache: jax.Array, v_cache: jax.Array,
            start_pos: jax.Array, *, dtype=jnp.float32, axis_name: str | None = None,
            sp_axis_name: str | None = None, sp_size: int = 1,
            use_pallas: bool = False, compress_collectives: bool = False,
            attn_window: int | None = None, cache_write: str = "inscan",
            fused_prologue: bool = False, paged_cold=None,
            block_tables=None, block_tokens: int = 0,
            paged_kernel: bool = False):
    """Run T tokens through the model against the KV cache.

    tokens: (B, T) int32; k_cache/v_cache: (L, B, hk[/tp], S, hs); start_pos: scalar
    (all rows at one offset — the reference's single `pos`) or (B,) per-row offsets
    (continuous batching: each sequence decodes at its own position; the reference's
    single-slot pos has no analog). Returns (logits (B, T, vocab) f32, caches).

    Per-row start_pos also carries MIXED batches (BatchEngine): rows need not
    all use their T positions — a decode row in a (B, T=chunk) prefill step
    puts its one real token at index 0 and scratch beyond. Causal masking
    confines token 0's attention to the row's committed history plus itself,
    so its logits[row, 0] equal a T=1 step's, and the scratch writes land on
    positions > start_pos that every read path masks until the row's own
    later tokens overwrite them. The batched decode scan
    (runtime/device_loop.py) parks finished rows on the same invariant.

    cache_write selects the cache discipline:
    - "inscan": caches are scan CARRIES, updated in place per layer at a dynamic
      layer index — NOT scan xs/ys, which would restack (read+write) the full
      (L, B, hk, S, hs) buffers every step (~4 GB/token at 7B/2048, measured as
      half the step time in round 3).
    - "deferred": caches are loop-INVARIANT operands of the scan (read-only);
      each layer's new K/V rows leave as ys ((L, B, hk, T, hs), tiny) and ONE
      top-level dynamic_update_slice per cache commits them after the scan.
      Motivation: the round-4 TPU trace shows the in-scan carries being copied
      whole at the step boundary (~11.6 ms/token at 7B) — XLA TPU's in-place
      while-buffer optimization does not fire for a carry that is
      dynamic-update-sliced at a loop-varying index. Under sp the same
      discipline applies to the sequence-sharded caches: the ring attends
      committed rows + the chunk's K/V as a register block, and the commit is
      a masked window write into the owning shard (commit_kv_rows_sharded).

    attn_window: static bound on cache positions attention reads (must cover
    start_pos + T). None reads the full seq_len. Callers bucket it (Engine) so decode
    cache traffic tracks the live context length.

    Equivalent of Inference::infer (tasks.cpp:173-184) for the whole token chunk; the
    embedding-row copy at tasks.cpp:176-177 is the take() below, the task loop is the scan.
    """
    t = tokens.shape[1]
    if axis_name is not None:
        params = _localize_qtensors(params)
    start_pos = jnp.asarray(start_pos)
    if start_pos.ndim == 1:
        assert sp_size == 1, "per-row start_pos is not supported with sp (ring) sharding"
        positions = start_pos[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]  # (B, T)
    else:
        positions = start_pos + jnp.arange(t, dtype=jnp.int32)
    x = jnp.take(params["embedding"], tokens, axis=0).astype(dtype)
    if spec.arch_type == ArchType.GROK1:
        x = x * GROK_EMBEDDING_SCALE

    assert cache_write in ("inscan", "deferred"), cache_write
    deferred = cache_write == "deferred"
    sp_active = sp_axis_name is not None and sp_size > 1
    if paged_cold is not None:
        assert deferred and not sp_active and start_pos.ndim == 0, (
            "paged KV cache requires the deferred discipline, no sp sharding, "
            "and a scalar start_pos")
        assert t <= k_cache.shape[3], (
            f"chunk {t} exceeds the {k_cache.shape[3]}-slot resident ring")
    if block_tables is not None:
        assert deferred and not sp_active and paged_cold is None, (
            "device-resident paged KV requires the deferred discipline and "
            "no sp sharding / host-spill paging")
        assert block_tokens >= 1 and start_pos.ndim == 1, (
            "paged KV needs block_tokens and per-row start_pos")
    # fused rmsnorm+quantize prologue (ops/pallas_prologue.py): single-row decode
    # only (the kernels take one activation row), opt-in via fused_prologue
    if fused_prologue:
        from ..ops.pallas_prologue import prologue_supported

        fused_prologue = (use_pallas and t == 1 and tokens.shape[0] == 1
                          and start_pos.ndim == 0
                          and prologue_supported(spec.dim))
    block_fn = functools.partial(_block, spec=spec, rope=rope, start_pos=start_pos,
                                 positions=positions, axis_name=axis_name,
                                 sp_axis_name=sp_axis_name, sp_size=sp_size,
                                 use_pallas=use_pallas, compress=compress_collectives,
                                 window=attn_window,
                                 kc_ro=k_cache if deferred else None,
                                 vc_ro=v_cache if deferred else None,
                                 prologue=fused_prologue, paged_cold=paged_cold,
                                 block_tables=block_tables,
                                 block_tokens=block_tokens,
                                 paged_kernel=paged_kernel)
    layer_ids = jnp.arange(spec.n_layers, dtype=jnp.int32)
    if deferred:
        x, (k_rows, v_rows) = jax.lax.scan(
            block_fn, x, (params["blocks"], layer_ids))
        # commit all layers' new rows in one write per cache: (L, B, hk, T, hs)
        # lands at [.., .., .., start_pos : start_pos+T, ..]
        if block_tables is not None:
            # paged commit: position p of row b lands in pool block
            # tables[b, p // bt] at offset p % bt — one scatter per cache,
            # through the same table the read path consumed. Out-of-range
            # positions cannot occur by scheduler invariant (coverage is
            # ensured pre-dispatch; parked rows clamp below seq_len).
            pos_bt = positions  # (B, T) absolute positions
            blk = jnp.take_along_axis(
                block_tables, jnp.minimum(pos_bt // block_tokens,
                                          block_tables.shape[1] - 1), axis=1)
            off = pos_bt % block_tokens  # (B, T)
            k_cache = k_cache.at[:, blk, :, off, :].set(
                jnp.transpose(k_rows, (1, 3, 0, 2, 4)))
            v_cache = v_cache.at[:, blk, :, off, :].set(
                jnp.transpose(v_rows, (1, 3, 0, 2, 4)))
        elif paged_cold is not None:
            # ring commit: position p lands in slot p mod R (scatter — the
            # chunk may wrap the ring boundary). The rows being overwritten
            # need no flush: the HOST store is authoritative for every
            # committed position (Engine writes the same rows there).
            ring = k_cache.shape[3]
            idx = (start_pos + jnp.arange(t)) % ring
            k_cache = k_cache.at[:, :, :, idx, :].set(k_rows)
            v_cache = v_cache.at[:, :, :, idx, :].set(v_rows)
        elif sp_active:
            # sequence-sharded caches: masked window write into the owning
            # shards, striped layout (see the _attention sp-deferred branch)
            k_cache, v_cache = commit_kv_rows_sharded(
                k_cache, v_cache, k_rows, v_rows, start_pos,
                axis_name=sp_axis_name, striped=True, axis_size=sp_size)
        elif start_pos.ndim == 0:
            k_cache = jax.lax.dynamic_update_slice(
                k_cache, k_rows, (0, 0, 0, start_pos, 0))
            v_cache = jax.lax.dynamic_update_slice(
                v_cache, v_rows, (0, 0, 0, start_pos, 0))
        else:  # per-row offsets: vmap the write over the batch axis
            row_write = jax.vmap(
                lambda c, n, p: jax.lax.dynamic_update_slice(c, n, (0, 0, p, 0)),
                in_axes=(1, 1, 0), out_axes=1)
            k_cache = row_write(k_cache, k_rows, start_pos)
            v_cache = row_write(v_cache, v_rows, start_pos)
    else:
        (x, k_cache, v_cache), _ = jax.lax.scan(
            block_fn, (x, k_cache, v_cache), (params["blocks"], layer_ids))

    x = rmsnorm(x, params["rms_final"], spec.norm_eps)
    logits = qmatmul(x, params["wcls"], use_pallas=use_pallas, out_dtype=jnp.float32)
    if axis_name is not None:
        # wcls is row(vocab)-sharded: concatenate the vocab shards
        logits = jax.lax.all_gather(logits, axis_name, axis=-1, tiled=True)
    if spec.arch_type == ArchType.GROK1:
        logits = logits * GROK_LOGITS_SCALE
    if paged_cold is not None:
        # the new rows ride out so the caller can append them to the host
        # store — the step's one extra device->host payload (L, B, hk, T, hs)
        return logits, k_cache, v_cache, (k_rows, v_rows)
    return logits, k_cache, v_cache


def init_kv_cache(spec: ModelSpec, batch: int = 1, dtype=jnp.float32,
                  n_kv_heads: int | None = None, seq_len: int | None = None):
    """Zeroed head-major KV caches (L, B, hk, S, hs); hk may be a TP-local count."""
    hk = n_kv_heads if n_kv_heads is not None else spec.n_kv_heads
    s = seq_len if seq_len is not None else spec.seq_len
    shape = (spec.n_layers, batch, hk, s, spec.head_size)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)
