"""Model hyperparameter spec — TPU-native equivalent of TransformerSpec.

Mirrors the reference header schema (src/transformer.hpp:10-90, parsing at
src/transformer.cpp:12-148): same arch types, activation enum, rope types, derived
head_size/kv_dim, seq-len clamping, and the `.m` header key numbering (used by
formats/mfile.py).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, replace


class ArchType(enum.IntEnum):
    """Reference: src/transformer.hpp:44-48 (also the legacy file magics)."""

    LLAMA = 0xABCD00
    GROK1 = 0xABCD01
    MIXTRAL = 0xABCD02


class HiddenAct(enum.IntEnum):
    GELU = 0
    SILU = 1


class RopeType(enum.IntEnum):
    UNKNOWN = -1
    LLAMA = 0
    FALCON = 1
    LLAMA3_1 = 2


# .m header key ids (reference: src/transformer.hpp:10-30 / converter/writer.py:109-130)
class HeaderKey(enum.IntEnum):
    VERSION = 0
    ARCH_TYPE = 1
    DIM = 2
    HIDDEN_DIM = 3
    N_LAYERS = 4
    N_HEADS = 5
    N_KV_HEADS = 6
    N_EXPERTS = 7
    N_ACTIVE_EXPERTS = 8
    VOCAB_SIZE = 9
    SEQ_LEN = 10
    HIDDEN_ACT = 11
    ROPE_THETA = 12
    WEIGHTS_FLOAT_TYPE = 13
    ROPE_SCALING_FACTOR = 14
    ROPE_SCALING_LOW_FREQ_FACTOR = 15
    ROPE_SCALING_HIGH_FREQ_FACTOR = 16  # reference spells this "FACTORY"
    ROPE_SCALING_ORIG_MAX_SEQ_LEN = 17
    ROPE_TYPE = 18


@dataclass(frozen=True)
class ModelSpec:
    arch_type: ArchType
    dim: int
    hidden_dim: int
    n_layers: int
    n_heads: int
    n_kv_heads: int
    vocab_size: int
    seq_len: int
    n_experts: int = 0
    n_active_experts: int = 0
    hidden_act: HiddenAct = HiddenAct.SILU
    rope_theta: float = 10000.0
    rope_type: RopeType = RopeType.UNKNOWN
    rope_scaling_factor: float = 0.0
    rope_scaling_low_freq_factor: float = 0.0
    rope_scaling_high_freq_factor: float = 0.0
    rope_scaling_orig_max_seq_len: int = 0
    orig_seq_len: int = 0
    version: int = 0
    norm_eps: float = 1e-5

    # --- derived (reference: transformer.cpp:102-106) ---
    @property
    def head_size(self) -> int:
        return self.dim // self.n_heads

    @property
    def kv_dim(self) -> int:
        return (self.dim * self.n_kv_heads) // self.n_heads

    @property
    def q_group(self) -> int:
        """GQA group size: query heads per kv head."""
        return self.n_heads // self.n_kv_heads

    def resolved(self, max_seq_len: int = 0) -> "ModelSpec":
        """Fill in defaults the way loadSpecFromFile does (transformer.cpp:88-106)."""
        spec = self
        if spec.rope_type == RopeType.UNKNOWN:
            if spec.arch_type == ArchType.LLAMA:
                spec = replace(spec, rope_type=RopeType.LLAMA)
            elif spec.arch_type in (ArchType.GROK1, ArchType.MIXTRAL):
                spec = replace(spec, rope_type=RopeType.FALCON)
            else:
                raise ValueError(f"cannot resolve rope type for arch {spec.arch_type}")
        orig = spec.orig_seq_len or spec.seq_len
        seq = spec.seq_len
        if max_seq_len > 0 and seq > max_seq_len:
            seq = max_seq_len
        spec = replace(spec, seq_len=seq, orig_seq_len=orig)
        assert spec.dim % spec.n_heads == 0, (spec.dim, spec.n_heads)
        assert spec.n_heads % spec.n_kv_heads == 0, (spec.n_heads, spec.n_kv_heads)
        return spec

    @property
    def is_moe(self) -> bool:
        return self.n_experts > 0
