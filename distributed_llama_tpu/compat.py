"""Version compatibility shims for the jax API surface this repo uses.

The codebase targets current jax (`jax.shard_map` with `check_vma`); older
runtimes (<= 0.4.x) only ship `jax.experimental.shard_map.shard_map`, whose
replication-check kwarg is spelled `check_rep`. Every sharded entry point
imports `shard_map` from here so one shim covers the whole repo.
"""

from __future__ import annotations

import jax

if hasattr(jax, "shard_map"):

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
else:  # jax <= 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma)
