"""Honor the caller's JAX platform choice even when jax was pre-imported.

Some launch environments (e.g. the axon TPU tunnel) import jax from sitecustomize at
interpreter startup, freezing its snapshot of JAX_PLATFORMS before application code
runs. Entry points call apply_platform_env() first so an explicit
`JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=N` (the virtual
CPU mesh used for multi-device runs without a pod) actually takes effect.
"""

from __future__ import annotations

import os


def apply_platform_env() -> None:
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
