"""Honor the caller's JAX platform choice even when jax was pre-imported.

Some launch environments (e.g. the axon TPU tunnel) import jax from sitecustomize at
interpreter startup, freezing its snapshot of JAX_PLATFORMS before application code
runs. Entry points call apply_platform_env() first so an explicit
`JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=N` (the virtual
CPU mesh used for multi-device runs without a pod) actually takes effect.
"""

from __future__ import annotations

import os


def apply_platform_env() -> None:
    import jax

    want = os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    # If a backend was already initialized (something called jax.devices() before us),
    # the config update cannot take effect — warn loudly instead of silently running
    # on the wrong platform (e.g. a CPU-mesh dry run landing on the TPU).
    try:
        from jax._src import xla_bridge

        initialized = xla_bridge.backends_are_initialized()
    except Exception:
        initialized = False
    if initialized and jax.default_backend() not in want.split(","):
        import sys

        print(f"warning: JAX_PLATFORMS={want} requested but the "
              f"'{jax.default_backend()}' backend is already initialized; "
              "the platform cannot change now", file=sys.stderr)
        return
    jax.config.update("jax_platforms", want)
