"""Fused Q40 dequant-matmul Pallas kernel — the TPU descendant of matmulQ40vQ80.

The reference's hot loop (src/funcs.cpp:287-396) dot-products 4-bit weight blocks against
int8 activations with NEON `vdotq_s32`, rows split across threads. Here the same
weight-stationary idea maps to TPU: packed nibbles stream HBM -> VMEM (4.5 bits/weight of
HBM traffic instead of 16 for bf16), the VPU unpacks and scales them, and the MXU
contracts against the activations — the dequantized weight matrix is never materialized
in HBM (the jnp fallback in ops/matmul.py may be, at XLA's discretion).

Weights must be in the block-strided "tpu" layout (quants.q40_repack_tpu): element
(block b, intra i) at column i*nb + b. That makes both Mosaic-hostile ops disappear:
- scale broadcast: lane j's scale is scales[j % nb] == pltpu.repeat(scales, 32) (tile
  semantics), no (BN, nb, 32)->(BN, K) reshape;
- nibble halves: low nibbles are permuted columns [0, K/2), high [K/2, K) — a lane-axis
  concat, no interleave.
The matching activation permutation (quants.permute_activations_tpu) runs in XLA outside
the kernel, where it fuses with the producer.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..quants import QK, FloatType, QTensor, permute_activations_tpu


def _q40_kernel(x_ref, p_ref, s_ref, o_ref, *, nb: int, precise: bool):
    # Mosaic has no sub-32-bit integer arithmetic: widen bytes to int32 first
    mm_dtype = jnp.float32 if precise else jnp.bfloat16
    p = p_ref[:].astype(jnp.int32)  # (BN, K//2) from uint8, permuted layout
    lo = (p & 0x0F).astype(mm_dtype) - 8.0  # permuted cols [0, K/2)
    hi = ((p >> 4) & 0x0F).astype(mm_dtype) - 8.0  # permuted cols [K/2, K)
    w_int = jnp.concatenate([lo, hi], axis=1)  # (BN, K)
    s_full = pltpu.repeat(s_ref[:].astype(mm_dtype), QK, axis=1)  # lane j -> scales[j % nb]
    w = w_int * s_full
    # precise: f32 multiplies via HIGHEST (MXU default is bf16) — used by parity tests;
    # fast path: bf16 operands, f32 accumulate (standard inference numerics). Decode is
    # HBM-bandwidth-bound either way.
    o_ref[:] = jax.lax.dot_general(
        x_ref[:].astype(mm_dtype), w, dimension_numbers=(((1,), (1,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST if precise else None,
        preferred_element_type=jnp.float32)


@functools.partial(jax.jit, static_argnames=("block_n", "interpret", "precise"))
def _q40_matmul_2d(x, packed2, scales, *, block_n: int = 512, interpret: bool = False,
                   precise: bool = False):
    """y (M, N) f32 = x (M, K) · W^T from TPU-layout Q40 (N, K//2)+(N, K//32)."""
    m, k = x.shape
    n, k2 = packed2.shape
    nb = scales.shape[-1]
    assert k2 * 2 == k and nb * QK == k, (packed2.shape, x.shape, scales.shape)
    # largest divisor of n that is a multiple of 8 and <= block_n (Mosaic needs the
    # sublane block divisible by 8 unless it spans the whole axis); tiny/odd n falls
    # back to a single whole-array block
    start = min(block_n, n) // 8 * 8
    bn = next((b for b in range(start, 7, -8) if n % b == 0), n)
    x_perm = permute_activations_tpu(x, nb)

    return pl.pallas_call(
        functools.partial(_q40_kernel, nb=nb, precise=precise),
        grid=(n // bn,),
        in_specs=[
            pl.BlockSpec((m, k), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, k2), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, nb), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((m, bn), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        interpret=interpret,
    )(x_perm, packed2, scales)


def q40_matmul(x: jax.Array, w: QTensor, *, out_dtype=None,
               interpret: bool | None = None, precise: bool | None = None) -> jax.Array:
    """qmatmul entry point: x (..., K) x tpu-layout Q40 QTensor (N, K) -> (..., N)."""
    if w.layout != "tpu":
        raise ValueError(
            "q40_matmul needs tpu-layout weights; run models.params.prepare_for_pallas "
            "(or QTensor.to_tpu_layout) on the params first")
    assert w.ftype == FloatType.Q40 and w.data.ndim == 2, (w.ftype, w.data.shape)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if precise is None:
        precise = x.dtype == jnp.float32
    lead = x.shape[:-1]
    x2 = x.reshape(-1, x.shape[-1])
    y = _q40_matmul_2d(x2, w.data, w.scales, interpret=interpret, precise=precise)
    return y.reshape(*lead, y.shape[-1]).astype(out_dtype or x.dtype)
