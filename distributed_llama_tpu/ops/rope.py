"""Rotary position embeddings — the three styles of the reference (src/commands.cpp:140-257).

- ROPE_LLAMA: interleaved pairs (2k, 2k+1), freq_k = theta^(-2k/head_size), precomputed
  cos/sin tables over the full sequence (LlamaRopeCommand, commands.cpp:140-179).
- ROPE_LLAMA3_1: same rotation with Llama-3.1 frequency-dependent NTK scaling. NOTE: the
  reference (Llama3_1RopeCommand::forward, commands.cpp:207-227) applies `scale()` to the
  *rotated output values* — an upstream bug; the correct (and Meta-official) behavior is to
  scale the *frequencies*, which is what we do here.
- ROPE_FALCON: GPT-NeoX half-rotation layout, pairs (j, j+hs/2), freq_j = theta^(-2j/hs)
  (FalconRopeCommand, commands.cpp:229-257); used by Grok-1 and Mixtral.

Tables are computed once per model (host numpy, f32) and live on device; application is a
pure jnp function usable inside jit/scan/shard_map. Slicing across TP devices is by whole
heads, and both layouts rotate within a head, so sliced==unsliced holds by construction —
the property the reference's commands-test.cpp checks explicitly.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..models.spec import ModelSpec, RopeType


def _llama31_scale_freqs(freqs: np.ndarray, factor: float, low_freq_factor: float,
                         high_freq_factor: float, orig_max_seq_len: int) -> np.ndarray:
    """Llama-3.1 NTK-by-parts frequency scaling (correct form of commands.cpp:193-205)."""
    wavelens = 2.0 * math.pi / freqs
    low_freq_wavelen = orig_max_seq_len / low_freq_factor
    high_freq_wavelen = orig_max_seq_len / high_freq_factor
    smooth = (orig_max_seq_len / wavelens - low_freq_factor) / (high_freq_factor - low_freq_factor)
    scaled = np.where(
        wavelens < high_freq_wavelen,
        freqs,
        np.where(wavelens > low_freq_wavelen, freqs / factor,
                 (1.0 - smooth) * freqs / factor + smooth * freqs),
    )
    return scaled


@jax.tree_util.register_pytree_node_class
@dataclass
class RopeTables:
    """Precomputed per-position cos/sin, shape (seq_len, head_size // 2)."""

    cos: jax.Array
    sin: jax.Array
    rope_type: RopeType

    def tree_flatten(self):
        return (self.cos, self.sin), (self.rope_type,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux[0])

    @classmethod
    def create(cls, spec: ModelSpec) -> "RopeTables":
        hs = spec.head_size
        k = np.arange(hs // 2, dtype=np.float64)
        freqs = 1.0 / (spec.rope_theta ** (2.0 * k / hs))
        if spec.rope_type == RopeType.LLAMA3_1:
            freqs = _llama31_scale_freqs(
                freqs, spec.rope_scaling_factor, spec.rope_scaling_low_freq_factor,
                spec.rope_scaling_high_freq_factor, spec.rope_scaling_orig_max_seq_len)
        t = np.arange(spec.seq_len, dtype=np.float64)
        angles = np.outer(t, freqs)  # (seq_len, hs//2)
        return cls(
            cos=jnp.asarray(np.cos(angles), dtype=jnp.float32),
            sin=jnp.asarray(np.sin(angles), dtype=jnp.float32),
            rope_type=spec.rope_type,
        )


def apply_rope(x: jax.Array, tables: RopeTables, positions: jax.Array) -> jax.Array:
    """Rotate q or k. x: (..., T, n_heads, head_size); positions: (T,) int32.

    Both interleaved (llama) and half-rotation (neox/falcon) layouts rotate pair
    (a, b) -> (a*cos - b*sin, a*sin + b*cos); only the pairing differs.
    """
    cos = tables.cos[positions][..., :, None, :]  # (..., T, 1, hs//2)
    sin = tables.sin[positions][..., :, None, :]
    hs = x.shape[-1]
    xf = x.astype(jnp.float32)
    if tables.rope_type in (RopeType.LLAMA, RopeType.LLAMA3_1):
        xp = xf.reshape(*x.shape[:-1], hs // 2, 2)
        a, b = xp[..., 0], xp[..., 1]
        ra = a * cos - b * sin
        rb = a * sin + b * cos
        out = jnp.stack([ra, rb], axis=-1).reshape(x.shape)
    elif tables.rope_type == RopeType.FALCON:
        a, b = xf[..., : hs // 2], xf[..., hs // 2 :]
        ra = a * cos - b * sin
        rb = a * sin + b * cos
        out = jnp.concatenate([ra, rb], axis=-1)
    else:
        raise ValueError(tables.rope_type)
    return out.astype(x.dtype)
