"""Fused int8-plane quantized matvec — the TPU descendant of matmulQ40vQ80.

The reference's hot loop (src/funcs.cpp:287-396) dot-products 4-bit weight blocks against
Q80-quantized activations with NEON `vdotq_s32`. A literal nibble-unpack kernel on TPU is
VPU-bound (~4 vector ops per weight swamp the MXU). Instead the load path expands Q40
nibbles once into **int8 planes** (`QTensor.to_i8_layout`): data int8 (out, K) holding
(nibble - 8), scales f32 (out, K/32). That costs 1 B/weight of HBM instead of 0.56, but
decode becomes pure MXU int8 work with zero per-weight VPU ops:

    y[n] = sum_b s[n,b] * sx[b] * P[n,b],   P = W8 @ Xexp   (int8 x int8 -> int32 MXU)

where Xexp (K, nb) is the activation vector quantized to int8 per 32-block (exactly the
reference's Q80 buffer semantics, src/tasks.cpp:96-135) and scattered block-diagonally:
Xexp[j, b] = xq[j] if j//32 == b else 0. A batch-1 matvec wastes 127/128 of every MXU pass
anyway; Xexp fills those wasted columns with the per-block partial sums, so the int8
matmul costs the same MXU passes as a plain matvec while making the per-block scale
structure a 32x-smaller (out, nb) elementwise epilogue instead of a per-weight multiply.

Decode (M=1) uses this kernel; prefill (M>1) amortizes a per-weight dequant over the
batch and goes through the XLA path in ops/matmul.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..quants import QK, QTensor


def _matvec_kernel(xexp_ref, sx_ref, w_ref, s_ref, o_ref):
    # P[n, b] = sum_{j in block b} W8[n, j] * xq[j] — int8 x int8 -> int32 on the MXU
    p = jax.lax.dot_general(w_ref[:], xexp_ref[:], (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.int32)
    y = (s_ref[:] * sx_ref[:]) * p.astype(jnp.float32)  # (bn, nb) epilogue
    o_ref[:] = jnp.sum(y, axis=1, keepdims=True)


def _matvec_kernel_inline(xq_ref, sx_ref, w_ref, s_ref, o_ref, xexp_ref):
    """Inline-Xexp variant (the pallas_q4 pattern): the raw int8 activation row
    (K bytes of HBM instead of K*nb) is scattered block-diagonally into VMEM
    scratch at grid step 0 and reused by every row block."""
    _, nb = xexp_ref.shape

    @pl.when(pl.program_id(0) == 0)
    def _build():
        xexp_ref[:] = block_diag_scatter(xq_ref[0], nb)

    _matvec_kernel(xexp_ref, sx_ref, w_ref, s_ref, o_ref)


def _matvec_kernel_f32(xexp_ref, sx_ref, w_ref, s_ref, o_ref):
    # precise path: activations stay f32 (no Q80 step); weights convert once to f32.
    # Used by parity tests; decode perf path is the int8 kernel above.
    p = jax.lax.dot_general(w_ref[:].astype(jnp.float32), xexp_ref[:],
                            (((1,), (0,)), ((), ())),
                            precision=jax.lax.Precision.HIGHEST,
                            preferred_element_type=jnp.float32)
    y = (s_ref[:] * sx_ref[:]) * p
    o_ref[:] = jnp.sum(y, axis=1, keepdims=True)


def _pick_bn(n: int, k: int, budget_bytes: int = 3 << 20) -> int:
    """Largest 128-multiple row-block whose (bn, K) int8 block fits the VMEM budget
    (double-buffered by Pallas). bn need not divide n: the grid is cdiv(n, bn) and
    Mosaic masks the trailing partial block. Tiny n uses the whole axis."""
    if n <= 128:
        return n
    cap = max(budget_bytes // max(k, 1), 128)
    return max(min(cap, n) // 128 * 128, 128)


# Above this VMEM footprint for the resident (K, nb) Xexp operand the kernel would not
# fit alongside the double-buffered weight blocks; callers (ops.matmul.qmatmul) fall back
# to the XLA dequant path. K=16384 (405B-class dim) stays comfortably under it.
_XEXP_VMEM_LIMIT = 9 << 20


def q8_shape_supported(n: int, k: int, precise: bool = False) -> bool:
    """Whether the fused matvec kernel can run a (n, k)-logical weight on TPU."""
    nb = k // QK
    esize = 4 if precise else 1
    return k * nb * esize <= _XEXP_VMEM_LIMIT


def q8_decode_supported(w: QTensor, precise: bool = False) -> bool:
    """Whether the fused matvec kernel can run this weight tensor on TPU."""
    if w.layout != "i8" or w.data.ndim != 2:
        return False
    return q8_shape_supported(*w.data.shape, precise=precise)


@functools.partial(jax.jit, static_argnames=("interpret", "precise"))
def _q8_matvec(xexp, sx, w8, scales, *, interpret: bool = False, precise: bool = False):
    """y (n, 1) f32 from block-diagonal Xexp (K, nb), sx (1, nb), int8 planes (n, K),
    scales (n, nb)."""
    k, nb = xexp.shape
    n, k2 = w8.shape
    assert k2 == k and scales.shape == (n, nb) and nb * QK == k, (
        xexp.shape, w8.shape, scales.shape)
    bn = _pick_bn(n, k)
    kernel = _matvec_kernel_f32 if precise else _matvec_kernel
    return pl.pallas_call(
        kernel,
        grid=(pl.cdiv(n, bn),),
        in_specs=[
            pl.BlockSpec((k, nb), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, nb), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, k), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, nb), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bn, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n, 1), jnp.float32),
        interpret=interpret,
    )(xexp, sx, w8, scales)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _q8_matvec_inline(xq, sx, w8, scales, *, interpret: bool = False):
    """Inline-Xexp variant: xq (1, K) int8 streamed to VMEM; the block-diagonal
    operand lives only in kernel scratch."""
    _, k = xq.shape
    n, k2 = w8.shape
    nb = k // QK
    assert k2 == k and scales.shape == (n, nb) and nb * QK == k, (
        xq.shape, w8.shape, scales.shape)
    bn = _pick_bn(n, k)
    return pl.pallas_call(
        _matvec_kernel_inline,
        grid=(pl.cdiv(n, bn),),
        in_specs=[
            pl.BlockSpec((1, k), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, nb), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, k), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, nb), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bn, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n, 1), jnp.float32),
        scratch_shapes=[pltpu.VMEM((k, nb), jnp.int8)],
        interpret=interpret,
    )(xq, sx, w8, scales)


def _quantize_row(x_row: jax.Array, nb: int):
    """Per-32-block Q80 quantization of one activation row (K,) -> (xq (K,) int8,
    sx (1, nb) f32). Exactly the reference's Q80 buffer semantics
    (src/tasks.cpp:96-135)."""
    k = x_row.shape[0]
    g = x_row.reshape(nb, QK).astype(jnp.float32)
    absmax = jnp.max(jnp.abs(g), axis=-1)
    sx = absmax / 127.0
    inv = jnp.where(absmax > 0, 127.0 / absmax, 0.0)
    xq = jnp.round(g * inv[:, None]).astype(jnp.int8).reshape(k)
    return xq, sx[None, :]


def block_diag_scatter(xq: jax.Array, nb: int) -> jax.Array:
    """Scatter a quantized row (K,) block-diagonally: Xexp[j, b] = xq[j] iff
    j // QK == b. Pure jnp — usable both in XLA and inside Pallas kernel bodies.

    Sub-32-bit dtypes broadcast through i32: Mosaic cannot insert a minor dim on
    narrow vectors ("Insertion of minor dim that is not a no-op only supported for
    32-bit types"), so the int8 path widens for the where and narrows after."""
    k = xq.shape[0]
    block_of = jax.lax.broadcasted_iota(jnp.int32, (k, nb), 0) // QK
    b_idx = jax.lax.broadcasted_iota(jnp.int32, (k, nb), 1)
    if xq.dtype.itemsize < 4:
        wide = jnp.where(block_of == b_idx, xq.astype(jnp.int32)[:, None], 0)
        return wide.astype(xq.dtype)
    return jnp.where(block_of == b_idx, xq[:, None], jnp.zeros((), xq.dtype))


def _expand_q80(x_row: jax.Array, nb: int):
    """Quantize one activation row (K,) to per-block int8 and scatter block-diagonally.

    Returns (Xexp (K, nb) int8, sx (1, nb) f32). Runs in XLA outside the kernel, where
    the quantize fuses with the producer.
    """
    xq, sx = _quantize_row(x_row, nb)
    return block_diag_scatter(xq, nb), sx


def _expand_f32(x_row: jax.Array, nb: int):
    """Precise-path variant: no activation quantization, unit block scales."""
    xexp = block_diag_scatter(x_row.astype(jnp.float32), nb)
    return xexp, jnp.ones((1, nb), jnp.float32)


def q8_matvec(x: jax.Array, w: QTensor, *, out_dtype=None,
              interpret: bool | None = None, precise: bool | None = None) -> jax.Array:
    """Decode-path matmul: x (..., K) with leading dims multiplying to 1, int8-layout
    QTensor (N, K) -> (..., N)."""
    if w.layout != "i8":
        raise ValueError(
            "q8_matvec needs i8-layout weights; run models.params.prepare_for_pallas "
            "(or QTensor.to_i8_layout) on the params first")
    assert w.data.ndim == 2, w.data.shape
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # precise (f32 activations, no Q80 step) is a parity-test tool, explicit opt-in only:
    # the production decode path quantizes activations to int8 exactly like the
    # reference's Q80 buffers regardless of the ambient compute dtype.
    precise = bool(precise)
    lead = x.shape[:-1]
    k = x.shape[-1]
    nb = k // QK
    x_row = x.reshape(k)
    if precise:
        xexp, sx = _expand_f32(x_row, nb)
    else:
        xexp, sx = _expand_q80(x_row, nb)
    y = _q8_matvec(xexp, sx, w.data, w.scales, interpret=interpret, precise=precise)
    return y.reshape(*lead, y.shape[0]).astype(out_dtype or x.dtype)
