"""Fused 4-bit split-plane quantized matvec — true-Q40-footprint decode kernel.

The int8-plane kernel (ops/pallas_q8.py) spends 1 B/weight of HBM; decode is
HBM-bandwidth-bound, so on a ~300 GB/s effective chip a 7B model costs ~25 ms/token in
weight traffic alone. This kernel keeps weights PACKED at 4 bits (0.5 B/weight + f16
scales = 0.5625 B/weight, the reference's own Q40 density, src/quants.hpp:17-20) and
unpacks in VMEM with zero cross-lane shuffles:

Layout "i4p" (split-plane packing, `QTensor.to_i4p_layout`):
    data   uint8 (out, K/2):  byte j = q[j] | (q[j + K/2] << 4),  q = nibble+8 in [0,16)
    scales int16 (out, K/32): the reference's per-block f16 deltas as raw BIT PATTERNS
                              (bit-exact, same 2 B/block). Mosaic on this toolchain
                              cannot lower f16 refs ("Unsupported type in mosaic
                              dialect: 'f16'"), so the kernel ships the bits as int16
                              and decodes f16->f32 in-kernel with exact integer math
                              (`_f16_bits_to_f32`).

Unpacking byte j's low nibble yields element j and the high nibble element j + K/2 —
both planes land in natural element order, so the unpack is 4 elementwise VPU ops per
byte (and/shift/two subs) and the per-block scale structure is untouched. The dot is the
same block-diagonal Xexp trick as pallas_q8 (P[n,b] = per-block int32 partial sums on
the MXU), split into the two K/2 halves:

    P = (lo - 8) @ Xexp[:K/2] + (hi - 8) @ Xexp[K/2:]
    y[n] = sum_b scales[n,b] * sx[b] * P[n,b]

This is the TPU descendant of matmulQ40vQ80 (src/funcs.cpp:287-396) at the reference's
exact storage density; the reference unpacks nibbles per dot-product on NEON the same
way, just 32 lanes at a time instead of 4096.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..quants import QK, QTensor


def _f16_bits_to_f32(h16):
    """Exact f16-bit-pattern (int16) -> f32 decode using only int ops + one bitcast.

    Mosaic cannot lower f16 refs, and the TPU VPU flushes subnormal f32 to zero, so
    the usual magic-multiply half->float trick silently zeroes subnormal deltas.
    Instead use  value = (m + (e>0)*1024) * 2^(max(e,1) - 25)  with the power of two
    built by bitcasting (k+127)<<23: every intermediate is a normal f32, making the
    decode bit-exact for all 65024 finite f16 patterns (verified exhaustively on a
    real v5e chip; f16 inf/nan decode wrong but Q40 deltas are always finite)."""
    h = h16.astype(jnp.int32) & 0xFFFF
    e = (h >> 10) & 0x1F
    mant = jnp.where(e > 0, (h & 0x3FF) + 1024, h & 0x3FF).astype(jnp.float32)
    p2 = jax.lax.bitcast_convert_type((jnp.maximum(e, 1) + 102) << 23, jnp.float32)
    f = mant * p2
    return jnp.where((h & 0x8000) != 0, -f, f)


def _unpack_dot_epilogue(xexp_ref, sx_ref, ssum_ref, wp_ref, s_ref, o_ref):
    """Shared kernel body: split-plane unpack, per-half MXU dots, scale epilogue.

    Mosaic on this toolchain cannot legalize elementwise subtract or logical shift on
    i8/u8 vectors (arith.subi / arith.shrui), so (a) the high nibble's shift widens
    through i32 (the only narrow-int ops Mosaic does lower are and/cast), and (b) the
    nibble's +8 offset is NOT removed per weight: the unsigned nibbles q in [0,16) go
    straight to the MXU and the offset folds into a per-block int32 correction:
    (q-8)·x = q·x - 8·Σ_block(x)  with Σ_block(x) = ssum_ref (the Q80 activation
    block sums, computed once per row outside the kernel). Same integer result
    bit-for-bit as subtracting 8 per weight."""
    wp = wp_ref[:]  # (bn, K/2) uint8
    lo = (wp & jnp.uint8(0x0F)).astype(jnp.int8)  # q of elements [0, K/2)
    hi = (wp.astype(jnp.int32) >> 4).astype(jnp.int8)  # q of elements [K/2, K)
    kh = wp.shape[1]
    # P[n, b] = sum_{j in block b} q[n, j] * xq[j] — int8 x int8 -> int32 on the MXU
    p = jax.lax.dot_general(lo, xexp_ref[:kh], (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.int32)
    p += jax.lax.dot_general(hi, xexp_ref[kh:], (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.int32)
    p -= ssum_ref[:] * 8  # remove the nibble offset per block (broadcast over rows)
    y = (_f16_bits_to_f32(s_ref[:]) * sx_ref[:]) * p.astype(jnp.float32)
    o_ref[:] = jnp.sum(y, axis=1, keepdims=True)


def _matvec_kernel(xexp_ref, sx_ref, ssum_ref, wp_ref, s_ref, o_ref):
    _unpack_dot_epilogue(xexp_ref, sx_ref, ssum_ref, wp_ref, s_ref, o_ref)


def _matvec_kernel_inline(xq_ref, sx_ref, ssum_ref, wp_ref, s_ref, o_ref, xexp_ref):
    """Variant generating the block-diagonal Xexp in VMEM scratch from the raw int8
    activation row (k bytes of HBM instead of k*nb): built once at grid step 0, reused
    by every row block."""
    _, nb = xexp_ref.shape

    @pl.when(pl.program_id(0) == 0)
    def _build():
        from .pallas_q8 import block_diag_scatter

        xexp_ref[:] = block_diag_scatter(xq_ref[0], nb)

    _unpack_dot_epilogue(xexp_ref, sx_ref, ssum_ref, wp_ref, s_ref, o_ref)


def _pick_bn(n: int, k: int, budget_bytes: int = 3 << 20) -> int:
    """Largest 128-multiple row-block whose (bn, K/2) packed block fits the VMEM budget
    (double-buffered by Pallas)."""
    if n <= 128:
        return n
    cap = max(budget_bytes // max(k // 2, 1), 128)
    return max(min(cap, n) // 128 * 128, 128)


_XEXP_VMEM_LIMIT = 9 << 20


def q4_shape_supported(n: int, k: int) -> bool:
    nb = k // QK
    return k % (2 * QK) == 0 and k * nb <= _XEXP_VMEM_LIMIT


def q4_decode_supported(w: QTensor) -> bool:
    """Whether the fused 4-bit matvec kernel can run this weight tensor on TPU."""
    if w.layout != "i4p" or w.data.ndim != 2:
        return False
    n, kh = w.data.shape
    return q4_shape_supported(n, kh * 2)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _q4_matvec(xexp, sx, wp, scales, *, interpret: bool = False):
    """y (n, 1) f32 from block-diagonal Xexp (K, nb) int8, sx (1, nb) f32,
    packed nibbles (n, K/2) uint8, scales (n, nb) int16 f16-bit-patterns."""
    k, nb = xexp.shape
    n, kh = wp.shape
    assert kh * 2 == k and scales.shape == (n, nb) and nb * QK == k, (
        xexp.shape, wp.shape, scales.shape)
    # activation block sums for the nibble-offset correction (colsum works because
    # Xexp's column b is exactly block b's xq values scattered along its rows)
    ssum = jnp.sum(xexp, axis=0, dtype=jnp.int32)[None, :]
    bn = _pick_bn(n, k)
    return pl.pallas_call(
        _matvec_kernel,
        grid=(pl.cdiv(n, bn),),
        in_specs=[
            pl.BlockSpec((k, nb), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, nb), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, nb), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, kh), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, nb), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bn, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n, 1), jnp.float32),
        interpret=interpret,
    )(xexp, sx, ssum, wp, scales)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _q4_matvec_inline(xq, sx, wp, scales, *, interpret: bool = False):
    """Inline-Xexp variant: xq (1, K) int8 streamed to VMEM; the block-diagonal
    operand lives only in kernel scratch."""
    _, k = xq.shape
    n, kh = wp.shape
    nb = k // QK
    assert kh * 2 == k and scales.shape == (n, nb), (xq.shape, wp.shape, scales.shape)
    ssum = jnp.sum(xq.reshape(nb, QK), axis=1, dtype=jnp.int32)[None, :]
    bn = _pick_bn(n, k)
    return pl.pallas_call(
        _matvec_kernel_inline,
        grid=(pl.cdiv(n, bn),),
        in_specs=[
            pl.BlockSpec((1, k), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, nb), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, nb), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, kh), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, nb), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bn, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n, 1), jnp.float32),
        scratch_shapes=[pltpu.VMEM((k, nb), jnp.int8)],
        interpret=interpret,
    )(xq, sx, ssum, wp, scales)


# flip after measuring on hardware (perf/microbench.py --section matvec compares both)
INLINE_XEXP_DEFAULT = False


def q4_matvec(x: jax.Array, w: QTensor, *, out_dtype=None,
              interpret: bool | None = None,
              inline_xexp: bool | None = None) -> jax.Array:
    """Decode-path matmul: x (..., K) with leading dims multiplying to 1, i4p-layout
    QTensor (N, K) -> (..., N)."""
    if w.layout != "i4p":
        raise ValueError("q4_matvec needs i4p-layout weights (QTensor.to_i4p_layout)")
    assert w.data.ndim == 2, w.data.shape
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if inline_xexp is None:
        inline_xexp = INLINE_XEXP_DEFAULT
    from .pallas_q8 import _expand_q80, _quantize_row

    lead = x.shape[:-1]
    k = x.shape[-1]
    nb = k // QK
    if inline_xexp:
        xq, sx = _quantize_row(x.reshape(k), nb)
        y = _q4_matvec_inline(xq[None, :], sx, w.data, w.scales, interpret=interpret)
    else:
        xexp, sx = _expand_q80(x.reshape(k), nb)
        y = _q4_matvec(xexp, sx, w.data, w.scales, interpret=interpret)
    return y.reshape(*lead, y.shape[0]).astype(out_dtype or x.dtype)
