from .kernels import gelu_tanh, rmsnorm, silu  # noqa: F401
from .rope import RopeTables, apply_rope  # noqa: F401
from .attention import gqa_attention  # noqa: F401
from .matmul import qmatmul  # noqa: F401
