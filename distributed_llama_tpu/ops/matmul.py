"""Quantized matmul dispatch — TPU equivalent of the reference matmul layer.

The reference dispatches on (weightType x inputType) pairs of hand-written SIMD loops
(src/funcs.cpp:424-465, hot path matmulQ40vQ80 at funcs.cpp:287-396). Here there is ONE
logical op: y[..., out] = x[..., in] · W[out, in], where W may be dense or block-quantized.

Execution paths:
- decode (one row of activations) with i8-layout weights: `pallas_q8.q8_matvec`, the
  fused int8-plane MXU kernel (HBM-bandwidth-bound, zero per-weight VPU work).
- everything else: dequantize-to-dtype + `dot_general`; XLA fuses the scale broadcast
  into the matmul's operand pipeline. Prefill lands here on purpose — with many
  activation rows the per-weight dequant amortizes and the MXU runs dense bf16.

Weights keep the reference's (out, in) row-major orientation with quant blocks along `in`
(src/commands.cpp:22-39), so TP row/col splits slice whole blocks.
"""

from __future__ import annotations

import math
import threading

import jax
import jax.numpy as jnp

from ..obs import metrics
from ..quants import QTensor
from ..resilience import faults

# "fused" is a strict superset of "all": everything "all" lowers plus the
# residual-add / silu·mul epilogue fusions wired through models/forward.py
FUSED_POLICIES = ("all", "fused")

_KERNEL_SELECTED = metrics.counter(
    "matmul_kernel_selected_total",
    "matmul kernel lowerings by selected kernel (counted at trace time: one "
    "per compiled program per call site, not per dispatch)",
    labelnames=("kernel",))

# trace-time record of which kernel served each (M, N, K, layout) bucket —
# the per-shape truth behind bench.py's provenance fields and /v1/stats'
# kernel block. Keys are dispatch-shape buckets (bounded: one per distinct
# lowered matmul shape), values are kernel names.
_selections: dict[str, str] = {}
_selections_lock = threading.Lock()


def _record(kernel: str, m: int, w: QTensor, op: str = "mm") -> None:
    n, kin = w.shape
    key = f"m={m},n={n},k={kin},layout={w.layout},op={op}"
    with _selections_lock:
        if _selections.get(key) != kernel:
            _selections[key] = kernel
            _KERNEL_SELECTED.labels(kernel=kernel).inc()


def kernel_selections() -> dict[str, str]:
    """Snapshot of {shape-bucket: kernel} selections recorded at trace time
    (bench.py provenance + /v1/stats). Kernel names: q4_matvec, q8_matvec,
    q4_mm, q4_mm+res, q4_gated_mm, xla, xla-fallback."""
    with _selections_lock:
        return dict(_selections)


def reset_kernel_selections() -> None:
    """Tests/bench only: drop the recorded selection map."""
    with _selections_lock:
        _selections.clear()


def qmatmul(x: jax.Array, w: QTensor, *, use_pallas: bool | str = False,
            out_dtype=None, residual: jax.Array | None = None) -> jax.Array:
    """y = x @ W^T for W of logical shape (out, in); x: (..., in) -> (..., out).

    use_pallas: False = XLA everywhere; True = fused kernels for decode (one
    activation row); "all" = additionally the fused dequant-matmul for M>1
    (prefill / batched decode — ops/pallas_q4_mm.py); "fused" = "all" plus the
    fused epilogues (--fused-matmul / DLT_FUSED_MATMUL).

    residual: optional (..., out) tensor; the result is residual + x @ W^T on
    EVERY path (under "fused" the add runs inside the kernel's accumulator;
    the fallbacks add in f32 before the out_dtype cast — same rounding as one
    fused f32 accumulate, so a shape-gated fallback stays token-identical)."""
    m = math.prod(x.shape[:-1])
    if use_pallas and m == 1:
        if w.layout == "i4p":
            from .pallas_q4 import q4_decode_supported, q4_matvec

            if w.groups == 1 and q4_decode_supported(w):
                _record("q4_matvec", m, w)
                y = q4_matvec(x, w, out_dtype=out_dtype or x.dtype)
                return y if residual is None else _res_add(y, residual,
                                                           out_dtype or x.dtype)
        else:
            from .pallas_q8 import q8_decode_supported, q8_matvec

            if q8_decode_supported(w):
                _record("q8_matvec", m, w)
                y = q8_matvec(x, w, out_dtype=out_dtype or x.dtype)
                return y if residual is None else _res_add(y, residual,
                                                           out_dtype or x.dtype)
    if use_pallas in FUSED_POLICIES and m > 1 and w.layout == "i4p":
        from .pallas_q4_mm import q4_matmul, q4_mm_supported

        try:
            # fires BEFORE the shape gate so the fault-matrix cells are
            # non-vacuous on any fused engine; any raise degrades to XLA
            faults.fire("matmul.kernel_select", m=m, n=w.shape[0])
            if q4_mm_supported(w, m):
                fuse_res = residual is not None and use_pallas == "fused"
                y = q4_matmul(x, w, out_dtype=out_dtype or x.dtype,
                              residual=residual if fuse_res else None)
                _record("q4_mm+res" if fuse_res else "q4_mm", m, w)
                if residual is not None and not fuse_res:
                    return _res_add(y, residual, out_dtype or x.dtype)
                return y
        except Exception:  # noqa: BLE001 — any kernel-path failure -> XLA
            _record("xla-fallback", m, w)
            return _qmatmul_xla(x, w, out_dtype=out_dtype, residual=residual)
    _record("xla", m, w)
    return _qmatmul_xla(x, w, out_dtype=out_dtype, residual=residual)


def _res_add(y: jax.Array, residual: jax.Array, out_dtype) -> jax.Array:
    return (residual.astype(jnp.float32)
            + y.astype(jnp.float32)).astype(out_dtype)


def _qmatmul_xla(x: jax.Array, w: QTensor, *, out_dtype=None,
                 residual: jax.Array | None = None) -> jax.Array:
    """The oracle path: dequantize + dot_general; XLA fuses the scale
    broadcast into the operand pipeline. Residual adds in f32 before the
    cast (identical rounding to the kernel's f32 accumulator-init)."""
    wd = w.dequantize(dtype=x.dtype)
    y = jax.lax.dot_general(
        x, wd,
        dimension_numbers=(((x.ndim - 1,), (wd.ndim - 1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    if residual is not None:
        y = residual.astype(jnp.float32) + y
    return y.astype(out_dtype or x.dtype)


def qmatmul_gated(x: jax.Array, w1: QTensor, w3: QTensor, *, act,
                  act_name: str, use_pallas: bool | str = False,
                  out_dtype=None) -> jax.Array:
    """FFN gate-pair: act(x @ w1^T) * (x @ w3^T). Under use_pallas == "fused"
    with M>1 and a kernel-eligible i4p pair this lowers to ONE
    q4_gated_matmul (both weight streams at packed density, intermediates
    VMEM-only); every other configuration runs two qmatmul calls + the jnp
    activation (`act`, matching the kernel's `act_name` epilogue)."""
    m = math.prod(x.shape[:-1])
    if (use_pallas == "fused" and m > 1
            and w1.layout == "i4p" and w3.layout == "i4p"
            and act_name in ("silu", "gelu_tanh")):
        from .pallas_q4_mm import q4_gated_matmul, q4_gated_supported

        try:
            faults.fire("matmul.kernel_select", m=m, n=w1.shape[0])
            if q4_gated_supported(w1, w3, m):
                y = q4_gated_matmul(x, w1, w3, act=act_name,
                                    out_dtype=out_dtype or x.dtype)
                _record("q4_gated_mm", m, w1, op="gated")
                return y
        except Exception:  # noqa: BLE001 — any kernel-path failure -> XLA
            _record("xla-fallback", m, w1, op="gated")
            return (act(_qmatmul_xla(x, w1, out_dtype=out_dtype))
                    * _qmatmul_xla(x, w3, out_dtype=out_dtype))
    return (act(qmatmul(x, w1, use_pallas=use_pallas, out_dtype=out_dtype))
            * qmatmul(x, w3, use_pallas=use_pallas, out_dtype=out_dtype))


def qmatmul_q80(xq: jax.Array, sx: jax.Array, w: QTensor, *,
                use_pallas: bool = False, out_dtype=jnp.float32) -> jax.Array:
    """Decode matvec against a PRE-QUANTIZED activation row.

    xq (1, K) int8 + sx (1, K//32) f32 are the Q80 form of the activation (from
    ops.pallas_prologue); returns (1, 1, N). Routes into the inline-Xexp matvec
    variants so the quantized row is the only activation HBM traffic; the XLA
    fallback dequantizes x̂ = xq·sx and runs the dense path (same numerics —
    activation quantization already happened upstream either way).
    """
    from ..quants import jnp_dequantize_i8

    if use_pallas:
        if w.layout == "i4p":
            from .pallas_q4 import _q4_matvec_inline, q4_decode_supported

            if w.groups == 1 and q4_decode_supported(w):
                y = _q4_matvec_inline(xq, sx, w.data, w.scales,
                                      interpret=jax.default_backend() != "tpu")
                return y.reshape(1, 1, y.shape[0]).astype(out_dtype)
        elif w.layout == "i8":
            from .pallas_q8 import _q8_matvec_inline, q8_decode_supported

            if q8_decode_supported(w):
                y = _q8_matvec_inline(xq, sx, w.data, w.scales,
                                      interpret=jax.default_backend() != "tpu")
                return y.reshape(1, 1, y.shape[0]).astype(out_dtype)
    xhat = jnp_dequantize_i8(xq, sx, dtype=jnp.float32)  # (1, K)
    wd = w.dequantize(dtype=jnp.float32)
    y = jax.lax.dot_general(xhat, wd, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    return y.reshape(1, 1, y.shape[-1]).astype(out_dtype)
