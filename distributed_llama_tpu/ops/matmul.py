"""Quantized matmul dispatch — TPU equivalent of the reference matmul layer.

The reference dispatches on (weightType x inputType) pairs of hand-written SIMD loops
(src/funcs.cpp:424-465, hot path matmulQ40vQ80 at funcs.cpp:287-396). Here there is ONE
logical op: y[..., out] = x[..., in] · W[out, in], where W may be dense or block-quantized.

Two execution paths:
- `qmatmul` (this module): dequantize-to-dtype + `jnp.einsum`; XLA fuses the nibble unpack
  and scale broadcast into the matmul's operand pipeline. Correct everywhere (CPU mesh
  tests, TPU), and the baseline the Pallas kernel must beat.
- `pallas_q40.q40_matmul`: fused HBM->VMEM dequant matmul kernel (see ops/pallas_q40.py),
  enabled via `use_pallas=True` when running on real TPU.

Weights keep the reference's (out, in) row-major orientation with quant blocks along `in`
(src/commands.cpp:22-39), so TP row/col splits slice whole blocks.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..quants import FloatType, QTensor


def qmatmul(x: jax.Array, w: QTensor, *, use_pallas: bool = False,
            out_dtype=None) -> jax.Array:
    """y = x @ W^T for W of logical shape (out, in); x: (..., in) -> (..., out)."""
    if use_pallas and w.ftype == FloatType.Q40 and w.layout == "tpu" and w.data.ndim == 2:
        from .pallas_q40 import q40_matmul

        return q40_matmul(x, w, out_dtype=out_dtype or x.dtype)
    wd = w.dequantize(dtype=x.dtype)
    y = jax.lax.dot_general(
        x, wd,
        dimension_numbers=(((x.ndim - 1,), (wd.ndim - 1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return y.astype(out_dtype or x.dtype)
