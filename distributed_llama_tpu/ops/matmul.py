"""Quantized matmul dispatch — TPU equivalent of the reference matmul layer.

The reference dispatches on (weightType x inputType) pairs of hand-written SIMD loops
(src/funcs.cpp:424-465, hot path matmulQ40vQ80 at funcs.cpp:287-396). Here there is ONE
logical op: y[..., out] = x[..., in] · W[out, in], where W may be dense or block-quantized.

Execution paths:
- decode (one row of activations) with i8-layout weights: `pallas_q8.q8_matvec`, the
  fused int8-plane MXU kernel (HBM-bandwidth-bound, zero per-weight VPU work).
- everything else: dequantize-to-dtype + `dot_general`; XLA fuses the scale broadcast
  into the matmul's operand pipeline. Prefill lands here on purpose — with many
  activation rows the per-weight dequant amortizes and the MXU runs dense bf16.

Weights keep the reference's (out, in) row-major orientation with quant blocks along `in`
(src/commands.cpp:22-39), so TP row/col splits slice whole blocks.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..quants import QTensor


def qmatmul(x: jax.Array, w: QTensor, *, use_pallas: bool | str = False,
            out_dtype=None) -> jax.Array:
    """y = x @ W^T for W of logical shape (out, in); x: (..., in) -> (..., out).

    use_pallas: False = XLA everywhere; True = fused kernels for decode (one
    activation row); "all" = additionally the fused dequant-matmul for M>1
    (prefill / batched decode — ops/pallas_q4_mm.py, opt-in until the hardware
    A/B lands)."""
    m = math.prod(x.shape[:-1])
    if use_pallas and m == 1:
        if w.layout == "i4p":
            from .pallas_q4 import q4_decode_supported, q4_matvec

            if w.groups == 1 and q4_decode_supported(w):
                return q4_matvec(x, w, out_dtype=out_dtype or x.dtype)
        else:
            from .pallas_q8 import q8_decode_supported, q8_matvec

            if q8_decode_supported(w):
                return q8_matvec(x, w, out_dtype=out_dtype or x.dtype)
    if use_pallas == "all" and m > 1 and w.layout == "i4p":
        from .pallas_q4_mm import q4_matmul, q4_mm_supported

        if q4_mm_supported(w, m):
            return q4_matmul(x, w, out_dtype=out_dtype or x.dtype)
    wd = w.dequantize(dtype=x.dtype)
    y = jax.lax.dot_general(
        x, wd,
        dimension_numbers=(((x.ndim - 1,), (wd.ndim - 1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return y.astype(out_dtype or x.dtype)


def qmatmul_q80(xq: jax.Array, sx: jax.Array, w: QTensor, *,
                use_pallas: bool = False, out_dtype=jnp.float32) -> jax.Array:
    """Decode matvec against a PRE-QUANTIZED activation row.

    xq (1, K) int8 + sx (1, K//32) f32 are the Q80 form of the activation (from
    ops.pallas_prologue); returns (1, 1, N). Routes into the inline-Xexp matvec
    variants so the quantized row is the only activation HBM traffic; the XLA
    fallback dequantizes x̂ = xq·sx and runs the dense path (same numerics —
    activation quantization already happened upstream either way).
    """
    from ..quants import jnp_dequantize_i8

    if use_pallas:
        if w.layout == "i4p":
            from .pallas_q4 import _q4_matvec_inline, q4_decode_supported

            if w.groups == 1 and q4_decode_supported(w):
                y = _q4_matvec_inline(xq, sx, w.data, w.scales,
                                      interpret=jax.default_backend() != "tpu")
                return y.reshape(1, 1, y.shape[0]).astype(out_dtype)
        elif w.layout == "i8":
            from .pallas_q8 import _q8_matvec_inline, q8_decode_supported

            if q8_decode_supported(w):
                y = _q8_matvec_inline(xq, sx, w.data, w.scales,
                                      interpret=jax.default_backend() != "tpu")
                return y.reshape(1, 1, y.shape[0]).astype(out_dtype)
    xhat = jnp_dequantize_i8(xq, sx, dtype=jnp.float32)  # (1, K)
    wd = w.dequantize(dtype=jnp.float32)
    y = jax.lax.dot_general(xhat, wd, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    return y.reshape(1, 1, y.shape[-1]).astype(out_dtype)
