"""Ring attention: causal GQA attention over a sequence-sharded KV cache.

Long-context sequence parallelism — absent from the reference, which keeps the FULL
seqLen KV slice resident per node and only shards heads (SURVEY.md §5: KvCacheSlice,
src/commands.cpp:97-102, per-head quadratic loop llama2-tasks.cpp:62-93). Here the cache's
sequence axis is sharded over the mesh's `sp` axis, so max context scales linearly with
devices; each device attends its local KV block, and the blocks rotate around the ring
with `ppermute` while a numerically stable online softmax (flash-attention-style
m/denominator carry) accumulates the output. Compute and ICI transfer overlap: while a
device contracts block r it can already be sending/receiving block r+1.

Every device holds the full Q (queries are small; KV is what grows with context), so the
output is replicated over sp and no final gather is needed. Combines with TP head
sharding orthogonally: cache is (B, hk/tp, S/sp, hs) on a (dp, sp, tp) mesh.

Two sequence layouts (selected by the cache-write discipline, models/forward.py):
contiguous (inscan: device i holds positions [i*Sb, (i+1)*Sb)) and STRIPED
(deferred: device i's slot j holds position j*sp + i), which spreads the live
context evenly so static window buckets bound each rotation to ceil(window/sp)
columns — decode ICI/HBM then tracks the live context, not the allocated seq_len.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _block_attend(qg, k_blk, v_blk, positions, col_offset, col_stride=1,
                  live_end=None):
    """Masked scores + unnormalized accumulation for one KV block.

    qg: (B, hk, g, T, hs) f32; k_blk/v_blk: (B, hk, Sb, hs); positions: (T,) absolute
    query positions. Block column j sits at absolute position
    col_offset + col_stride*j — contiguous shards use (owner*Sb, 1), the striped
    layout uses (owner, sp). live_end, if given, additionally masks columns at
    positions >= live_end — the deferred-write discipline attends cache blocks
    only over COMMITTED rows (the current chunk arrives as its own register
    block instead).
    Returns (m (…, T), l (…, T), acc (…, T, hs)) partial softmax stats.
    """
    sb = k_blk.shape[2]
    hs = qg.shape[-1]
    scale = 1.0 / math.sqrt(hs)
    scores = jnp.einsum("bkgtd,bksd->bkgts", qg,
                        k_blk.astype(jnp.float32)) * scale  # (B, hk, g, T, Sb)
    col_pos = col_offset + col_stride * jnp.arange(sb)  # absolute column positions
    valid = col_pos[None, :] <= positions[:, None]  # (T, Sb) causal
    if live_end is not None:
        valid = valid & (col_pos[None, :] < live_end)
    scores = jnp.where(valid[None, None, None], scores, _NEG_INF)
    m = jnp.max(scores, axis=-1)  # (B, hk, g, T)
    # guard fully-masked blocks: exp(NEG_INF - NEG_INF) would be 1, so clamp m
    safe_m = jnp.maximum(m, _NEG_INF / 2)
    p = jnp.exp(scores - safe_m[..., None])
    p = jnp.where(valid[None, None, None], p, 0.0)
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bkgts,bksd->bkgtd", p, v_blk.astype(jnp.float32))
    return m, l, acc


def _combine(m1, l1, acc1, m2, l2, acc2):
    """Merge two partial softmax accumulations (flash-attention combine)."""
    m = jnp.maximum(m1, m2)
    safe_m = jnp.maximum(m, _NEG_INF / 2)
    a1 = jnp.exp(m1 - safe_m)
    a2 = jnp.exp(m2 - safe_m)
    return m, l1 * a1 + l2 * a2, acc1 * a1[..., None] + acc2 * a2[..., None]


def ring_attention(q: jax.Array, k_shard: jax.Array, v_shard: jax.Array,
                   positions: jax.Array, *, axis_name: str, axis_size: int,
                   live_end: jax.Array | None = None,
                   chunk: tuple[jax.Array, jax.Array, jax.Array] | None = None,
                   striped: bool = False,
                   window_slots: int | None = None) -> jax.Array:
    """Causal GQA attention of T query tokens against a sequence-sharded cache.

    q: (B, T, hq, hs) replicated over sp; k_shard/v_shard: (B, hk, S/sp, hs), the
    local sequence shard. Two layouts:

    - contiguous (striped=False): device i holds absolute positions
      [i*Sb, (i+1)*Sb). The live context [0, pos) is a prefix that concentrates
      on low-index devices, so every rotation must move the FULL shard.
    - striped (striped=True): device i's local slot j holds absolute position
      j*axis_size + i. The live context occupies the first ceil(pos/sp) slots of
      EVERY shard, so with a static window bucket W covering pos, only
      window_slots = ceil(W/sp) slots participate — each ring rotation moves
      W/sp columns instead of S/sp, bounding both ICI and HBM per step by the
      LIVE context (the sp analog of the dense path's attn_window).

    Returns (B, T, hq*hs), replicated over sp.

    Deferred-write mode (models/forward.py cache_write="deferred"): the cache holds
    only COMMITTED rows (positions < live_end == start_pos); the current chunk's
    K/V ride in as `chunk=(k_c (B, hk, T, hs), v_c, chunk_start)` and are attended
    as one extra register block folded into the same online softmax — no cache
    write happens inside the step at all.
    """
    b, t, hq, hs = q.shape
    _, hk, sb, _ = k_shard.shape
    g = hq // hk
    if window_slots is not None and window_slots < sb:
        assert striped, "window_slots only bounds the striped layout"
        k_shard = k_shard[:, :, :window_slots]
        v_shard = v_shard[:, :, :window_slots]
        sb = window_slots
    # (B, hk, g, T, hs) — block-attend subscripts are head-major
    qg = jnp.moveaxis(q.reshape(b, t, hk, g, hs), 1, 3).astype(jnp.float32)

    idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i - 1) % axis_size) for i in range(axis_size)]  # send left, recv right

    m = jnp.full((b, hk, g, t), _NEG_INF, jnp.float32)
    l = jnp.zeros((b, hk, g, t), jnp.float32)
    acc = jnp.zeros((b, hk, g, t, hs), jnp.float32)
    k_blk, v_blk = k_shard, v_shard
    for r in range(axis_size):
        owner = (idx + r) % axis_size  # whose shard I currently hold
        offset, stride = (owner, axis_size) if striped else (owner * sb, 1)
        mb, lb, ab = _block_attend(qg, k_blk, v_blk, positions, offset, stride,
                                   live_end=live_end)
        m, l, acc = _combine(m, l, acc, mb, lb, ab)
        if r + 1 < axis_size:
            k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
            v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
    if chunk is not None:
        k_c, v_c, chunk_start = chunk
        mb, lb, ab = _block_attend(qg, k_c, v_c, positions, chunk_start)
        m, l, acc = _combine(m, l, acc, mb, lb, ab)
    out = acc / jnp.maximum(l, 1e-30)[..., None]  # (B, hk, g, T, hs)
    out = jnp.moveaxis(out, 3, 1)  # (B, T, hk, g, hs)
    return out.reshape(b, t, hq * hs).astype(q.dtype)


def commit_kv_rows_sharded(k_cache: jax.Array, v_cache: jax.Array,
                           k_rows: jax.Array, v_rows: jax.Array,
                           start_pos: jax.Array, *, axis_name: str,
                           striped: bool = False, axis_size: int | None = None
                           ) -> tuple[jax.Array, jax.Array]:
    """Deferred-write commit for sequence-sharded caches: write ALL layers' new
    rows in one tiny masked window write per cache.

    caches: (L, B, hk, Sb, hs) local shards; rows: (L, B, hk, T, hs) (every sp
    member computed identical rows — activations are sp-replicated). The write
    window is clipped into the shard with a per-slot hit mask so a chunk
    straddling shard boundaries writes each member exactly its own positions.
    Total write traffic is O(L·T) rows — the sp counterpart of forward()'s
    top-level dynamic_update_slice, replacing the full-local-cache carry the
    in-scan discipline pays.

    striped=True uses the interleaved layout (member m's local slot j holds
    absolute position j*sp + m — see ring_attention): member m takes the chunk
    positions with p % sp == m, landing in a ceil(T/sp)(+1) slot window."""
    t = k_rows.shape[3]
    sb = k_cache.shape[3]
    idx = jax.lax.axis_index(axis_name)

    if striped:
        sp = axis_size
        assert sp is not None, "striped commit needs the static axis_size"
        wl = min((t - 1) // sp + 2, sb)  # slot-window width (static)
        j0 = jnp.clip(start_pos // sp, 0, sb - wl)
        slots = j0 + jnp.arange(wl)
        src = slots * sp + idx - start_pos  # which chunk token lands in each slot
        hit = (src >= 0) & (src < t)
        src_c = jnp.clip(src, 0, t - 1)

        def write_striped(cache, rows):
            rows = rows.astype(cache.dtype)
            cur = jax.lax.dynamic_slice(
                cache, (0, 0, 0, j0, 0), (*cache.shape[:3], wl, cache.shape[4]))
            gathered = jnp.take(rows, src_c, axis=3)
            val = jnp.where(hit[None, None, None, :, None], gathered, cur)
            return jax.lax.dynamic_update_slice(cache, val, (0, 0, 0, j0, 0))

        return write_striped(k_cache, k_rows), write_striped(v_cache, v_rows)

    local = start_pos - idx * sb  # chunk start in MY shard coordinates (may be <0)

    if t > sb:
        # prefill chunk wider than a shard (tiny seq_len/sp): masked scatter over
        # the whole local shard — a full-shard write, but amortized over >= sb
        # prefill tokens and unreachable from decode (T=1)
        slot = jnp.arange(sb)
        src = slot - local
        hit = (src >= 0) & (src < t)
        src_c = jnp.clip(src, 0, t - 1)

        def write_full(cache, rows):
            gathered = jnp.take(rows.astype(cache.dtype), src_c, axis=3)
            return jnp.where(hit[None, None, None, :, None], gathered, cache)

        return write_full(k_cache, k_rows), write_full(v_cache, v_rows)

    at = jnp.clip(local, 0, sb - t)
    win_slot = at + jnp.arange(t)  # absolute local slots of the write window
    src = win_slot - local  # which chunk token lands in each window slot
    hit = (src >= 0) & (src < t)
    src_c = jnp.clip(src, 0, t - 1)

    def write(cache, rows):
        rows = rows.astype(cache.dtype)
        cur = jax.lax.dynamic_slice(
            cache, (0, 0, 0, at, 0), (*cache.shape[:3], t, cache.shape[4]))
        gathered = jnp.take(rows, src_c, axis=3)
        val = jnp.where(hit[None, None, None, :, None], gathered, cur)
        return jax.lax.dynamic_update_slice(cache, val, (0, 0, 0, at, 0))

    return write(k_cache, k_rows), write(v_cache, v_rows)


def update_kv_cache_sharded(k_cache: jax.Array, v_cache: jax.Array, k_new: jax.Array,
                            v_new: jax.Array, start_pos: jax.Array, *,
                            axis_name: str) -> tuple[jax.Array, jax.Array]:
    """Write T new kv vectors into sequence-sharded caches; each sp member keeps only
    the positions that land in its shard.

    k_new/v_new: (B, T, hk, hs); caches: (B, hk, Sb, hs) local shards. The write may
    straddle a shard boundary, so it is a masked positional update. Replaces
    ops.attention.update_kv_cache when the cache's S axis is sp-sharded.
    """
    b, t, hk, hs = k_new.shape
    sb = k_cache.shape[2]
    idx = jax.lax.axis_index(axis_name)
    local = start_pos - idx * sb  # where the chunk starts in MY shard (may be <0)

    if t == 1:
        in_range = (local >= 0) & (local < sb)
        at = jnp.clip(local, 0, sb - 1)
        def write(cache, new):
            new_t = jnp.swapaxes(new, 1, 2).astype(cache.dtype)  # (B, hk, 1, hs)
            cur = jax.lax.dynamic_slice(cache, (0, 0, at, 0), new_t.shape)
            val = jnp.where(in_range, new_t, cur)
            return jax.lax.dynamic_update_slice(cache, val, (0, 0, at, 0))
        return write(k_cache, k_new), write(v_cache, v_new)

    # chunk write, possibly straddling shards: scatter by position mask over the shard
    slot = jnp.arange(sb)  # local slots
    src = slot - local  # which chunk token lands in this slot
    hit = (src >= 0) & (src < t)  # (Sb,)
    src_c = jnp.clip(src, 0, t - 1)

    def write(cache, new):
        new_t = jnp.swapaxes(new, 1, 2).astype(cache.dtype)  # (B, hk, T, hs)
        gathered = jnp.take(new_t, src_c, axis=2)  # (B, hk, Sb, hs)
        return jnp.where(hit[None, None, :, None], gathered, cache)

    return write(k_cache, k_new), write(v_cache, v_new)
