"""Fused 4-bit dequant-matmul — the prefill / batched-decode counterpart of the
q4 matvec kernel.

The decode matvec (ops/pallas_q4.py) is a T=1 tool: its block-diagonal Xexp
trick needs one activation row. Prefill (T>1) and batched decode (B>1) run the
XLA dequant+dot path (ops/matmul.py), which dequantizes the i4p planes to bf16
operands that XLA may MATERIALIZE through HBM (~3.6x the packed bytes at 7B;
perf/PROFILE.md's prefill cost model). This kernel keeps the dequant in VMEM:
each grid step loads a packed (bn, bkp) nibble tile + its f16-bit scales,
decodes to bf16 in registers, and feeds the MXU — weights stream from HBM
exactly once at the file's own 0.5625 B/weight density regardless of M.

Split-plane addressing: i4p byte column c holds the LOW nibble of element c and
the HIGH nibble of element K/2 + c (QTensor.to_i4p_layout), so one packed tile
covers two disjoint K-ranges; the kernel takes the activation block TWICE with
block-index maps offset by K/2 (x_lo / x_hi views of the same array) and the
scales likewise (s_lo / s_hi).

Mosaic portability (perf/PROFILE.md op matrix): nibble extraction widens
through i32 (no narrow shifts), the -8 offset and per-block scaling happen in
f32 (no i8 subtract), scales decode from f16 BIT PATTERNS with the proven
integer-exact _f16_bits_to_f32, and the dot is bf16xbf16->f32 on the MXU. No
f16 refs anywhere.

Opt-in (Engine prefill_kernel / DLT_PREFILL_KERNEL, bench --prefill-kernel)
until a hardware A/B lands — same policy as the prologue kernels.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..quants import QK, QTensor
from .pallas_q4 import _f16_bits_to_f32


def _mm_kernel(xlo_ref, xhi_ref, wp_ref, slo_ref, shi_ref, o_ref, *, bn, bkp):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        o_ref[:] = jnp.zeros_like(o_ref)

    wp = wp_ref[:]  # (bn, bkp) uint8 packed columns
    lo = (wp & jnp.uint8(0x0F)).astype(jnp.int32)  # elements [c, c+bkp)
    hi = wp.astype(jnp.int32) >> 4  # elements [K/2+c, K/2+c+bkp)

    def dequant(q_i32, s_ref):
        s = _f16_bits_to_f32(s_ref[:])  # (bn, bkp//QK)
        qf = q_i32.astype(jnp.float32) - 8.0
        qf = qf.reshape(bn, bkp // QK, QK) * s[:, :, None]
        return qf.reshape(bn, bkp).astype(jnp.bfloat16)

    w_lo = dequant(lo, slo_ref)
    w_hi = dequant(hi, shi_ref)
    acc = jax.lax.dot_general(
        xlo_ref[:].astype(jnp.bfloat16), w_lo, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)  # (M, bn)
    acc += jax.lax.dot_general(
        xhi_ref[:].astype(jnp.bfloat16), w_hi, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    o_ref[:] += acc


_BN = 256  # weight rows per grid step


def _pick_bkp(kh: int) -> int | None:
    """Packed columns per grid step: the largest lane-aligned tile width that
    divides the half-plane exactly (7B's w2 has kh=5504 -> 128; most dims take
    512). None = untileable (kh not a multiple of 128)."""
    for b in (512, 256, 128):
        if kh % b == 0:
            return b
    return None


def q4_mm_supported(w: QTensor, m: int) -> bool:
    """Whether the fused dequant-matmul can run this weight for M activation
    rows: i4p layout, self-contained pack (groups folded away by
    _localize_qtensors under TP), half-plane divisible into lane-aligned tiles,
    and an (M, bn) f32 accumulator that stays tiny."""
    if w.layout != "i4p" or w.groups != 1 or w.data.ndim != 2:
        return False
    kh = w.data.shape[1]  # K/2 packed columns
    return _pick_bkp(kh) is not None and m <= 512


@functools.partial(jax.jit, static_argnames=("interpret",))
def _q4_matmul(x, wp, scales, *, interpret: bool = False):
    """x (M, K) -> (M, N) against packed nibbles (N, K/2) + int16 f16-bit scales
    (N, K/32)."""
    m, k = x.shape
    n, kh = wp.shape
    nb = k // QK
    assert kh * 2 == k and scales.shape == (n, nb), (x.shape, wp.shape,
                                                     scales.shape)
    bkp = _pick_bkp(kh)
    assert bkp is not None, (kh, "half-plane not tileable; gate with "
                                 "q4_mm_supported")
    bn = min(_BN, n)
    gk = kh // bkp
    sb = bkp // QK  # scale columns per tile
    kernel = functools.partial(_mm_kernel, bn=bn, bkp=bkp)
    return pl.pallas_call(
        kernel,
        grid=(pl.cdiv(n, bn), gk),
        in_specs=[
            # two views of x: the tile's low-plane and high-plane K-ranges
            pl.BlockSpec((m, bkp), lambda i, j: (0, j), memory_space=pltpu.VMEM),
            pl.BlockSpec((m, bkp), lambda i, j: (0, j + gk),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, bkp), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, sb), lambda i, j: (i, j), memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, sb), lambda i, j: (i, j + gk),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((m, bn), lambda i, j: (0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        interpret=interpret,
    )(x, x, wp, scales, scales)


def q4_matmul(x: jax.Array, w: QTensor, *, out_dtype=None,
              interpret: bool | None = None) -> jax.Array:
    """Prefill/batched matmul: x (..., K) against an i4p QTensor (N, K) ->
    (..., N), weights streamed once at 4-bit density."""
    m_total = 1
    for d in x.shape[:-1]:
        m_total *= d
    if not q4_mm_supported(w, m_total):
        raise ValueError(
            f"q4_matmul cannot run this weight (layout={w.layout}, "
            f"groups={w.groups}, shape={getattr(w.data, 'shape', None)}, "
            f"M={m_total}); gate with q4_mm_supported")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    lead = x.shape[:-1]
    k = x.shape[-1]
    y = _q4_matmul(x.reshape(m_total, k), w.data, w.scales, interpret=interpret)
    return y.reshape(*lead, y.shape[-1]).astype(out_dtype or x.dtype)
