"""Fused 4-bit dequant-matmul — the prefill / batched-decode counterpart of the
q4 matvec kernel.

The decode matvec (ops/pallas_q4.py) is a T=1 tool: its block-diagonal Xexp
trick needs one activation row. Prefill (T>1) and batched decode (B>1) run the
XLA dequant+dot path (ops/matmul.py), which dequantizes the i4p planes to bf16
operands that XLA may MATERIALIZE through HBM (~3.6x the packed bytes at 7B;
perf/PROFILE.md's prefill cost model). This kernel keeps the dequant in VMEM:
each grid step loads a packed (bn, bkp) nibble tile + its f16-bit scales,
decodes to bf16 in registers, and feeds the MXU — weights stream from HBM
exactly once at the file's own 0.5625 B/weight density regardless of M.

Split-plane addressing: i4p byte column c holds the LOW nibble of element c and
the HIGH nibble of element K/2 + c (QTensor.to_i4p_layout), so one packed tile
covers two disjoint K-ranges; the kernel takes the activation block TWICE with
block-index maps offset by K/2 (x_lo / x_hi views of the same array) and the
scales likewise (s_lo / s_hi).

Mosaic portability (perf/PROFILE.md op matrix): nibble extraction widens
through i32 (no narrow shifts), the -8 offset and per-block scaling happen in
f32 (no i8 subtract), scales decode from f16 BIT PATTERNS with the proven
integer-exact _f16_bits_to_f32, and the dot is bf16xbf16->f32 on the MXU. No
f16 refs anywhere.

Opt-in (Engine prefill_kernel / DLT_PREFILL_KERNEL, bench --prefill-kernel)
until a hardware A/B lands — same policy as the prologue kernels. The batched
serving runtime opts in one level higher (Engine fused_matmul /
DLT_FUSED_MATMUL, --fused-matmul): the same kernel family with the legal
epilogues fused — residual add in the accumulator init (q4_matmul residual=)
and the silu·mul FFN gate pair as one kernel over the separate w1/w3 planes
(q4_gated_matmul) — serving decode M=B, verify M=B·(1+k), and drafter rows
(docs/SERVING.md "Kernel selection"; byte model in perf/PROFILE.md "Batched
fused Q40 cost model", measured by perf/q4_mm_bench.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..quants import QK, QTensor
from .pallas_q4 import _f16_bits_to_f32


# hot-path: traced
def _tile_partial(xlo_ref, xhi_ref, wp_ref, slo_ref, shi_ref, *, bn, bkp):
    """One grid step's (M, bn) partial product: decode the packed (bn, bkp)
    nibble tile + both scale views in VMEM and hit the MXU twice (low-plane
    and high-plane K-ranges of the split-plane layout)."""
    wp = wp_ref[:]  # (bn, bkp) uint8 packed columns
    lo = (wp & jnp.uint8(0x0F)).astype(jnp.int32)  # elements [c, c+bkp)
    hi = wp.astype(jnp.int32) >> 4  # elements [K/2+c, K/2+c+bkp)

    def dequant(q_i32, s_ref):
        s = _f16_bits_to_f32(s_ref[:])  # (bn, bkp//QK)
        qf = q_i32.astype(jnp.float32) - 8.0
        qf = qf.reshape(bn, bkp // QK, QK) * s[:, :, None]
        return qf.reshape(bn, bkp).astype(jnp.bfloat16)

    w_lo = dequant(lo, slo_ref)
    w_hi = dequant(hi, shi_ref)
    acc = jax.lax.dot_general(
        xlo_ref[:].astype(jnp.bfloat16), w_lo, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)  # (M, bn)
    acc += jax.lax.dot_general(
        xhi_ref[:].astype(jnp.bfloat16), w_hi, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    return acc


# hot-path: traced
def _act_f32(a, act: str):
    """Epilogue activation on the f32 accumulator, formulas matching
    ops/kernels.py bit-for-bit in f32 (silu / tanh-approx GELU)."""
    if act == "silu":
        return a / (1.0 + jnp.exp(-a))
    c = 0.79788456080286535587989211986876  # sqrt(2/pi), as gelu_tanh
    return 0.5 * a * (1.0 + jnp.tanh(c * a * (1.0 + 0.044715 * a * a)))


def _mm_kernel(xlo_ref, xhi_ref, wp_ref, slo_ref, shi_ref, o_ref, *, bn, bkp):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        o_ref[:] = jnp.zeros_like(o_ref)

    o_ref[:] += _tile_partial(xlo_ref, xhi_ref, wp_ref, slo_ref, shi_ref,
                              bn=bn, bkp=bkp)


def _mm_res_kernel(xlo_ref, xhi_ref, wp_ref, slo_ref, shi_ref, res_ref, o_ref,
                   *, bn, bkp):
    """Residual-fused variant: the accumulator STARTS at the residual block
    (same (M, bn) tile the output covers), so `res + x @ w.T` costs zero extra
    HBM round-trips — the residual streams in once with the output tile."""
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        o_ref[:] = res_ref[:].astype(jnp.float32)

    o_ref[:] += _tile_partial(xlo_ref, xhi_ref, wp_ref, slo_ref, shi_ref,
                              bn=bn, bkp=bkp)


def _gated_mm_kernel(xlo_ref, xhi_ref, w1p_ref, s1lo_ref, s1hi_ref,
                     w3p_ref, s3lo_ref, s3hi_ref, o_ref, acc1_ref, acc3_ref,
                     *, bn, bkp, gk, act):
    """FFN gate-pair fusion: act(x @ w1.T) * (x @ w3.T) in ONE kernel. Both
    accumulators live in VMEM scratch across the sequential K grid; the
    silu/gelu·mul epilogue runs on the last K step, so the (M, hidden)
    intermediate activations never exist in HBM at all."""
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc1_ref[:] = jnp.zeros_like(acc1_ref)
        acc3_ref[:] = jnp.zeros_like(acc3_ref)

    acc1_ref[:] += _tile_partial(xlo_ref, xhi_ref, w1p_ref, s1lo_ref, s1hi_ref,
                                 bn=bn, bkp=bkp)
    acc3_ref[:] += _tile_partial(xlo_ref, xhi_ref, w3p_ref, s3lo_ref, s3hi_ref,
                                 bn=bn, bkp=bkp)

    @pl.when(j == gk - 1)
    def _epilogue():
        o_ref[:] = _act_f32(acc1_ref[:], act) * acc3_ref[:]


_BN = 256  # weight rows per grid step


def _pick_bkp(kh: int) -> int | None:
    """Packed columns per grid step: the largest lane-aligned tile width that
    divides the half-plane exactly (7B's w2 has kh=5504 -> 128; most dims take
    512). None = untileable (kh not a multiple of 128)."""
    for b in (512, 256, 128):
        if kh % b == 0:
            return b
    return None


def q4_mm_supported(w: QTensor, m: int) -> bool:
    """Whether the fused dequant-matmul can run this weight for M activation
    rows: i4p layout, self-contained pack (groups folded away by
    _localize_qtensors under TP), half-plane divisible into lane-aligned tiles,
    and an (M, bn) f32 accumulator that stays tiny."""
    if w.layout != "i4p" or w.groups != 1 or w.data.ndim != 2:
        return False
    kh = w.data.shape[1]  # K/2 packed columns
    return _pick_bkp(kh) is not None and m <= 512


def _grid_geom(x, wp, scales):
    """(bn, bkp, gk, sb) for one (M, K) x (N, K/2) dispatch, asserting the
    split-plane shapes line up."""
    m, k = x.shape
    n, kh = wp.shape
    nb = k // QK
    assert kh * 2 == k and scales.shape == (n, nb), (x.shape, wp.shape,
                                                     scales.shape)
    bkp = _pick_bkp(kh)
    assert bkp is not None, (kh, "half-plane not tileable; gate with "
                                 "q4_mm_supported")
    return min(_BN, n), bkp, kh // bkp, bkp // QK


def _x_specs(m, bkp, gk):
    # two views of x: the tile's low-plane and high-plane K-ranges
    return [
        pl.BlockSpec((m, bkp), lambda i, j: (0, j), memory_space=pltpu.VMEM),
        pl.BlockSpec((m, bkp), lambda i, j: (0, j + gk),
                     memory_space=pltpu.VMEM),
    ]


def _w_specs(bn, bkp, sb, gk):
    # one packed-nibble tile + its low/high scale views
    return [
        pl.BlockSpec((bn, bkp), lambda i, j: (i, j), memory_space=pltpu.VMEM),
        pl.BlockSpec((bn, sb), lambda i, j: (i, j), memory_space=pltpu.VMEM),
        pl.BlockSpec((bn, sb), lambda i, j: (i, j + gk),
                     memory_space=pltpu.VMEM),
    ]


@functools.partial(jax.jit, static_argnames=("interpret",))
def _q4_matmul(x, wp, scales, *, interpret: bool = False):
    """x (M, K) -> (M, N) against packed nibbles (N, K/2) + int16 f16-bit scales
    (N, K/32)."""
    m = x.shape[0]
    n = wp.shape[0]
    bn, bkp, gk, sb = _grid_geom(x, wp, scales)
    kernel = functools.partial(_mm_kernel, bn=bn, bkp=bkp)
    return pl.pallas_call(
        kernel,
        grid=(pl.cdiv(n, bn), gk),
        in_specs=_x_specs(m, bkp, gk) + _w_specs(bn, bkp, sb, gk),
        out_specs=pl.BlockSpec((m, bn), lambda i, j: (0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        interpret=interpret,
    )(x, x, wp, scales, scales)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _q4_matmul_res(x, wp, scales, res, *, interpret: bool = False):
    """x (M, K), res (M, N) -> res + x @ dequant(w).T, residual folded into
    the accumulator init (one extra streamed operand, no epilogue pass)."""
    m = x.shape[0]
    n = wp.shape[0]
    assert res.shape == (m, n), (res.shape, (m, n))
    bn, bkp, gk, sb = _grid_geom(x, wp, scales)
    kernel = functools.partial(_mm_res_kernel, bn=bn, bkp=bkp)
    return pl.pallas_call(
        kernel,
        grid=(pl.cdiv(n, bn), gk),
        in_specs=(_x_specs(m, bkp, gk) + _w_specs(bn, bkp, sb, gk) + [
            pl.BlockSpec((m, bn), lambda i, j: (0, i),
                         memory_space=pltpu.VMEM),
        ]),
        out_specs=pl.BlockSpec((m, bn), lambda i, j: (0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        interpret=interpret,
    )(x, x, wp, scales, scales, res)


@functools.partial(jax.jit, static_argnames=("act", "interpret"))
def _q4_gated_matmul(x, w1p, s1, w3p, s3, *, act: str,
                     interpret: bool = False):
    """act(x @ w1.T) * (x @ w3.T) with both (M, N) accumulators in VMEM
    scratch — the FFN pair's intermediate activations never touch HBM."""
    m = x.shape[0]
    n = w1p.shape[0]
    assert w3p.shape == w1p.shape and s3.shape == s1.shape, (
        w1p.shape, w3p.shape, s1.shape, s3.shape)
    bn, bkp, gk, sb = _grid_geom(x, w1p, s1)
    kernel = functools.partial(_gated_mm_kernel, bn=bn, bkp=bkp, gk=gk,
                               act=act)
    return pl.pallas_call(
        kernel,
        grid=(pl.cdiv(n, bn), gk),
        in_specs=(_x_specs(m, bkp, gk) + _w_specs(bn, bkp, sb, gk)
                  + _w_specs(bn, bkp, sb, gk)),
        out_specs=pl.BlockSpec((m, bn), lambda i, j: (0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        scratch_shapes=[pltpu.VMEM((m, bn), jnp.float32),
                        pltpu.VMEM((m, bn), jnp.float32)],
        interpret=interpret,
    )(x, x, w1p, s1, s1, w3p, s3, s3)


def _flatten_rows(x):
    m_total = 1
    for d in x.shape[:-1]:
        m_total *= d
    return m_total, x.shape[:-1]


def q4_matmul(x: jax.Array, w: QTensor, *, out_dtype=None,
              interpret: bool | None = None,
              residual: jax.Array | None = None) -> jax.Array:
    """Prefill/batched matmul: x (..., K) against an i4p QTensor (N, K) ->
    (..., N), weights streamed once at 4-bit density. With `residual`
    (shape (..., N)) the add is fused into the accumulator init."""
    m_total, lead = _flatten_rows(x)
    if not q4_mm_supported(w, m_total):
        raise ValueError(
            f"q4_matmul cannot run this weight (layout={w.layout}, "
            f"groups={w.groups}, shape={getattr(w.data, 'shape', None)}, "
            f"M={m_total}); gate with q4_mm_supported")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    k = x.shape[-1]
    if residual is None:
        y = _q4_matmul(x.reshape(m_total, k), w.data, w.scales,
                       interpret=interpret)
    else:
        y = _q4_matmul_res(x.reshape(m_total, k), w.data, w.scales,
                           residual.reshape(m_total, residual.shape[-1]),
                           interpret=interpret)
    return y.reshape(*lead, y.shape[-1]).astype(out_dtype or x.dtype)


def q4_gated_supported(w1: QTensor, w3: QTensor, m: int) -> bool:
    """Whether the fused FFN gate-pair kernel can serve act(x@w1.T) * (x@w3.T):
    both weights individually kernel-eligible and shape-identical (they tile
    on one grid), plus VMEM headroom for the two (M, bn) scratch
    accumulators."""
    return (q4_mm_supported(w1, m) and q4_mm_supported(w3, m)
            and w1.data.shape == w3.data.shape
            and w1.scales.shape == w3.scales.shape)


def q4_gated_matmul(x: jax.Array, w1: QTensor, w3: QTensor, *,
                    act: str = "silu", out_dtype=None,
                    interpret: bool | None = None) -> jax.Array:
    """FFN gate-pair: act(x @ w1.T) * (x @ w3.T) for x (..., K) against two
    i4p QTensors (N, K), one fused kernel — both weight streams at 4-bit
    density and ZERO HBM traffic for the (..., N) intermediates."""
    m_total, lead = _flatten_rows(x)
    if not q4_gated_supported(w1, w3, m_total):
        raise ValueError(
            f"q4_gated_matmul cannot run this pair (layouts={w1.layout}/"
            f"{w3.layout}, shapes={getattr(w1.data, 'shape', None)}/"
            f"{getattr(w3.data, 'shape', None)}, M={m_total}); gate with "
            f"q4_gated_supported")
    if act not in ("silu", "gelu_tanh"):
        raise ValueError(f"unsupported epilogue activation {act!r}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    k = x.shape[-1]
    y = _q4_gated_matmul(x.reshape(m_total, k), w1.data, w1.scales,
                         w3.data, w3.scales, act=act, interpret=interpret)
    return y.reshape(*lead, y.shape[-1]).astype(out_dtype or x.dtype)
