"""Grouped-query causal attention over a resident KV cache.

TPU-native replacement for the reference's per-head scalar attention loop
(src/llama2-tasks.cpp:54-94: per head, dot q·k over 0..pos, softmax, weighted sum of v).
Here the whole (heads x positions) score matrix is one batched einsum on the MXU, masked
and softmaxed on the VPU, for T query tokens at once — which also gives chunked prefill,
something the reference (token-at-a-time prefill) lacks.

Shapes (batch-first, head-major cache):
    q: (B, T, n_q_heads, hs)     k_cache/v_cache: (B, n_kv_heads, S, hs)
TP slices along the kv-head axis (reference MultiHeadAttSlice, commands.cpp:104-108);
sequence parallelism slices along S (ring attention, see ops/ring_attention.py).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .kernels import masked_softmax


def gqa_attention(q: jax.Array, k_cache: jax.Array, v_cache: jax.Array,
                  positions: jax.Array,
                  key_positions: jax.Array | None = None) -> jax.Array:
    """Causal GQA attention of T query tokens against the full cache.

    positions: absolute query positions, (T,) shared across the batch or (B, T)
    per-row (continuous batching: each batch row decodes at its own offset).
    key_positions: absolute position of each key slot, (S,) or per-row (B, S).
    Defaults to arange(S) (slot index == position, the resident-cache layout);
    the deferred-cache-write path passes [window slots ++ current-chunk positions]
    with garbage slots pushed past seq_len so the causal compare masks them.
    Returns (B, T, n_q_heads * hs)."""
    b, t, hq, hs = q.shape
    _, hk, s, _ = k_cache.shape
    g = hq // hk
    qg = q.reshape(b, t, hk, g, hs)
    scale = 1.0 / math.sqrt(hs)
    # (B, hk, g, T, S)
    scores = jnp.einsum("btkgd,bksd->bkgts", qg.astype(jnp.float32),
                        k_cache.astype(jnp.float32)) * scale
    if key_positions is None:
        key_positions = jnp.arange(s)
    if positions.ndim == 1:
        assert key_positions.ndim == 1
        valid = key_positions[None, :] <= positions[:, None]  # (T, S) causal mask
        mask = valid[None, None, None, :, :]
    else:
        kp = key_positions if key_positions.ndim == 2 else key_positions[None, :]
        valid = kp[:, None, :] <= positions[:, :, None]  # (B, T, S)
        mask = valid[:, None, None, :, :]
    probs = masked_softmax(scores, mask)
    out = jnp.einsum("bkgts,bksd->btkgd", probs, v_cache.astype(jnp.float32))
    return out.reshape(b, t, hq * hs).astype(q.dtype)


def gqa_attention_lse(q: jax.Array, k_cache: jax.Array, v_cache: jax.Array,
                      positions: jax.Array,
                      key_positions: jax.Array | None = None
                      ) -> tuple[jax.Array, jax.Array]:
    """gqa_attention that ALSO returns the log-sum-exp of the (masked) scores.

    The flash-attention segment form: a softmax over keys split across segments
    equals merge_attention_partials() of each segment's (normalized output, lse).
    Used by the paged KV cache (runtime/paged_cache.py) to combine the device-
    resident hot ring with the host-resident cold history — the TPU-native
    answer to the reference's mmap'd disk KV cache (transformer.cpp:312-318).

    Returns (out (B, T, hq, hs) f32, lse (B, T, hq) f32); fully-masked rows give
    out 0 and lse -inf (a zero-weight segment under the merge)."""
    b, t, hq, hs = q.shape
    _, hk, s, _ = k_cache.shape
    g = hq // hk
    qg = q.reshape(b, t, hk, g, hs)
    scale = 1.0 / math.sqrt(hs)
    scores = jnp.einsum("btkgd,bksd->bkgts", qg.astype(jnp.float32),
                        k_cache.astype(jnp.float32)) * scale  # (B, hk, g, T, S)
    if key_positions is None:
        key_positions = jnp.arange(s)
    if positions.ndim == 1:
        mask = (key_positions[None, :] <= positions[:, None])[None, None, None]
    else:
        kp = key_positions if key_positions.ndim == 2 else key_positions[None, :]
        mask = (kp[:, None, :] <= positions[:, :, None])[:, None, None]
    neg = jnp.finfo(jnp.float32).min
    sm = jnp.where(mask, scores, neg)
    m = jnp.max(sm, axis=-1)  # (B, hk, g, T)
    e = jnp.where(mask, jnp.exp(sm - m[..., None]), 0.0)
    l = jnp.sum(e, axis=-1)  # (B, hk, g, T)
    out = jnp.einsum("bkgts,bksd->btkgd", e, v_cache.astype(jnp.float32))
    l_t = jnp.transpose(l, (0, 3, 1, 2))  # (B, T, hk, g)
    m_t = jnp.transpose(m, (0, 3, 1, 2))
    out = out / jnp.maximum(l_t, 1e-30)[..., None]
    lse = jnp.where(l_t > 0.0, m_t + jnp.log(jnp.maximum(l_t, 1e-30)), -jnp.inf)
    return out.reshape(b, t, hq, hs), lse.reshape(b, t, hq)


def merge_attention_partials(out_a: jax.Array, lse_a: jax.Array,
                             out_b: jax.Array, lse_b: jax.Array) -> jax.Array:
    """Combine two attention segments' (normalized output, lse) into the exact
    full-softmax output: softmax weights re-derive from exp(lse_i - max) and an
    empty segment (lse -inf) contributes zero weight. out_*: (..., hs),
    lse_*: (...) matching out's leading axes."""
    m = jnp.maximum(lse_a, lse_b)
    m = jnp.where(jnp.isfinite(m), m, 0.0)  # both segments empty: output zeros
    wa = jnp.exp(lse_a - m)
    wb = jnp.exp(lse_b - m)
    den = jnp.maximum(wa + wb, 1e-30)[..., None]
    return (out_a * wa[..., None] + out_b * wb[..., None]) / den


def update_kv_cache(k_cache: jax.Array, v_cache: jax.Array, k_new: jax.Array,
                    v_new: jax.Array, start_pos: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Write T new kv vectors at [start_pos, start_pos+T) into head-major caches.

    k_new/v_new: (B, T, n_kv_heads, hs) -> caches (B, n_kv_heads, S, hs).
    start_pos: scalar (all rows write at the same offset) or (B,) per-row offsets
    (continuous batching). Replaces the reference's direct in-cache matmul write
    (llama2-tasks.cpp:38-44).
    """
    k_t = jnp.swapaxes(k_new, 1, 2).astype(k_cache.dtype)  # (B, hk, T, hs)
    v_t = jnp.swapaxes(v_new, 1, 2).astype(v_cache.dtype)
    if start_pos.ndim == 0:
        k_cache = jax.lax.dynamic_update_slice(k_cache, k_t, (0, 0, start_pos, 0))
        v_cache = jax.lax.dynamic_update_slice(v_cache, v_t, (0, 0, start_pos, 0))
        return k_cache, v_cache
    row_write = jax.vmap(
        lambda c, n, p: jax.lax.dynamic_update_slice(c, n, (0, p, 0)))
    return row_write(k_cache, k_t, start_pos), row_write(v_cache, v_t, start_pos)
