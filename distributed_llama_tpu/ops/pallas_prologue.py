"""Fused activation-prologue kernels: rmsnorm (+) Q80 quantization in one pass.

Every decode matvec quantizes its activation row to per-32-block int8 (the
reference's Q80 buffer discipline, src/tasks.cpp:96-135) before the weight kernel
runs. On the XLA path that costs, per layer, a handful of small fusions (rmsnorm
reduce, absmax, round/scale) plus — for the non-inline matvec variant — a
(K, nb) block-diagonal Xexp materialization through HBM. These kernels collapse
the whole prologue into ONE VPU pass per activation:

    rmsnorm_quantize_q80:  x (1,K) f32/bf16, w (K,)  ->  xq (1,K) i8, sx (1,nb) f32
    quantize_q80_row:      x (1,K)                   ->  xq (1,K) i8, sx (1,nb) f32

The outputs feed ops.matmul.qmatmul_q80, which routes into the inline-Xexp
matvec variants for BOTH layouts (scatter built in kernel scratch —
pallas_q4._matvec_kernel_inline / pallas_q8._matvec_kernel_inline), so the
quantized row is the only activation HBM traffic.

Numerics: the rmsnorm reduction runs in f32 with the same mean-square + eps
formula as ops.kernels.rmsnorm (reference funcs.cpp rms(), eps inside the mean);
quantization IS pallas_q8._quantize_row (shared helper, pure jnp, usable inside
kernel bodies). Mosaic portability: all intermediates are f32/i32 except the
final i8 cast — every op is in the known-good set (perf/PROFILE.md op matrix);
no f16, no narrow-int arithmetic, no sub-32-bit minor-dim insertion (the one
f32 minor-dim insert is 32-bit, which Mosaic supports).

Opt-in (Engine fused_prologue / bench --prologue) until a hardware A/B lands —
the round-4 lesson is not to ship never-executed kernels as defaults.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..quants import QK


def _quantize_store(xb, xq_ref, sx_ref):
    """Shared epilogue: per-32-block absmax quantize of xb (1, K) f32 into the
    int8 row + f32 block-scale outputs. The math is pallas_q8._quantize_row
    itself (pure jnp, kernel-body safe) — one source of truth for the Q80
    formula."""
    from .pallas_q8 import _quantize_row

    k = xb.shape[1]
    xq, sx = _quantize_row(xb.reshape(k), k // QK)
    xq_ref[:] = xq.reshape(1, k)
    sx_ref[:] = sx


def _rmsnorm_q80_kernel(x_ref, w_ref, xq_ref, sx_ref, *, eps: float):
    x = x_ref[:].astype(jnp.float32)  # (1, K)
    k = x.shape[1]
    ms = jnp.sum(x * x, axis=1, keepdims=True) / k  # (1, 1), f32 reduction
    inv = jnp.reciprocal(jnp.sqrt(ms + eps))
    xb = x * inv * w_ref[:].astype(jnp.float32)
    _quantize_store(xb, xq_ref, sx_ref)


def _quantize_kernel(x_ref, xq_ref, sx_ref):
    _quantize_store(x_ref[:].astype(jnp.float32), xq_ref, sx_ref)


def prologue_supported(k: int) -> bool:
    """Single-block VMEM kernel: the row (f32) plus outputs must be tiny. K up to
    64k (256 KB f32) is far under VMEM; require whole 32-blocks."""
    return k % QK == 0 and k <= (1 << 16)


@functools.partial(jax.jit, static_argnames=("eps", "interpret"))
def _rmsnorm_q80(x, w, *, eps: float, interpret: bool):
    _, k = x.shape
    nb = k // QK
    return pl.pallas_call(
        functools.partial(_rmsnorm_q80_kernel, eps=eps),
        in_specs=[
            pl.BlockSpec((1, k), lambda: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k), lambda: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, k), lambda: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, nb), lambda: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[jax.ShapeDtypeStruct((1, k), jnp.int8),
                   jax.ShapeDtypeStruct((1, nb), jnp.float32)],
        interpret=interpret,
    )(x, w)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _quantize(x, *, interpret: bool):
    _, k = x.shape
    nb = k // QK
    return pl.pallas_call(
        _quantize_kernel,
        in_specs=[pl.BlockSpec((1, k), lambda: (0, 0), memory_space=pltpu.VMEM)],
        out_specs=[
            pl.BlockSpec((1, k), lambda: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, nb), lambda: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[jax.ShapeDtypeStruct((1, k), jnp.int8),
                   jax.ShapeDtypeStruct((1, nb), jnp.float32)],
        interpret=interpret,
    )(x)


def rmsnorm_quantize_q80(x: jax.Array, w: jax.Array, eps: float,
                         *, interpret: bool | None = None):
    """x (..., K) with leading dims multiplying to 1 -> (xq (1, K) i8,
    sx (1, nb) f32) of rmsnorm(x, w) quantized per 32-block."""
    k = x.shape[-1]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _rmsnorm_q80(x.reshape(1, k), w.reshape(1, k), eps=float(eps),
                        interpret=interpret)


def quantize_q80_row(x: jax.Array, *, interpret: bool | None = None):
    """x (..., K) with leading dims multiplying to 1 -> (xq (1, K) i8,
    sx (1, nb) f32)."""
    k = x.shape[-1]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _quantize(x.reshape(1, k), interpret=interpret)
