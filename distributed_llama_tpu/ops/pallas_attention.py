"""Fused decode attention — windowed cache read + GQA scores + softmax + AV in one
kernel, reading the cache window STRAIGHT out of the stacked (L, B, hk, S, hs)
buffers.

The XLA path (models/forward.py deferred branch + ops/attention.py) materializes a
(B, hk, win, hs) dynamic-slice of each cache per layer before attention — at 7B /
window 256 that is ~134 MB/step of slice traffic plus separate softmax fusions (the
`dynamic-slice_bitcast_fusion` + `convert_reduce_fusion` lines in the round-4
profile, ~4-5 ms/step together). This kernel takes the FULL stacked caches as
operands and lets the Pallas pipeline DMA exactly the (layer_idx, 0, h, 0:win)
block per kv-head grid step — the layer index rides in as a scalar-prefetch
argument, so nothing is sliced or copied in XLA.

The reference's counterpart is the per-head attention loop at
src/llama2-tasks.cpp:54-94 (dot q·k over 0..pos, softmax, weighted v sum); the
windowed-read semantics match ops/attention.gqa_attention with the deferred-write
key layout: window slots are valid iff slot < pos, and the current token's k/v
(not yet committed to the cache) attends from registers.

Decode-only by design: T = 1 query row, scalar pos (the host-loop/device-loop hot
path). Prefill and batched/per-row paths keep the XLA route, which amortizes fine
at T > 1.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = -1e30  # f32 mask value; exp(_NEG - max) == 0 exactly in f32


def _kernel(pos_ref, q_ref, kn_ref, vn_ref, kw_ref, vw_ref, o_ref):
    """Grid step = one kv head. Blocks:
    q (1, g, hs) f32 | k_new/v_new (1, 1, hs) | kw/vw (1, 1, win, hs) cache dtype |
    out (1, g, hs) f32. pos is scalar-prefetched."""
    pos = pos_ref[0]
    q = q_ref[0]  # (g, hs) f32
    kw = kw_ref[0, 0].astype(jnp.float32)  # (win, hs)
    vw = vw_ref[0, 0].astype(jnp.float32)
    kn = kn_ref[0].astype(jnp.float32)  # (1, hs) current token
    vn = vn_ref[0].astype(jnp.float32)
    win = kw.shape[0]
    scale = jnp.float32(1.0 / math.sqrt(q.shape[-1]))

    s_old = jax.lax.dot_general(q, kw, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale  # (g, win)
    slot = jax.lax.broadcasted_iota(jnp.int32, s_old.shape, 1)
    s_old = jnp.where(slot < pos, s_old, _NEG)  # committed rows only
    s_new = jnp.sum(q * kn, axis=-1, keepdims=True) * scale  # (g, 1) current token

    m = jnp.maximum(jnp.max(s_old, axis=1, keepdims=True), s_new)  # (g, 1)
    p_old = jnp.exp(s_old - m)  # (g, win); masked slots exp(_NEG - m) == 0
    p_new = jnp.exp(s_new - m)  # (g, 1)
    denom = jnp.sum(p_old, axis=1, keepdims=True) + p_new
    out = jax.lax.dot_general(p_old, vw, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)  # (g, hs)
    out = (out + p_new * vn) / denom
    o_ref[0] = out


def _kernel_tiled(pos_ref, q_ref, kn_ref, vn_ref, kw_ref, vw_ref, o_ref,
                  m_ref, l_ref, acc_ref, *, wt, gw):
    """Window-tiled variant: grid (hk, gw); each step attends one (wt, hs) slice
    of the window with a flash-attention m/l/acc carry in VMEM scratch, so VMEM
    holds one tile regardless of the window (long-context decode keeps the fused
    kernel instead of falling back to the XLA path). The current token's k/v
    fold in at the last tile."""
    j = pl.program_id(1)
    pos = pos_ref[0]
    q = q_ref[0]  # (g, hs) f32
    scale = jnp.float32(1.0 / math.sqrt(q.shape[-1]))

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    kw = kw_ref[0, 0].astype(jnp.float32)  # (wt, hs)
    vw = vw_ref[0, 0].astype(jnp.float32)
    # a trailing partial tile's padded region holds UNSPECIFIED bits; the score
    # mask alone cannot save acc from 0*NaN, so zero the invalid V rows too
    row = jax.lax.broadcasted_iota(jnp.int32, (vw.shape[0], 1), 0) + j * wt
    vw = jnp.where(row < pos, vw, 0.0)
    s = jax.lax.dot_general(q, kw, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale  # (g, wt)
    slot = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + j * wt
    s = jnp.where(slot < pos, s, _NEG)  # committed rows only; masks tile padding
    # (NaN scores from padded K rows are replaced by _NEG here — jnp.where
    # selects the mask value regardless of NaN)
    m_new = jnp.maximum(m_ref[:], jnp.max(s, axis=1, keepdims=True))
    a = jnp.exp(m_ref[:] - m_new)
    p = jnp.exp(s - m_new)
    l_ref[:] = l_ref[:] * a + jnp.sum(p, axis=1, keepdims=True)
    acc_ref[:] = acc_ref[:] * a + jax.lax.dot_general(
        p, vw, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    m_ref[:] = m_new

    @pl.when(j == gw - 1)
    def _finalize():
        kn = kn_ref[0].astype(jnp.float32)  # (1, hs) current token
        vn = vn_ref[0].astype(jnp.float32)
        s_new = jnp.sum(q * kn, axis=-1, keepdims=True) * scale  # (g, 1)
        m_f = jnp.maximum(m_ref[:], s_new)
        a_f = jnp.exp(m_ref[:] - m_f)
        p_new = jnp.exp(s_new - m_f)
        denom = l_ref[:] * a_f + p_new
        o_ref[0] = (acc_ref[:] * a_f + p_new * vn) / denom


# per-operand VMEM budget for the single-block kernel; larger windows tile
_FUSED_ONE_BLOCK_LIMIT = 4 << 20
_WT = 2048  # window slots per tile in the tiled kernel


@functools.partial(jax.jit, static_argnames=("window", "interpret"))
def fused_decode_attention(q, kc, vc, k_new, v_new, layer_idx, pos, *,
                           window: int, interpret: bool | None = None):
    """One decode token's attention for one layer against the stacked caches.

    q: (hk, g, hs) f32/bf16 — query heads grouped per kv head.
    kc/vc: (L, B=1, hk, S, hs) FULL stacked caches (any dtype); only the
        (layer_idx, 0, h, 0:window) block is ever moved on-chip.
    k_new/v_new: (hk, 1, hs) — the current token's uncommitted k/v.
    layer_idx, pos: i32 scalars. window: static read bound (>= pos+1... the
        current token comes from k_new, so window >= pos suffices).
    Returns (hk, g, hs) f32.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    hk, g, hs = q.shape
    l, b, hk2, s, hs2 = kc.shape
    assert b == 1 and hk2 == hk and hs2 == hs, (q.shape, kc.shape)
    assert k_new.shape == (hk, 1, hs), k_new.shape
    win = min(window, s)
    one_block = win * hs * jnp.dtype(kc.dtype).itemsize <= _FUSED_ONE_BLOCK_LIMIT

    if one_block:
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,  # (layer_idx_arr, pos_arr)
            grid=(hk,),
            in_specs=[
                pl.BlockSpec((1, g, hs), lambda h, li, po: (h, 0, 0)),
                pl.BlockSpec((1, 1, hs), lambda h, li, po: (h, 0, 0)),
                pl.BlockSpec((1, 1, hs), lambda h, li, po: (h, 0, 0)),
                pl.BlockSpec((1, 1, win, hs), lambda h, li, po: (li[0], h, 0, 0)),
                pl.BlockSpec((1, 1, win, hs), lambda h, li, po: (li[0], h, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, g, hs), lambda h, li, po: (h, 0, 0)),
        )

        def kernel(li_ref, pos_ref, q_ref, kn_ref, vn_ref, kw_ref, vw_ref, o_ref):
            # li_ref is consumed by the BlockSpec index_maps only
            _kernel(pos_ref, q_ref, kn_ref, vn_ref, kw_ref, vw_ref, o_ref)

    else:
        # long-context form: tile the window axis with a flash-attention carry
        wt = min(_WT, win)
        gw = pl.cdiv(win, wt)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(hk, gw),
            in_specs=[
                pl.BlockSpec((1, g, hs), lambda h, j, li, po: (h, 0, 0)),
                pl.BlockSpec((1, 1, hs), lambda h, j, li, po: (h, 0, 0)),
                pl.BlockSpec((1, 1, hs), lambda h, j, li, po: (h, 0, 0)),
                pl.BlockSpec((1, 1, wt, hs),
                             lambda h, j, li, po: (li[0], h, j, 0)),
                pl.BlockSpec((1, 1, wt, hs),
                             lambda h, j, li, po: (li[0], h, j, 0)),
            ],
            out_specs=pl.BlockSpec((1, g, hs), lambda h, j, li, po: (h, 0, 0)),
            scratch_shapes=[pltpu.VMEM((g, 1), jnp.float32),
                            pltpu.VMEM((g, 1), jnp.float32),
                            pltpu.VMEM((g, hs), jnp.float32)],
        )
        body = functools.partial(_kernel_tiled, wt=wt, gw=gw)

        def kernel(li_ref, pos_ref, q_ref, kn_ref, vn_ref, kw_ref, vw_ref,
                   o_ref, m_ref, l_ref, acc_ref):
            body(pos_ref, q_ref, kn_ref, vn_ref, kw_ref, vw_ref, o_ref,
                 m_ref, l_ref, acc_ref)


    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((hk, g, hs), jnp.float32),
        interpret=interpret,
    )(jnp.asarray([layer_idx], jnp.int32), jnp.asarray([pos], jnp.int32),
      q.astype(jnp.float32), k_new, v_new,
      kc.reshape(l, hk, s, hs), vc.reshape(l, hk, s, hs))
