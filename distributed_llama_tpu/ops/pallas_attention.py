"""Fused decode attention — windowed cache read + GQA scores + softmax + AV in one
kernel, reading the cache window STRAIGHT out of the stacked (L, B, hk, S, hs)
buffers.

The XLA path (models/forward.py deferred branch + ops/attention.py) materializes a
(B, hk, win, hs) dynamic-slice of each cache per layer before attention — at 7B /
window 256 that is ~134 MB/step of slice traffic plus separate softmax fusions (the
`dynamic-slice_bitcast_fusion` + `convert_reduce_fusion` lines in the round-4
profile, ~4-5 ms/step together). This kernel takes the FULL stacked caches as
operands and lets the Pallas pipeline DMA exactly the (layer_idx, 0, h, 0:win)
block per kv-head grid step — the layer index rides in as a scalar-prefetch
argument, so nothing is sliced or copied in XLA.

The reference's counterpart is the per-head attention loop at
src/llama2-tasks.cpp:54-94 (dot q·k over 0..pos, softmax, weighted v sum); the
windowed-read semantics match ops/attention.gqa_attention with the deferred-write
key layout: window slots are valid iff slot < pos, and the current token's k/v
(not yet committed to the cache) attends from registers.

Decode-only by design: T = 1 query row, scalar pos (the host-loop/device-loop hot
path). Prefill and batched/per-row paths keep the XLA route, which amortizes fine
at T > 1.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = -1e30  # f32 mask value; exp(_NEG - max) == 0 exactly in f32


def _kernel(pos_ref, q_ref, kn_ref, vn_ref, kw_ref, vw_ref, o_ref):
    """Grid step = one kv head. Blocks:
    q (1, g, hs) f32 | k_new/v_new (1, 1, hs) | kw/vw (1, 1, win, hs) cache dtype |
    out (1, g, hs) f32. pos is scalar-prefetched."""
    pos = pos_ref[0]
    q = q_ref[0]  # (g, hs) f32
    kw = kw_ref[0, 0].astype(jnp.float32)  # (win, hs)
    vw = vw_ref[0, 0].astype(jnp.float32)
    kn = kn_ref[0].astype(jnp.float32)  # (1, hs) current token
    vn = vn_ref[0].astype(jnp.float32)
    win = kw.shape[0]
    scale = jnp.float32(1.0 / math.sqrt(q.shape[-1]))

    s_old = jax.lax.dot_general(q, kw, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale  # (g, win)
    slot = jax.lax.broadcasted_iota(jnp.int32, s_old.shape, 1)
    s_old = jnp.where(slot < pos, s_old, _NEG)  # committed rows only
    s_new = jnp.sum(q * kn, axis=-1, keepdims=True) * scale  # (g, 1) current token

    m = jnp.maximum(jnp.max(s_old, axis=1, keepdims=True), s_new)  # (g, 1)
    p_old = jnp.exp(s_old - m)  # (g, win); masked slots exp(_NEG - m) == 0
    p_new = jnp.exp(s_new - m)  # (g, 1)
    denom = jnp.sum(p_old, axis=1, keepdims=True) + p_new
    out = jax.lax.dot_general(p_old, vw, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)  # (g, hs)
    out = (out + p_new * vn) / denom
    o_ref[0] = out


@functools.partial(jax.jit, static_argnames=("window", "interpret"))
def fused_decode_attention(q, kc, vc, k_new, v_new, layer_idx, pos, *,
                           window: int, interpret: bool | None = None):
    """One decode token's attention for one layer against the stacked caches.

    q: (hk, g, hs) f32/bf16 — query heads grouped per kv head.
    kc/vc: (L, B=1, hk, S, hs) FULL stacked caches (any dtype); only the
        (layer_idx, 0, h, 0:window) block is ever moved on-chip.
    k_new/v_new: (hk, 1, hs) — the current token's uncommitted k/v.
    layer_idx, pos: i32 scalars. window: static read bound (>= pos+1... the
        current token comes from k_new, so window >= pos suffices).
    Returns (hk, g, hs) f32.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    hk, g, hs = q.shape
    l, b, hk2, s, hs2 = kc.shape
    assert b == 1 and hk2 == hk and hs2 == hs, (q.shape, kc.shape)
    assert k_new.shape == (hk, 1, hs), k_new.shape
    win = min(window, s)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # (layer_idx_arr, pos_arr)
        grid=(hk,),
        in_specs=[
            pl.BlockSpec((1, g, hs), lambda h, li, po: (h, 0, 0)),
            pl.BlockSpec((1, 1, hs), lambda h, li, po: (h, 0, 0)),
            pl.BlockSpec((1, 1, hs), lambda h, li, po: (h, 0, 0)),
            pl.BlockSpec((1, 1, win, hs), lambda h, li, po: (li[0], h, 0, 0)),
            pl.BlockSpec((1, 1, win, hs), lambda h, li, po: (li[0], h, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, g, hs), lambda h, li, po: (h, 0, 0)),
    )
    def kernel(li_ref, pos_ref, q_ref, kn_ref, vn_ref, kw_ref, vw_ref, o_ref):
        # li_ref is consumed by the BlockSpec index_maps only
        _kernel(pos_ref, q_ref, kn_ref, vn_ref, kw_ref, vw_ref, o_ref)

    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((hk, g, hs), jnp.float32),
        interpret=interpret,
    )(jnp.asarray([layer_idx], jnp.int32), jnp.asarray([pos], jnp.int32),
      q.astype(jnp.float32), k_new, v_new,
      kc.reshape(l, hk, s, hs), vc.reshape(l, hk, s, hs))
