"""Paged attention over the device block pool — decode AND speculative verify.

The production counterpart of the device-resident paged KV refactor
(docs/PAGED_KV.md): KV lives in a (L, N, hk, bt, hs) block pool and each
batch row's BLOCK TABLE maps virtual positions to pool blocks. Two readers
live here:

- `paged_gather_kv` — the XLA fallback: gather the table's blocks into a
  contiguous (B, hk, win, hs) buffer, exactly the dense deferred-write
  window layout. models/forward.py feeds it to the SAME gqa_attention code
  path as the dense cache, so on the CPU mesh the paged engine is
  bit-identical to the dense engine (the token-identity acceptance bar).

- `paged_attention` — the Pallas kernel: grid (B, hk, n_blocks); the block
  table rides in as a SCALAR-PREFETCH argument so each grid step's
  BlockSpec index_map DMAs exactly (layer, table[b, j], h) — no gather, no
  materialized window, the cache bytes move straight pool→VMEM. A
  flash-attention (m, l, acc) carry in VMEM scratch merges the blocks; the
  current chunk's uncommitted K/V (T = 1 for the decode scan, T = 1+k for
  the speculative verify dispatch) folds in at the last grid step with an
  in-chunk causal mask. f16 never appears (BENCH_r03's mosaic 'f16' trap):
  cache blocks load in their storage dtype and are cast to f32 in-kernel.

Numerics: the kernel's blockwise online softmax is mathematically exact but
not bit-identical to the one-shot XLA softmax; it is the TPU path
(`use_pallas` engines / DLT_PAGED_KERNEL=1), with interpret mode on CPU for
parity tests (perf/paged_attn_bench.py gates max|Δ|)."""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = -1e30  # f32 mask value; exp(_NEG - max) == 0 exactly in f32


def paged_gather_kv(kc, vc, layer_idx, tables, n_read: int):
    """Gather the first `n_read` table entries' blocks of one layer into
    contiguous (B, hk, n_read*bt, hs) K/V buffers (virtual-position order:
    table entry j supplies positions [j*bt, (j+1)*bt)).

    kc/vc: (L, N, hk, bt, hs) stacked pools; layer_idx: i32 scalar (traced —
    called inside the layer scan); tables: (B, W >= n_read) i32."""
    l, n, hk, bt, hs = kc.shape
    kl = jax.lax.dynamic_slice(kc, (layer_idx, 0, 0, 0, 0),
                               (1, n, hk, bt, hs))[0]
    vl = jax.lax.dynamic_slice(vc, (layer_idx, 0, 0, 0, 0),
                               (1, n, hk, bt, hs))[0]
    tbl = tables[:, :n_read]  # (B, n_read)
    b = tbl.shape[0]

    def grab(pool_layer):
        g = pool_layer[tbl]  # (B, n_read, hk, bt, hs)
        return jnp.transpose(g, (0, 2, 1, 3, 4)).reshape(
            b, hk, n_read * bt, hs)

    return grab(kl), grab(vl)


def _kernel(li_ref, tbl_ref, len_ref, q_ref, kn_ref, vn_ref, kb_ref, vb_ref,
            o_ref, m_ref, l_ref, acc_ref, *, bt, nb, t, g):
    """Grid step (b, h, j): one kv head's queries against table block j.

    Blocks: q (1, 1, t*g, hs) f32 | k_new/v_new (1, 1, t, hs) | kb/vb
    (1, 1, 1, bt, hs) cache dtype | out (1, 1, t*g, hs) f32. Scratch: the
    flash (m, l, acc) carry. li/tbl/len are scalar-prefetched (li and tbl
    are consumed by the BlockSpec index_maps; len masks in-body)."""
    b = pl.program_id(0)
    j = pl.program_id(2)
    q = q_ref[0, 0]  # (t*g, hs) f32
    scale = jnp.float32(1.0 / math.sqrt(q.shape[-1]))

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    kb = kb_ref[0, 0, 0].astype(jnp.float32)  # (bt, hs)
    vb = vb_ref[0, 0, 0].astype(jnp.float32)
    # virtual position of block row r is j*bt + r; rows at/after the row's
    # committed length are uncommitted garbage (scratch writes, CoW slack)
    pos = jax.lax.broadcasted_iota(jnp.int32, (bt, 1), 0) + j * bt
    live = pos < len_ref[b]
    vb = jnp.where(live, vb, 0.0)  # NaN guard: 0 * garbage stays finite
    s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    s = jnp.where(live.reshape(1, bt), s, _NEG)  # (t*g, bt)
    m_new = jnp.maximum(m_ref[:], jnp.max(s, axis=1, keepdims=True))
    a = jnp.exp(m_ref[:] - m_new)
    p = jnp.exp(s - m_new)
    l_ref[:] = l_ref[:] * a + jnp.sum(p, axis=1, keepdims=True)
    acc_ref[:] = acc_ref[:] * a + jax.lax.dot_general(
        p, vb, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    m_ref[:] = m_new

    @pl.when(j == nb - 1)
    def _finalize():
        # fold the current chunk's uncommitted K/V: query row r (= ti*g+gi)
        # sits at position len+ti and may attend chunk key tau iff tau <= ti
        kn = kn_ref[0, 0].astype(jnp.float32)  # (t, hs)
        vn = vn_ref[0, 0].astype(jnp.float32)
        s_new = jax.lax.dot_general(q, kn, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32) * scale
        ti = jax.lax.broadcasted_iota(jnp.int32, (t * g, t), 0) // g
        tau = jax.lax.broadcasted_iota(jnp.int32, (t * g, t), 1)
        s_new = jnp.where(tau <= ti, s_new, _NEG)
        m_f = jnp.maximum(m_ref[:], jnp.max(s_new, axis=1, keepdims=True))
        a_f = jnp.exp(m_ref[:] - m_f)
        p_new = jnp.exp(s_new - m_f)
        denom = l_ref[:] * a_f + jnp.sum(p_new, axis=1, keepdims=True)
        out = acc_ref[:] * a_f + jax.lax.dot_general(
            p_new, vn, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        o_ref[0, 0] = out / denom


@functools.partial(jax.jit,
                   static_argnames=("n_read", "interpret"))
def paged_attention(q, kc, vc, k_new, v_new, tables, lengths, layer_idx, *,
                    n_read: int, interpret: bool | None = None):
    """Paged attention of T chunk queries per row against block-table KV.

    q: (B, T, hq, hs) f32/bf16 — T = 1 (decode scan step) or 1+k (verify).
    kc/vc: (L, N, hk, bt, hs) FULL stacked pools (any dtype); only the
        (layer, tables[b, j], h) blocks are ever moved on-chip.
    k_new/v_new: (B, hk, T, hs) — the chunk's uncommitted K/V.
    tables: (B, W) i32 block table (first n_read entries are read).
    lengths: (B,) i32 committed length (row's start position).
    layer_idx: i32 scalar. n_read: static read-block count (the window
        bucket divided by bt — callers bucket it so shapes never vary per
        request, analysis/compile_audit.py).
    Returns (B, T, hq, hs) f32.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, t, hq, hs = q.shape
    l, n, hk, bt, hs2 = kc.shape
    assert hs2 == hs and k_new.shape == (b, hk, t, hs), (q.shape, kc.shape,
                                                         k_new.shape)
    g = hq // hk
    nb = n_read
    qr = q.astype(jnp.float32).reshape(b, t, hk, g, hs)
    qr = jnp.transpose(qr, (0, 2, 1, 3, 4)).reshape(b, hk, t * g, hs)
    tbl_flat = tables[:, :nb].reshape(-1).astype(jnp.int32)  # (B*nb,)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,  # (layer_idx_arr, tbl_flat, lengths)
        grid=(b, hk, nb),
        in_specs=[
            pl.BlockSpec((1, 1, t * g, hs),
                         lambda bi, h, j, li, tb, ln: (bi, h, 0, 0)),
            pl.BlockSpec((1, 1, t, hs),
                         lambda bi, h, j, li, tb, ln: (bi, h, 0, 0)),
            pl.BlockSpec((1, 1, t, hs),
                         lambda bi, h, j, li, tb, ln: (bi, h, 0, 0)),
            pl.BlockSpec((1, 1, 1, bt, hs),
                         lambda bi, h, j, li, tb, ln:
                         (li[0], tb[bi * nb + j], h, 0, 0)),
            pl.BlockSpec((1, 1, 1, bt, hs),
                         lambda bi, h, j, li, tb, ln:
                         (li[0], tb[bi * nb + j], h, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, t * g, hs),
                               lambda bi, h, j, li, tb, ln: (bi, h, 0, 0)),
        scratch_shapes=[pltpu.VMEM((t * g, 1), jnp.float32),
                        pltpu.VMEM((t * g, 1), jnp.float32),
                        pltpu.VMEM((t * g, hs), jnp.float32)],
    )
    body = functools.partial(_kernel, bt=bt, nb=nb, t=t, g=g)
    out = pl.pallas_call(
        body,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hk, t * g, hs), jnp.float32),
        interpret=interpret,
    )(jnp.asarray([layer_idx], jnp.int32), tbl_flat,
      jnp.asarray(lengths, jnp.int32), qr, k_new, v_new, kc, vc)
    # (B, hk, t*g, hs) -> (B, T, hq, hs)
    out = out.reshape(b, hk, t, g, hs)
    return jnp.transpose(out, (0, 2, 1, 3, 4)).reshape(b, t, hq, hs)


def paged_attention_xla(q, kc, vc, k_new, v_new, tables, lengths, layer_idx,
                        *, n_read: int, virtual_len: int | None = None):
    """XLA reference for the kernel (and the bench oracle): gather the
    table's blocks into the dense window layout and run the SAME
    gqa_attention the dense cache path runs — bit-identical to a dense
    engine whose window equals n_read*bt. Shapes as paged_attention."""
    from .attention import gqa_attention

    b, t, hq, hs = q.shape
    bt = kc.shape[3]
    win = n_read * bt
    s_virtual = virtual_len if virtual_len is not None else win
    kw, vw = paged_gather_kv(kc, vc, layer_idx, tables, n_read)
    slot = jnp.arange(win)
    lengths = jnp.asarray(lengths, jnp.int32)
    slot_pos = jnp.where(slot[None, :] < lengths[:, None], slot[None, :],
                         s_virtual + 1)
    key_pos = jnp.concatenate(
        [slot_pos, lengths[:, None] + jnp.arange(t)[None, :]], axis=1)
    kfull = jnp.concatenate([kw, jnp.asarray(k_new, kw.dtype)], axis=2)
    vfull = jnp.concatenate([vw, jnp.asarray(v_new, vw.dtype)], axis=2)
    positions = lengths[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]
    out = gqa_attention(q.astype(jnp.float32), kfull, vfull, positions,
                        key_positions=key_pos)
    return out.reshape(b, t, hq, hs)
