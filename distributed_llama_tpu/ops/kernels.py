"""Elementwise / normalization ops.

TPU-native equivalents of the reference SIMD kernel layer (src/funcs.{hpp,cpp}): rmsnorm
(funcs.cpp rms+rmsnorm, eps=1e-5, reduction in f32), softmax, SiLU, tanh-GELU
(funcs.cpp:498-517). On TPU these are VPU ops that XLA fuses into surrounding matmuls, so
each is a plain jnp expression — no hand scheduling.
"""

import jax.numpy as jnp

RMS_EPS = 1e-5  # reference: funcs.cpp rms() `ss += 1e-5f`


def rmsnorm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = RMS_EPS) -> jnp.ndarray:
    """RMS-normalize the last axis; reduction in f32 regardless of activation dtype."""
    xf = x.astype(jnp.float32)
    ms = jnp.mean(xf * xf, axis=-1, keepdims=True)
    inv = jnp.reciprocal(jnp.sqrt(ms + eps))
    return (weight.astype(jnp.float32) * (xf * inv)).astype(x.dtype)


def silu(x: jnp.ndarray) -> jnp.ndarray:
    """x * sigmoid(x) (reference: funcs.cpp:510-517)."""
    xf = x.astype(jnp.float32)
    return (xf / (1.0 + jnp.exp(-xf))).astype(x.dtype)


def gelu_tanh(x: jnp.ndarray) -> jnp.ndarray:
    """tanh-approximation GELU, coefficients as in reference funcs.cpp:498-508."""
    xf = x.astype(jnp.float32)
    c = 0.79788456080286535587989211986876  # sqrt(2/pi)
    out = 0.5 * xf * (1.0 + jnp.tanh(c * xf * (1.0 + 0.044715 * xf * xf)))
    return out.astype(x.dtype)


def masked_softmax(scores: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Numerically-stable softmax over the last axis with a boolean validity mask.

    Reference softmax (funcs.cpp:64-93) subtracts the max; here invalid lanes are driven to
    -inf before the max so fully-masked rows still produce zeros (not NaN).
    """
    neg = jnp.finfo(jnp.float32).min
    s = jnp.where(mask, scores.astype(jnp.float32), neg)
    m = jnp.max(s, axis=-1, keepdims=True)
    e = jnp.exp(s - m)
    e = jnp.where(mask, e, 0.0)
    denom = jnp.sum(e, axis=-1, keepdims=True)
    return e / jnp.maximum(denom, 1e-30)
