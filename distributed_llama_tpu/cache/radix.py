"""Token-block radix index: prefix -> KV block handles, refcounted, LRU-evicted.

The reference's NaiveCache (dllama-api.cpp:187-232) and the BatchEngine's
per-slot descendant can only reuse a prefix when a free slot *happens* to still
hold a matching conversation. This index decouples prefix identity from slots:
token prefixes are chopped into fixed-size blocks (`block_tokens` tokens each)
and arranged in a radix tree whose nodes carry opaque block handles (owned by
cache/block_pool.py). Any request — whichever slot it lands on — can look up
the longest cached block-prefix of its prompt.

Because blocks are fixed-size, every edge is exactly one `block_tokens`-tuple,
so the "radix tree" degenerates to a block-granular trie; the radix property
that matters is the structural invariant it enforces: a node exists only if
its whole ancestor chain exists, so a match is always a contiguous prefix and
cached data can never be a mid-sequence island.

Concurrency: this structure is NOT internally locked — cache/prefix_cache.py
owns the single lock covering the tree and the pool together.

Invariants (property-tested against a brute-force oracle in
tests/test_prefix_cache.py):
- prefix-closed: every non-root node's parent chain is present;
- `refs >= 0` everywhere; eviction never removes a node with `refs > 0`
  or with live children (leaves first, so the tree stays prefix-closed);
- eviction order among evictable leaves is LRU by last touch (match/insert).
"""

from __future__ import annotations

__all__ = ["RadixIndex", "RadixNode"]


class RadixNode:
    __slots__ = ("key", "parent", "children", "handle", "refs", "stamp")

    def __init__(self, key: tuple[int, ...] | None, parent: "RadixNode | None",
                 handle: int | None = None):
        self.key = key          # the block of tokens labeling the edge from parent
        self.parent = parent
        self.children: dict[tuple[int, ...], RadixNode] = {}
        self.handle = handle    # opaque block-pool handle (None only at the root)
        self.refs = 0           # in-flight leases pinning this block
        self.stamp = 0          # LRU clock value of the last touch


class RadixIndex:
    def __init__(self, block_tokens: int = 16):
        assert block_tokens >= 1
        self.block_tokens = block_tokens
        self.root = RadixNode(None, None)
        self._clock = 0
        self.nodes = 0

    # ------------------------------------------------------------------
    # helpers
    # ------------------------------------------------------------------

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def _blocks(self, tokens: list[int]):
        n = self.block_tokens
        for i in range(0, len(tokens) - n + 1, n):
            yield tuple(tokens[i:i + n])

    # ------------------------------------------------------------------
    # queries / mutation
    # ------------------------------------------------------------------

    def match(self, tokens: list[int]) -> list[RadixNode]:
        """Longest chain of cached full blocks prefixing `tokens` (root-first).
        Touches the chain's LRU stamps; does NOT acquire references."""
        out: list[RadixNode] = []
        node = self.root
        stamp = self._tick()
        for blk in self._blocks(tokens):
            child = node.children.get(blk)
            if child is None:
                break
            child.stamp = stamp
            out.append(child)
            node = child
        return out

    def acquire(self, nodes: list[RadixNode]) -> None:
        stamp = self._tick()
        for n in nodes:
            n.refs += 1
            n.stamp = stamp

    def release(self, nodes: list[RadixNode]) -> None:
        for n in nodes:
            assert n.refs > 0, "radix release without matching acquire"
            n.refs -= 1

    def insert(self, tokens: list[int], make_handle) -> list[RadixNode]:
        """Ensure a chain for every full block of `tokens`; returns the chain.

        `make_handle(block_index)` is called for each MISSING block (missing
        blocks are always a suffix of the chain — the prefix-closed invariant)
        and must return a pool handle, or None to stop extending (pool full and
        nothing evictable). Existing blocks are never re-made.

        The chain built so far is ref-pinned while make_handle runs: a
        make_handle that evicts to free pool room (cache/prefix_cache.py)
        must never be handed this chain's own freshly-attached ancestors —
        evicting one would detach the node the next block attaches under."""
        node = self.root
        stamp = self._tick()
        chain: list[RadixNode] = []
        try:
            for i, blk in enumerate(self._blocks(tokens)):
                child = node.children.get(blk)
                if child is None:
                    handle = make_handle(i)
                    if handle is None:
                        break
                    child = RadixNode(blk, node, handle)
                    node.children[blk] = child
                    self.nodes += 1
                child.refs += 1  # pin against self-eviction (released below)
                child.stamp = stamp
                chain.append(child)
                node = child
        finally:
            for c in chain:
                c.refs -= 1
        return chain

    # ------------------------------------------------------------------
    # eviction
    # ------------------------------------------------------------------

    def _evictable_leaves(self) -> list[RadixNode]:
        out = []
        stack = [self.root]
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            if n is not self.root and not n.children and n.refs == 0:
                out.append(n)
        return out

    def evict(self, n_blocks: int) -> list[int]:
        """Remove up to `n_blocks` LRU unreferenced leaves; returns their
        handles (for the pool to free). Removing a leaf may expose its parent —
        the sweep cascades so one call can free a whole cold branch."""
        import heapq

        heap = [(leaf.stamp, id(leaf), leaf) for leaf in self._evictable_leaves()]
        heapq.heapify(heap)
        freed: list[int] = []
        while heap and len(freed) < n_blocks:
            _, _, leaf = heapq.heappop(heap)
            parent = leaf.parent
            del parent.children[leaf.key]
            self.nodes -= 1
            freed.append(leaf.handle)
            if (parent is not self.root and not parent.children
                    and parent.refs == 0):
                heapq.heappush(heap, (parent.stamp, id(parent), parent))
        return freed

    # ------------------------------------------------------------------
    # introspection (tests / stats)
    # ------------------------------------------------------------------

    def chains(self) -> list[tuple[tuple[int, ...], ...]]:
        """Every stored block-chain as a tuple of block keys (tests/oracle)."""
        out = []
        stack = [(self.root, ())]
        while stack:
            node, prefix = stack.pop()
            for key, child in node.children.items():
                chain = prefix + (key,)
                out.append(chain)
                stack.append((child, chain))
        return out

    def total_refs(self) -> int:
        total = 0
        stack = [self.root]
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            total += n.refs
        return total
